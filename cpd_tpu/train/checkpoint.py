"""Checkpoint / auto-resume — orbax over the whole TrainState pytree.

Parity with the reference's three ad-hoc schemes (SURVEY.md §5):
  * ResNet18: rank-0 `save_checkpoint` of state_dict + optimizer + step +
    best_prec1, with a `_best` copy (mix.py:345-356, train_util.py:268-271);
  * ResNet50: per-epoch `checkpoint-{E}.pth.tar` + auto-resume by scanning
    for the latest file (main.py:70-75,134-138,261-269);
  * `load_state`'s `module.`-prefix surgery (train_util.py:274-318)
    disappears — a pytree has no wrapper prefixes.

Here: one CheckpointManager per run directory, step-indexed, keep-N,
`best_fn`-tracked best, and `restore_latest` as the auto-resume.  Works for
any TrainState (params/batch_stats/opt_state/step) because it's all one
pytree.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import signal as _signal
import sys
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from .state import TrainState

__all__ = ["CheckpointManager", "PreemptionGuard", "preempt_save",
           "save_checkpoint", "restore_latest", "RestoreResult",
           "checkpoint_digest"]


def checkpoint_digest(step_dir: str, exclude: tuple = ()) -> dict:
    """Content checksum of one step's checkpoint directory.

    sha256 over (relative path, size, bytes) of every file, in sorted
    order — any truncation, bit-flip, or missing file changes the
    digest.  Orbax finalizes a step atomically (write to a tmp dir, then
    rename), so by the time a step is listed its files are stable.

    ``exclude`` skips files by step-dir-relative path: a sidecar that
    STORES the digest cannot be covered by it (the serving engine's
    crash-recovery snapshots put ``meta.json`` inside the snapshot
    directory — `serve.engine.ServeEngine.snapshot`)."""
    h = hashlib.sha256()
    n_files = 0
    n_bytes = 0
    for root, dirs, files in os.walk(step_dir):
        dirs.sort()
        for name in sorted(files):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, step_dir)
            if rel in exclude:
                continue
            size = os.path.getsize(path)
            h.update(rel.encode())
            h.update(str(size).encode())
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            n_files += 1
            n_bytes += size
    return {"algo": "sha256", "digest": h.hexdigest(),
            "files": n_files, "bytes": n_bytes}


class RestoreResult(NamedTuple):
    state: TrainState
    step: int
    skipped: tuple      # steps rejected (bad digest / unrestorable)
    # integrity verdict of the RESTORED step: True = digest matched,
    # None = no digest was ever recorded (pre-integrity checkpoint, or
    # integrity=False saves).  A None restore is a silent-integrity gap
    # — counted (`ckpts_unverified`) and logged separately so it cannot
    # masquerade as a verified one.  False never appears here: digest
    # mismatches are skipped, not restored.
    verified: Any = True
    # the restored step's metadata sidecar (None when absent) — how
    # supervisor state saved WITH a checkpoint comes back with it: the
    # precision ladder resumes at its escalated format
    # (resilience/precision.py state_dict under the "precision" key)
    # instead of re-diverging from home after a rollback or restart.
    metadata: Any = None


def preempt_save(manager: "CheckpointManager", step_no, state, rank: int,
                 metadata: Optional[dict] = None,
                 what: str = "iter") -> None:
    """The shared preemption-boundary save used by every trainer loop.

    Skips the save when a checkpoint at this exact step already exists
    (a periodic save just before the signal, or a resume that never
    stepped) — saving again would raise orbax's StepAlreadyExistsError
    mid-grace-period.  Blocks for in-flight device work first and waits
    for the write, so the process can exit immediately after."""
    jax.block_until_ready(state.params)
    if manager.latest_step() != int(step_no):
        manager.save(int(step_no), state, force=True, metadata=metadata)
        manager.wait()
    if rank == 0:
        # stdout on purpose: tests/test_examples.py asserts this exact
        # line in captured stdout (reference-parity operator protocol)
        print(f"=> preempted: saved {what} {int(step_no)}; exiting")  # cpd: disable=obs-print


class PreemptionGuard:
    """Turn SIGTERM into a save-at-the-next-step-boundary request.

    Cloud TPU VMs (spot/preemptible, maintenance events) deliver SIGTERM
    with a grace period before the kill.  The reference's only recovery
    is re-scanning for the last *per-epoch* file after the fact
    (reference main.py:70-75), losing everything since.  Trainers poll
    ``triggered`` once per step; on True they checkpoint — including the
    exact iteration, so the deterministic epoch-seeded sampler order lets
    resume continue mid-epoch without re-training a single batch — and
    exit cleanly.

    Signal handlers are process-global state: install once in the CLI
    entry, not in library code, and ``uninstall()`` in tests.
    """

    def __init__(self, signals=(_signal.SIGTERM, _signal.SIGINT)):
        # SIGINT is trapped too (it IS in the default set): a Ctrl-C on a
        # long run should save-at-the-boundary exactly like a spot-VM
        # SIGTERM, not lose the epoch to a KeyboardInterrupt traceback.
        self._triggered = False
        self._prev = {}
        for s in signals:
            self._prev[s] = _signal.signal(s, self._handle)

    def _handle(self, signum, frame):
        if self._triggered and signum == _signal.SIGINT:
            # second Ctrl-C: the user means it.  A wedged step never
            # reaches the boundary where `triggered` is consulted, so
            # the save-at-boundary protocol must not absorb Ctrl-C
            # forever — escalate to the ordinary KeyboardInterrupt.
            raise KeyboardInterrupt
        self._triggered = True

    @property
    def triggered(self) -> bool:
        return self._triggered

    def uninstall(self) -> None:
        """Restore the pre-install handlers (idempotent).  Signal
        handlers are process-global: a trainer that returns without this
        leaves the NEXT run (or the test harness) with a stale handler,
        which is why `close()`/context-exit route here."""
        for s, prev in self._prev.items():
            _signal.signal(s, prev)
        self._prev = {}

    close = uninstall

    def __enter__(self) -> "PreemptionGuard":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.uninstall()

    def should_stop(self) -> bool:
        """Cluster-wide preemption decision — EVERY host must call this at
        the same step boundary (it is a collective when multi-host).

        The local flag alone would desync hosts: a maintenance event
        signals VMs at slightly different times, so one host could enter
        the checkpoint save while another dispatches the next step's
        all-reduce — mismatched collectives, deadlock, grace period lost.
        Agreeing on max(flag) over all hosts makes every host take the
        same branch; a host signaled *after* the agreement simply stops at
        the next boundary."""
        if jax.process_count() == 1:
            return self._triggered
        from ..compat import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(self._triggered, np.int32))
        return bool(np.max(flags))


def jnp_dtype(x):
    """dtype of an array-like leaf (scalars included)."""
    return getattr(x, "dtype", None) or np.asarray(x).dtype


def _find_zero_state(opt_state):
    """The ZeRO flat-momentum state (parallel.zero.Zero1State) nested
    anywhere in ``opt_state``, or None.  Lazy import: checkpointing a
    plain optax state must not pay for the parallel stack."""
    if opt_state is None:
        return None
    try:
        from ..parallel.zero import Zero1State
    except ImportError:      # pragma: no cover - parallel always ships
        return None

    def is_z(n):
        return isinstance(n, Zero1State)

    for node in jax.tree_util.tree_leaves(opt_state, is_leaf=is_z):
        if is_z(node):
            return node
    return None


class CheckpointManager:
    """Thin orbax wrapper with the reference's retention semantics, plus
    content-integrity checking (``integrity=True``, the default): every
    save records a sha256 digest of the step's files in the metadata
    sidecar, and ``restore_latest_valid`` walks steps newest-first,
    skipping any whose bytes no longer match — a truncated or bit-flipped
    checkpoint degrades the run by one save interval instead of killing
    the resume (or worse, silently restoring garbage arrays).

    **Durable-store mode (ISSUE 20).**  Pass ``store=`` a
    `cpd_tpu.store.DurableStore` and the checkpoint surface migrates
    off orbax onto the crash-consistent generation store: each save
    publishes ONE sealed generation (``state.npz`` of the flattened
    pytree + ``tree.json`` layout record, per-artifact digests in the
    manifest), fenced by a writer epoch the manager acquires at
    construction — a stale elastic-restart writer gets
    `store.FencedWriterError` instead of clobbering its successor's
    checkpoints.  Retention is ``store.gc(max_to_keep)`` (provably
    never the newest valid generation), corruption lands in quarantine
    instead of being restored, and transient EIO/ENOSPC mid-save is
    absorbed by the store's deterministic retry — the previous
    generation stays restorable throughout.  The public API (save /
    restore / restore_latest_valid / metadata / verify_step /
    latest_step, including the elastic ``world=`` re-flatten) is
    unchanged; store-on vs store-off runs are bitwise identical because
    checkpointing is passive.
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 track_best: bool = True, integrity: bool = True,
                 store=None):
        directory = os.path.abspath(directory)
        self._dir = directory
        self._integrity = integrity
        self._keep = int(max_to_keep)
        self._store = store
        if store is not None:
            # the store IS the checkpoint directory; orbax never starts.
            # The writer epoch is the fence: acquired once per manager
            # (per process incarnation), refreshed via `refence()` after
            # an elastic recovery.  `directory` follows the store root
            # so every path consumer (the legacy corruption drills
            # included) aims at the generations that actually exist.
            self._dir = store.root
            self._mgr = None
            self._writer = store.acquire_writer()
            return
        kwargs = {}
        if track_best:   # orbax requires best_mode in {'min','max'} if set
            kwargs = {"best_fn": lambda m: m.get("best_metric", 0.0),
                      "best_mode": "max"}
        options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                               **kwargs)
        self._mgr = ocp.CheckpointManager(directory, options=options)

    @property
    def directory(self) -> str:
        return self._dir

    @property
    def store(self):
        """The backing `DurableStore` (None on the orbax path)."""
        return self._store

    def refence(self) -> int:
        """Store mode: acquire a FRESH writer epoch (after an elastic
        recovery — the rebuilt incarnation must fence out any save the
        pre-failure incarnation still has in flight)."""
        if self._store is None:
            raise ValueError("refence() only exists in store mode")
        self._writer = self._store.acquire_writer()
        return self._writer

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._dir, str(step))

    def save(self, step: int, state: TrainState,
             best_metric: Optional[float] = None, force: bool = False,
             metadata: Optional[dict] = None):
        """Save at `step`; only the process-0 host writes (orbax handles
        multi-host coordination — the reference gates on rank==0 manually,
        mix.py:345).

        `metadata`: small JSON-able dict stored in a sidecar file next to
        the checkpoint — e.g. the epoch number, so resume doesn't have to
        re-derive it from step // iters_per_epoch (which breaks when batch
        size / device count / --max-batches-per-epoch change between runs).

        With ``integrity`` on, the save is waited for and the sidecar
        additionally records the step's content digest.  The sidecar
        itself is written atomically (tmp + rename), so a crash mid-write
        leaves either the old sidecar or the new one, never a torn file.
        """
        if self._store is not None:
            self._store_save(step, state, best_metric, metadata)
            return
        metrics = ({"best_metric": float(best_metric)}
                   if best_metric is not None else None)
        if force and step in self._mgr.all_steps():
            # a rollback replay re-reaches an already-saved step (often
            # the corrupted one that caused the rollback): the fresh
            # save must REPLACE it — orbax's force only bypasses
            # should_save, it still refuses an existing step
            self._mgr.delete(step)
        self._mgr.save(step, args=ocp.args.StandardSave(state),
                       metrics=metrics, force=force)
        z = _find_zero_state(getattr(state, "opt_state", None))
        if z is not None:
            # elastic-restart layout record (ISSUE 4): the flat-momentum
            # length at THIS world size, so `restore(world=W')` can
            # re-flatten a W-padded shard layout through pad_to_world at
            # the new world instead of failing on a shape mismatch
            metadata = dict(metadata or {})
            metadata["zero_layout"] = {
                "momentum_padded": int(np.shape(z.momentum)[0])}
        if self._integrity:
            # the digest must cover the FINAL bytes: wait for orbax's
            # async write + atomic rename before hashing.  Hash on
            # process 0 only — it is the sole sidecar writer, and (N-1)
            # redundant full reads of the checkpoint would be pure waste
            # on a pod.  (Cost note: integrity makes save() synchronous;
            # pass integrity=False to keep the async-save overlap.)
            self._mgr.wait_until_finished()
            if jax.process_index() == 0:
                metadata = dict(metadata or {})
                metadata["integrity"] = checkpoint_digest(
                    self._step_dir(step))
        if metadata is not None and jax.process_index() == 0:
            tmp = os.path.join(self._dir, f".meta-{step}.json.tmp")
            with open(tmp, "w") as f:
                json.dump(metadata, f)
            os.replace(tmp, os.path.join(self._dir, f"meta-{step}.json"))
            self._gc_metadata(keep=step)

    # -- durable-store backend (ISSUE 20) ---------------------------------

    def _store_save(self, step: int, state: TrainState,
                    best_metric, metadata) -> None:
        """One checkpoint = one sealed generation: the flattened pytree
        as ``state.npz`` (leaf order = tree order, dtype-exact), the
        layout as ``tree.json``, the sidecar dict in the manifest's
        ``meta``.  Rank gating: only process 0 publishes, matching the
        orbax path's sole-sidecar-writer rule."""
        if jax.process_index() != 0:
            return
        leaves = jax.tree_util.tree_leaves(jax.device_get(state))
        buf = io.BytesIO()
        np.savez(buf, **{f"leaf{i:06d}": np.asarray(l)
                         for i, l in enumerate(leaves)})
        tree = {"n_leaves": len(leaves),
                "shapes": [list(np.shape(l)) for l in leaves],
                "dtypes": [str(jnp_dtype(l)) for l in leaves]}
        meta = dict(metadata or {})
        if best_metric is not None:
            meta["best_metric"] = float(best_metric)
        z = _find_zero_state(getattr(state, "opt_state", None))
        if z is not None:
            meta["zero_layout"] = {
                "momentum_padded": int(np.shape(z.momentum)[0])}
        self._store.publish(
            {"state.npz": buf.getvalue(),
             "tree.json": json.dumps(tree, sort_keys=True).encode()},
            step=int(step), meta=meta, writer=self._writer)
        self._store.gc(keep=self._keep)

    def _store_gens(self) -> list:
        """Valid generations newest-token-first, manifests loaded;
        invalid ones are quarantined on the way (the store's contract).
        Newest generation wins for a step saved twice (rollback replay
        re-saves)."""
        out = []
        for info in self._store.generations():
            man = self._store.validate(info)
            if man is None:
                self._store._quarantine(info)
                continue
            info.manifest = man
            out.append(info)
        return out

    def _store_lookup(self, step: int):
        for info in self._store_gens():
            if info.step == int(step):
                return info
        return None

    def _store_restore(self, info, state_template: TrainState):
        blobs = self._store.load(info)
        tree = json.loads(blobs["tree.json"].decode())
        with np.load(io.BytesIO(blobs["state.npz"])) as z:
            saved = [z[f"leaf{i:06d}"] for i in range(tree["n_leaves"])]
        tleaves, treedef = jax.tree_util.tree_flatten(state_template)
        if len(saved) != len(tleaves):
            raise ValueError(
                f"store checkpoint step {info.step}: {len(saved)} saved "
                f"leaves vs {len(tleaves)} in the template")
        out = []
        for i, (s, t) in enumerate(zip(saved, tleaves)):
            want = np.dtype(tree["dtypes"][i])
            if s.dtype != want and s.dtype.itemsize == want.itemsize:
                # npz round-trips extension dtypes (bfloat16, fp8) as
                # raw void bytes; the recorded dtype restores the view
                # bit-exactly
                s = s.view(want)
            if tuple(s.shape) != tuple(np.shape(t)) or \
                    s.dtype != np.dtype(jnp_dtype(t)):
                raise ValueError(
                    f"store checkpoint step {info.step}, leaf {i}: saved "
                    f"{s.shape}/{s.dtype} vs template "
                    f"{np.shape(t)}/{jnp_dtype(t)}")
            out.append(jnp.asarray(s))
        return jax.tree_util.tree_unflatten(treedef, out)

    def verify_step(self, step: int) -> Optional[bool]:
        """Re-hash `step`'s files against the recorded digest.  True =
        match, False = mismatch (or unreadable — a sidecar that EXISTS
        but does not parse is a torn write, invalid, not unknown), None
        = no digest was recorded (pre-integrity checkpoint: unknown,
        not invalid)."""
        if self._store is not None:
            info = self._store_lookup(step)
            return False if info is None else True
        status, meta = self._read_sidecar(step)
        if status == "torn":
            return False
        recorded = (meta or {}).get("integrity")
        if not recorded:
            return None
        try:
            actual = checkpoint_digest(self._step_dir(step))
        except OSError:
            return False
        return actual["digest"] == recorded["digest"]

    def _gc_metadata(self, keep: Optional[int] = None) -> None:
        """Drop meta-*.json sidecars whose checkpoint was purged by orbax's
        max_to_keep retention (best-effort; `keep` is the step being written
        right now, whose orbax save may still be in flight)."""
        live = set(self._mgr.all_steps())
        if keep is not None:
            live.add(keep)
        for fname in os.listdir(self._dir):
            if fname.startswith("meta-") and fname.endswith(".json"):
                try:
                    step = int(fname[len("meta-"):-len(".json")])
                except ValueError:
                    continue
                if step not in live:
                    try:
                        os.remove(os.path.join(self._dir, fname))
                    except OSError:
                        pass

    def _read_sidecar(self, step: int) -> tuple:
        """Tri-state sidecar read: ``("ok", dict)``, ``("absent",
        None)``, or ``("torn", None)`` for a sidecar that exists but
        does not parse — a truncated/garbled write that must read as
        *invalid-and-skip*, never as "no digest recorded" (which would
        let a corrupt checkpoint restore unverified) and never as a
        crash (which would kill the whole resume scan)."""
        path = os.path.join(self._dir, f"meta-{step}.json")
        if not os.path.exists(path):
            return "absent", None
        try:
            with open(path) as f:
                return "ok", json.load(f)
        except (json.JSONDecodeError, UnicodeDecodeError, OSError):
            return "torn", None

    def metadata(self, step: Optional[int] = None) -> Optional[dict]:
        """Sidecar metadata saved with `step` (default: latest), or None
        (absent OR torn — `verify_step` tells the two apart)."""
        if self._store is not None:
            if step is None:
                step = self.latest_step()
            if step is None:
                return None
            info = self._store_lookup(step)
            return None if info is None else (info.meta or None)
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        return self._read_sidecar(step)[1]

    def wait(self):
        if self._mgr is not None:     # store publishes are synchronous
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        if self._store is not None:
            info = self._store.newest_valid()
            return None if info is None else int(info.step)
        return self._mgr.latest_step()

    def restore(self, state_template: TrainState,
                step: Optional[int] = None,
                shardings: Optional[Any] = None,
                world: Optional[int] = None) -> Optional[TrainState]:
        """Restore `step` (default latest) shaped like `state_template`;
        None if no checkpoint exists — the auto-resume scan of
        main.py:70-75.

        `shardings`: optional pytree of jax.sharding.Sharding matching the
        state — orbax then materializes each array DIRECTLY in its target
        layout (sharded/replicated on the mesh), skipping the
        single-device restore + device_put relayout (2x host memory on
        big states).

        `world`: elastic ZeRO-1/2 restart (ISSUE 4).  When the template
        carries a `parallel.zero.Zero1State` whose flat momentum was
        PADDED for a different world size than the checkpoint's (a
        preemption replay that resumes on a shrunken/grown mesh), the
        momentum is restored at its saved length, trimmed of the old
        world-size pad, and re-flattened through `pad_to_world` at the
        new world — bitwise-faithful, because the pad region holds exact
        zeros by construction (zero gradients keep zero momentum).

        Store mode restores unsharded and ignores ``shardings`` (the
        elastic path's documented trade: every trainer re-lays the
        state out on its mesh after restore anyway)."""
        if step is None:
            step = self.latest_step() if self._store is not None \
                else self._mgr.latest_step()
        if step is None:
            return None
        if world is not None:
            ztmpl = _find_zero_state(getattr(state_template, "opt_state",
                                             None))
            zl = (self.metadata(step) or {}).get("zero_layout")
            if ztmpl is not None and zl is not None:
                saved_len = int(zl["momentum_padded"])
                if saved_len != int(np.shape(ztmpl.momentum)[0]):
                    return self._restore_elastic(state_template, step,
                                                 world, saved_len)
        if self._store is not None:
            info = self._store_lookup(step)
            if info is None:
                return None
            return self._store_restore(info, state_template)
        if shardings is None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct,
                                    state_template)
        else:
            # `shardings` may be a PREFIX tree (e.g. one sharding for the
            # whole params subtree); broadcast it over the state leaves
            try:   # not yet in the public tree_util namespace
                from jax._src.tree_util import broadcast_prefix
            except ImportError:  # pragma: no cover - newer jax
                from jax.tree_util import broadcast_prefix  # type: ignore
            flat_shard = broadcast_prefix(
                shardings, state_template,
                is_leaf=lambda s: isinstance(s, jax.sharding.Sharding))
            leaves, treedef = jax.tree_util.tree_flatten(state_template)
            abstract = jax.tree_util.tree_unflatten(treedef, [
                jax.ShapeDtypeStruct(np.shape(x), jnp_dtype(x), sharding=s)
                for x, s in zip(leaves, flat_shard)])
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(abstract))

    def _restore_elastic(self, state_template: TrainState, step: int,
                         world: int, saved_len: int) -> TrainState:
        """The ZeRO-1/2 re-flatten: restore the flat momentum at the
        length it was SAVED with, trim the old world-size pad (the real
        data is the first `total` elements — parallel/zero.py
        export_state's portable contract), and re-pad for `world`.

        Restores UNSHARDED (the caller's `shardings` describe the
        target shapes, not the saved momentum length) — an elastic
        restore pays the single-device materialize + relayout cost the
        sharded path avoids; every trainer re-lays the state out on its
        mesh after restore anyway (their `relayout`/`mesh_layout`)."""
        from ..parallel.ring import reflatten_to_world
        from ..parallel.zero import Zero1State

        def is_z(n):
            return isinstance(n, Zero1State)

        tmpl = jax.tree_util.tree_map(
            lambda n: (Zero1State(n.step,
                                  jnp.zeros((saved_len,), jnp.float32))
                       if is_z(n) else n),
            state_template, is_leaf=is_z)
        restored = self.restore(tmpl, step=step)
        total = sum(int(np.size(l))
                    for l in jax.tree_util.tree_leaves(restored.params))

        def refl(saved, want):
            if not is_z(saved):
                return saved
            mom = reflatten_to_world(jnp.asarray(saved.momentum), total,
                                     world)
            want_len = int(np.shape(want.momentum)[0])
            if int(mom.shape[0]) != want_len:
                raise ValueError(
                    f"elastic restore at world={world}: re-flattened "
                    f"momentum has {int(mom.shape[0])} elements but the "
                    f"template expects {want_len} — template world and "
                    f"`world=` disagree (build the template with the "
                    f"updater for the NEW world size)")
            return Zero1State(saved.step, mom)

        new_opt = jax.tree_util.tree_map(
            refl, restored.opt_state, state_template.opt_state,
            is_leaf=is_z)
        return restored.replace(opt_state=new_opt)

    def restore_latest_valid(self, state_template: TrainState,
                             shardings: Optional[Any] = None,
                             rank: int = 0,
                             world: Optional[int] = None
                             ) -> Optional[RestoreResult]:
        """Restore the newest step that (a) passes the integrity check
        and (b) actually restores.  Steps failing either are skipped
        with a rank-0 warning and reported in ``RestoreResult.skipped``
        (the resilience counters' `restores`/`skipped` feed).  Returns
        None when no step survives.

        A step with NO recorded digest (pre-integrity checkpoint) still
        restores — rejecting it would turn a config change into data
        loss — but the gap is surfaced: rank-0 warning, and
        ``RestoreResult.verified is None`` so callers count it
        (`ckpts_unverified`) instead of silently treating it as
        verified.  `world` enables the elastic ZeRO re-flatten (see
        `restore`)."""
        if self._store is not None:
            return self._store_restore_latest_valid(state_template, rank,
                                                    world)
        skipped = []
        for step in sorted(self._mgr.all_steps(), reverse=True):
            verdict = self.verify_step(step)
            if verdict is False:
                if rank == 0:
                    print(f"=> checkpoint {step}: integrity digest "
                          f"mismatch — skipping", file=sys.stderr)
                skipped.append(step)
                continue
            try:
                state = self.restore(state_template, step=step,
                                     shardings=shardings, world=world)
            except Exception as e:
                # a checkpoint that fails integrity-unknown restore is
                # exactly what this scan exists to survive: report and
                # fall back to the next-newest step
                if rank == 0:
                    print(f"=> checkpoint {step}: restore failed "
                          f"({type(e).__name__}: {e}) — skipping",
                          file=sys.stderr)
                skipped.append(step)
                continue
            if verdict is None and rank == 0:
                print(f"=> checkpoint {step}: restored WITHOUT an "
                      f"integrity digest (pre-integrity save) — "
                      f"corruption would be undetectable here",
                      file=sys.stderr)
            return RestoreResult(state, step, tuple(skipped),
                                 verified=verdict,
                                 metadata=self.metadata(step))
        return None

    def _store_restore_latest_valid(self, state_template: TrainState,
                                    rank: int, world: Optional[int]
                                    ) -> Optional[RestoreResult]:
        """The store-mode resume scan: newest generation down, corrupt
        ones quarantined + reported in ``skipped`` (they feed
        ``ckpts_invalid`` exactly like an orbax digest mismatch).
        ``verified`` is always True here — a sealed manifest with
        per-artifact digests exists for every generation by
        construction, so the unverified-restore gap cannot occur."""
        skipped: list = []
        seen: set = set()
        for info in self._store.generations():
            man = self._store.validate(info)
            if man is None:
                # report the STEP like the orbax scan does (callers
                # match on ints); the generation name is only the
                # fallback label when the manifest itself is the
                # casualty and the step is unrecoverable
                try:
                    with open(os.path.join(info.path,
                                           "MANIFEST.json")) as fh:
                        label: Any = int(json.load(fh)["step"])
                except (OSError, ValueError, KeyError, TypeError):
                    label = info.name
                self._store._quarantine(info)
                if rank == 0:
                    print(f"=> store checkpoint {info.name}: failed "
                          f"validation — quarantined, skipping",
                          file=sys.stderr)
                skipped.append(label)
                continue
            info.manifest = man
            step = int(info.step)
            if step in seen:
                continue        # older duplicate of a re-saved step
            seen.add(step)
            try:
                state = self.restore(state_template, step=step,
                                     world=world)
            except Exception as e:
                if rank == 0:
                    print(f"=> store checkpoint {step}: restore failed "
                          f"({type(e).__name__}: {e}) — skipping",
                          file=sys.stderr)
                skipped.append(step)
                continue
            return RestoreResult(state, step, tuple(skipped),
                                 verified=True,
                                 metadata=info.meta or None)
        return None

    def close(self):
        if self._mgr is not None:
            self._mgr.close()


def save_checkpoint(directory: str, step: int, state: TrainState,
                    best_metric: Optional[float] = None):
    """One-shot save (train_util.py:268-271 equivalent)."""
    mgr = CheckpointManager(directory, track_best=best_metric is not None)
    mgr.save(step, state, best_metric=best_metric, force=True)
    mgr.wait()
    mgr.close()


def restore_latest(directory: str,
                   state_template: TrainState) -> Optional[TrainState]:
    """Auto-resume from the newest checkpoint in `directory`, or None."""
    if not os.path.isdir(directory):
        return None
    mgr = CheckpointManager(directory, track_best=False)
    try:
        return mgr.restore(state_template)
    finally:
        mgr.close()
