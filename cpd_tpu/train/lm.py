"""LM train step over a ("dp","sp","tp") mesh — the long-context /
multi-axis companion of train/step.py.

One jitted shard_map program per config, composing every parallel axis the
framework supports:

* dp — data parallelism with the reference's quantized gradient all-reduce
  (APS / ordered / Kahan, parallel/dist.py) — the low-precision collective
  is the framework's core capability (reference dist_util.py:22-89);
* sp — sequence parallelism: tokens sharded on T, Ring Attention inside
  the model (ops/attention.py), plus an fp32 `psum` of gradients over sp
  (each sp rank sees different tokens);
* tp — Megatron tensor parallelism: params sharded per
  `lm_param_specs`, activations replicated between the per-block psums;
  replicated-param gradients are `psum`'d over tp, sharded-param gradients
  are already complete on their shard.

Gradient flow: local grads → psum over sp (all) → psum over tp
(replicated params only) → quantized sum_gradients over dp → optimizer.
The optimizer update runs shard-local, which is exact for the elementwise
SGD family (train/optim.py); LARS trust ratios would need global norms —
use sgd/nesterov here.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import lm_param_specs
from ..compat import shard_map
from ..parallel.dist import grad_sr_key, sum_gradients
from ..parallel.emulate import emulate_node_reduce
from .state import (TrainState, make_sharded_stepper, reject_norm_based,
                    state_specs_like)

__all__ = ["make_lm_train_step", "make_lm_eval_step", "lm_state_specs"]


def lm_state_specs(state: TrainState, tp_axis: str = "tp") -> TrainState:
    """PartitionSpec pytree shaped like `state`: params (and their optimizer
    momentum mirror) follow the Megatron rules, scalars replicated."""
    return state_specs_like(state, lm_param_specs(state.params, tp_axis))


def make_lm_train_step(model, tx: optax.GradientTransformation, mesh: Mesh,
                       *, axis_dp: str = "dp", axis_sp: str = "sp",
                       axis_tp: str = "tp", emulate_node: int = 1,
                       use_aps: bool = False, grad_exp: int = 8,
                       grad_man: int = 23, use_kahan: bool = False,
                       mode: str = "faithful", donate: bool = True,
                       label_smoothing: float = 0.0, rng_seed: int = 0,
                       grad_rounding: str = "nearest", grad_seed: int = 0,
                       verify_reduce: bool = False,
                       wire_fault_plan=None,
                       quant_stats: bool = False,
                       sat_fault_plan=None,
                       overlap_reduce: bool = False,
                       bucket_elems=None,
                       block_scale: bool = False,
                       block_size: int = 128):
    """Build jitted ``(state, tokens, targets) -> (state, metrics)``.

    tokens/targets: (global_batch * emulate_node, T_global) int32, sharded
    (dp, sp).  Loss is next-token CE averaged over all target positions;
    ``label_smoothing`` in [0, 1) mixes the one-hot targets with uniform
    mass (training loss only — eval stays plain CE).

    verify_reduce / wire_fault_plan: the self-verifying dp reduction and
    its deterministic wire-fault table, exactly as on
    `train.step.make_train_step` (the reduce_ok/... metrics feed the
    transport supervisor).  The sp/tp psums stay unverified — they are
    XLA's own collectives with no custom wire.

    quant_stats / sat_fault_plan: reduce-wire numeric-health telemetry
    (``prec_wire_*`` / ``prec_aps_bad`` metrics feeding the
    `resilience.precision.PrecisionSupervisor`) and the deterministic
    2^k saturation-pressure table, exactly as on `make_train_step` —
    the pressure scales the post-sp/tp-psum local gradients, so every
    dp rank's wire cast sees it identically.

    overlap_reduce / bucket_elems: the bucketed, dependency-scheduled
    transport, exactly as on `make_train_step` (parallel/overlap.py) —
    per-bucket taps run the dp reduction inside the backward; each
    leaf's sp psum (and tp psum for replicated params) moves INTO its
    bucket's tap, so the whole per-leaf reduction chain starts when
    that bucket closes.  Bitwise identical to the monolithic step.
    Composes with emulate_node > 1 (ISSUE 12): the first N-1
    micro-batches run unrolled and their sp/tp-reduced stacked grads
    ride into the last micro-batch's taps, whose per-bucket
    emulate-node reduce + dp collective fire as each bucket closes.

    block_scale / block_size: the EQuARX-style block-scaled ring wire
    for the dp reduction, exactly as on `make_train_step` — ring mode
    only; a distinct accumulation numerics (own StepTable key via
    `ladder_step_key(block=...)`); composes with overlap_reduce
    bitwise.  The sp/tp psums are untouched (fp32 XLA collectives).
    """
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(f"label_smoothing must be in [0, 1), got "
                         f"{label_smoothing}")
    if grad_rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown grad_rounding {grad_rounding!r}")
    if block_scale and mode != "ring":
        raise ValueError(
            f"block_scale=True needs mode='ring' (got {mode!r}): the "
            f"per-block scale sidecar rides the ring's packed wire")
    # Guard: the optimizer update runs shard-local, which is only exact for
    # elementwise transforms (see reject_norm_based).  With tp=1 all params
    # are replicated and grads fully reduced before the update, so
    # per-shard norms ARE global norms — LARS is fine there.
    if mesh.shape.get(axis_tp, 1) > 1:
        reject_norm_based(tx, "tp-sharded LM step")

    has_dropout = getattr(model, "dropout_rate", 0.0) > 0.0

    def step_fn(state: TrainState, tokens, targets):
        def loss_of(params, toks, tgts, micro_idx):
            rngs = {}
            if has_dropout:
                # deterministic in (seed, global step, micro index) and
                # decorrelated across dp/sp ranks — but NOT tp: the tp
                # ranks compute the same activations redundantly, so
                # their masks must be identical (Block applies dropout
                # post-psum)
                key = jax.random.fold_in(jax.random.PRNGKey(rng_seed),
                                         state.step * emulate_node
                                         + micro_idx)
                key = jax.random.fold_in(
                    key, lax.axis_index(axis_dp).astype(jnp.int32))
                key = jax.random.fold_in(
                    key, lax.axis_index(axis_sp).astype(jnp.int32))
                rngs = {"dropout": key}
            logits = model.apply({"params": params}, toks, train=True,
                                 rngs=rngs)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, tgts)                       # (B_local, T_local)
            if label_smoothing:
                # closed form of CE against one_hot*(1-a) + a/V targets:
                # (1-a)*CE_int + a*(logsumexp - mean(logits)) — no dense
                # (B, T, V) target tensor, which at long-context shapes
                # (V=32k) would cost GBs per microbatch
                lf32 = logits.astype(jnp.float32)
                uniform = (jax.scipy.special.logsumexp(lf32, axis=-1)
                           - lf32.mean(axis=-1))
                ce = ((1.0 - label_smoothing) * ce
                      + label_smoothing * uniform)
            local_sum = ce.sum()
            local_n = jnp.float32(ce.size)
            # Normalizer includes the tp axis: the loss is computed
            # redundantly on every tp rank and shard_map's transpose of the
            # forward tp-psums sums those redundant cotangents, so without
            # the /tp every gradient comes out exactly tp-times too large
            # (verified against single-device grads).
            global_n = lax.psum(local_n, (axis_dp, axis_sp, axis_tp))
            # normalize by the emulated-cluster size too (mix.py:239's
            # divide-so-the-sum-is-the-mean, per micro-batch)
            loss = local_sum / global_n / emulate_node
            hits = jnp.sum(jnp.argmax(logits, -1) == tgts)
            return loss, (local_sum, local_n, hits)

        n = emulate_node
        mb = tokens.shape[0] // n
        # --- cross-axis gradient reduction (see module docstring) ---
        specs = lm_param_specs(state.params, axis_tp)

        def sp_tp_reduce(stacked_g, spec):
            g = lax.psum(stacked_g, axis_sp)
            if spec == P():                 # replicated param: finish tp sum
                g = lax.psum(g, axis_tp)
            return g

        # SR keys (grad_rounding='stochastic'): the rank-local emulate key
        # folds ONLY the dp index — post-psum grads are identical across
        # sp (and across tp for replicated params), so sp/tp copies must
        # draw identical bits or their optimizer states would diverge;
        # dp ranks hold different grads and decorrelate (see
        # parallel/dist.py on coherent rounding error).
        sr = grad_rounding == "stochastic"
        sum_key = grad_sr_key(grad_seed, state.step, 1) if sr else None
        wf = None
        if wire_fault_plan is not None and mode == "ring":
            codes = jnp.asarray(wire_fault_plan[0], jnp.int32)
            ranks = jnp.asarray(wire_fault_plan[1], jnp.int32)
            idx = jnp.clip(state.step, 0, codes.shape[0] - 1)
            wf = (jnp.where(state.step < codes.shape[0], codes[idx], 0),
                  ranks[idx])
        sfac = None
        if sat_fault_plan is not None:
            # saturation-pressure attack (resilience/inject.py
            # `sat_pressure`): 2^k exact power-of-two scaling, shared
            # lookup (see make_train_step)
            from ..resilience.inject import sat_pressure_factor
            sfac = sat_pressure_factor(sat_fault_plan, state.step)
        vreport = None
        if overlap_reduce:
            # Bucketed dependency-scheduled transport (parallel/
            # overlap.py): per-bucket taps own the WHOLE per-leaf
            # reduction chain — sp psum, tp psum for replicated params
            # (leaf_pre), sat pressure, emulate-node reduce (n > 1:
            # micro-batches 0..N-2 run unrolled and their sp/tp-reduced
            # stacked grads ride into the LAST micro-batch's taps as
            # extras, ISSUE 12 leg 3), then the dp quantized collective
            # — so a bucket's work starts the moment its last cotangent
            # closes.  Bitwise identical to the monolithic path below.
            from ..parallel.overlap import BucketPlan, overlapped_grads
            plan = BucketPlan.for_tree(state.params, bucket_elems)
            specs_flat = jax.tree_util.tree_flatten(
                specs, is_leaf=lambda s: isinstance(s, P))[0]

            def leaf_pre(g, i):
                return sp_tp_reduce(g, specs_flat[i])

            extras = emulate_fn = emu_key = None
            micro_sums, micro_ns, micro_hits = [], [], []
            if n > 1:
                toks_u = tokens.reshape(n, mb, tokens.shape[1])
                tgts_u = targets.reshape(n, mb, targets.shape[1])
                prev = []
                for mi in range(n - 1):
                    (_, (s_mi, n_mi, h_mi)), g_mi = jax.value_and_grad(
                        loss_of, has_aux=True)(state.params, toks_u[mi],
                                               tgts_u[mi], jnp.int32(mi))
                    micro_sums.append(s_mi)
                    micro_ns.append(n_mi)
                    micro_hits.append(h_mi)
                    prev.append(jax.tree_util.tree_leaves(g_mi))
                # sp/tp-reduce + sat-scale the prior micros here (the
                # taps apply leaf_pre/aux[0] to the LAST micro's
                # cotangent only) — elementwise psums, so per-micro
                # equals the monolith's stacked psum bit for bit
                extras = []
                for i in range(len(plan.sizes)):
                    st = jnp.stack([prev[mi][i] for mi in range(n - 1)])
                    st = sp_tp_reduce(st, specs_flat[i])
                    if sfac is not None:
                        st = st * sfac
                    extras.append(st)
                if sr:
                    emu_key = jax.random.fold_in(
                        grad_sr_key(grad_seed, state.step, 0),
                        lax.axis_index(axis_dp).astype(jnp.int32))
                from ..parallel.emulate import make_overlap_emulate_fn
                emulate_fn = make_overlap_emulate_fn(
                    n, use_aps, grad_exp, grad_man, sr)
                tk_last, tg_last = toks_u[n - 1], tgts_u[n - 1]
                last_idx = jnp.int32(n - 1)
            else:
                tk_last, tg_last = tokens, targets
                last_idx = jnp.zeros([], jnp.int32)

            def loss_closure(p):
                loss, aux = loss_of(p, tk_last, tg_last, last_idx)
                return loss, aux

            ((_, (l_sum, l_n, l_hits)), reduced,
             vreport) = overlapped_grads(
                loss_closure, state.params, axis_name=axis_dp, plan=plan,
                reduce_kw=dict(use_aps=use_aps, grad_exp=grad_exp,
                               grad_man=grad_man, use_kahan=use_kahan,
                               mode=mode, rounding=grad_rounding,
                               bucket_elems=bucket_elems,
                               block_scale=block_scale,
                               block_size=block_size),
                key=sum_key, sat_factor=sfac, wire_fault=wf,
                verify=verify_reduce, stats=quant_stats,
                leaf_pre=leaf_pre, collective=None, extras=extras,
                emulate_reduce=emulate_fn, emulate_key=emu_key)
            sums = jnp.stack(micro_sums + [l_sum])
            ns = jnp.stack(micro_ns + [l_n])
            hits = jnp.stack(micro_hits + [l_hits])
        else:
            toks = tokens.reshape(n, mb, tokens.shape[1])
            tgts = targets.reshape(n, mb, targets.shape[1])

            def micro(micro_idx, xy):
                tk, tg = xy
                (_, aux), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(state.params, tk, tg, micro_idx)
                return micro_idx + 1, (grads, *aux)

            _, (stacked, sums, ns, hits) = lax.scan(
                micro, jnp.zeros([], jnp.int32), (toks, tgts))

            stacked = jax.tree.map(sp_tp_reduce, stacked, specs)
            if sfac is not None:
                stacked = jax.tree.map(lambda g: g * sfac, stacked)
            local = emulate_node_reduce(
                stacked, n, use_aps, grad_exp, grad_man,
                rounding=grad_rounding,
                key=jax.random.fold_in(
                    grad_sr_key(grad_seed, state.step, 0),
                    lax.axis_index(axis_dp).astype(jnp.int32)) if sr
                else None)
            reduced = sum_gradients(
                local, axis_dp, use_aps=use_aps,
                grad_exp=grad_exp, grad_man=grad_man,
                use_kahan=use_kahan, mode=mode, rounding=grad_rounding,
                key=sum_key, verify=verify_reduce, wire_fault=wf,
                stats=quant_stats, bucket_elems=bucket_elems,
                block_scale=block_scale, block_size=block_size)
            if verify_reduce or quant_stats:
                reduced, vreport = reduced

        updates, new_opt = tx.update(reduced, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               batch_stats=state.batch_stats,
                               opt_state=new_opt)
        # metrics use the dp/sp token count only (tp ranks duplicate the
        # same tokens, and these psums exclude tp)
        from ..resilience.guard import guard_metrics
        total_n = lax.psum(ns.sum(), (axis_dp, axis_sp))
        metrics = {
            **guard_metrics(new_opt),
            "loss": lax.psum(sums.sum(), (axis_dp, axis_sp)) / total_n,
            "accuracy": lax.psum(hits.sum().astype(jnp.float32),
                                 (axis_dp, axis_sp)) / total_n,
        }
        if vreport is not None:
            f32 = jnp.float32
            if verify_reduce:
                metrics.update(
                    reduce_ok=vreport["ok"].astype(f32),
                    reduce_hop_bad=vreport["hop_bad"].astype(f32),
                    reduce_gather_bad=vreport["gather_bad"].astype(f32),
                    reduce_agree=vreport["agree"].astype(f32))
            if quant_stats:
                metrics.update(
                    prec_wire_sat=vreport["wire_sat"].astype(f32),
                    prec_wire_underflow=vreport["wire_underflow"]
                    .astype(f32),
                    prec_wire_nan=vreport["wire_nan"].astype(f32),
                    prec_wire_total=vreport["wire_total"].astype(f32),
                    prec_aps_bad=vreport["aps_bad"].astype(f32))
        return new_state, metrics

    return make_sharded_stepper(
        step_fn, lambda s: lm_state_specs(s, axis_tp), mesh,
        P(axis_dp, axis_sp), donate=donate)


def make_lm_eval_step(model, mesh: Mesh, *, axis_dp: str = "dp",
                      axis_sp: str = "sp", axis_tp: str = "tp"):
    """Jitted ``(state, tokens, targets) -> {'loss','accuracy'}`` over the
    same dp x sp x tp sharding as the train step (no grads, no update)."""
    cache: dict = {}

    def eval_fn(state: TrainState, tokens, targets):
        logits = model.apply({"params": state.params}, tokens, train=False)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        hits = jnp.sum(jnp.argmax(logits, -1) == targets)
        total_n = lax.psum(jnp.float32(ce.size), (axis_dp, axis_sp))
        return {
            "loss": lax.psum(ce.sum(), (axis_dp, axis_sp)) / total_n,
            "accuracy": lax.psum(hits.astype(jnp.float32),
                                 (axis_dp, axis_sp)) / total_n,
        }

    def runner(state, tokens, targets):
        key = jax.tree.structure(state)
        if key not in cache:
            specs = lm_state_specs(state, axis_tp)
            data_spec = P(axis_dp, axis_sp)
            cache[key] = jax.jit(shard_map(
                eval_fn, mesh=mesh,
                in_specs=(specs, data_spec, data_spec),
                out_specs=P(), check_vma=False))
        return cache[key](state, tokens, targets)

    return runner


def ir_programs(reg):
    """Program-contract declarations (analysis/ir/registry.py): the LM
    step builder on the dp x sp x tp mesh, overlap on/off — the twins
    whose bitwise parity tests/test_overlap.py gates.  `ir-schedule`
    pins their collective multisets identical (the dp ring wire AND the
    forward sp ring-attention ppermutes), `ir-overlap` the interleaving
    verdicts, `ir-bitwise` the absence of ulp-unstable transcendentals
    under the whole traced step (constant LR for the same reason as the
    vision declarations — `pow` is not the contract)."""
    from ..models.transformer import transformer_lm
    from .optim import make_optimizer
    from .state import create_train_state

    deps = ("cpd_tpu.train.lm", "cpd_tpu.parallel.dist",
            "cpd_tpu.parallel.ring", "cpd_tpu.parallel.overlap",
            "cpd_tpu.parallel.aps", "cpd_tpu.quant.numerics",
            "cpd_tpu.models.transformer")

    def _lm(overlap):
        def build():
            from ..parallel.mesh import make_mesh
            mesh = make_mesh(dp=2, sp=2, tp=2)
            model = transformer_lm(vocab_size=64, d_model=32,
                                   n_layers=2, n_heads=4, tp_axis="tp",
                                   sp_axis="sp", tp_size=2)
            init_model = transformer_lm(vocab_size=64, d_model=32,
                                        n_layers=2, n_heads=4)
            tx = make_optimizer("sgd", lambda step: 0.01, momentum=0.9)
            state = jax.eval_shape(lambda: create_train_state(
                init_model, tx, jnp.zeros((1, 16), jnp.int32),
                jax.random.PRNGKey(0)))
            step = make_lm_train_step(
                model, tx, mesh, mode="ring", use_aps=True, grad_exp=5,
                grad_man=2, grad_rounding="stochastic", grad_seed=3,
                donate=False, bucket_elems=2000,
                overlap_reduce=overlap)
            toks = jax.ShapeDtypeStruct((4, 16), jnp.int32)
            return step, (state, toks, toks)
        return build

    # the monolith carries NO overlap expectation: the forward pass's
    # sp ring-attention ppermutes legitimately precede all backward
    # compute, so the structural probe reads "interleaved" on both
    # twins — only the overlapped step's verdict is a contract here
    reg.declare("lm.ring[e5m2,sr,aps]", _lm(False),
                deps=deps, axis_sizes={"dp": 2, "sp": 2, "tp": 2},
                bitwise=True, twin="lm.ring-overlap")
    reg.declare("lm.ring[e5m2,sr,aps]+overlap", _lm(True),
                deps=deps, axis_sizes={"dp": 2, "sp": 2, "tp": 2},
                bitwise=True, twin="lm.ring-overlap", overlap=True)
