"""MoE LM train step over a ("dp", "ep") mesh — expert parallelism.

Companion of train/lm.py (tp/sp) and train/pp.py (pp) for the `ep` axis.
Tokens are sharded over BOTH dp and ep (ep doubles as a data axis outside
the expert dispatch); expert weight stacks are ep-sharded; router /
attention / norm params are replicated over ep.

Gradient flow: expert-stack grads are complete on their owner rank (the
all_to_all transpose routes cotangents back to the token's home rank);
replicated params get a `psum` over ep; then the quantized dp
`sum_gradients` (APS / ordered / Kahan) and a shard-local elementwise
optimizer update (LARS refused, same argument as train/lm.py).

The Switch load-balancing auxiliary loss (sown by MoEFeedForward) is
collected per block and added with weight `aux_weight` — without it top-1
routing degenerates to one hot expert.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.moe import MoETransformerLM, moe_param_specs
from ..compat import shard_map
from ..parallel.dist import grad_sr_key, sum_gradients
from .state import (TrainState, make_sharded_stepper, reject_norm_based,
                    state_specs_like)

__all__ = ["make_moe_train_step", "make_moe_eval_step", "moe_state_specs"]


def moe_state_specs(state: TrainState, ep_axis: str = "ep") -> TrainState:
    return state_specs_like(state, moe_param_specs(state.params, ep_axis))


def make_moe_train_step(model: MoETransformerLM,
                        tx: optax.GradientTransformation, mesh: Mesh, *,
                        axis_dp: str = "dp", axis_ep: str = "ep",
                        aux_weight: float = 0.01, use_aps: bool = False,
                        grad_exp: int = 8, grad_man: int = 23,
                        use_kahan: bool = False, mode: str = "faithful",
                        grad_rounding: str = "nearest", grad_seed: int = 0,
                        donate: bool = True):
    """Build jitted ``(state, tokens, targets) -> (state, metrics)``.

    tokens/targets: (global_batch, T) int32 sharded over (dp, ep).

    grad_rounding='stochastic': unbiased SR through the dp all-reduce.
    The key depends only on (grad_seed, step) — identical across ep,
    which is required for replicated leaves (their post-ep-psum grads
    are identical on every ep copy and must round identically) and
    harmless for expert stacks (ep ranks own disjoint experts, nothing
    sums across ep); `sum_gradients` folds the dp rank into its
    pre-quantize key for the dp-sum decorrelation."""
    if grad_rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown grad_rounding {grad_rounding!r}")
    reject_norm_based(tx, "ep-sharded step")
    data_axes = (axis_dp, axis_ep)

    def step_fn(state: TrainState, tokens, targets):
        def loss_of(params, toks, tgts):
            logits, mut = model.apply({"params": params}, toks, train=True,
                                      mutable=["intermediates"])
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, tgts)
            local_sum = ce.sum()
            local_n = jnp.float32(ce.size)
            global_n = lax.psum(local_n, data_axes)
            aux = jnp.sum(jnp.stack(jax.tree.leaves(
                mut["intermediates"]))) if aux_weight else jnp.float32(0.0)
            # normalize the aux term by the number of contributing ranks:
            # every dp x ep rank adds its own copy and the dp reduction
            # SUMS gradients, so without /world the aux gradient would
            # scale with device count while CE stays world-invariant
            world = lax.psum(jnp.float32(1.0), data_axes)
            loss = local_sum / global_n + aux_weight * aux / world
            hits = jnp.sum(jnp.argmax(logits, -1) == tgts)
            return loss, (local_sum, local_n, hits)

        (_, (lsum, ln, hits)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state.params, tokens, targets)

        # replicated params: finish the ep sum; expert stacks (spec names
        # the ep axis) are complete on their owner rank
        specs = moe_param_specs(state.params, axis_ep)
        grads = jax.tree.map(
            lambda g, s: g if axis_ep in tuple(
                a for a in s if a is not None) else lax.psum(g, axis_ep),
            grads, specs, is_leaf=lambda x: isinstance(x, P))
        gkey = (grad_sr_key(grad_seed, state.step, 1)
                if grad_rounding == "stochastic" else None)
        grads = sum_gradients(grads, axis_dp, use_aps=use_aps,
                              grad_exp=grad_exp, grad_man=grad_man,
                              use_kahan=use_kahan, mode=mode,
                              rounding=grad_rounding, key=gkey)

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               batch_stats=state.batch_stats,
                               opt_state=new_opt)
        from ..resilience.guard import guard_metrics
        total = lax.psum(ln, data_axes)
        metrics = {
            **guard_metrics(new_opt),
            "loss": lax.psum(lsum, data_axes) / total,
            "accuracy": lax.psum(hits.astype(jnp.float32),
                                 data_axes) / total,
        }
        return new_state, metrics

    return make_sharded_stepper(
        step_fn, lambda s: moe_state_specs(s, axis_ep), mesh,
        P(data_axes), donate=donate)


def make_moe_eval_step(model: MoETransformerLM, mesh: Mesh, *,
                       axis_dp: str = "dp", axis_ep: str = "ep"):
    """Jitted ``(state, tokens, targets) -> {'loss','accuracy'}`` over the
    same (dp, ep) token sharding as the train step."""
    data_axes = (axis_dp, axis_ep)
    cache: dict = {}

    def eval_fn(state: TrainState, tokens, targets):
        logits = model.apply({"params": state.params}, tokens, train=False)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        hits = jnp.sum(jnp.argmax(logits, -1) == targets)
        total = lax.psum(jnp.float32(ce.size), data_axes)
        return {
            "loss": lax.psum(ce.sum(), data_axes) / total,
            "accuracy": lax.psum(hits.astype(jnp.float32),
                                 data_axes) / total,
        }

    def runner(state, tokens, targets):
        key = jax.tree.structure(state)
        if key not in cache:
            specs = moe_state_specs(state, axis_ep)
            cache[key] = jax.jit(shard_map(
                eval_fn, mesh=mesh,
                in_specs=(specs, P(data_axes), P(data_axes)),
                out_specs=P(), check_vma=False))
        return cache[key](state, tokens, targets)

    return runner
