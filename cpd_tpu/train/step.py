"""The shared train/eval step — one traced graph per configuration.

This replaces the reference's three copy-pasted training loops
(example/ResNet18/tools/mix.py:224-356, example/DavidNet/utils.py:328-344,
example/ResNet50/main.py:141-212).  Where the reference's step is a Python
loop issuing one CUDA kernel / NCCL op per parameter per micro-batch
(SURVEY.md §3.1 "kernel-launch storm"), here the WHOLE step — micro-batch
scan, local emulated-node reduction, APS, the quantized cross-device
all-reduce, and the optimizer — is a single jitted shard_map program, so XLA
fuses the quantize math into the surrounding elementwise work and schedules
the ICI collectives back-to-back.

Semantics preserved from the reference step (mix.py:224-314):
  * loss divided by world*emulate_node so the distributed SUM equals the
    mean (mix.py:239);
  * optional loss scaling, multiplied into the loss before grad and NOT
    unscaled before the step — faithful to DavidNet/utils.py:332-334, which
    never unscales (default scale 1.0 makes it a no-op); beyond-reference,
    ``loss_scale="dynamic"`` reads the scale from a
    `with_dynamic_loss_scale` optimizer state instead (train/scaling.py:
    GradScaler policy — unscale, skip non-finite steps, halve/double);
  * micro-batches run sequentially (lax.scan), so BN running stats update
    in the same order as the reference's sequential sub-batch loop;
  * the reported loss is the cross-rank all-reduced copy (mix.py:240-242).

Deviation (documented): BN running stats are cross-replica pmean'd at the
end of the step.  The reference keeps per-rank stats and checkpoints
rank-0's (train_util.py:268-271); with jit+shard_map, replicated outputs
must be bitwise-replicated, and averaging is strictly more principled than
"whatever rank 0 saw".
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..compat import shard_map
from ..parallel.dist import grad_sr_key, sum_gradients
from ..parallel.emulate import emulate_node_reduce
from .state import TrainState

__all__ = ["cross_entropy_loss", "seg_cross_entropy_loss",
           "seg_loss_with_aux", "make_train_step", "make_eval_step",
           "make_seg_eval_step"]


def _main_logits(out):
    """Models with an auxiliary head return (main, aux); metrics and eval
    use the main logits only (mmseg semantics: aux is train-time loss)."""
    return out[0] if isinstance(out, tuple) else out


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels (the criterion of all
    three reference trainers, e.g. mix.py:104)."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def seg_cross_entropy_loss(ignore_label: int = 255) -> Callable:
    """Per-pixel CE averaged over non-ignored pixels — the segmentation
    criterion of the FCN/Cityscapes config (reference README.md:132-150;
    mmseg's CrossEntropyLoss with ignore_index=255)."""

    def loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
        valid = labels != ignore_label
        safe = jnp.where(valid, labels, 0)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
        return jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1)

    return loss


def seg_loss_with_aux(ignore_label: int = 255,
                      aux_weight: float = 0.4) -> Callable:
    """Main + aux_weight * auxiliary segmentation loss for models returning
    (main_logits, aux_logits) — mmseg's fcn_r50-d8 trains the aux FCN head
    on layer3 features at loss weight 0.4 (reference README.md:132-150)."""
    base = seg_cross_entropy_loss(ignore_label)

    def loss(out, labels: jnp.ndarray) -> jnp.ndarray:
        main, aux = out
        return base(main, labels) + aux_weight * base(aux, labels)

    return loss


def make_train_step(model, tx: optax.GradientTransformation, mesh: Mesh,
                    *, axis_name: str = "dp", emulate_node: int = 1,
                    use_aps: bool = False, grad_exp: int = 8,
                    grad_man: int = 23, use_kahan: bool = False,
                    mode: str = "faithful", loss_scale: float = 1.0,
                    grad_rounding: str = "nearest", grad_seed: int = 0,
                    loss_fn: Callable = cross_entropy_loss,
                    rng_keys: tuple = (), rng_seed: int = 0,
                    ignore_label: Optional[int] = None,
                    donate: bool = True,
                    update_fn: Optional[Callable] = None,
                    opt_state_spec: Optional[Any] = None,
                    reduce_in_update: bool = False,
                    params_spec: Optional[Any] = None,
                    unpack_params: Optional[Callable] = None,
                    tap_reduce: Optional[Callable] = None,
                    verify_reduce: bool = False,
                    wire_fault_plan: Optional[tuple] = None,
                    quant_stats: bool = False,
                    sat_fault_plan: Optional[Any] = None,
                    overlap_reduce: bool = False,
                    bucket_elems: Optional[int] = None,
                    block_scale: bool = False,
                    block_size: int = 128):
    """Build the jitted ``(state, images, labels) -> (state, metrics)`` step.

    images: (global_batch * emulate_node, H, W, C) sharded over `axis_name`;
    each device's local slice is split into `emulate_node` sequential
    micro-batches (the reference's virtual-node emulation, mix.py:224-285).
    Returned metrics: {'loss': all-reduced mean loss, 'accuracy': top-1 over
    the global batch, 'lr'-free — schedule owns lr}.

    reduce_in_update=True (requires update_fn) skips the step's own
    `sum_gradients` and hands update_fn the rank-LOCAL post-emulate
    gradients — for updaters that fold the collective into the update,
    e.g. ZeRO-2's sharded faithful reduce-scatter (parallel/zero.py).

    params_spec / unpack_params support ZeRO-3 parameter sharding:
    `params_spec` is the PartitionSpec of TrainState.params (default
    replicated), and `unpack_params(stored_params, axis_name)` maps the
    stored layout to the model's param pytree inside shard_map (e.g. the
    flat-shard all_gather + unflatten of parallel/zero.py `_Zero3`);
    update_fn then returns params back in the STORED layout.

    verify_reduce=True runs the self-verifying reduction
    (`sum_gradients(..., verify=True)`, parallel/integrity.py) and adds
    the replicated scalars ``reduce_ok`` / ``reduce_hop_bad`` /
    ``reduce_gather_bad`` / ``reduce_agree`` to the metrics — the feed
    for `resilience.transport.TransportSupervisor`.  wire_fault_plan is
    a ``FaultPlan.wire_schedule(n_steps)`` (codes, ranks) table baked
    into the program; entry ``state.step`` corrupts the ring wire on
    that rank (ignored outside mode="ring" — the ring's wire IS the one
    under attack, and downgrading transports is the escape).

    quant_stats=True threads the reduce-wire numeric-health telemetry
    (`sum_gradients(..., stats=True)`) into the metrics as the
    replicated scalars ``prec_wire_sat`` / ``prec_wire_underflow`` /
    ``prec_wire_nan`` / ``prec_wire_total`` / ``prec_aps_bad`` — the
    feed for `resilience.precision.PrecisionSupervisor`'s escalation
    ladder.  The gradient path stays bitwise unchanged.  sat_fault_plan
    is a ``FaultPlan.sat_schedule(n_steps)`` int32 exponent table baked
    into the program: entry ``state.step`` scales this step's LOCAL
    post-backward gradients by 2^k before the emulate-node reduce and
    the quantized collective, deterministically driving the wire cast
    into saturation (the attack the ladder is exercised against; 0 =
    off, and scaling by 2^0 == 1.0 is an exact fp32 no-op).

    overlap_reduce=True replaces the post-backward reduction monolith
    with the bucketed, dependency-scheduled transport
    (parallel/overlap.py): per-bucket custom_vjp taps on the parameters
    run each bucket's quantized all-reduce INSIDE the backward pass, the
    moment that bucket's last gradient closes — late-layer buckets ring
    while early-layer backward compute is still pending, which is the
    dependency structure XLA needs to overlap collectives with compute
    (MLPerf TPU-pod bucketed gradient summation, PAPERS.md #4).  The
    reduced gradients — and therefore the updated parameters — are
    BITWISE identical to the non-overlapped step (tests/test_overlap.py);
    verify/stats reports ride out of the backward on the tap-cotangent
    channel, and sat_pressure / wire faults keep firing (wire faults hit
    bucket 0 only, preserving exact drill counters).  bucket_elems caps
    the bucket size for BOTH the overlapped taps and the post-backward
    bucketed/ring layouts (default: parallel/dist._BUCKET_ELEMS).

    overlap_reduce composes with emulate_node > 1 (ISSUE 12): the first
    N-1 micro-batches run as an unrolled value_and_grad chain (same
    sequential BN-stat order as the scan) and their stacked gradients
    ride into the LAST micro-batch's taps, where each bucket's
    rank-local emulate-node reduce + cross-device collective fire as
    that bucket's final cotangent closes.  Gradients and therefore
    PARAMS are bitwise identical to the scan + post-backward monolith
    (tests/test_overlap.py); BN running stats agree to the last ulp
    only — XLA fuses the scanned vs unrolled forward differently, and a
    batch-mean reduction can differ in its final bit (training-mode BN
    normalizes by the batch stats, so gradients never see the drift).

    overlap_reduce also composes with reduce_in_update when the updater
    provides the ``tap_reduce`` hook (ZeRO-2's
    `zero2_sgd(...).mesh_layout` wires it): the taps run the updater's
    per-bucket all_to_all reduce-scatter inside the backward and
    `update_fn` consumes the extracted bucket shards
    (``pre_sharded=True``) — bitwise identical to the post-backward
    reduce_in_update monolith at a fixed bucket layout.

    block_scale / block_size thread the EQuARX-style block-scaled ring
    wire (`sum_gradients(block_scale=...)`, quant/numerics.py
    "Block-scaled eXmY codec"): every hop cast shares one power-of-2
    scale per `block_size` consecutive elements and the 1-byte-per-block
    shift sidecar rides the packed wire.  Ring mode only (validated at
    build time — the other transports have no sidecar lane), EXCEPT
    with reduce_in_update, where the pair is forwarded to the updater
    and ZeRO-2's faithful all_to_all carries the blocked wire instead
    (parallel/zero.py, ISSUE 12 leg 1).  A DIFFERENT documented
    accumulation numerics than per-tensor: steps with and without it
    are distinct StepTable entries (`ladder_step_key(block=...)`).
    Composes with overlap_reduce — overlap on/off stays bitwise
    identical with block scaling on.
    """
    if grad_rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown grad_rounding {grad_rounding!r}")
    dynamic_scale = loss_scale == "dynamic"
    if dynamic_scale and update_fn is not None:
        raise ValueError("loss_scale='dynamic' requires the default optax "
                         "update path (the wrapper owns unscale+skip); "
                         "custom update_fn steppers must manage scaling "
                         "themselves")
    if not dynamic_scale:
        loss_scale = float(loss_scale)
    if reduce_in_update and update_fn is None:
        raise ValueError("reduce_in_update=True requires update_fn")
    if unpack_params is not None and update_fn is None:
        raise ValueError("unpack_params requires update_fn (the default "
                         "optax update assumes stored params == model "
                         "params)")
    if params_spec is not None and unpack_params is None:
        raise ValueError("params_spec (sharded stored params) requires "
                         "unpack_params to rebuild the model pytree "
                         "inside the step")
    if verify_reduce and reduce_in_update:
        raise ValueError("verify_reduce=True needs the step's own "
                         "sum_gradients call; reduce_in_update hands the "
                         "collective to the updater (ZeRO-2/3), which "
                         "does not thread a verification report")
    if quant_stats and reduce_in_update:
        raise ValueError("quant_stats=True needs the step's own "
                         "sum_gradients call; reduce_in_update hands the "
                         "collective to the updater (ZeRO-2/3), which "
                         "does not thread a telemetry report")
    if tap_reduce is not None and not reduce_in_update:
        raise ValueError("tap_reduce is the ZeRO-2 overlap hook — it "
                         "only makes sense with reduce_in_update=True")
    if overlap_reduce and reduce_in_update and tap_reduce is None:
        raise ValueError(
            "overlap_reduce=True with reduce_in_update needs the "
            "updater's tap_reduce hook (zero2_sgd's mesh_layout wires "
            "it); ZeRO-3 and other custom updaters without one own the "
            "whole post-backward collective — run without "
            "overlap_reduce")
    if block_scale and mode != "ring" and not reduce_in_update:
        raise ValueError(
            f"block_scale=True needs mode='ring' (got {mode!r}): the "
            f"per-block scale sidecar rides the ring's packed wire "
            f"(with reduce_in_update the ZeRO-2 updater's all_to_all "
            f"carries it instead — parallel/zero.py)")
    has_stats_cache: dict = {}

    def make_loss_of(world, scale):
        """The per-micro-batch loss closure — ONE definition feeding both
        the scan path and the overlapped-taps path, so their numerics
        cannot drift."""

        def loss_of(p, stats, x, y, rngs):
            variables = {"params": p}
            kwargs = {"rngs": rngs} if rngs else {}
            has_stats = bool(jax.tree.leaves(stats))
            if has_stats:
                variables["batch_stats"] = stats
                logits, mut = model.apply(variables, x, train=True,
                                          mutable=["batch_stats"], **kwargs)
                new_stats = mut["batch_stats"]
            else:
                logits = model.apply(variables, x, train=True, **kwargs)
                new_stats = stats
            loss = loss_fn(logits, y) / (world * emulate_node)  # mix.py:239
            return loss * scale, (logits, new_stats, loss)

        return loss_of

    def micro_rngs(step, micro_idx):
        """Per-micro-step stream rngs (dropout etc.), deterministic in
        (rng_seed, replica, global step, micro index) — the replica fold
        keeps dropout masks decorrelated across data-parallel shards
        (one rng stream per rank, as torch DDP gives)."""
        if not rng_keys:
            return {}
        base = jax.random.fold_in(jax.random.PRNGKey(rng_seed),
                                  step * emulate_node + micro_idx)
        base = jax.random.fold_in(
            base, lax.axis_index(axis_name).astype(jnp.int32))
        return {k: jax.random.fold_in(base, i)
                for i, k in enumerate(rng_keys)}

    def local_micro_grads(params, batch_stats, images, labels, world, step,
                          scale):
        """Sequential scan over micro-batches -> stacked grads (N, ...)."""
        n = emulate_node
        if images.shape[0] < n or images.shape[0] % n:
            # a 0-sample micro-batch silently yields NaN losses (mean over
            # an empty batch); fail at trace time with the actual geometry
            raise ValueError(
                f"per-device batch {images.shape[0]} must be a positive "
                f"multiple of emulate_node={n} (global batch = "
                f"devices * per-device batch; each device slice is split "
                f"into emulate_node sequential micro-batches)")
        mb = images.shape[0] // n
        images = images.reshape(n, mb, *images.shape[1:])
        labels = labels.reshape(n, mb, *labels.shape[1:])
        loss_of = make_loss_of(world, scale)

        def micro(carry, xy):
            stats, micro_idx = carry
            x, y = xy
            rngs = micro_rngs(step, micro_idx)
            (_, (logits, new_stats, loss)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, stats, x, y, rngs)
            correct, counted = _count_hits(logits, y)
            return (new_stats, micro_idx + 1), (grads, loss, correct, counted)

        (final_stats, _), (stacked_grads, losses, corrects, counts) = lax.scan(
            micro, (batch_stats, jnp.zeros([], jnp.int32)), (images, labels))
        return (stacked_grads, final_stats, losses.sum(), corrects.sum(),
                counts.sum())

    def _count_hits(logits, y):
        hit = jnp.argmax(_main_logits(logits), -1) == y
        if ignore_label is not None:
            valid = y != ignore_label
            return jnp.sum(hit & valid), jnp.sum(valid)
        return jnp.sum(hit), jnp.asarray(y.size)

    def step_fn(state: TrainState, images, labels):
        world = lax.psum(jnp.float32(1.0), axis_name)
        model_params = (unpack_params(state.params, axis_name)
                        if unpack_params is not None else state.params)
        from .scaling import DynamicScaleState, current_scale
        if dynamic_scale:
            scale = current_scale(state.opt_state)
        else:
            # symmetric to current_scale's TypeError: a wrapped optimizer
            # with a static loss_scale would silently divide every update
            # by the (growing) scale.  The search covers the WHOLE
            # opt_state pytree, not just the outermost node — e.g.
            # optax.chain(clip, with_dynamic_loss_scale(tx)) nests the
            # wrapper's state one level down.
            def _is_dyn(n):
                return isinstance(n, DynamicScaleState)
            if any(map(_is_dyn, jax.tree.leaves(
                    state.opt_state, is_leaf=_is_dyn))):
                raise ValueError(
                    "optimizer is wrapped with with_dynamic_loss_scale but "
                    "loss_scale is static; pass loss_scale='dynamic' to "
                    "make_train_step")
            scale = jnp.float32(loss_scale)
        sr = grad_rounding == "stochastic"
        sum_key = grad_sr_key(grad_seed, state.step, 1) if sr else None
        # wire-fault table lookup, keyed by the optimizer-update index —
        # the same clock as with_fault_injection's grad schedule
        wf = None
        if wire_fault_plan is not None and mode == "ring":
            codes = jnp.asarray(wire_fault_plan[0], jnp.int32)
            ranks = jnp.asarray(wire_fault_plan[1], jnp.int32)
            idx = jnp.clip(state.step, 0, codes.shape[0] - 1)
            in_range = state.step < codes.shape[0]
            wf = (jnp.where(in_range, codes[idx], 0), ranks[idx])
        sfac = None
        if sat_fault_plan is not None:
            # saturation-pressure attack (resilience/inject.py
            # `sat_pressure`): scale this step's local grads by 2^k.  An
            # exact power of two, rank-agnostic (every replica scales
            # identically, so replication is preserved)
            from ..resilience.inject import sat_pressure_factor
            sfac = sat_pressure_factor(sat_fault_plan, state.step)
        vreport = None
        pre_sharded_vec = None
        if overlap_reduce:
            # Bucketed, dependency-scheduled transport: the reduction
            # runs INSIDE the backward via per-bucket custom_vjp taps
            # (parallel/overlap.py) — bitwise identical to the
            # post-backward path below, but each bucket's collective is
            # emitted the moment its last cotangent closes, so XLA may
            # overlap ring hops with the remaining backward compute.
            #
            # emulate_node > 1 (ISSUE 12 leg 3): micro-batches 0..N-2
            # run as a plain unrolled value_and_grad chain (same
            # sequential BN-stat order as the monolith's scan); their
            # stacked gradients ride into the LAST micro-batch's taps as
            # extras, where each bucket's rank-local emulate-node reduce
            # + cross-device collective fire the moment that bucket's
            # final cotangent closes — the collectives overlap the last
            # backward instead of waiting behind the whole scan.
            #
            # reduce_in_update + tap_reduce (ZeRO-2): the taps run the
            # updater's per-bucket reduce-scatter (`make_tap_reduce`)
            # and the update consumes the extracted bucket shards.
            from ..parallel.overlap import (BucketPlan,
                                            extract_bucket_shards,
                                            overlapped_grads)
            n = emulate_node
            if images.shape[0] < n or images.shape[0] % n:
                raise ValueError(
                    f"per-device batch {images.shape[0]} must be a "
                    f"positive multiple of emulate_node={n}")
            if tap_reduce is not None:
                plan, tap_chunks, tap_collective = tap_reduce(
                    model_params,  axis_name,
                    dict(use_aps=use_aps, grad_exp=grad_exp,
                         grad_man=grad_man, use_kahan=use_kahan,
                         mode=mode, rounding=grad_rounding,
                         block_scale=block_scale, block_size=block_size))
                if (bucket_elems is not None
                        and plan.bucket_elems != bucket_elems):
                    # the tap plan comes SOLELY from the updater's
                    # layout (the update must consume the same shards
                    # the taps produce) — a step-side cap that differs
                    # would be a silently ignored tuning knob, the
                    # exact hazard the old CLI fail-fast rejected
                    raise ValueError(
                        f"bucket_elems={bucket_elems} does not match "
                        f"the ZeRO updater's bucket layout (cap "
                        f"{plan.bucket_elems}): with reduce_in_update "
                        f"the tap plan comes from the updater — pass "
                        f"the same value to zero2_sgd(bucket_elems=)")
            else:
                plan = BucketPlan.for_tree(model_params, bucket_elems)
                tap_chunks = tap_collective = None
            loss_of = make_loss_of(world, scale)
            stats_c = state.batch_stats
            extras = emulate_fn = emu_key = None
            micro_losses, micro_correct, micro_counted = [], [], []
            if n > 1:
                mb = images.shape[0] // n
                imgs = images.reshape(n, mb, *images.shape[1:])
                lbls = labels.reshape(n, mb, *labels.shape[1:])
                prev = []
                for mi in range(n - 1):
                    rngs_mi = micro_rngs(state.step, jnp.int32(mi))
                    (_, (lg, stats_c, l_mi)), g_mi = jax.value_and_grad(
                        loss_of, has_aux=True)(model_params, stats_c,
                                               imgs[mi], lbls[mi],
                                               rngs_mi)
                    c_mi, n_mi = _count_hits(lg, lbls[mi])
                    micro_losses.append(l_mi)
                    micro_correct.append(c_mi)
                    micro_counted.append(n_mi)
                    prev.append(jax.tree_util.tree_leaves(g_mi))
                extras = [jnp.stack([prev[mi][i] for mi in range(n - 1)])
                          for i in range(len(plan.sizes))]
                if sfac is not None:
                    # the monolith scales the whole stacked-grad tensor;
                    # the taps scale the last micro's cotangent (aux[0])
                    # — scale the prior micros here so every micro sees
                    # the same 2^k pressure
                    extras = [e * sfac for e in extras]
                if sr:
                    emu_key = jax.random.fold_in(
                        grad_sr_key(grad_seed, state.step, 0),
                        lax.axis_index(axis_name).astype(jnp.int32))
                from ..parallel.emulate import make_overlap_emulate_fn
                emulate_fn = make_overlap_emulate_fn(
                    n, use_aps, grad_exp, grad_man, sr)
                x_last, y_last = imgs[n - 1], lbls[n - 1]
                rngs = micro_rngs(state.step, jnp.int32(n - 1))
            else:
                x_last, y_last = images, labels
                rngs = micro_rngs(state.step, jnp.zeros([], jnp.int32))
            base_stats = stats_c

            def loss_closure(p):
                return loss_of(p, base_stats, x_last, y_last, rngs)

            ((_, (logits, new_stats, loss_last)), reduced,
             vreport) = overlapped_grads(
                loss_closure, model_params, axis_name=axis_name,
                plan=plan,
                reduce_kw=dict(use_aps=use_aps, grad_exp=grad_exp,
                               grad_man=grad_man, use_kahan=use_kahan,
                               mode=mode, rounding=grad_rounding,
                               bucket_elems=bucket_elems,
                               block_scale=block_scale,
                               block_size=block_size),
                key=sum_key, sat_factor=sfac, wire_fault=wf,
                verify=verify_reduce, stats=quant_stats,
                collective=tap_collective, extras=extras,
                emulate_reduce=emulate_fn, emulate_key=emu_key)
            c_last, n_last = _count_hits(logits, y_last)
            # same associativity as the monolith's stacked-sum metrics
            loss = jnp.stack(micro_losses + [loss_last]).sum()
            correct = jnp.stack(micro_correct + [c_last]).sum()
            counted = jnp.stack(micro_counted + [n_last]).sum()
            if tap_collective is not None:
                pre_sharded_vec = extract_bucket_shards(reduced, plan,
                                                        tap_chunks)
        else:
            stacked, new_stats, loss, correct, counted = local_micro_grads(
                model_params, state.batch_stats, images, labels, world,
                state.step, scale)
            if sfac is not None:
                stacked = jax.tree.map(lambda g: g * sfac, stacked)

            # Local emulated-node reduction (mix.py:251-282), then the
            # cross-device low-precision all-reduce (mix.py:286-291).
            # grad_rounding='stochastic': fresh unbiased SR bits per step
            # via the shared derivation (parallel/dist.py grad_sr_key —
            # rank-free by contract, so replicated reduction outputs stay
            # consistent).  The emulate-node reduce is rank-LOCAL, so its
            # key also folds in the rank index (same decorrelation the
            # dropout rngs get; sum_gradients folds the rank into its own
            # pre-quantize key).
            local = emulate_node_reduce(
                stacked, emulate_node, use_aps, grad_exp, grad_man,
                rounding=grad_rounding,
                key=jax.random.fold_in(
                    grad_sr_key(grad_seed, state.step, 0),
                    lax.axis_index(axis_name).astype(jnp.int32)) if sr
                else None)
            if reduce_in_update:
                reduced = local       # update_fn owns the collective
            else:
                reduced = sum_gradients(
                    local, axis_name, use_aps=use_aps,
                    grad_exp=grad_exp, grad_man=grad_man,
                    use_kahan=use_kahan, mode=mode, rounding=grad_rounding,
                    key=sum_key, verify=verify_reduce, wire_fault=wf,
                    stats=quant_stats, bucket_elems=bucket_elems,
                    block_scale=block_scale, block_size=block_size)
                if verify_reduce or quant_stats:
                    reduced, vreport = reduced

        if update_fn is not None:
            # custom update (e.g. parallel/zero.py ZeRO: shard-local
            # optimizer math); must return params in the STORED layout
            # (full replicated by default; the rank's shard when
            # params_spec/unpack_params are in play) and the (possibly
            # sharded) new opt state.
            # With reduce_in_update the step's precision settings ride
            # along so the updater's collective cannot drift from the
            # emulate-node quantization above.  The SR key is the SAME
            # fold the replicated path hands sum_gradients, so a ZeRO
            # reduce-scatter draws exactly the bits the replicated
            # faithful reduction would (parallel/zero.py).
            if pre_sharded_vec is not None:
                # ZeRO-2 overlap: the taps already ran the per-bucket
                # reduce-scatter — the update just consumes the shards
                new_params, new_opt = update_fn(pre_sharded_vec, state,
                                                axis_name,
                                                pre_sharded=True)
            else:
                quant_kw = dict(use_aps=use_aps, grad_exp=grad_exp,
                                grad_man=grad_man, use_kahan=use_kahan,
                                mode=mode, rounding=grad_rounding,
                                key=sum_key, block_scale=block_scale,
                                block_size=block_size) \
                    if reduce_in_update else {}
                new_params, new_opt = update_fn(reduced, state, axis_name,
                                                **quant_kw)
        else:
            updates, new_opt = tx.update(reduced, state.opt_state,
                                         state.params)
            new_params = optax.apply_updates(state.params, updates)
        new_stats = jax.tree.map(lambda s: lax.pmean(s, axis_name), new_stats)

        new_state = TrainState(step=state.step + 1, params=new_params,
                               batch_stats=new_stats, opt_state=new_opt)
        # resilience counters (guard skip/overflow/spike totals, injected
        # fault count) ride along as replicated scalars whenever the
        # optimizer is wrapped with resilience.with_grad_guard /
        # with_fault_injection; {} otherwise, so the metric dict shape is
        # unchanged for unguarded runs.
        from ..resilience.guard import guard_metrics
        metrics = {
            **guard_metrics(new_opt),
            # loss is the per-rank sum of micro losses (already /world/n);
            # psum across ranks gives the global mean (mix.py:240-242).
            # (`loss` aux output is the UNSCALED per-micro loss, so no
            # scale division is needed for either static or dynamic.)
            "loss": lax.psum(loss, axis_name),
            # element counts (not shape[0]) so dense label maps (FCN pixel
            # accuracy, minus ignore_label pixels) and flat class labels
            # share one metric definition.
            "accuracy": lax.psum(correct.astype(jnp.float32), axis_name)
                        / jnp.maximum(
                            lax.psum(counted.astype(jnp.float32), axis_name),
                            1.0),
        }
        if vreport is not None:
            # replicated scalars: the wire-integrity verdict / numeric-
            # health telemetry of THIS step's reduce, consumed by the
            # transport / precision supervisors in the loop
            f32 = jnp.float32
            if verify_reduce:
                metrics.update(
                    reduce_ok=vreport["ok"].astype(f32),
                    reduce_hop_bad=vreport["hop_bad"].astype(f32),
                    reduce_gather_bad=vreport["gather_bad"].astype(f32),
                    reduce_agree=vreport["agree"].astype(f32))
            if quant_stats:
                metrics.update(
                    prec_wire_sat=vreport["wire_sat"].astype(f32),
                    prec_wire_underflow=vreport["wire_underflow"]
                    .astype(f32),
                    prec_wire_nan=vreport["wire_nan"].astype(f32),
                    prec_wire_total=vreport["wire_total"].astype(f32),
                    prec_aps_bad=vreport["aps_bad"].astype(f32))
        return new_state, metrics

    if opt_state_spec is None and params_spec is None:
        state_spec: Any = P()   # fully replicated state
    else:
        state_spec = TrainState(step=P(), params=params_spec or P(),
                                batch_stats=P(),
                                opt_state=opt_state_spec
                                if opt_state_spec is not None else P())
    data_spec = P(axis_name)    # batch-sharded
    shard_fn = shard_map(
        step_fn, mesh=mesh,
        in_specs=(state_spec, data_spec, data_spec),
        out_specs=(state_spec, P()),
        check_vma=False)
    return jax.jit(shard_fn, donate_argnums=(0,) if donate else ())


def make_multi_train_step(model, tx: optax.GradientTransformation,
                          mesh: Mesh, k: int, *, axis_name: str = "dp",
                          donate: bool = True, **kw):
    """K train steps fused into ONE executable via `lax.scan`.

    ``(state, images (k, B, ...), labels (k, B, ...)) -> (state, metrics)``
    where metrics are the LAST step's.  Semantically identical to calling
    the single step k times; operationally it amortizes per-dispatch
    overhead (host->device launch, and on the tunneled dev TPU the
    transport round-trip) over k steps — the idiomatic TPU training loop
    shape.  Batches for all k steps must be resident up front.
    """
    # the inner jit inlines when traced inside the scan body
    single = make_train_step(model, tx, mesh, axis_name=axis_name,
                             donate=False, **kw)

    def multi(state, xs, ys):
        def body(s, xy):
            s, m = single(s, xy[0], xy[1])
            return s, m

        state, ms = jax.lax.scan(body, state, (xs, ys))
        last = jax.tree.map(lambda a: a[-1], ms)
        return state, last

    return jax.jit(multi, donate_argnums=(0,) if donate else ())


def make_eval_step(model, mesh: Mesh, *, axis_name: str = "dp",
                   loss_fn: Callable = cross_entropy_loss):
    """Jitted ``(state, images, labels) -> metrics`` (validate() parity,
    mix.py:359-425: all-reduced loss sum + top-1/top-5 counts)."""

    def step_fn(state: TrainState, images, labels):
        variables = {"params": state.params}
        if jax.tree.leaves(state.batch_stats):
            variables["batch_stats"] = state.batch_stats
        logits = _main_logits(model.apply(variables, images, train=False))
        loss = loss_fn(logits, labels)
        top1 = jnp.sum(jnp.argmax(logits, -1) == labels)
        k = min(5, logits.shape[-1])
        topk = jnp.sum(jnp.any(
            lax.top_k(logits, k)[1] == labels[:, None], axis=-1))
        n = jnp.float32(labels.shape[0])
        return {
            "loss": lax.psum(loss * n, axis_name) / lax.psum(n, axis_name),
            "top1": lax.psum(top1.astype(jnp.float32), axis_name)
                    / lax.psum(n, axis_name),
            "top5": lax.psum(topk.astype(jnp.float32), axis_name)
                    / lax.psum(n, axis_name),
        }

    shard_fn = shard_map(
        step_fn, mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name)),
        out_specs=P(),
        check_vma=False)
    return jax.jit(shard_fn)


def make_seg_eval_step(model, mesh: Mesh, num_classes: int, *,
                       axis_name: str = "dp", ignore_label: int = 255):
    """Jitted segmentation eval: ``(state, images, labels) -> metrics``.

    The mmseg-style periodic evaluation the reference's FCN workload
    relies on (its mmcv runner's EvalHook; README.md:132-150).  Returns
    per-batch sums so the caller can stream over a whole split:
      loss_sum / n_pix  — ignored pixels excluded;
      correct           — pixel-accuracy numerator;
      inter / union     — per-class (num_classes,) intersection and union
                          counts; mIoU = mean over classes with union>0
                          after accumulating all batches (the standard
                          Cityscapes metric over the 19 train classes).
    """

    def step_fn(state: TrainState, images, labels):
        variables = {"params": state.params}
        if jax.tree.leaves(state.batch_stats):
            variables["batch_stats"] = state.batch_stats
        logits = _main_logits(model.apply(variables, images, train=False))
        valid = labels != ignore_label
        safe = jnp.where(valid, labels, 0)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), safe)   # same op as the train loss
        loss_sum = jnp.sum(ce * valid)
        pred = jnp.argmax(logits, -1)
        correct = jnp.sum((pred == labels) & valid)
        cls = jnp.arange(num_classes)
        pred_m = (pred[..., None] == cls) & valid[..., None]
        lab_m = (safe[..., None] == cls) & valid[..., None]
        inter = jnp.sum(pred_m & lab_m, axis=tuple(range(labels.ndim)))
        union = jnp.sum(pred_m | lab_m, axis=tuple(range(labels.ndim)))
        f = jnp.float32
        return {
            "loss_sum": lax.psum(f(loss_sum), axis_name),
            "n_pix": lax.psum(f(jnp.sum(valid)), axis_name),
            "correct": lax.psum(f(correct), axis_name),
            "inter": lax.psum(inter.astype(jnp.float32), axis_name),
            "union": lax.psum(union.astype(jnp.float32), axis_name),
        }

    shard_fn = shard_map(
        step_fn, mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name)),
        out_specs=P(),
        check_vma=False)
    return jax.jit(shard_fn)


def ir_programs(reg):
    """Program-contract declarations (analysis/ir/registry.py): the
    vision step builder traced at representative ladder coordinates.

    * overlap twins — the ring step with and without ``overlap_reduce``
      (and a ZeRO-2 tap-reduce pair) claim bitwise parity
      (tests/test_overlap.py); `ir-schedule` pins their collective
      multisets identical and `ir-overlap` their interleaving verdicts.
    * the ``step.ladder`` retrace family — the SAME perturbed config
      coordinates the CLIs' StepTable would hold, each declared with
      its REAL `ladder_step_key`; `ir-retrace` asserts distinct traced
      programs never share a key (the PR 5 half-keyed bug, verified
      dynamically rather than by AST pattern).
    * every member is bitwise-gated: the step wraps the whole
      reduce/APS pipeline, so one stray `exp2` anywhere under it fails
      `ir-bitwise` (the PR 12 class).

    The LR schedule is a constant on purpose: `warmup_step_decay`'s
    ``gamma ** k`` lowers to the unstable `pow` primitive, and the lr
    is not the contract under test."""
    from types import SimpleNamespace

    from ..models.tiny import tiny_cnn
    from ..resilience.precision import ladder_step_key
    from .optim import make_optimizer
    from .state import create_train_state

    W, BUCKET = 8, 100
    deps = ("cpd_tpu.train.step", "cpd_tpu.parallel.dist",
            "cpd_tpu.parallel.ring", "cpd_tpu.parallel.overlap",
            "cpd_tpu.parallel.aps", "cpd_tpu.parallel.emulate",
            "cpd_tpu.parallel.zero", "cpd_tpu.quant.numerics",
            "cpd_tpu.models.tiny")

    def _key(mode, fmt, overlap=None, block=None):
        return ladder_step_key(transport=SimpleNamespace(mode=mode),
                               precision=SimpleNamespace(fmt=fmt),
                               overlap=overlap, block=block)

    def _vision(mode, fmt, overlap=False, block=None, zero2=False):
        def build():
            from ..parallel.mesh import data_parallel_mesh
            mesh = data_parallel_mesh()
            model = tiny_cnn(num_classes=4, width=4)
            tx = make_optimizer("sgd", lambda step: 0.1, momentum=0.9)
            def fresh_state():
                return create_train_state(model, tx,
                                          jnp.zeros((2, 8, 8, 3)),
                                          jax.random.PRNGKey(0))

            kw = dict(use_aps=True, grad_exp=fmt[0], grad_man=fmt[1],
                      mode=mode, grad_rounding="stochastic",
                      grad_seed=5, bucket_elems=BUCKET, donate=False,
                      overlap_reduce=overlap,
                      block_scale=block is not None,
                      block_size=block if block is not None else 128)
            if zero2:
                from ..parallel.zero import zero2_sgd
                z = zero2_sgd(lambda step: 0.1, W, bucket_elems=BUCKET)

                def mk():
                    st = fresh_state()
                    return TrainState(step=st.step, params=st.params,
                                      batch_stats=st.batch_stats,
                                      opt_state=z.init(st.params))

                state = jax.eval_shape(mk)
                kw.update(mode="faithful", grad_rounding="nearest",
                          bucket_elems=BUCKET if overlap else None,
                          update_fn=z.update_fn,
                          opt_state_spec=z.state_spec(),
                          reduce_in_update=True,
                          block_scale=False, block_size=128)
                if overlap:
                    kw["tap_reduce"] = z.make_tap_reduce
            else:
                state = jax.eval_shape(fresh_state)
            step = make_train_step(model, tx, mesh, **kw)
            abstract = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(jnp.shape(l),
                                               jnp.result_type(l)),
                state)
            x = jax.ShapeDtypeStruct((16, 8, 8, 3), jnp.float32)
            y = jax.ShapeDtypeStruct((16,), jnp.int32)
            return step, (abstract, x, y)
        return build

    reg.declare(
        "step.ring[e5m2,sr,aps]", _vision("ring", (5, 2)),
        deps=deps, axis_sizes={"dp": W}, bitwise=True,
        twin="step.ring-overlap", overlap=False,
        retrace_group="step.ladder",
        retrace_key=_key("ring", (5, 2), overlap=(False, BUCKET)))
    reg.declare(
        "step.ring[e5m2,sr,aps]+overlap", _vision("ring", (5, 2),
                                                  overlap=True),
        deps=deps, axis_sizes={"dp": W}, bitwise=True,
        twin="step.ring-overlap", overlap=True,
        retrace_group="step.ladder",
        retrace_key=_key("ring", (5, 2), overlap=(True, BUCKET)))
    reg.declare(
        "step.faithful[e5m2,sr,aps]", _vision("faithful", (5, 2)),
        deps=deps, axis_sizes={"dp": W}, bitwise=True,
        retrace_group="step.ladder",
        retrace_key=_key("faithful", (5, 2), overlap=(False, BUCKET)))
    reg.declare(
        "step.ring[e5m7,sr,aps]", _vision("ring", (5, 7)),
        deps=deps, axis_sizes={"dp": W}, bitwise=True,
        retrace_group="step.ladder",
        retrace_key=_key("ring", (5, 7), overlap=(False, BUCKET)))
    reg.declare(
        "step.ring[blocked-e4m3,b32,sr,aps]",
        _vision("ring", (4, 3), block=32),
        deps=deps, axis_sizes={"dp": W}, bitwise=True,
        retrace_group="step.ladder",
        retrace_key=_key("ring", (4, 3), overlap=(False, BUCKET),
                         block=(True, 32)))
    reg.declare(
        "step.zero2[aps,e5m2]", _vision("ring", (5, 2), zero2=True),
        deps=deps, axis_sizes={"dp": W}, bitwise=True,
        twin="step.zero2-overlap", overlap=False)
    reg.declare(
        "step.zero2[aps,e5m2]+overlap",
        _vision("ring", (5, 2), overlap=True, zero2=True),
        deps=deps, axis_sizes={"dp": W}, bitwise=True,
        twin="step.zero2-overlap", overlap=True)
