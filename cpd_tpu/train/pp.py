"""Pipeline-parallel LM train step over a ("dp", "pp") mesh.

Companion of train/lm.py for the `pp` axis (round-1 review: pp was a
placeholder).  One jitted shard_map program:

* dp — data parallelism with the reference's quantized gradient all-reduce
  (APS / ordered / Kahan, parallel/dist.py);
* pp — GPipe pipelining (parallel/pipeline.py): tokens replicated over pp,
  microbatches streamed through layer stages, loss computed on the last
  stage and masked to zero elsewhere.

Gradient flow: block (stage-local) grads are complete per pp rank — each
rank is the sole owner of its layer slice; replicated params (embed, ln_f)
get a `psum` over pp (embedding gradients arrive on stage 0 via the input
path and on the last stage via the tied head).  Then the dp quantized
`sum_gradients`, then a shard-local elementwise optimizer update (the same
exactness argument as train/lm.py — LARS refused).

With ``model.vocab_pp`` (round 5) the tied table is vocab-sharded over pp
(models/pipeline_lm.py docstring): its grads are shard-complete (no pp
psum — the spec-driven `reduce_leaf` already skips sharded leaves) and
the loss runs through `vocab_parallel_ce` on the (B, T, V/pp) logits
slices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.pipeline_lm import (PipelinedLM, pp_param_specs,
                                  vocab_parallel_ce)
from ..compat import shard_map
from ..parallel.dist import grad_sr_key, sum_gradients
from .state import (TrainState, make_sharded_stepper, reject_norm_based,
                    state_specs_like)

__all__ = ["make_pp_train_step", "make_pp_eval_step", "pp_state_specs"]


def pp_state_specs(state: TrainState, pp_axis: str = "pp",
                   tp_axis: str = "tp",
                   vocab_pp: bool = False) -> TrainState:
    return state_specs_like(
        state, pp_param_specs(state.params, pp_axis, tp_axis, vocab_pp))


def make_pp_train_step(model: PipelinedLM, tx: optax.GradientTransformation,
                       mesh: Mesh, *, n_microbatches: int = 4,
                       axis_dp: str = "dp", axis_pp: str = "pp",
                       axis_tp: str = "tp", use_aps: bool = False,
                       grad_exp: int = 8, grad_man: int = 23,
                       use_kahan: bool = False, mode: str = "faithful",
                       grad_rounding: str = "nearest", grad_seed: int = 0,
                       donate: bool = True):
    """Build jitted ``(state, tokens, targets) -> (state, metrics)``.

    tokens/targets: (global_batch, T) int32 sharded over dp (replicated
    over pp); the per-dp-rank batch is split into `n_microbatches`
    pipeline microbatches.  Keep n_microbatches >= pp for a small bubble
    (fraction (pp-1)/(n_microbatches+pp-1)).

    grad_rounding='stochastic': unbiased SR through the dp all-reduce
    (same contract as train/step.py).  The key depends only on
    (grad_seed, step) — identical across pp/tp ranks, which is required
    (replicated leaves like the embedding must reduce to identical bits
    on every pp copy) and harmless for stage-sharded leaves (pp ranks
    hold different parameters, nothing sums across pp);
    `sum_gradients` itself folds the dp rank into its pre-quantize key.
    """
    if grad_rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown grad_rounding {grad_rounding!r}")
    reject_norm_based(tx, "pp-sharded step")
    pp_size = mesh.shape.get(axis_pp, 1)
    all_axes = (axis_dp, axis_pp, axis_tp)  # size-1 axes psum as no-ops

    def step_fn(state: TrainState, tokens, targets):
        is_last = (lax.axis_index(axis_pp) == pp_size - 1
                   ).astype(jnp.float32)

        def loss_of(params, toks, tgts):
            logits = model.apply_pipelined({"params": params}, toks,
                                           n_microbatches)
            if model.vocab_pp:
                # vocab-sharded logits (B, T, V/pp), valid on EVERY pp
                # rank (the head broadcast already ran inside
                # apply_pipelined); the CE is a pp collective.  is_last
                # masking still applies — it de-duplicates the count and
                # routes exactly one rank's cotangent into the psum
                # transposes (which re-broadcast it to every slice).
                ce, pred = vocab_parallel_ce(logits, tgts, axis_pp)
            else:
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, tgts)
                pred = jnp.argmax(logits, -1)
            # valid on the last stage only; masking zeroes both the loss
            # and (through autodiff) every non-last-stage head cotangent
            local_sum = ce.sum() * is_last
            local_n = jnp.float32(ce.size) * is_last
            # tp ranks compute the loss redundantly; /tp via the global
            # count (same correction as train/lm.py:101-108)
            global_n = lax.psum(local_n, all_axes)
            hits = jnp.sum(pred == tgts) * is_last
            return local_sum / global_n, (local_sum, local_n, hits)

        (_, (lsum, ln, hits)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state.params, tokens, targets)

        # Replicated params (embed, ln_f): finish the pp/tp sum.  A leaf
        # whose spec names an axis is SHARDED over it (sole owner per
        # shard, grads already complete); a leaf whose spec doesn't is
        # replicated over it and its per-rank grads are partial sums.
        specs = pp_param_specs(state.params, axis_pp, axis_tp,
                               model.vocab_pp)

        def named_axes(spec):
            out = []
            for part in spec:
                if isinstance(part, (tuple, list)):
                    out.extend(part)
                elif part is not None:
                    out.append(part)
            return out

        def reduce_leaf(g, spec):
            axes = tuple(a for a in (axis_pp, axis_tp)
                         if a not in named_axes(spec))
            return lax.psum(g, axes) if axes else g

        grads = jax.tree.map(reduce_leaf, grads, specs,
                             is_leaf=lambda x: isinstance(x, P))
        gkey = (grad_sr_key(grad_seed, state.step, 1)
                if grad_rounding == "stochastic" else None)
        grads = sum_gradients(grads, axis_dp, use_aps=use_aps,
                              grad_exp=grad_exp, grad_man=grad_man,
                              use_kahan=use_kahan, mode=mode,
                              rounding=grad_rounding, key=gkey)

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               batch_stats=state.batch_stats,
                               opt_state=new_opt)
        from ..resilience.guard import guard_metrics
        total = lax.psum(ln, all_axes)
        metrics = {
            **guard_metrics(new_opt),
            "loss": lax.psum(lsum, all_axes) / total,
            "accuracy": lax.psum(hits.astype(jnp.float32), all_axes) / total,
        }
        return new_state, metrics

    return make_sharded_stepper(
        step_fn,
        lambda s: pp_state_specs(s, axis_pp, axis_tp, model.vocab_pp),
        mesh, P(axis_dp), donate=donate)


def make_pp_eval_step(model: PipelinedLM, mesh: Mesh, *,
                      n_microbatches: int = 4, axis_dp: str = "dp",
                      axis_pp: str = "pp", axis_tp: str = "tp"):
    """Jitted ``(state, tokens, targets) -> {'loss','accuracy'}`` over the
    same dp x pp sharding as the train step (no grads, no update)."""
    pp_size = mesh.shape.get(axis_pp, 1)
    all_axes = (axis_dp, axis_pp, axis_tp)
    cache: dict = {}

    def eval_fn(state: TrainState, tokens, targets):
        is_last = (lax.axis_index(axis_pp) == pp_size - 1
                   ).astype(jnp.float32)
        logits = model.apply_pipelined({"params": state.params}, tokens,
                                       n_microbatches)
        if model.vocab_pp:
            ce, pred = vocab_parallel_ce(logits, targets, axis_pp)
        else:
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets)
            pred = jnp.argmax(logits, -1)
        hits = jnp.sum(pred == targets) * is_last
        n = jnp.float32(ce.size) * is_last
        total = lax.psum(n, all_axes)
        return {
            "loss": lax.psum(ce.sum() * is_last, all_axes) / total,
            "accuracy": lax.psum(hits.astype(jnp.float32),
                                 all_axes) / total,
        }

    def runner(state, tokens, targets):
        key = jax.tree.structure(state)
        if key not in cache:
            specs = pp_state_specs(state, axis_pp, axis_tp,
                                    model.vocab_pp)
            cache[key] = jax.jit(shard_map(
                eval_fn, mesh=mesh,
                in_specs=(specs, P(axis_dp), P(axis_dp)),
                out_specs=P(), check_vma=False))
        return cache[key](state, tokens, targets)

    return runner
