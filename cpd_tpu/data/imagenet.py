"""ImageNet-shaped data: PIL ImageFolder loader + synthetic fallback.

The reference trains ResNet-50 from `torchvision.datasets.ImageFolder`
with RandomResizedCrop/flip for train and Resize/CenterCrop for val
(example/ResNet50/main.py:90-110).  torchvision is not a dependency here;
`ImageFolderDataset` re-implements that contract directly on PIL, emitting
NHWC fp32 numpy batches (the TPU conv layout).  `SyntheticImageNet` is the
zero-egress stand-in: deterministic, class-dependent images generated on
demand so nothing of ImageNet's 150 GB needs to exist on disk.

Both expose the same surface: `.labels`, `len()`, and
`batch(indices, seed) -> (x, y)` — the contract CIFAR10Pipeline.batch set.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["IMAGENET_MEAN", "IMAGENET_STD", "SyntheticImageNet",
           "ImageFolderDataset", "load_imagenet"]

IMAGENET_MEAN = np.asarray((0.485, 0.456, 0.406), np.float32)  # main.py:101
IMAGENET_STD = np.asarray((0.229, 0.224, 0.225), np.float32)


def _normalise(x: np.ndarray) -> np.ndarray:
    """x in [0,1] NHWC -> channel-standardised."""
    return (x - IMAGENET_MEAN) / IMAGENET_STD


class SyntheticImageNet:
    """Deterministic on-demand ImageNet-shaped data with learnable
    class-dependent structure (cf. synthetic_cifar10 in cifar.py)."""

    def __init__(self, n: int = 12800, num_classes: int = 1000,
                 size: int = 224, seed: int = 0):
        self.size = size
        self.num_classes = num_classes
        rng = np.random.RandomState(seed)
        self.labels = rng.randint(0, num_classes, size=n).astype(np.int32)
        self._seed = seed
        yy, xx = np.mgrid[0:size, 0:size] / max(size - 1, 1)
        self._yy, self._xx = yy.astype(np.float32), xx.astype(np.float32)

    def __len__(self) -> int:
        return len(self.labels)

    def batch(self, indices: Sequence[int], seed: int = 0
              ) -> Tuple[np.ndarray, np.ndarray]:
        indices = np.asarray(indices)
        y = self.labels[indices]
        n, s = len(indices), self.size
        out = np.empty((n, s, s, 3), np.float32)
        for i, (idx, c) in enumerate(zip(indices, y)):
            rng = np.random.RandomState((self._seed * 1_000_003 + idx)
                                        % (2 ** 31))
            freq = 1 + (c % 16)
            phase = (c // 16) / 64.0
            pattern = (np.cos(2 * np.pi * (freq * self._yy + phase))
                       + np.sin(2 * np.pi * (freq * self._xx)))
            base = 0.5 + 0.2 * pattern + (c / self.num_classes - 0.5) * 0.3
            noise = rng.randn(s, s, 3).astype(np.float32) * 0.2
            out[i] = base[:, :, None] + noise
        return _normalise(np.clip(out, 0.0, 1.0)), y


class ImageFolderDataset:
    """`root/<class_name>/*.{jpg,png,...}` loader (ImageFolder contract).

    train=True: RandomResizedCrop(size) + horizontal flip;
    train=False: Resize(size*256/224) + CenterCrop(size) — the val
    transform of main.py:105-110.  Decoding is PIL, per batch, on host.
    """

    _EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")

    def __init__(self, root: str, size: int = 224, train: bool = True):
        from PIL import Image  # noqa: F401 — fail early if PIL missing
        self.root = root
        self.size = size
        self.train = train
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(f"no class directories under {root}")
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        labels = []
        for c in classes:
            folder = os.path.join(root, c)
            for fname in sorted(os.listdir(folder)):
                if fname.lower().endswith(self._EXTS):
                    self.samples.append(os.path.join(folder, fname))
                    labels.append(self.class_to_idx[c])
        self.labels = np.asarray(labels, np.int32)

    def __len__(self) -> int:
        return len(self.samples)

    def _load_train(self, path: str, rng: np.random.RandomState) -> np.ndarray:
        from PIL import Image
        img = Image.open(path).convert("RGB")
        w, h = img.size
        # RandomResizedCrop: area in [0.08, 1.0], ratio in [3/4, 4/3]
        for _ in range(10):
            area = w * h * rng.uniform(0.08, 1.0)
            ratio = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
            cw = int(round(np.sqrt(area * ratio)))
            ch = int(round(np.sqrt(area / ratio)))
            if cw <= w and ch <= h:
                x0 = rng.randint(0, w - cw + 1)
                y0 = rng.randint(0, h - ch + 1)
                img = img.crop((x0, y0, x0 + cw, y0 + ch))
                break
        else:
            # torchvision fallback: center crop of the short side, so the
            # final resize never distorts aspect ratio
            side = min(w, h)
            x0 = (w - side) // 2
            y0 = (h - side) // 2
            img = img.crop((x0, y0, x0 + side, y0 + side))
        img = img.resize((self.size, self.size))
        if rng.rand() < 0.5:
            img = img.transpose(0)  # FLIP_LEFT_RIGHT
        return np.asarray(img, np.float32) / 255.0

    def _load_eval(self, path: str) -> np.ndarray:
        from PIL import Image
        img = Image.open(path).convert("RGB")
        short = int(self.size * 256 / 224)
        w, h = img.size
        scale = short / min(w, h)
        img = img.resize((max(1, round(w * scale)), max(1, round(h * scale))))
        w, h = img.size
        x0 = (w - self.size) // 2
        y0 = (h - self.size) // 2
        img = img.crop((x0, y0, x0 + self.size, y0 + self.size))
        return np.asarray(img, np.float32) / 255.0

    def batch(self, indices: Sequence[int], seed: int = 0
              ) -> Tuple[np.ndarray, np.ndarray]:
        indices = np.asarray(indices)
        n = len(indices)
        out = np.empty((n, self.size, self.size, 3), np.float32)
        for i, idx in enumerate(indices):
            if self.train:
                rng = np.random.RandomState((seed * 1_000_003 + int(idx))
                                            % (2 ** 31))
                out[i] = self._load_train(self.samples[idx], rng)
            else:
                out[i] = self._load_eval(self.samples[idx])
        return _normalise(out), self.labels[indices]


def load_imagenet(root: Optional[str], size: int = 224,
                  synthetic_train: int = 12800, synthetic_val: int = 1280,
                  num_classes: int = 1000):
    """Return (train_ds, val_ds): real ImageFolder pair if `root` has
    train/ and val/ subdirs, synthetic stand-in when no root is given.

    An explicit `root` without the expected layout raises — a typo'd
    --train-dir must not silently fabricate a synthetic run."""
    if root:
        train_dir = os.path.join(root, "train")
        val_dir = os.path.join(root, "val")
        if not (os.path.isdir(train_dir) and os.path.isdir(val_dir)):
            raise FileNotFoundError(
                f"no train/ + val/ ImageFolder layout under {root}")
        return (ImageFolderDataset(train_dir, size, train=True),
                ImageFolderDataset(val_dir, size, train=False))
    return (SyntheticImageNet(synthetic_train, num_classes, size, seed=0),
            SyntheticImageNet(synthetic_val, num_classes, size, seed=1))
