"""Data layer: datasets, vectorized augmentation, deterministic samplers."""

from .augment import (CIFAR10_MEAN, CIFAR10_STD, Crop, Cutout, FlipLR,
                      TransformPipeline, normalise, pad_reflect)
from .cifar import CIFAR10Pipeline, load_cifar10, synthetic_cifar10
from .samplers import (DistributedEpochSampler,
                       DistributedGivenIterationSampler,
                       GivenIterationSampler)
from .imagenet import (IMAGENET_MEAN, IMAGENET_STD, ImageFolderDataset,
                       SyntheticImageNet, load_imagenet)
from .segmentation import (CityscapesDataset, SyntheticSegmentation,
                           load_segmentation)

__all__ = [
    "CIFAR10_MEAN", "CIFAR10_STD", "Crop", "Cutout", "FlipLR",
    "TransformPipeline", "normalise", "pad_reflect",
    "CIFAR10Pipeline", "load_cifar10", "synthetic_cifar10",
    "DistributedEpochSampler", "DistributedGivenIterationSampler",
    "GivenIterationSampler",
    "IMAGENET_MEAN", "IMAGENET_STD", "ImageFolderDataset",
    "SyntheticImageNet", "load_imagenet", "SyntheticSegmentation",
    "CityscapesDataset", "load_segmentation",
]
