"""Batched, pre-sampled-choice data augmentation (NHWC, numpy).

Parity with the reference's DavidNet pipeline
(example/DavidNet/utils.py:69-145): `normalise` (mean/std in 0-255 units),
reflect `pad`, and the Crop / FlipLR / Cutout transforms whose random
choices are pre-sampled per epoch for the whole dataset
(`Transform.set_random_choices`, utils.py:131-145) — pre-sampling is what
makes runs with a fixed seed reproducible and is kept here.

TPU-first deviation: transforms are vectorized over the whole batch (one
gather per transform) instead of the reference's per-sample `__getitem__`
Python loop, and the layout is NHWC end-to-end — there is no
transpose-to-NCHW step (utils.py:81-82) because TPU convs want NHWC.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["CIFAR10_MEAN", "CIFAR10_STD", "normalise", "pad_reflect",
           "Crop", "FlipLR", "Cutout", "TransformPipeline"]

CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)   # utils.py:64
CIFAR10_STD = (0.2471, 0.2435, 0.2616)    # utils.py:67


def normalise(x: np.ndarray, mean=CIFAR10_MEAN, std=CIFAR10_STD) -> np.ndarray:
    """(x - 255*mean) / (255*std) on uint8-scale NHWC input (utils.py:70-74)."""
    x = np.asarray(x, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    return (x - mean * 255.0) / (255.0 * std)


def pad_reflect(x: np.ndarray, border: int = 4) -> np.ndarray:
    """Reflect-pad H and W of an NHWC batch (utils.py:77-79)."""
    return np.pad(x, [(0, 0), (border, border), (border, border), (0, 0)],
                  mode="reflect")


class Crop:
    """Random crop to (h, w); choices are (x0, y0) per sample (utils.py:89-99)."""

    def __init__(self, h: int, w: int):
        self.h, self.w = h, w

    def sample_choices(self, rng: np.random.RandomState, n: int, shape):
        H, W = shape[0], shape[1]
        return {"x0": rng.choice(W + 1 - self.w, size=n),
                "y0": rng.choice(H + 1 - self.h, size=n)}

    def output_shape(self, shape):
        return (self.h, self.w, shape[2])

    def __call__(self, x: np.ndarray, choices) -> np.ndarray:
        n = x.shape[0]
        out = np.empty((n, self.h, self.w, x.shape[3]), x.dtype)
        x0, y0 = choices["x0"], choices["y0"]
        for start_x in np.unique(x0):        # few distinct offsets -> few slices
            for start_y in np.unique(y0[x0 == start_x]):
                m = (x0 == start_x) & (y0 == start_y)
                out[m] = x[m, start_y:start_y + self.h,
                           start_x:start_x + self.w, :]
        return out


class FlipLR:
    """Random horizontal flip; choice is a bool per sample (utils.py:101-106)."""

    def sample_choices(self, rng: np.random.RandomState, n: int, shape):
        return {"choice": rng.choice([True, False], size=n)}

    def output_shape(self, shape):
        return shape

    def __call__(self, x: np.ndarray, choices) -> np.ndarray:
        flip = choices["choice"]
        out = x.copy()
        out[flip] = out[flip, :, ::-1, :]
        return out


class Cutout:
    """Zero out a random (h, w) patch per sample (utils.py:109-117)."""

    def __init__(self, h: int, w: int):
        self.h, self.w = h, w

    def sample_choices(self, rng: np.random.RandomState, n: int, shape):
        H, W = shape[0], shape[1]
        return {"x0": rng.choice(W + 1 - self.w, size=n),
                "y0": rng.choice(H + 1 - self.h, size=n)}

    def output_shape(self, shape):
        return shape

    def __call__(self, x: np.ndarray, choices) -> np.ndarray:
        out = x.copy()
        for i in range(x.shape[0]):
            y0, x0 = choices["y0"][i], choices["x0"][i]
            out[i, y0:y0 + self.h, x0:x0 + self.w, :] = 0.0
        return out


class TransformPipeline:
    """Epoch-level pre-sampled augmentation over a full NHWC dataset array.

    `resample(seed)` draws all per-sample choices for the epoch (the
    reference's set_random_choices, utils.py:138-145); `apply(x, indices)`
    augments the selected samples with their pre-drawn choices."""

    def __init__(self, transforms: Sequence, dataset_shape):
        self.transforms = list(transforms)
        self.dataset_shape = tuple(dataset_shape)  # (N, H, W, C)
        self.choices: Optional[list] = None

    def resample(self, seed: int):
        rng = np.random.RandomState(seed)
        n = self.dataset_shape[0]
        shape = self.dataset_shape[1:]
        self.choices = []
        for t in self.transforms:
            self.choices.append(t.sample_choices(rng, n, shape))
            shape = t.output_shape(shape)

    def apply(self, x: np.ndarray, indices: np.ndarray) -> np.ndarray:
        if self.choices is None:
            raise RuntimeError("call resample(seed) before apply()")
        fused = self._apply_fused(x, indices)
        if fused is not None:
            return fused
        out = x[indices]
        for t, ch in zip(self.transforms, self.choices):
            out = t(out, {k: v[indices] for k, v in ch.items()})
        return out

    def _apply_fused(self, x: np.ndarray, indices: np.ndarray
                     ) -> Optional[np.ndarray]:
        """Native fused Crop -> FlipLR [-> Cutout] executor (one threaded
        C++ pass, cpd_tpu/native/augment_native.cpp) for the canonical
        chain; bitwise identical to the numpy path (pure copies/zeros).
        Returns None when the chain doesn't match or the native lib is
        unavailable — callers fall back transparently."""
        kinds = [type(t).__name__ for t in self.transforms]
        if (kinds not in (["Crop", "FlipLR"], ["Crop", "FlipLR", "Cutout"])
                or x.dtype != np.float32):
            return None
        from .. import native
        if not native.available():
            return None
        crop = self.transforms[0]
        crop_ch, flip_ch = self.choices[0], self.choices[1]
        cut_kwargs = {}
        if len(self.transforms) == 3:
            cut = self.transforms[2]
            cut_kwargs = dict(cut_y=self.choices[2]["y0"],
                              cut_x=self.choices[2]["x0"],
                              cut_h=cut.h, cut_w=cut.w)
        return native.fused_augment_np(
            x, np.asarray(indices), crop_ch["y0"], crop_ch["x0"],
            crop.h, crop.w, flip_ch["choice"].astype(np.uint8),
            **cut_kwargs)
