"""Synthetic token streams for LM training (zero-egress stand-in).

Sequences follow a deterministic order-1 Markov chain with
class-structured transitions, so short training runs show a clearly
decreasing loss (the chain's entropy is well below uniform).  Real corpora
plug in by implementing the same `batch(indices, seed) -> (tokens,
targets)` contract.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

__all__ = ["SyntheticText"]


class SyntheticText:
    """n sequences of length seq_len + 1; batch() returns (tokens, targets)
    as the usual next-token split."""

    def __init__(self, n: int = 4096, seq_len: int = 128,
                 vocab_size: int = 256, seed: int = 0):
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self._seed = seed
        self.labels = np.zeros(n, np.int32)   # dataset contract
        self._n = n
        # banded transition matrix: from token t, mass concentrated on
        # {t-1, t+1, t+7 mod V} — learnable, low-entropy
        rng = np.random.RandomState(seed)
        base = rng.rand(vocab_size, vocab_size).astype(np.float64) * 0.05
        idx = np.arange(vocab_size)
        base[idx, (idx + 1) % vocab_size] += 2.0
        base[idx, (idx - 1) % vocab_size] += 1.0
        base[idx, (idx + 7) % vocab_size] += 1.0
        self._cum = np.cumsum(base / base.sum(1, keepdims=True), axis=1)

    def __len__(self) -> int:
        return self._n

    def batch(self, indices: Sequence[int], seed: int = 0
              ) -> Tuple[np.ndarray, np.ndarray]:
        indices = np.asarray(indices)
        out = np.empty((len(indices), self.seq_len + 1), np.int32)
        for i, idx in enumerate(indices):
            rng = np.random.RandomState((self._seed * 1_000_003 + int(idx))
                                        % (2 ** 31))
            tok = rng.randint(0, self.vocab_size)
            for t in range(self.seq_len + 1):
                out[i, t] = tok
                tok = int(np.searchsorted(self._cum[tok], rng.rand()))
        return out[:, :-1], out[:, 1:]
