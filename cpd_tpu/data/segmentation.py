"""Segmentation data: synthetic Cityscapes-shaped crops.

The reference's FCN/Cityscapes workload lives out-of-repo (mmcv fork,
README.md:132-150): 769x769 random crops of 19-class street scenes.  The
synthetic stand-in emits (image NHWC fp32, label map HxW int32) pairs whose
label regions are geometric shapes correlated with the image content, so
short runs show the loss decreasing; real Cityscapes can be wired in by
implementing this same `batch()` contract over the leftImg8bit/gtFine pair
tree.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

__all__ = ["SyntheticSegmentation"]


class SyntheticSegmentation:
    """Deterministic synthetic scenes: `num_classes` horizontal bands with
    per-class texture, plus a random rectangle of another class per image."""

    def __init__(self, n: int = 256, num_classes: int = 19,
                 crop_size: int = 128, seed: int = 0):
        self.num_classes = num_classes
        self.crop_size = crop_size
        self._seed = seed
        self.labels = np.zeros(n, np.int32)  # unused; keeps dataset contract
        self._n = n

    def __len__(self) -> int:
        return self._n

    def batch(self, indices: Sequence[int], seed: int = 0
              ) -> Tuple[np.ndarray, np.ndarray]:
        indices = np.asarray(indices)
        s, c = self.crop_size, self.num_classes
        x = np.empty((len(indices), s, s, 3), np.float32)
        y = np.empty((len(indices), s, s), np.int32)
        for i, idx in enumerate(indices):
            rng = np.random.RandomState((self._seed * 1_000_003 + int(idx))
                                        % (2 ** 31))
            n_bands = rng.randint(2, 5)
            classes = rng.choice(c, size=n_bands, replace=False)
            bounds = np.sort(rng.choice(np.arange(1, s), n_bands - 1,
                                        replace=False)) if n_bands > 1 else []
            label = np.empty((s, s), np.int32)
            img = np.empty((s, s, 3), np.float32)
            lo = 0
            for b, cls in enumerate(classes):
                hi = bounds[b] if b < n_bands - 1 else s
                label[lo:hi] = cls
                img[lo:hi] = (cls + 1) / c + 0.1 * rng.randn(hi - lo, s, 3)
                lo = hi
            # one foreground rectangle
            cls = rng.randint(0, c)
            h0, w0 = rng.randint(0, s // 2, size=2)
            h1 = h0 + rng.randint(s // 8, s // 2)
            w1 = w0 + rng.randint(s // 8, s // 2)
            label[h0:h1, w0:w1] = cls
            img[h0:h1, w0:w1] = (cls + 1) / c + 0.1 * rng.randn(
                min(h1, s) - h0, min(w1, s) - w0, 3)
            x[i] = img
            y[i] = label
        return x, y
