"""Segmentation data: Cityscapes leftImg8bit/gtFine loader + synthetic fallback.

The reference's FCN/Cityscapes workload lives out-of-repo (mmcv fork,
README.md:132-150): 769x769 random crops of 19-class street scenes.
`CityscapesDataset` walks the standard tree

    <root>/leftImg8bit/<split>/<city>/<name>_leftImg8bit.png
    <root>/gtFine/<split>/<city>/<name>_gtFine_labelIds.png

maps the 34 raw labelIds to the 19 train classes (everything else
ignore_label=255), and emits random crops with the mmseg train pipeline's
geometry (random crop after optional padding, random horizontal flip,
mean/std normalization).  `SyntheticSegmentation` is the structure-matched
stand-in; `load_segmentation` picks whichever exists on disk.  Both expose
the same `batch(indices, seed) -> (NHWC fp32, HxW int32)` contract.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["SyntheticSegmentation", "CityscapesDataset", "load_segmentation",
           "CITYSCAPES_IGNORE", "cityscapes_train_ids"]

CITYSCAPES_IGNORE = 255

# raw labelId -> trainId for the 19 evaluated classes (the standard
# cityscapesScripts assignment mmseg's CityscapesDataset uses)
_LABEL_TO_TRAIN = {7: 0, 8: 1, 11: 2, 12: 3, 13: 4, 17: 5, 19: 6, 20: 7,
                   21: 8, 22: 9, 23: 10, 24: 11, 25: 12, 26: 13, 27: 14,
                   28: 15, 31: 16, 32: 17, 33: 18}

# mmseg's img_norm_cfg for the fcn_r50-d8 cityscapes configs (RGB, 0-255)
_SEG_MEAN = np.array([123.675, 116.28, 103.53], np.float32)
_SEG_STD = np.array([58.395, 57.12, 57.375], np.float32)


def cityscapes_train_ids() -> np.ndarray:
    """(256,) uint8 lookup: raw labelId -> trainId (255 = ignore)."""
    lut = np.full(256, CITYSCAPES_IGNORE, np.uint8)
    for raw, train in _LABEL_TO_TRAIN.items():
        lut[raw] = train
    return lut


class SyntheticSegmentation:
    """Deterministic synthetic scenes: `num_classes` horizontal bands with
    per-class texture, plus a random rectangle of another class per image."""

    def __init__(self, n: int = 256, num_classes: int = 19,
                 crop_size: int = 128, seed: int = 0):
        self.num_classes = num_classes
        self.crop_size = crop_size
        self._seed = seed
        self.labels = np.zeros(n, np.int32)  # unused; keeps dataset contract
        self._n = n

    def __len__(self) -> int:
        return self._n

    def batch(self, indices: Sequence[int], seed: int = 0
              ) -> Tuple[np.ndarray, np.ndarray]:
        indices = np.asarray(indices)
        s, c = self.crop_size, self.num_classes
        x = np.empty((len(indices), s, s, 3), np.float32)
        y = np.empty((len(indices), s, s), np.int32)
        for i, idx in enumerate(indices):
            rng = np.random.RandomState((self._seed * 1_000_003 + int(idx))
                                        % (2 ** 31))
            n_bands = rng.randint(2, 5)
            classes = rng.choice(c, size=n_bands, replace=False)
            bounds = np.sort(rng.choice(np.arange(1, s), n_bands - 1,
                                        replace=False)) if n_bands > 1 else []
            label = np.empty((s, s), np.int32)
            img = np.empty((s, s, 3), np.float32)
            lo = 0
            for b, cls in enumerate(classes):
                hi = bounds[b] if b < n_bands - 1 else s
                label[lo:hi] = cls
                img[lo:hi] = (cls + 1) / c + 0.1 * rng.randn(hi - lo, s, 3)
                lo = hi
            # one foreground rectangle
            cls = rng.randint(0, c)
            h0, w0 = rng.randint(0, s // 2, size=2)
            h1 = h0 + rng.randint(s // 8, s // 2)
            w1 = w0 + rng.randint(s // 8, s // 2)
            label[h0:h1, w0:w1] = cls
            img[h0:h1, w0:w1] = (cls + 1) / c + 0.1 * rng.randn(
                min(h1, s) - h0, min(w1, s) - w0, 3)
            x[i] = img
            y[i] = label
        return x, y


class CityscapesDataset:
    """Random-crop training view of a Cityscapes tree.

    Replaces the reference's out-of-repo mmsegmentation data pipeline
    (README.md:132-150) for the FCN trainer: 769x769 random crops (the
    fcn_r50-d8 config's crop), random horizontal flip, labelId->trainId
    mapping with ignore 255, and the mmseg mean/std normalization.  Images
    shorter than the crop on either side are zero-padded (labels padded
    with ignore), as mmseg's Pad transform does.
    """

    def __init__(self, root: str, split: str = "train",
                 crop_size: int = 769, num_classes: int = 19,
                 flip: bool = True):
        if num_classes != 19:
            # the labelId->trainId LUT emits exactly the 19 evaluated
            # classes; training a smaller head on it would silently clip
            # out-of-range labels inside the CE gather
            raise ValueError(
                f"Cityscapes trainId labels have 19 classes, got "
                f"num_classes={num_classes}")
        self.crop_size = crop_size
        self.num_classes = num_classes
        self.flip = flip
        self._lut = cityscapes_train_ids()
        img_dir = os.path.join(root, "leftImg8bit", split)
        lab_dir = os.path.join(root, "gtFine", split)
        pairs = []
        for city in sorted(os.listdir(img_dir)):
            cdir = os.path.join(img_dir, city)
            if not os.path.isdir(cdir):
                continue
            for name in sorted(os.listdir(cdir)):
                if not name.endswith("_leftImg8bit.png"):
                    continue
                stem = name[:-len("_leftImg8bit.png")]
                lab = os.path.join(lab_dir, city,
                                   stem + "_gtFine_labelIds.png")
                if os.path.isfile(lab):
                    pairs.append((os.path.join(cdir, name), lab))
        if not pairs:
            raise FileNotFoundError(
                f"no leftImg8bit/gtFine pairs under {root} split={split}")
        self._pairs = pairs
        self.labels = np.zeros(len(pairs), np.int32)  # dataset contract

    def __len__(self) -> int:
        return len(self._pairs)

    def _load_pair(self, idx: int) -> Tuple[np.ndarray, np.ndarray]:
        from PIL import Image

        img_path, lab_path = self._pairs[idx]
        img = np.asarray(Image.open(img_path).convert("RGB"), np.uint8)
        lab = np.asarray(Image.open(lab_path), np.uint8)
        return img, self._lut[lab]

    def batch(self, indices: Sequence[int], seed: int = 0
              ) -> Tuple[np.ndarray, np.ndarray]:
        s = self.crop_size
        n = len(indices)
        x = np.zeros((n, s, s, 3), np.float32)
        y = np.full((n, s, s), CITYSCAPES_IGNORE, np.int32)
        for i, idx in enumerate(np.asarray(indices)):
            rng = np.random.RandomState((seed * 1_000_003 + int(idx))
                                        % (2 ** 31))
            img, lab = self._load_pair(int(idx))
            h, w = lab.shape
            # pad-to-crop (ignore-filled labels, zero-pixel images), then a
            # uniform random crop — mmseg's Pad + RandomCrop
            top = rng.randint(0, max(h - s, 0) + 1)
            left = rng.randint(0, max(w - s, 0) + 1)
            ch, cw = min(s, h), min(s, w)
            img_c = img[top:top + ch, left:left + cw].astype(np.float32)
            lab_c = lab[top:top + ch, left:left + cw]
            if self.flip and rng.rand() < 0.5:
                img_c = img_c[:, ::-1]
                lab_c = lab_c[:, ::-1]
            x[i, :ch, :cw] = (img_c - _SEG_MEAN) / _SEG_STD
            y[i, :ch, :cw] = lab_c
        return x, y


def load_segmentation(root: Optional[str] = None, split: str = "train",
                      crop_size: int = 128, num_classes: int = 19,
                      synthetic_size: int = 256, seed: int = 0,
                      flip: bool = True):
    """Real Cityscapes if `root` holds a leftImg8bit/gtFine tree, synthetic
    stand-in when no root is given (same batch() contract).  Pass
    ``flip=False`` for evaluation splits — mmseg's eval pipeline has no
    random flip.

    An explicit `root` without the expected tree raises — a typo'd
    --data-root must not silently fabricate a synthetic run."""
    if root:
        if not os.path.isdir(os.path.join(root, "leftImg8bit", split)):
            raise FileNotFoundError(
                f"no leftImg8bit/{split} tree under {root}")
        return CityscapesDataset(root, split=split, crop_size=crop_size,
                                 num_classes=num_classes, flip=flip)
    return SyntheticSegmentation(n=synthetic_size, num_classes=num_classes,
                                 crop_size=crop_size, seed=seed)
