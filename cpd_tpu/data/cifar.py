"""CIFAR-10 dataset + batch pipeline (host-side numpy, NHWC).

The reference loads CIFAR-10 via torchvision with per-sample torch
transforms (example/ResNet18/tools/mix.py:106-122) or via the DavidNet numpy
pipeline (example/DavidNet/dawn.py:60-71, utils.py:60-82).  Here loading is
array-at-once: the whole 50k x 32 x 32 x 3 uint8 cube lives in host RAM,
augmentation is vectorized (augment.py), and batches transfer to device as
one contiguous NHWC array — the TPU-friendly shape of the same capability.

Offline environments: if no CIFAR-10 copy exists on disk (zero-egress), a
deterministic synthetic stand-in with class-dependent structure is
generated so every trainer/test/bench runs anywhere; real-data paths are
picked up automatically when present.
"""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import Iterator, Optional, Tuple

import numpy as np

from .augment import (CIFAR10_MEAN, CIFAR10_STD, Crop, Cutout, FlipLR,
                      TransformPipeline, normalise, pad_reflect)

__all__ = ["load_cifar10", "CIFAR10Pipeline", "synthetic_cifar10"]

_CIFAR_DIRS = ("cifar-10-batches-py",)
_DEFAULT_ROOTS = ("./data", "/root/data", "/tmp/data",
                  os.path.expanduser("~/data"))


def _load_pickle_batches(folder: str) -> Tuple[np.ndarray, np.ndarray,
                                               np.ndarray, np.ndarray]:
    xs, ys = [], []
    for i in range(1, 6):
        with open(os.path.join(folder, f"data_batch_{i}"), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(d[b"data"])
        ys.append(d[b"labels"])
    train_x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    train_y = np.concatenate(ys).astype(np.int32)
    with open(os.path.join(folder, "test_batch"), "rb") as f:
        d = pickle.load(f, encoding="bytes")
    test_x = np.asarray(d[b"data"]).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    test_y = np.asarray(d[b"labels"], np.int32)
    return train_x.astype(np.uint8), train_y, test_x.astype(np.uint8), test_y


def synthetic_cifar10(n_train: int = 50000, n_test: int = 10000,
                      seed: int = 0):
    """Deterministic synthetic CIFAR-shaped data whose pixel statistics
    depend on the label, so short training runs show real learning signal
    (loss decreases, APS-vs-no-APS ordering is observable)."""
    rng = np.random.RandomState(seed)

    def make(n):
        y = rng.randint(0, 10, size=n).astype(np.int32)
        x = rng.randint(0, 256, size=(n, 32, 32, 3)).astype(np.float32)
        # class-dependent low-frequency pattern: mean shift + per-class
        # spatial gradient, strong enough to be learnable.
        yy, xx = np.mgrid[0:32, 0:32] / 31.0
        for c in range(10):
            m = y == c
            pattern = (np.cos(2 * np.pi * (c + 1) * yy / 10.0)
                       + np.sin(2 * np.pi * (c + 1) * xx / 10.0))
            x[m] = 0.5 * x[m] + 0.5 * (128 + 64 * pattern)[None, :, :, None] \
                + 8.0 * c
        return np.clip(x, 0, 255).astype(np.uint8), y

    train_x, train_y = make(n_train)
    test_x, test_y = make(n_test)
    return train_x, train_y, test_x, test_y


def load_cifar10(root: Optional[str] = None, allow_synthetic: bool = True):
    """Return (train_x u8 NHWC, train_y, test_x, test_y); real data if found
    under `root` (or common roots), else synthetic (see module docstring).

    An EXPLICIT `root` is strict: if no CIFAR-10 tree is found there, this
    raises instead of silently training on synthetic data (a typo'd
    --data-root must not fabricate a run that looks real).  The synthetic
    fallback applies only to the no-root default search."""
    if root:
        allow_synthetic = False
    roots = [root] if root else list(_DEFAULT_ROOTS)
    for r in roots:
        if not r:
            continue
        for d in _CIFAR_DIRS:
            folder = os.path.join(r, d)
            if os.path.isfile(os.path.join(folder, "data_batch_1")):
                return _load_pickle_batches(folder)
        tgz = os.path.join(r or ".", "cifar-10-python.tar.gz")
        if os.path.isfile(tgz):
            with tarfile.open(tgz) as tf:
                tf.extractall(r)
            folder = os.path.join(r, _CIFAR_DIRS[0])
            if os.path.isfile(os.path.join(folder, "data_batch_1")):
                return _load_pickle_batches(folder)
    if not allow_synthetic:
        raise FileNotFoundError(f"CIFAR-10 not found under {roots}")
    return synthetic_cifar10()


class CIFAR10Pipeline:
    """Epoch iterator producing augmented, normalised NHWC fp32 batches.

    Augmentation recipe = the DavidNet one (pad 4 reflect -> random 32x32
    crop -> flip -> cutout 8x8, dawn.py:66) with per-epoch pre-sampled
    choices; `augment=False` gives the eval pipeline (normalise only)."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, augment: bool = True, cutout: int = 8,
                 drop_last: bool = True):
        self.labels = np.asarray(labels, np.int32)
        self.batch_size = batch_size
        self.augment = augment
        self.drop_last = drop_last
        base = normalise(images.astype(np.float32))
        self._choice_seed: Optional[int] = None
        if augment:
            self.data = pad_reflect(base, 4)
            transforms = [Crop(32, 32), FlipLR()]
            if cutout:
                transforms.append(Cutout(cutout, cutout))
            self.pipeline = TransformPipeline(transforms, self.data.shape)
        else:
            self.data = base
            self.pipeline = None

    def batch(self, indices: np.ndarray, seed: int = 0
              ) -> Tuple[np.ndarray, np.ndarray]:
        """One batch for explicit `indices` (iteration-based samplers).
        Choices are re-drawn only when `seed` changes, matching the
        reference's once-per-epoch set_random_choices (utils.py:138-145)."""
        indices = np.asarray(indices)
        if self.pipeline is not None:
            if self._choice_seed != seed:
                self.pipeline.resample(seed)
                self._choice_seed = seed
            x = self.pipeline.apply(self.data, indices)
        else:
            x = self.data[indices]
        return x, self.labels[indices]

    def __len__(self) -> int:
        n = len(self.labels)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def epoch(self, indices: np.ndarray, seed: int = 0,
              ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield (x, y) batches for a precomputed index order (from a
        sampler in data/samplers.py)."""
        if self.pipeline is not None:
            self.pipeline.resample(seed)
            self._choice_seed = seed   # keep batch()'s cache coherent
        bs = self.batch_size
        limit = len(indices) - (len(indices) % bs if self.drop_last else 0)
        for lo in range(0, limit, bs):
            idx = np.asarray(indices[lo:lo + bs])
            if self.pipeline is not None:
                x = self.pipeline.apply(self.data, idx)
            else:
                x = self.data[idx]
            yield x, self.labels[idx]
