"""Deterministic, iteration-based, resumable samplers.

Parity with reference `example/ResNet18/utils/train_util.py`:
  * DistributedGivenIterationSampler (train_util.py:159-222): bit-exact
    `gen_new_list` — seed-0 RandomState, dataset indices capped at all_size
    BEFORE tiling (the reference's `indices[:all_size]` quirk at :204,
    which silently truncates datasets larger than the schedule), tiled to
    all_size, ONE whole-schedule shuffle (:208), contiguous per-rank slice
    (:209-210); resume by skipping `last_iter * batch_size`;
  * DistributedSampler (train_util.py:225-265): epoch-seeded randperm,
    padded to a multiple of world, strided per rank;
  * GivenIterationSampler (train_util.py:110-156): the single-rank variant
    (same gen_new_list with world_size=1).

`np.random.RandomState(0).shuffle` is bit-identical to the reference's
legacy `np.random.seed(0); np.random.shuffle` — the global generator IS a
RandomState.  Index sequences are checked against a vendored transcript of
the reference's output in tests/test_train.py.

These are numpy index generators (no torch dependency); the trainer feeds
the indices to whatever array-backed dataset it holds.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

__all__ = ["GivenIterationSampler", "DistributedGivenIterationSampler",
           "DistributedEpochSampler"]


def _gen_new_list(dataset_len: int, total_size: int, world_size: int,
                  rank: int, seed: int) -> np.ndarray:
    """Bit-exact transcription of the reference schedule recipe
    (train_util.py:196-215): cap-at-all_size, tile, one shuffle, contiguous
    rank slice."""
    all_size = total_size * world_size
    indices = np.arange(dataset_len)
    indices = indices[:all_size]                    # the :204 cap quirk
    num_repeat = (all_size - 1) // indices.shape[0] + 1
    indices = np.tile(indices, num_repeat)
    indices = indices[:all_size]
    rng = np.random.RandomState(seed)               # == np.random.seed(0)
    rng.shuffle(indices)                            # ONE global shuffle :208
    beg = total_size * rank
    return indices[beg:beg + total_size]


class GivenIterationSampler:
    """Fixed-length schedule of total_iter*batch_size indices, seed-shuffled
    (train_util.py:110-156).  Iterating yields single indices; `last_iter`
    skips the first `last_iter + 1` batches on resume."""

    def __init__(self, dataset_len: int, total_iter: int, batch_size: int,
                 seed: int = 0, last_iter: int = -1):
        self.dataset_len = dataset_len
        self.total_iter = total_iter
        self.batch_size = batch_size
        self.seed = seed
        self.last_iter = last_iter
        self.indices = self._gen_indices()

    def _gen_indices(self) -> np.ndarray:
        return _gen_new_list(self.dataset_len,
                             self.total_iter * self.batch_size,
                             world_size=1, rank=0, seed=self.seed)

    def __iter__(self) -> Iterator[int]:
        start = (self.last_iter + 1) * self.batch_size
        return iter(self.indices[start:])

    def __len__(self) -> int:
        return self.total_iter * self.batch_size

    def batches(self) -> Iterator[np.ndarray]:
        start = self.last_iter + 1
        for it in range(start, self.total_iter):
            lo = it * self.batch_size
            yield self.indices[lo:lo + self.batch_size]


class DistributedGivenIterationSampler(GivenIterationSampler):
    """Per-rank slice of the global schedule (train_util.py:159-222).

    The reference builds world*total*batch indices by seed-0 shuffling and
    tiling, then takes the rank-th contiguous block (`beg = total_size//world
    * rank`, train_util.py:212-215) — contiguous block, NOT strided."""

    def __init__(self, dataset_len: int, total_iter: int, batch_size: int,
                 world_size: int = 1, rank: int = 0, seed: int = 0,
                 last_iter: int = -1):
        self.world_size = world_size
        self.rank = rank
        super().__init__(dataset_len, total_iter, batch_size, seed, last_iter)

    def _gen_indices(self) -> np.ndarray:
        return _gen_new_list(self.dataset_len,
                             self.total_iter * self.batch_size,
                             world_size=self.world_size, rank=self.rank,
                             seed=self.seed)


class DistributedEpochSampler:
    """Epoch-seeded shuffling sampler (train_util.py:225-265): randperm with
    `seed = epoch`, padded to a multiple of world_size, strided per rank —
    the torch DistributedSampler contract ResNet50 relies on
    (main.py:111-120 + set_epoch at :222)."""

    def __init__(self, dataset_len: int, world_size: int = 1, rank: int = 0,
                 shuffle: bool = True):
        self.dataset_len = dataset_len
        self.world_size = world_size
        self.rank = rank
        self.shuffle = shuffle
        self.epoch = 0
        self.num_samples = -(-dataset_len // world_size)  # ceil
        self.total_size = self.num_samples * world_size

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(self.dataset_len)
        else:
            indices = np.arange(self.dataset_len)
        pad = self.total_size - len(indices)
        if pad:
            indices = np.concatenate([indices, indices[:pad]])
        return iter(indices[self.rank:self.total_size:self.world_size])

    def __len__(self) -> int:
        return self.num_samples
