// Fused data-augmentation executor — the native IO/runtime component of
// the data pipeline (cpd_tpu/data/augment.py's Crop -> FlipLR -> Cutout
// recipe, itself the DavidNet pipeline of the reference's
// example/DavidNet/utils.py:89-145).
//
// One pass per output pixel: gather from the reflect-padded normalized
// dataset at the sample's pre-drawn crop offset, horizontally mirrored
// when the flip choice is set, zeroed inside the cutout box (cutout
// coordinates are in post-flip output frame, matching the numpy order
// crop -> flip -> cutout).  Batch is split across std::thread workers —
// the host-side analog of the reference's CUDA thread grid, sized for
// TPU-host CPUs (the numpy path is single-threaded gather chains).
//
// Bit-exactness contract: pure copies and zero-writes of fp32 values, no
// arithmetic — results are bitwise identical to the numpy path, which
// tests/test_native.py asserts.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// in:       (n_total, ih, iw, ch) fp32, C-contiguous (padded dataset)
// indices:  (b,) int64 rows of `in` to augment
// crop_y/x: (n_total,) int64 per-DATASET-sample crop origins
// flip:     (n_total,) uint8 booleans
// cut_y/x:  (n_total,) int64 cutout origins in output coords (ignored
//           when cut_h == 0)
// out:      (b, oh, ow, ch) fp32
void cpd_fused_augment(const float* in, const int64_t* indices, int64_t b,
                       int64_t ih, int64_t iw, int64_t ch,
                       const int64_t* crop_y, const int64_t* crop_x,
                       int64_t oh, int64_t ow,
                       const uint8_t* flip,
                       const int64_t* cut_y, const int64_t* cut_x,
                       int64_t cut_h, int64_t cut_w,
                       float* out, int64_t n_threads) {
  const int64_t in_row = iw * ch;
  const int64_t in_img = ih * in_row;
  const int64_t out_row = ow * ch;
  const int64_t out_img = oh * out_row;

  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const int64_t src_idx = indices[i];
      const float* src = in + src_idx * in_img;
      float* dst = out + i * out_img;
      const int64_t y0 = crop_y[src_idx];
      const int64_t x0 = crop_x[src_idx];
      const bool fl = flip[src_idx] != 0;
      const int64_t cy = cut_h ? cut_y[src_idx] : -1;
      const int64_t cx = cut_h ? cut_x[src_idx] : -1;
      for (int64_t oy = 0; oy < oh; ++oy) {
        const float* srow = src + (y0 + oy) * in_row + x0 * ch;
        float* drow = dst + oy * out_row;
        if (!fl) {
          std::memcpy(drow, srow, out_row * sizeof(float));
        } else {
          for (int64_t ox = 0; ox < ow; ++ox)
            std::memcpy(drow + ox * ch, srow + (ow - 1 - ox) * ch,
                        ch * sizeof(float));
        }
        if (cut_h && oy >= cy && oy < cy + cut_h) {
          const int64_t lo_x = std::max<int64_t>(cx, 0);
          const int64_t hi_x = std::min<int64_t>(cx + cut_w, ow);
          if (hi_x > lo_x)
            std::memset(drow + lo_x * ch, 0, (hi_x - lo_x) * ch
                        * sizeof(float));
        }
      }
    }
  };

  int64_t workers = std::min<int64_t>(
      n_threads > 0 ? n_threads
                    : (int64_t)std::thread::hardware_concurrency(),
      b);
  if (workers <= 1) {
    work(0, b);
    return;
  }
  std::vector<std::thread> pool;
  const int64_t chunk = (b + workers - 1) / workers;
  for (int64_t w = 0; w < workers; ++w) {
    const int64_t lo = w * chunk;
    const int64_t hi = std::min(lo + chunk, b);
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi);
  }
  for (auto& t : pool) t.join();
}

}  // extern "C"
