"""Native host library: JIT-compiled C++ eXmY numerics via ctypes.

The reference compiles its native layer at import time with
`torch.utils.cpp_extension.load` (reference:
CPDtorch/quant/quant_function.py:10-17) and degrades to None on CPU-only
environments (:18-19).  Same contract here, minus the torch dependency:
`g++ -O2 -shared -fPIC` into a cached .so beside the source, ctypes
bindings, and graceful degradation (`available() == False`) when no
compiler exists.

Public surface (numpy in/out, pure — no in-place mutation):
  * `float_quantize_np(x, exp, man)`   — elementwise eXmY cast
  * `quant_gemm_np(a, b, exp, man)`    — Kahan eXmY-accumulator GEMM
  * `ordered_sum_np(stacked, exp, man, kahan)` — rank-ordered quantized
    reduction over axis 0
These are bit-identical to the jnp implementations (tests/test_native.py
cross-checks all three) and serve host-side data-path quantization plus
independent oracles.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

__all__ = ["available", "float_quantize_np", "quant_gemm_np",
           "ordered_sum_np", "fused_augment_np", "build", "load"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = (os.path.join(_HERE, "quant_native.cpp"),
         os.path.join(_HERE, "augment_native.cpp"))
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _so_path() -> str:
    return os.path.join(_HERE, "_cpd_native.so")


def build(force: bool = False) -> Optional[str]:
    """Compile the shared library if absent/stale; return its path or None
    when no toolchain is available."""
    so = _so_path()
    if (not force and os.path.exists(so)
            and os.path.getmtime(so) >= max(os.path.getmtime(s)
                                            for s in _SRCS)):
        return so
    for cxx in (os.environ.get("CXX"), "g++", "c++", "clang++"):
        if not cxx:
            continue
        # build into a temp file then rename: atomic under concurrent
        # imports (e.g. pytest-xdist workers racing).
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
        os.close(fd)
        cmd = [cxx, "-O2", "-shared", "-fPIC", "-pthread", "-o", tmp,
               *_SRCS]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
            return so
        except (OSError, subprocess.SubprocessError):
            if os.path.exists(tmp):
                os.unlink(tmp)
            continue
    return None


def load() -> Optional[ctypes.CDLL]:
    """Build-if-needed and dlopen; cached.  None when unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    so = build()
    if so is None:
        return None
    lib = ctypes.CDLL(so)
    i64, i32 = ctypes.c_int64, ctypes.c_int
    fptr = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    lib.cpd_cast_one.restype = ctypes.c_float
    lib.cpd_cast_one.argtypes = [ctypes.c_float, i32, i32]
    lib.cpd_quantize.restype = None
    lib.cpd_quantize.argtypes = [fptr, fptr, i64, i32, i32]
    lib.cpd_qgemm.restype = None
    lib.cpd_qgemm.argtypes = [fptr, fptr, fptr, i64, i64, i64, i32, i32]
    lib.cpd_ordered_sum.restype = None
    lib.cpd_ordered_sum.argtypes = [fptr, fptr, i64, i64, i32, i32, i32]
    iptr = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    bptr = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.cpd_fused_augment.restype = None
    lib.cpd_fused_augment.argtypes = [
        fptr, iptr, i64, i64, i64, i64, iptr, iptr, i64, i64, bptr,
        iptr, iptr, i64, i64, fptr, i64]
    _LIB = lib
    return _LIB


def available() -> bool:
    return load() is not None


def _require() -> ctypes.CDLL:
    lib = load()
    if lib is None:
        raise NotImplementedError(
            "native quant library unavailable (no C++ compiler found); "
            "use the jnp path cpd_tpu.quant.float_quantize")
    return lib


def float_quantize_np(x: np.ndarray, exp: int, man: int) -> np.ndarray:
    """Elementwise eXmY cast on host (numpy), any shape."""
    lib = _require()
    x = np.ascontiguousarray(x, np.float32)
    out = np.empty_like(x)
    lib.cpd_quantize(x.reshape(-1), out.reshape(-1), x.size, exp, man)
    return out


def quant_gemm_np(a: np.ndarray, b: np.ndarray, exp: int, man: int
                  ) -> np.ndarray:
    """a(M,K) @ b(K,N) with the faithful Kahan eXmY accumulator."""
    lib = _require()
    a = np.ascontiguousarray(a, np.float32)
    b = np.ascontiguousarray(b, np.float32)
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise ValueError(f"expected (M,K)x(K,N), got {a.shape} x {b.shape}")
    M, K = a.shape
    N = b.shape[1]
    out = np.empty((M, N), np.float32)
    lib.cpd_qgemm(a, b, out, M, N, K, exp, man)
    return out


def ordered_sum_np(stacked: np.ndarray, exp: int, man: int,
                   kahan: bool = False) -> np.ndarray:
    """Rank-ordered quantized reduction over axis 0 of (W, ...)."""
    lib = _require()
    stacked = np.ascontiguousarray(stacked, np.float32)
    W = stacked.shape[0]
    n = stacked.size // max(W, 1)
    out = np.empty(stacked.shape[1:], np.float32)
    lib.cpd_ordered_sum(stacked.reshape(W, -1), out.reshape(-1), W, n,
                        exp, man, int(kahan))
    return out


def fused_augment_np(data: np.ndarray, indices: np.ndarray,
                     crop_y: np.ndarray, crop_x: np.ndarray,
                     oh: int, ow: int, flip: np.ndarray,
                     cut_y: Optional[np.ndarray] = None,
                     cut_x: Optional[np.ndarray] = None,
                     cut_h: int = 0, cut_w: int = 0,
                     n_threads: int = 0) -> np.ndarray:
    """Fused crop -> flip -> cutout over a padded fp32 NHWC dataset.

    `crop_*`/`flip`/`cut_*` are per-DATASET-sample pre-drawn choices
    (TransformPipeline.resample's layout); `indices` selects the batch.
    Bitwise identical to the numpy transform chain (pure copies/zeros).
    n_threads=0 -> hardware concurrency."""
    lib = _require()
    data = np.ascontiguousarray(data, np.float32)
    n_total, ih, iw, ch = data.shape
    indices = np.ascontiguousarray(indices, np.int64)
    b = indices.size
    if not cut_h:
        # unused by the kernel when cut_h == 0; a 1-element placeholder
        # satisfies the ctypes signature without an n_total-sized alloc
        cut_y = cut_x = np.zeros(1, np.int64)
    out = np.empty((b, oh, ow, ch), np.float32)
    lib.cpd_fused_augment(
        data.reshape(-1), indices, b, ih, iw, ch,
        np.ascontiguousarray(crop_y, np.int64),
        np.ascontiguousarray(crop_x, np.int64), oh, ow,
        np.ascontiguousarray(flip, np.uint8),
        np.ascontiguousarray(cut_y, np.int64),
        np.ascontiguousarray(cut_x, np.int64),
        cut_h, cut_w, out.reshape(-1), n_threads)
    return out
