// Native (host) eXmY numerics — the C++ counterpart of the reference's
// native layer (reference: CPDtorch/quant/quant_cuda/, 442 LoC of
// CUDA/C++; SURVEY.md C1-C5).  On TPU the *device* kernels are Pallas
// (cpd_tpu/ops/); this library serves the host side of the runtime:
//
//   * data-pipeline quantization (quantize training inputs / gradients on
//     host without a device round-trip),
//   * an independent, third implementation of the cast semantics used as a
//     cross-oracle in tests (jnp bit-twiddle vs NumPy transliteration vs
//     this), and
//   * host-side reference reductions for validating collectives.
//
// Semantics are the documented contract of cpd_tpu/quant/numerics.py
// (which mirrors float_kernel.cu:10-92 with its two UB deviations
// defined): RTNE at 23-man_bits, custom subnormals via truncating
// right-shift then RTNE, pre-rounding saturation to +/-Inf, FP32
// subnormal inputs flush to +0, Inf/NaN/+-0 passthrough.
//
// Build: cc -O2 -shared -fPIC (driven by cpd_tpu/native/__init__.py, the
// analog of the reference's JIT-at-import, quant_function.py:10-17).

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

inline uint32_t bits_of(float f) {
  uint32_t u;
  std::memcpy(&u, &f, sizeof(u));
  return u;
}

inline float float_of(uint32_t u) {
  float f;
  std::memcpy(&f, &u, sizeof(f));
  return f;
}

// Round-to-nearest-even of an integer significand at bit `shift`.
inline uint32_t rtne(uint32_t man, int shift) {
  if (shift <= 0) return man;
  const uint32_t half = 1u << (shift - 1);
  const uint32_t sticky_mask = half - 1u;
  const uint32_t keep_mask = ~((1u << shift) - 1u);
  const bool round_bit = (man & half) != 0;
  const bool sticky = (man & sticky_mask) != 0;
  const bool lsb = (man & (1u << shift)) != 0;
  if (round_bit && (sticky || lsb)) man += half;
  return man & keep_mask;
}

float cast_one(float x, int exp_bits, int man_bits) {
  const uint32_t u = bits_of(x);
  const int exp_f = (u >> 23) & 0xFF;
  const uint32_t man_f = u & 0x007FFFFFu;
  const bool negative = (u >> 31) != 0;

  if (exp_f == 0xFF || (exp_f == 0 && man_f == 0)) return x;  // Inf/NaN/+-0
  if (exp_f == 0) return 0.0f;  // FP32 subnormal input flushes to +0

  const int bias = (1 << (exp_bits - 1)) - 1;
  uint32_t man24 = man_f | (1u << 23);
  const int new_e = exp_f - 127 + bias;

  // Pre-rounding saturation (mantissa round-up past max still carries
  // into the exponent instead of saturating — deliberate, see
  // numerics.py docstring).
  if (new_e >= (1 << exp_bits) - 1) {
    return negative ? -INFINITY : INFINITY;
  }

  const int shift = 23 - man_bits;
  uint32_t man_out;
  int e_out;
  if (new_e > 0) {                      // normal target
    man_out = rtne(man24, shift);
    e_out = exp_f - 127;
  } else {                              // subnormal target
    int sub_shift = 1 - new_e;
    if (sub_shift > 24) sub_shift = 24;   // man24 < 2^24
    man24 >>= sub_shift;                  // truncating (double-round quirk)
    // man_bits == 23 => no rounding (deviation 1: defined, not UB)
    man_out = (man_bits == 23) ? man24 : rtne(man24, shift);
    e_out = 1 - bias;
  }

  // man * 2^(e-23); ldexpf is exact here (result is k * 2^(e-23) with
  // k < 2^25, representable whenever e-23 >= -149; below that the true
  // value rounds to 0 identically in both implementations).
  float mag = std::ldexp(static_cast<float>(man_out), e_out - 23);
  return negative ? -mag : mag;
}

}  // namespace

extern "C" {

float cpd_cast_one(float x, int exp_bits, int man_bits) {
  return cast_one(x, exp_bits, man_bits);
}

// Elementwise quantize (reference float_kernel_nearest, float_kernel.cu:
// 94-101 — pure here: in/out may alias but need not).
void cpd_quantize(const float* in, float* out, int64_t n, int exp_bits,
                  int man_bits) {
  for (int64_t i = 0; i < n; ++i) out[i] = cast_one(in[i], exp_bits, man_bits);
}

// GEMM out = a(M,K) @ b(K,N) with eXmY Kahan accumulator: the faithful
// recipe of quant_function.quant_gemm (tmp/y/t/c all re-cast, K visited
// in ascending order; zero-initialized residual — the reference edge
// path's uninitialized residual is UB, not semantics).
void cpd_qgemm(const float* a, const float* b, float* out, int64_t M,
               int64_t N, int64_t K, int exp_bits, int man_bits) {
  for (int64_t i = 0; i < M; ++i) {
    for (int64_t j = 0; j < N; ++j) {
      float s = 0.0f, c = 0.0f;
      for (int64_t k = 0; k < K; ++k) {
        const float tmp = cast_one(a[i * K + k] * b[k * N + j], exp_bits,
                                   man_bits);
        const float y = cast_one(tmp - c, exp_bits, man_bits);
        const float t = cast_one(s + y, exp_bits, man_bits);
        c = cast_one(cast_one(t - s, exp_bits, man_bits) - y, exp_bits,
                     man_bits);
        s = t;
      }
      out[i * N + j] = s;
    }
  }
}

// Ordered quantized reduction over the leading axis of stacked (W, n):
// res = q(res + g_r) in rank order (parallel/reduction.py
// ordered_quantized_sum; reference dist_util.py:60-69), or the Kahan
// variant (dist_util.py:72-89) when kahan != 0.
void cpd_ordered_sum(const float* stacked, float* out, int64_t W, int64_t n,
                     int exp_bits, int man_bits, int kahan) {
  if (kahan) {
    for (int64_t i = 0; i < n; ++i) {
      float res = 0.0f, c = 0.0f;
      for (int64_t r = 0; r < W; ++r) {
        const float g = stacked[r * n + i];
        const float y = cast_one(g - c, exp_bits, man_bits);
        const float t = cast_one(res + y, exp_bits, man_bits);
        c = cast_one(cast_one(t - res, exp_bits, man_bits) - y, exp_bits,
                     man_bits);
        res = t;
      }
      out[i] = res;
    }
  } else {
    for (int64_t i = 0; i < n; ++i) {
      float res = 0.0f;
      for (int64_t r = 0; r < W; ++r) {
        res = cast_one(res + stacked[r * n + i], exp_bits, man_bits);
      }
      out[i] = res;
    }
  }
}

}  // extern "C"
