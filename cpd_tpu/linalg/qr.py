"""Distributed one-sided QR on the quantized substrate: CholeskyQR2.

The TPU linear-algebra paper (PAPERS.md #3) runs one-sided
factorizations at pod scale because they need only GEMMs plus tiny
replicated host-sized factors — exactly the substrate this repo owns.
CholeskyQR2 (Yamamoto et al.) on tall-skinny ``A (m, nn)``, row-sharded
over one mesh axis:

    pass p = 1, 2:
        G_local = qgemm(A_p^T, A_p)            # quantized Kahan Gram
        G       = quantized reduce over axis   # ring | gather transport
        L       = cholesky(G);  R_p = L^T      # fp32, replicated
        A_{p+1} = qgemm(A_p, R_p^{-1})         # quantized apply
    Q = A_3;  R = qgemm(R_2, R_1)

One pass is classic CholeskyQR — orthogonality error ~ kappa(A)^2 * u;
the second pass squares it away (u = the eXmY unit roundoff here, so
the per-format orthogonality frontier is measured and documented
rather than assumed — `qr_error_metrics`, docs/PERF.md "Quantized
linalg").

Every Gram partial is a `quant_gemm`-accumulated tile, and the ONLY
cross-device numerics is the quantized reduction of the (nn, nn) Gram
— the same ordered transports as the gradient wire, so
`cholesky_qr2_oracle` reproduces the distributed factorization
bit-for-bit on one device via `ring_oracle_sum` / the ordered scan
(the shared-helper doctrine of parallel/ring.py).  The small factors
(Cholesky, triangular inverse) are computed REPLICATED in fp32 on
identical inputs, so they cannot diverge across ranks.

Zero-padded tail rows contribute exact zeros to every Gram and stay
exactly zero through ``A @ R^{-1}`` — sliced off at the end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..quant.quant_function import qgemm
from ..parallel.reduction import quantized_sum
from ..parallel.ring import ring_oracle_sum, ring_quantized_sum

__all__ = ["cholesky_qr2", "cholesky_qr2_oracle", "qr_error_metrics",
           "QR_ORTHO_BOUNDS"]

# Documented per-format orthogonality bounds: ||Q^T Q - I||_F / sqrt(nn)
# after TWO passes at the benchmark probe scale (tall-skinny N(0,1),
# kappa ~ 1).  Measured in tools/bench_linalg.py --smoke (asserted),
# recorded in docs/PERF.md; ~2x worst measured.  Keyed (exp, man).
QR_ORTHO_BOUNDS = {
    (8, 23): 1e-6,     # measured ~1.4e-7
    (5, 7):  1e-2,     # measured ~4.4e-3
    (4, 3):  1e-1,     # measured ~4.6e-2
    (5, 2):  4e-1,     # measured ~2.2e-1
}

_SALT_GRAM, _SALT_APPLY, _SALT_REDUCE = 0, 1, 2


def _pass_key(key, p: int, salt: int):
    if key is None:
        return None
    return jax.random.fold_in(jax.random.fold_in(key, salt), p)


def _gram_local(a_loc: jnp.ndarray, exp: int, man: int, key, rounding,
                gemm_mode: str) -> jnp.ndarray:
    """One device's Gram partial A_loc^T @ A_loc via the quantized-Kahan
    gemm.  Symmetric by construction: entry (i, j) and (j, i) accumulate
    the same products in the same K order, and every cast is
    elementwise."""
    return qgemm(a_loc.T, a_loc, exp=exp, man=man, mode=gemm_mode,
                 rounding=rounding, key=key)


def _chol_rinv(g: jnp.ndarray):
    """(R, R^{-1}) from a replicated Gram: lower Cholesky in fp32, R =
    L^T, R^{-1} = (L^{-1})^T via a triangular solve against I.  Runs on
    inputs that are identical on every rank, so the factors are
    replicated bit-for-bit without any collective."""
    from jax.scipy.linalg import solve_triangular
    l = jnp.linalg.cholesky(g.astype(jnp.float32))
    eye = jnp.eye(g.shape[0], dtype=jnp.float32)
    linv = solve_triangular(l, eye, lower=True)
    return l.T, linv.T


def _validate(exp, man, rounding, key, reduce, block_scale):
    from .blockmm import _validate as v
    v(exp, man, rounding, key, reduce, block_scale)


def cholesky_qr2(a, mesh, exp: int, man: int, *, axis: str = "dp",
                 use_kahan: bool = False, rounding: str = "nearest",
                 key=None, reduce: str = "ring",
                 block_scale: bool = False, block_size: int = 128,
                 gemm_mode: str = "faithful", passes: int = 2):
    """Distributed CholeskyQR2 -> ``(q, r)`` with ``q`` (m, nn) and
    ``r`` (nn, nn) upper-triangular, ``q @ r ~= a``.

    Row-sharded over ``axis``; every Gram reduction rides the
    configured quantized transport (`ring_quantized_sum` or all_gather
    + ordered scan), plain/Kahan/SR/blocked all plumbed through.
    Bit-identical to `cholesky_qr2_oracle` with the same knobs."""
    from jax.sharding import PartitionSpec as P

    from ..compat import shard_map

    _validate(exp, man, rounding, key, reduce, block_scale)
    if passes < 1:
        raise ValueError(f"passes must be >= 1, got {passes}")
    a = jnp.asarray(a, jnp.float32)
    if a.ndim != 2:
        raise ValueError(f"cholesky_qr2 expects a 2D (m, nn) operand, "
                         f"got {a.shape}")
    m, nn = a.shape
    world = int(mesh.shape[axis])
    rows_loc = -(-m // world)
    a_pad = jnp.pad(a, ((0, world * rows_loc - m), (0, 0)))

    def body(a_blk):
        cur = a_blk[0]                              # (rows_loc, nn)
        rank = lax.axis_index(axis)
        r_total = None
        for p in range(passes):
            gk = _pass_key(key, p, _SALT_GRAM)
            if gk is not None:
                gk = jax.random.fold_in(gk, rank)
            g_part = _gram_local(cur, exp, man, gk, rounding, gemm_mode)
            rk = _pass_key(key, p, _SALT_REDUCE)
            if reduce == "ring":
                g = ring_quantized_sum(
                    g_part.reshape(-1), axis, exp, man,
                    use_kahan=use_kahan, key=rk, world=world,
                    block_scale=block_scale, block_size=block_size)
            else:
                stacked = lax.all_gather(g_part.reshape(-1), axis,
                                         axis=0, tiled=False)
                g = quantized_sum(
                    stacked, exp, man, use_kahan=use_kahan, key=rk,
                    block_size=block_size if block_scale else None)
            r_p, rinv = _chol_rinv(g.reshape(nn, nn))
            ak = _pass_key(key, p, _SALT_APPLY)
            if ak is not None:
                ak = jax.random.fold_in(ak, rank)
            cur = qgemm(cur, rinv, exp=exp, man=man, mode=gemm_mode,
                        rounding=rounding, key=ak)
            if r_total is None:
                r_total = r_p
            else:
                fk = _pass_key(key, p, _SALT_APPLY)
                if fk is not None:
                    fk = jax.random.fold_in(fk, jnp.int32(world))
                r_total = qgemm(r_p, r_total, exp=exp, man=man,
                                mode=gemm_mode, rounding=rounding, key=fk)
        return cur[None], r_total

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(axis),),
                           out_specs=(P(axis), P()), check_vma=False))
    q_blk, r = fn(a_pad.reshape(world, rows_loc, nn))
    return q_blk.reshape(world * rows_loc, nn)[:m], r


def cholesky_qr2_oracle(a, world: int, exp: int, man: int, *,
                        use_kahan: bool = False,
                        rounding: str = "nearest", key=None,
                        reduce: str = "ring", block_scale: bool = False,
                        block_size: int = 128,
                        gemm_mode: str = "faithful", passes: int = 2):
    """Single-device oracle for `cholesky_qr2`: identical per-rank Gram
    partials and factor math, the transport replaced by its oracle."""
    _validate(exp, man, rounding, key, reduce, block_scale)
    a = jnp.asarray(a, jnp.float32)
    m, nn = a.shape
    rows_loc = -(-m // world)
    a_pad = jnp.pad(a, ((0, world * rows_loc - m), (0, 0)))
    blocks = a_pad.reshape(world, rows_loc, nn)
    cur = [blocks[r] for r in range(world)]
    r_total = None
    for p in range(passes):
        parts = []
        for r in range(world):
            gk = _pass_key(key, p, _SALT_GRAM)
            if gk is not None:
                gk = jax.random.fold_in(gk, r)
            parts.append(_gram_local(cur[r], exp, man, gk, rounding,
                                     gemm_mode).reshape(-1))
        stacked = jnp.stack(parts)
        rk = _pass_key(key, p, _SALT_REDUCE)
        if reduce == "ring":
            g = ring_oracle_sum(stacked, exp, man, use_kahan=use_kahan,
                                key=rk, block_scale=block_scale,
                                block_size=block_size)
        else:
            g = quantized_sum(stacked, exp, man, use_kahan=use_kahan,
                              key=rk,
                              block_size=block_size if block_scale
                              else None)
        r_p, rinv = _chol_rinv(g.reshape(nn, nn))
        nxt = []
        for r in range(world):
            ak = _pass_key(key, p, _SALT_APPLY)
            if ak is not None:
                ak = jax.random.fold_in(ak, r)
            nxt.append(qgemm(cur[r], rinv, exp=exp, man=man,
                             mode=gemm_mode, rounding=rounding, key=ak))
        cur = nxt
        if r_total is None:
            r_total = r_p
        else:
            fk = _pass_key(key, p, _SALT_APPLY)
            if fk is not None:
                fk = jax.random.fold_in(fk, jnp.int32(world))
            r_total = qgemm(r_p, r_total, exp=exp, man=man,
                            mode=gemm_mode, rounding=rounding, key=fk)
    q = jnp.concatenate(cur, axis=0)[:m]
    return q, r_total


def qr_error_metrics(q, r, a) -> dict:
    """fp64 accuracy metrics of a computed factorization: normalized
    orthogonality ``||Q^T Q - I||_F / sqrt(nn)`` and relative residual
    ``||Q R - A||_F / ||A||_F`` — the two axes of the QR frontier."""
    import numpy as np
    q64 = np.asarray(q, np.float64)
    r64 = np.asarray(r, np.float64)
    a64 = np.asarray(a, np.float64)
    nn = q64.shape[1]
    ortho = np.linalg.norm(q64.T @ q64 - np.eye(nn)) / np.sqrt(nn)
    resid = np.linalg.norm(q64 @ r64 - a64) / max(np.linalg.norm(a64),
                                                  1e-30)
    return {"orthogonality": float(ortho), "residual": float(resid)}


def ir_programs(reg):
    """Registry declarations: CholeskyQR2's wire is exactly two Gram
    reductions of nn*nn elements per pass transport — priced by the
    same `ring_transport_bytes` analytics as the gradient ring, and
    bitwise-gated (the oracle-parity claim covers the whole
    factorization)."""
    from ..parallel.mesh import data_parallel_mesh
    from ..parallel.ring import ring_transport_bytes

    W, m, nn = 8, 64, 16
    deps = ("cpd_tpu.quant.quant_function", "cpd_tpu.parallel.reduction",
            "cpd_tpu.parallel.ring", "cpd_tpu.linalg.qr",
            "cpd_tpu.linalg.blockmm")

    def build():
        mesh = data_parallel_mesh()

        def run(a):
            return cholesky_qr2(a, mesh, 5, 7, axis="dp", reduce="ring")

        return run, (jax.ShapeDtypeStruct((m, nn), jnp.float32),)

    reg.declare("linalg.qr[cholqr2,ring,e5m7,w8]", build,
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: 2 * ring_transport_bytes(nn * nn, W, 5, 7))
