"""Sharded block matmul with the quantized-Kahan accumulator (ISSUE 15).

The first non-SGD workload riding the repo's two hard primitives: every
tile product runs through `quant_gemm`'s eXmY Kahan accumulator
(quant/quant_function.py — the reference CUDA kernel's numerics), and
every cross-device partial-sum reduction rides the SAME ordered
quantized transports the gradient all-reduce uses (`ring_quantized_sum`
/ `reduction.quantized_sum` — ring or gather, plain/Kahan/SR/blocked all
plumbed through).  Ground: the TPU linear-algebra paper (PAPERS.md #3)
— pods doing matmul/QR/eigensolves at scale — crossed with EQuARX's
quantized wire (PAPERS.md #2).

Layout (2D block-cyclic)
------------------------

``C = A @ B`` over a 2D device grid ``(grid_r, grid_c)`` on two mesh
axes (rows × K): A's row tiles are dealt CYCLICALLY over the grid rows
(tile ``i`` lives on row ``i % grid_r``) and its K tiles cyclically
over the grid columns (tile ``j`` on column ``j % grid_c``); B's K
tiles follow A's K assignment and are replicated across grid rows.  N
is not tiled — each tile product is one ``(tile_m, tile_k) @ (tile_k,
n)`` `quant_gemm`, so the gemm's ordered K scan stays long enough to
mean something.  Non-divisible edges are zero-padded to whole tiles
(exact zeros are rounding-invariant on every cast path, and padded
output rows are sliced off).

Accumulation order (the semantics, documented like the ring's)
--------------------------------------------------------------

1. inside a tile: `quant_gemm`'s ordered K scan (the reference Kahan
   recurrence, every intermediate re-cast);
2. across a device's OWN K tiles: `reduction.quantized_sum` in
   ascending local tile order (global tile ``j = c + grid_c*jj`` —
   ascending ``jj``);
3. across grid columns: the configured transport —
   ``reduce="ring"``: `ring_quantized_sum` over the column axis (the
   documented per-chunk rank rotation), ``reduce="gather"``:
   `all_gather` + the rank-ordered `quantized_sum` scan.

`block_matmul_oracle` reproduces all three levels bit-for-bit on one
device (the distributed path and the oracle share `_local_partial` and
the transport oracles — a divergence can only come from the wire,
exactly like `ring_oracle_sum`'s contract).  Accuracy vs the exact
fp64 product is a separate, measured claim: `matmul_rel_error` +
`REL_ERROR_BOUNDS` (asserted in tools/bench_linalg.py --smoke,
recorded in docs/PERF.md "Quantized linalg").
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..quant.quant_function import qgemm
from ..parallel.reduction import quantized_sum
from ..parallel.ring import ring_oracle_sum, ring_quantized_sum

__all__ = ["BlockLayout", "block_matmul", "block_matmul_oracle",
           "make_block_matmul_fn", "matmul_rel_error",
           "REL_ERROR_BOUNDS"]

# Documented per-format relative-error bounds (Frobenius, vs the fp64
# numpy oracle) at the benchmark probe scale — N(0,1) operands, K <=
# 256, Kahan or plain RTNE.  Measured in tools/bench_linalg.py --smoke
# (which asserts them) and recorded in docs/PERF.md; roughly 2x the
# worst measured value so a genuine numerics regression trips the gate
# but noise cannot.  Keyed (exp, man).
REL_ERROR_BOUNDS = {
    (8, 23): 1e-6,     # fp32 Kahan scan: ~ulp-level (measured ~7e-8)
    (5, 7):  1.2e-2,   # e5m7: 7 mantissa bits     (measured ~6e-3)
    (4, 3):  1.5e-1,   # e4m3                      (measured ~7e-2)
    (5, 2):  3e-1,     # e5m2: 2 mantissa bits     (measured ~1.4e-1)
}

# fold_in salts separating the SR bitstreams of the three accumulation
# levels (tile gemm / local K-tile scan / cross-device transport)
_SALT_GEMM, _SALT_LOCAL, _SALT_REDUCE = 0, 1, 2


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


@dataclasses.dataclass(frozen=True)
class BlockLayout:
    """Static 2D block-cyclic layout for ``(m, k) @ (k, n)`` over a
    ``(grid_r, grid_c)`` device grid with ``(tile_m, tile_k)`` tiles.

    Derived fields give the padded extents and per-device tile counts;
    `pack_a`/`pack_b`/`unpack_c` are pure reshape/transpose/pad maps
    between the logical operands and the device-major layout shard_map
    shards contiguously (the cyclic deal happens in the transpose)."""
    m: int
    k: int
    n: int
    grid_r: int
    grid_c: int
    tile_m: int
    tile_k: int

    def __post_init__(self):
        if min(self.m, self.k, self.n) < 1:
            raise ValueError(f"degenerate operand shape "
                             f"({self.m}, {self.k}, {self.n})")
        if min(self.tile_m, self.tile_k) < 1:
            raise ValueError(f"tiles must be >= 1, got "
                             f"({self.tile_m}, {self.tile_k})")
        if min(self.grid_r, self.grid_c) < 1:
            raise ValueError(f"grid must be >= 1x1, got "
                             f"({self.grid_r}, {self.grid_c})")

    # -- derived extents --------------------------------------------------

    @property
    def row_tiles(self) -> int:
        return _ceil_div(self.m, self.tile_m)

    @property
    def k_tiles(self) -> int:
        return _ceil_div(self.k, self.tile_k)

    @property
    def tiles_per_row_dev(self) -> int:
        return _ceil_div(self.row_tiles, self.grid_r)

    @property
    def tiles_per_col_dev(self) -> int:
        return _ceil_div(self.k_tiles, self.grid_c)

    @property
    def m_pad(self) -> int:
        return self.grid_r * self.tiles_per_row_dev * self.tile_m

    @property
    def k_pad(self) -> int:
        return self.grid_c * self.tiles_per_col_dev * self.tile_k

    @property
    def partial_elems(self) -> int:
        """Flat element count of one device's C partial — the vector
        the column-axis transport reduces (the wire-ledger quantum)."""
        return self.tiles_per_row_dev * self.tile_m * self.n

    # -- packing ----------------------------------------------------------

    def pack_a(self, a: jnp.ndarray) -> jnp.ndarray:
        """(m, k) -> (grid_r, grid_c, tpr, tpc, tile_m, tile_k), row
        tile ``i`` at grid row ``i % grid_r`` slot ``i // grid_r`` (and
        the K mirror) — the cyclic deal as a transpose."""
        if a.shape != (self.m, self.k):
            raise ValueError(f"A must be ({self.m}, {self.k}), "
                             f"got {a.shape}")
        tpr, tpc = self.tiles_per_row_dev, self.tiles_per_col_dev
        pad = jnp.pad(jnp.asarray(a, jnp.float32),
                      ((0, self.m_pad - self.m), (0, self.k_pad - self.k)))
        t = pad.reshape(tpr, self.grid_r, self.tile_m,
                        tpc, self.grid_c, self.tile_k)
        return t.transpose(1, 4, 0, 3, 2, 5)

    def pack_b(self, b: jnp.ndarray) -> jnp.ndarray:
        """(k, n) -> (grid_c, tpc, tile_k, n): K tiles cyclic over grid
        columns, replicated across grid rows."""
        if b.shape != (self.k, self.n):
            raise ValueError(f"B must be ({self.k}, {self.n}), "
                             f"got {b.shape}")
        tpc = self.tiles_per_col_dev
        pad = jnp.pad(jnp.asarray(b, jnp.float32),
                      ((0, self.k_pad - self.k), (0, 0)))
        return pad.reshape(tpc, self.grid_c, self.tile_k,
                           self.n).transpose(1, 0, 2, 3)

    def unpack_c(self, c_dev: jnp.ndarray) -> jnp.ndarray:
        """(grid_r, tpr, tile_m, n) device-major partials -> (m, n)."""
        tpr = self.tiles_per_row_dev
        out = c_dev.reshape(self.grid_r, tpr, self.tile_m, self.n)
        out = out.transpose(1, 0, 2, 3).reshape(self.m_pad, self.n)
        return out[:self.m]


def _validate(exp: int, man: int, rounding: str, key, reduce: str,
              block_scale: bool) -> None:
    if reduce not in ("ring", "gather"):
        raise ValueError(f"unknown reduce transport {reduce!r} "
                         f"(ring | gather)")
    if rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown rounding mode: {rounding!r}")
    if rounding == "stochastic" and key is None:
        raise ValueError("rounding='stochastic' requires a PRNG key")
    if rounding == "nearest" and key is not None:
        raise ValueError("a PRNG key was passed but rounding='nearest' "
                         "would ignore it; did you mean "
                         "rounding='stochastic'?")
    if block_scale and (exp, man) == (8, 23):
        raise ValueError("block_scale=True at (8, 23): the fp32 partial "
                         "has nothing to scale")


def _local_partial(a_rc: jnp.ndarray, b_c: jnp.ndarray, exp: int,
                   man: int, *, use_kahan: bool, key, rounding: str,
                   gemm_mode: str) -> jnp.ndarray:
    """One device's C partial: per-tile `quant_gemm` products, then the
    ordered quantized scan across the device's own K tiles (ascending
    local tile order).  Shared verbatim by the sharded path and the
    oracle — level 1+2 of the documented accumulation order.

    ``key`` is the device's rank-folded base key (None = RTNE)."""
    tpr, tpc = a_rc.shape[0], a_rc.shape[1]
    rows = []
    for ii in range(tpr):
        prods = []
        for jj in range(tpc):
            kk = None
            if key is not None:
                kk = jax.random.fold_in(jax.random.fold_in(
                    jax.random.fold_in(key, _SALT_GEMM), ii), jj)
            prods.append(qgemm(a_rc[ii, jj], b_c[jj], exp=exp, man=man,
                               mode=gemm_mode, rounding=rounding, key=kk))
        stacked = jnp.stack(prods)                 # (tpc, tile_m, n)
        k_row = None
        if key is not None:
            k_row = jax.random.fold_in(
                jax.random.fold_in(key, _SALT_LOCAL), ii)
        rows.append(quantized_sum(stacked, exp, man, use_kahan=use_kahan,
                                  key=k_row))
    return jnp.stack(rows)                          # (tpr, tile_m, n)


def make_block_matmul_fn(mesh, layout: BlockLayout, exp: int, man: int,
                         *, row_axis: str = "dp", col_axis: str = "tp",
                         use_kahan: bool = False,
                         rounding: str = "nearest", key=None,
                         reduce: str = "ring",
                         block_scale: bool = False,
                         block_size: int = 128,
                         gemm_mode: str = "faithful"):
    """Build the jitted sharded matmul ``(a_packed, b_packed) ->
    c_device_major`` for one static configuration.

    `block_matmul` is the pack/unpack convenience wrapper; use the
    factory directly to amortize the compile across calls."""
    from jax.sharding import PartitionSpec as P

    from ..compat import shard_map

    _validate(exp, man, rounding, key, reduce, block_scale)
    if (mesh.shape[row_axis] != layout.grid_r
            or mesh.shape[col_axis] != layout.grid_c):
        raise ValueError(
            f"layout grid ({layout.grid_r}, {layout.grid_c}) != mesh "
            f"axes ({row_axis}={mesh.shape[row_axis]}, "
            f"{col_axis}={mesh.shape[col_axis]})")
    grid_c = layout.grid_c

    def body(a_loc, b_loc):
        a_rc = a_loc[0, 0]                  # (tpr, tpc, tile_m, tile_k)
        b_c = b_loc[0]                      # (tpc, tile_k, n)
        dev_key = None
        if key is not None:
            dev_key = jax.random.fold_in(
                jax.random.fold_in(key,
                                   lax.axis_index(row_axis)),
                lax.axis_index(col_axis))
        part = _local_partial(a_rc, b_c, exp, man, use_kahan=use_kahan,
                              key=dev_key, rounding=rounding,
                              gemm_mode=gemm_mode)
        flat = part.reshape(-1)
        red_key = None
        if key is not None:
            # transport bits must be identical on every rank of the
            # column ring (replicated output), so the reduce key folds
            # only the ROW index — see dist.sum_gradients' key doctrine
            red_key = jax.random.fold_in(
                jax.random.fold_in(key, _SALT_REDUCE),
                lax.axis_index(row_axis))
        if reduce == "ring":
            red = ring_quantized_sum(
                flat, col_axis, exp, man, use_kahan=use_kahan,
                key=red_key, world=grid_c, block_scale=block_scale,
                block_size=block_size)
        else:
            stacked = lax.all_gather(flat, col_axis, axis=0, tiled=False)
            red = quantized_sum(
                stacked, exp, man, use_kahan=use_kahan, key=red_key,
                block_size=block_size if block_scale else None)
        return red.reshape(part.shape)[None]        # (1, tpr, tile_m, n)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(row_axis, col_axis), P(col_axis)),
        out_specs=P(row_axis), check_vma=False))


def block_matmul(a, b, mesh, exp: int, man: int, *,
                 row_axis: str = "dp", col_axis: str = "tp",
                 tile_m: int = 128, tile_k: int = 128,
                 use_kahan: bool = False,
                 rounding: str = "nearest", key=None,
                 reduce: str = "ring", block_scale: bool = False,
                 block_size: int = 128,
                 gemm_mode: str = "faithful",
                 layout: Optional[BlockLayout] = None) -> jnp.ndarray:
    """Sharded quantized ``a @ b`` (module docstring) -> (m, n) fp32.

    Bit-identical to ``block_matmul_oracle`` with the same layout and
    knobs; `matmul_rel_error` vs the fp64 product stays within
    `REL_ERROR_BOUNDS[(exp, man)]` at the documented probe scale."""
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise ValueError(f"block_matmul expects (M,K)x(K,N); got "
                         f"{a.shape} x {b.shape}")
    if layout is None:
        layout = BlockLayout(a.shape[0], a.shape[1], b.shape[1],
                             int(mesh.shape[row_axis]),
                             int(mesh.shape[col_axis]),
                             tile_m, tile_k)
    fn = make_block_matmul_fn(
        mesh, layout, exp, man, row_axis=row_axis, col_axis=col_axis,
        use_kahan=use_kahan, rounding=rounding, key=key, reduce=reduce,
        block_scale=block_scale, block_size=block_size,
        gemm_mode=gemm_mode)
    c_dev = fn(layout.pack_a(a), layout.pack_b(b))
    return layout.unpack_c(c_dev)


def block_matmul_oracle(a, b, layout: BlockLayout, exp: int, man: int, *,
                        use_kahan: bool = False,
                        rounding: str = "nearest", key=None,
                        reduce: str = "ring", block_scale: bool = False,
                        block_size: int = 128,
                        gemm_mode: str = "faithful") -> jnp.ndarray:
    """Single-device oracle for `block_matmul`: same tile assignment,
    same per-tile gemms, same local scans, and the transport replaced
    by its own oracle (`ring_oracle_sum` / the ordered `quantized_sum`
    scan) — everything except the wire, bit-for-bit."""
    _validate(exp, man, rounding, key, reduce, block_scale)
    ap = layout.pack_a(jnp.asarray(a, jnp.float32))
    bp = layout.pack_b(jnp.asarray(b, jnp.float32))
    rows = []
    for r in range(layout.grid_r):
        parts = []
        for c in range(layout.grid_c):
            dev_key = None
            if key is not None:
                dev_key = jax.random.fold_in(
                    jax.random.fold_in(key, r), c)
            parts.append(_local_partial(
                ap[r, c], bp[c], exp, man, use_kahan=use_kahan,
                key=dev_key, rounding=rounding,
                gemm_mode=gemm_mode).reshape(-1))
        stacked = jnp.stack(parts)              # (grid_c, partial_elems)
        red_key = None
        if key is not None:
            red_key = jax.random.fold_in(
                jax.random.fold_in(key, _SALT_REDUCE), r)
        if reduce == "ring":
            red = ring_oracle_sum(stacked, exp, man, use_kahan=use_kahan,
                                  key=red_key, block_scale=block_scale,
                                  block_size=block_size)
        else:
            red = quantized_sum(
                stacked, exp, man, use_kahan=use_kahan, key=red_key,
                block_size=block_size if block_scale else None)
        rows.append(red.reshape(layout.tiles_per_row_dev, layout.tile_m,
                                layout.n))
    return layout.unpack_c(jnp.stack(rows))


def matmul_rel_error(c, a, b) -> float:
    """Relative Frobenius error of ``c`` vs the fp64 numpy product —
    the accuracy axis of the linalg frontier (docs/PERF.md)."""
    import numpy as np
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    ref = a64 @ b64
    denom = float(np.linalg.norm(ref))
    if denom == 0.0:
        return float(np.linalg.norm(np.asarray(c, np.float64)))
    return float(np.linalg.norm(np.asarray(c, np.float64) - ref) / denom)


def ir_programs(reg):
    """Program-contract declarations (analysis/ir/registry.py): the
    sharded matmul's transports are priced by the SAME analytics as the
    gradient wire — the ring arm must byte-match `ring_transport_bytes`
    of one device's flat partial, the gather arm
    `gather_transport_bytes` — and both arms are bitwise-gated (the
    oracle-parity claim), so an ulp-unstable primitive or a stray fp32
    debug gather fails lint before it fails a bitwise test."""
    from jax.sharding import PartitionSpec as P

    from ..compat import shard_map
    from ..parallel.mesh import make_mesh
    from ..parallel.ring import gather_transport_bytes, ring_transport_bytes

    R, C = 1, 8
    lay = BlockLayout(m=32, k=64, n=16, grid_r=R, grid_c=C,
                      tile_m=16, tile_k=8)
    deps = ("cpd_tpu.quant.quant_function", "cpd_tpu.parallel.reduction",
            "cpd_tpu.parallel.ring", "cpd_tpu.linalg.blockmm")

    def _mm(reduce, exp, man, use_kahan=False):
        def build():
            mesh = make_mesh(dp=R, tp=C)
            fn = make_block_matmul_fn(
                mesh, lay, exp, man, reduce=reduce, use_kahan=use_kahan)
            args = (jax.ShapeDtypeStruct(
                        (R, C, lay.tiles_per_row_dev,
                         lay.tiles_per_col_dev, lay.tile_m, lay.tile_k),
                        jnp.float32),
                    jax.ShapeDtypeStruct(
                        (C, lay.tiles_per_col_dev, lay.tile_k, lay.n),
                        jnp.float32))
            return fn, args
        return build

    n_flat = lay.partial_elems
    reg.declare("linalg.matmul[ring,e5m2,g1x8]", _mm("ring", 5, 2),
                deps=deps, axis_sizes={"dp": R, "tp": C}, bitwise=True,
                wire=lambda: ring_transport_bytes(n_flat, C, 5, 2))
    reg.declare("linalg.matmul[gather,e4m3,kahan,g1x8]",
                _mm("gather", 4, 3, use_kahan=True),
                deps=deps, axis_sizes={"dp": R, "tp": C}, bitwise=True,
                wire=lambda: gather_transport_bytes(n_flat, C, 4, 3,
                                                    compressed=False))
