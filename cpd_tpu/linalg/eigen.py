"""Power iteration and Lanczos top-k on the quantized matvec substrate,
plus the PSD inverse-root preconditioner math Shampoo-lite rides.

The iterative eigensolvers exercise the wire at a cadence training
never does: ONE quantized reduction per matvec, dozens of iterations,
with the iterate fed back through the quantized gemm every time — any
transport non-determinism compounds immediately, which is why each
solver is bit-gated against a single-device oracle exactly like the
ring (shared iteration cores; only the transport differs).

Substrate: symmetric ``S (nn, nn)`` COLUMN-sharded over one mesh axis.
``y = S x`` is computed as ``sum_c S[:, cols_c] x[cols_c]`` — each
device contributes a full-height partial from its column slab via
`qgemm` (the quantized-Kahan accumulator), and the partials reduce
over the configured quantized transport (`ring_quantized_sum` |
all_gather + ordered scan; plain/Kahan/SR/blocked plumbed through).
The scalar recurrences (Rayleigh quotients, norms, reorthogonalization)
run replicated in fp32 on identical inputs — sqrt and divide are
IEEE-exact, so they cannot diverge across ranks or programs (the
ir-bitwise doctrine: no exp2/log2/pow anywhere on this path).

`inv_root_psd` computes ``G^{-1/p}`` for p in {2, 4} via fp32 `eigh`
and a SQRT CHAIN (x^{-1/4} = 1/sqrt(sqrt(x))) — deliberately not
``pow``, which is the ulp-unstable primitive class the ir-bitwise rule
bans from bitwise-gated programs.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..quant.numerics import cast_to_format
from ..quant.quant_function import qgemm
from ..parallel.reduction import quantized_sum
from ..parallel.ring import ring_oracle_sum, ring_quantized_sum

__all__ = ["power_iteration", "power_iteration_oracle", "lanczos_topk",
           "lanczos_topk_oracle", "inv_root_psd", "EIG_REL_BOUNDS",
           "det_sum", "det_dot", "det_norm", "fence32"]

# Documented per-format relative error of the LEADING Ritz/power
# eigenvalue vs fp64 `numpy.linalg.eigvalsh`, at the benchmark probe
# scale (well-separated spectrum, nn <= 64).  Measured + asserted in
# tools/bench_linalg.py --smoke, recorded in docs/PERF.md.
EIG_REL_BOUNDS = {
    (8, 23): 1e-6,
    (5, 7):  5e-3,
    (4, 3):  8e-2,
    (5, 2):  3e-1,
}

_SALT_GEMM, _SALT_REDUCE = 0, 1


def _validate(exp, man, rounding, key, reduce, block_scale):
    from .blockmm import _validate as v
    v(exp, man, rounding, key, reduce, block_scale)


def _pad_cols(s: jnp.ndarray, world: int):
    """Pad symmetric (nn, nn) to (n_pad, n_pad), n_pad = world-multiple.
    Padded rows/cols are exact zeros: they contribute zero partials and
    keep the padded iterate entries exactly zero."""
    nn = s.shape[0]
    if s.ndim != 2 or s.shape[1] != nn:
        raise ValueError(f"expected a square (nn, nn) operand, got "
                         f"{s.shape}")
    cols = -(-nn // world)
    n_pad = world * cols
    return jnp.pad(jnp.asarray(s, jnp.float32),
                   ((0, n_pad - nn), (0, n_pad - nn))), cols, n_pad


def _slab_product(s_loc, x_slab, exp, man, key, rounding, gemm_mode):
    """One device's full-height matvec partial from its column slab —
    the quantized-Kahan gemm, shared by the sharded path and oracle."""
    return qgemm(s_loc, x_slab[:, None], exp=exp, man=man,
                 mode=gemm_mode, rounding=rounding, key=key)[:, 0]


def _it_key(key, it: int, salt: int):
    if key is None:
        return None
    return jax.random.fold_in(jax.random.fold_in(key, salt),
                              jnp.int32(it))


# ---------------------------------------------------------------------------
# Cross-program-deterministic fp32 scalar recurrences.
#
# The iteration cores' bitwise oracle gate compares values produced by
# TWO DIFFERENT compiled programs (the shard_map solver and its
# single-device oracle).  Two XLA:CPU behaviors are program-dependent
# at the last ulp and broke that gate (found mechanically by the gate
# itself): (a) `jnp.vdot`/`jnp.linalg.norm` pick their accumulation
# order per fusion context, and (b) LLVM contracts a multiply feeding
# an add/subtract into an FMA depending on how the surrounding program
# fused — `lax.optimization_barrier` does NOT survive to codegen, so
# it cannot stop (b).  The fixes are structural: every scalar
# reduction runs through `det_sum` — an EXPLICIT fixed binary tree of
# adds (XLA never reassociates written float adds) — and every product
# that feeds an add/subtract is fenced through `_fence`, the repo's
# own (8, 23) cast: a pile of integer-domain bit ops LLVM cannot
# contract a multiply through (and whose only value effect, the
# documented fp32-subnormal flush, is itself the canonicalization
# quant/numerics.py applies everywhere else).  Same doctrine as
# `aps.exp2_exact` (PR 12): cross-program bitwise contracts may not
# lean on lowering luck.
# ---------------------------------------------------------------------------


def fence32(x: jnp.ndarray) -> jnp.ndarray:
    """Contraction fence: the (8, 23) cast — value-preserving on every
    normal fp32 (subnormals flush to +0.0, the numerics.py
    canonicalization), routed through the integer domain so a fused
    consumer cannot FMA-contract the producing multiply."""
    return cast_to_format(x, 8, 23)


def det_sum(x: jnp.ndarray) -> jnp.ndarray:
    """Sum of ``x`` as an explicit zero-padded binary tree of adds —
    identical rounding in every program that computes it."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    if n == 0:
        return jnp.zeros([], x.dtype)
    p = 1 << (n - 1).bit_length() if n > 1 else 1
    flat = jnp.pad(flat, (0, p - n))
    while flat.shape[0] > 1:
        flat = flat[0::2] + flat[1::2]
    return flat[0]


def det_dot(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """<x, y> with the elementwise product fenced from the reduction
    (no FMA contraction) and the `det_sum` tree order."""
    return det_sum(fence32(x * y))


def det_norm(x: jnp.ndarray) -> jnp.ndarray:
    """||x||_2 via `det_dot`; sqrt is IEEE-exact, so the whole norm is
    cross-program deterministic."""
    return jnp.sqrt(det_dot(x, x))


def _axpy_sub(y: jnp.ndarray, s: jnp.ndarray, u: jnp.ndarray):
    """``y - s * u`` with the product fenced (module comment above —
    no program-dependent FMA contraction)."""
    return y - fence32(s * u)


def _power_core(matvec, x0, iters: int):
    """The one power-iteration recurrence (fp32 normalization; the
    matvec carries all quantization).  Shared by sharded + oracle."""
    x = x0 / det_norm(x0)
    lam = jnp.zeros([], jnp.float32)
    for it in range(iters):
        y = matvec(x, it)
        lam = det_dot(x, y)
        x = y / det_norm(y)
    return lam, x


def _lanczos_core(matvec, v0, steps: int, reorth: bool):
    """The one Lanczos recurrence (full reorthogonalization in a fixed
    ascending basis order when ``reorth``).  Returns the Ritz values
    DESCENDING and the matching Ritz vectors."""
    v = v0 / det_norm(v0)
    vs = [v]
    alphas, betas = [], []
    v_prev = jnp.zeros_like(v)
    beta_prev = jnp.zeros([], jnp.float32)
    for j in range(steps):
        w = matvec(vs[j], j)
        alpha = det_dot(w, vs[j])
        w = _axpy_sub(_axpy_sub(w, alpha, vs[j]), beta_prev, v_prev)
        if reorth:
            for u in vs:
                w = _axpy_sub(w, det_dot(w, u), u)
        beta = det_norm(w)
        alphas.append(alpha)
        betas.append(beta)
        v_prev = vs[j]
        beta_prev = beta
        # breakdown guard: an exactly-invariant Krylov space (or a
        # fully-flushed residual) gives beta == 0 — dividing would put
        # NaN in every later Ritz value silently.  The guarded basis
        # vector is zero, so later alphas/betas are zero rows of T and
        # the already-converged Ritz values survive finite.  Normal
        # path bitwise unchanged: beta > tiny selects w / beta exactly.
        safe = jnp.maximum(beta, jnp.float32(1e-38))
        vs.append(jnp.where(beta > 0.0, w / safe, jnp.zeros_like(w)))
    t = jnp.diag(jnp.stack(alphas))
    if steps > 1:               # steps == 1: T is the 1x1 [alpha_0]
        off = jnp.stack(betas[:-1])
        t = t + jnp.diag(off, 1) + jnp.diag(off, -1)
    evals, evecs = jnp.linalg.eigh(t)
    # Ritz vectors composed as explicit fenced axpy chains instead of a
    # dot_general: a small matmul's codegen (and FMA use) is fusion-
    # context-dependent on CPU — same cross-program concern as det_dot
    cols = []
    for i in range(steps):
        col = jnp.zeros_like(vs[0])
        for j in range(steps):
            col = col + fence32(evecs[j, i] * vs[j])
        cols.append(col)
    return evals[::-1], jnp.stack(cols[::-1], axis=1)


def _default_v0(n_pad: int) -> jnp.ndarray:
    """Deterministic dense start vector (no PRNG: the SR keys belong to
    the casts) — strictly positive, non-uniform, so it is never
    orthogonal to a Perron-like leading eigenvector and never aliases a
    coordinate axis.  Built from EXACT fp32 arithmetic only (mod,
    scale by 2^-6, add-below-1): ``arange(n) / n`` for non-power-of-2
    ``n`` rounds, and XLA constant-folds that division exactly while
    runtime codegen reciprocal-multiplies it — a 1-ulp cross-program
    divergence the W=2 oracle gate caught."""
    i = jnp.arange(n_pad, dtype=jnp.float32)
    return 1.0 + jnp.mod(i, 64.0) * jnp.float32(1.0 / 64.0)


def _sharded_solver(s, mesh, axis, world, exp, man, use_kahan, rounding,
                    key, reduce, block_scale, block_size, gemm_mode,
                    core):
    """Common scaffolding: pad/pack S, build the distributed matvec,
    run ``core(matvec, n_pad)`` inside one shard_map program."""
    from jax.sharding import PartitionSpec as P

    from ..compat import shard_map

    _validate(exp, man, rounding, key, reduce, block_scale)
    s_pad, cols, n_pad = _pad_cols(jnp.asarray(s, jnp.float32), world)
    # (world, n_pad, cols): device c's column slab S[:, c*cols:(c+1)*cols]
    packed = s_pad.reshape(n_pad, world, cols).transpose(1, 0, 2)

    def body(s_blk):
        s_loc = s_blk[0]                            # (n_pad, cols)
        rank = lax.axis_index(axis)

        def matvec(x, it):
            x_slab = lax.dynamic_slice(x, (rank * cols,), (cols,))
            gk = _it_key(key, it, _SALT_GEMM)
            if gk is not None:
                gk = jax.random.fold_in(gk, rank)
            part = _slab_product(s_loc, x_slab, exp, man, gk, rounding,
                                 gemm_mode)
            rk = _it_key(key, it, _SALT_REDUCE)
            if reduce == "ring":
                return ring_quantized_sum(
                    part, axis, exp, man, use_kahan=use_kahan, key=rk,
                    world=world, block_scale=block_scale,
                    block_size=block_size)
            stacked = lax.all_gather(part, axis, axis=0, tiled=False)
            return quantized_sum(
                stacked, exp, man, use_kahan=use_kahan, key=rk,
                block_size=block_size if block_scale else None)

        return core(matvec, n_pad)

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(axis),),
                           out_specs=P(), check_vma=False))
    return fn(packed), n_pad


def _oracle_solver(s, world, exp, man, use_kahan, rounding, key, reduce,
                   block_scale, block_size, gemm_mode, core):
    """Single-device twin of `_sharded_solver`: same slabs, same keys,
    the transport replaced by its oracle."""
    _validate(exp, man, rounding, key, reduce, block_scale)
    s_pad, cols, n_pad = _pad_cols(jnp.asarray(s, jnp.float32), world)
    slabs = [s_pad[:, c * cols:(c + 1) * cols] for c in range(world)]

    def matvec(x, it):
        parts = []
        for c in range(world):
            gk = _it_key(key, it, _SALT_GEMM)
            if gk is not None:
                gk = jax.random.fold_in(gk, c)
            parts.append(_slab_product(
                slabs[c], x[c * cols:(c + 1) * cols], exp, man, gk,
                rounding, gemm_mode))
        stacked = jnp.stack(parts)
        rk = _it_key(key, it, _SALT_REDUCE)
        if reduce == "ring":
            return ring_oracle_sum(stacked, exp, man,
                                   use_kahan=use_kahan, key=rk,
                                   block_scale=block_scale,
                                   block_size=block_size)
        return quantized_sum(stacked, exp, man, use_kahan=use_kahan,
                             key=rk,
                             block_size=block_size if block_scale
                             else None)

    return core(matvec, n_pad), n_pad


def power_iteration(s, mesh, exp: int, man: int, *, iters: int = 16,
                    axis: str = "dp", v0=None, use_kahan: bool = False,
                    rounding: str = "nearest", key=None,
                    reduce: str = "ring", block_scale: bool = False,
                    block_size: int = 128, gemm_mode: str = "faithful"):
    """Distributed power iteration -> ``(eigval, eigvec)`` for the
    leading eigenpair of symmetric ``s``, every matvec riding the
    quantized wire.  Bit-identical to `power_iteration_oracle`."""
    world = int(mesh.shape[axis])

    def core(matvec, n_pad):
        x0 = _default_v0(n_pad) if v0 is None else _pad_v0(v0, n_pad)
        return _power_core(matvec, x0, iters)

    (lam, x), n_pad = _sharded_solver(
        s, mesh, axis, world, exp, man, use_kahan, rounding, key, reduce,
        block_scale, block_size, gemm_mode, core)
    return lam, x[:s.shape[0]]


def power_iteration_oracle(s, world: int, exp: int, man: int, *,
                           iters: int = 16, v0=None,
                           use_kahan: bool = False,
                           rounding: str = "nearest", key=None,
                           reduce: str = "ring",
                           block_scale: bool = False,
                           block_size: int = 128,
                           gemm_mode: str = "faithful"):
    def core(matvec, n_pad):
        x0 = _default_v0(n_pad) if v0 is None else _pad_v0(v0, n_pad)
        return _power_core(matvec, x0, iters)

    (lam, x), n_pad = _oracle_solver(
        s, world, exp, man, use_kahan, rounding, key, reduce,
        block_scale, block_size, gemm_mode, core)
    return lam, x[:s.shape[0]]


def _pad_v0(v0, n_pad: int) -> jnp.ndarray:
    v0 = jnp.asarray(v0, jnp.float32)
    return jnp.pad(v0, (0, n_pad - v0.shape[0]))


def _lanczos_steps(k: int, steps, nn: int) -> int:
    """Resolve + validate the Krylov depth: default 2k capped at the
    matrix dimension (a Krylov space cannot exceed dim n, and running
    past it guarantees a breakdown step); explicit over-asks rejected
    loudly."""
    if steps is None:
        steps = min(2 * k, nn)
    if steps < k:
        raise ValueError(f"steps={steps} < k={k}: the Krylov basis "
                         f"cannot hold k Ritz pairs")
    if steps > nn:
        raise ValueError(f"steps={steps} > matrix dim {nn}: the Krylov "
                         f"space saturates at n — deeper iteration is "
                         f"a guaranteed breakdown")
    return steps


def lanczos_topk(s, mesh, exp: int, man: int, *, k: int,
                 steps: Optional[int] = None, axis: str = "dp", v0=None,
                 reorth: bool = True, use_kahan: bool = False,
                 rounding: str = "nearest", key=None,
                 reduce: str = "ring", block_scale: bool = False,
                 block_size: int = 128, gemm_mode: str = "faithful"):
    """Distributed Lanczos -> ``(ritz_vals (k,), ritz_vecs (nn, k))``:
    the top-k Ritz approximations of symmetric ``s`` after ``steps``
    (default ``min(2k, n)``) three-term iterations, one quantized-wire
    matvec each.  ``steps`` may exceed the per-device chunk edge ``n_pad /
    world`` — the pad/shard paths training shapes never hit (tested).
    Bit-identical to `lanczos_topk_oracle`."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    steps = _lanczos_steps(k, steps, s.shape[0] if hasattr(s, "shape")
                           else len(s))
    world = int(mesh.shape[axis])

    def core(matvec, n_pad):
        x0 = _default_v0(n_pad) if v0 is None else _pad_v0(v0, n_pad)
        return _lanczos_core(matvec, x0, steps, reorth)

    (vals, vecs), n_pad = _sharded_solver(
        s, mesh, axis, world, exp, man, use_kahan, rounding, key, reduce,
        block_scale, block_size, gemm_mode, core)
    return vals[:k], vecs[:s.shape[0], :k]


def lanczos_topk_oracle(s, world: int, exp: int, man: int, *, k: int,
                        steps: Optional[int] = None, v0=None,
                        reorth: bool = True, use_kahan: bool = False,
                        rounding: str = "nearest", key=None,
                        reduce: str = "ring", block_scale: bool = False,
                        block_size: int = 128,
                        gemm_mode: str = "faithful"):
    steps = _lanczos_steps(k, steps, s.shape[0] if hasattr(s, "shape")
                           else len(s))

    def core(matvec, n_pad):
        x0 = _default_v0(n_pad) if v0 is None else _pad_v0(v0, n_pad)
        return _lanczos_core(matvec, x0, steps, reorth)

    (vals, vecs), n_pad = _oracle_solver(
        s, world, exp, man, use_kahan, rounding, key, reduce,
        block_scale, block_size, gemm_mode, core)
    return vals[:k], vecs[:s.shape[0], :k]


def inv_root_psd(g, p: int = 4, eps: float = 1e-6) -> jnp.ndarray:
    """``(G + ridge I)^{-1/p}`` for a symmetric PSD ``G``, p in {2, 4}.

    fp32 `eigh`, eigenvalues floored at zero, a relative ridge
    ``eps * max(lambda_max, 1e-16)``, and the inverse root taken as a
    SQRT CHAIN (1/sqrt(x), 1/sqrt(sqrt(x))) — `pow` is the ulp-unstable
    primitive class banned from bitwise-gated programs (ir-bitwise),
    and Shampoo-lite's ×2-determinism gate runs straight through here.
    Runs replicated on identical inputs; no collective."""
    if p not in (2, 4):
        raise ValueError(f"p must be 2 or 4 (sqrt-chain exactness; pow "
                         f"is ulp-unstable), got {p}")
    g = jnp.asarray(g, jnp.float32)
    w, v = jnp.linalg.eigh(g)
    wmax = jnp.maximum(w[-1], 0.0)
    ridge = jnp.float32(eps) * jnp.maximum(wmax, jnp.float32(1e-16))
    wc = jnp.maximum(w, 0.0) + ridge
    root = jnp.sqrt(wc) if p == 2 else jnp.sqrt(jnp.sqrt(wc))
    return (v / root) @ v.T


def ir_programs(reg):
    """Registry declarations: the iterative solvers put one quantized
    reduction on the wire PER MATVEC — the ledger prices exactly
    ``iters x`` the single-reduction analytics, so a solver that grows
    a second hidden collective per iteration (or drops its packed wire)
    fails `ir-wire-ledger` immediately."""
    from ..parallel.mesh import data_parallel_mesh
    from ..parallel.ring import ring_transport_bytes

    W, nn = 8, 32
    n_pad = W * (-(-nn // W))
    deps = ("cpd_tpu.quant.quant_function", "cpd_tpu.parallel.reduction",
            "cpd_tpu.parallel.ring", "cpd_tpu.linalg.eigen",
            "cpd_tpu.linalg.blockmm")

    def _power(iters):
        def build():
            mesh = data_parallel_mesh()

            def run(s):
                return power_iteration(s, mesh, 5, 2, iters=iters,
                                       axis="dp", reduce="ring")

            return run, (jax.ShapeDtypeStruct((nn, nn), jnp.float32),)
        return build

    def _lanczos(k, steps):
        def build():
            mesh = data_parallel_mesh()

            def run(s):
                return lanczos_topk(s, mesh, 5, 2, k=k, steps=steps,
                                    axis="dp", reduce="ring")

            return run, (jax.ShapeDtypeStruct((nn, nn), jnp.float32),)
        return build

    reg.declare("linalg.power[ring,e5m2,w8,it3]", _power(3),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: 3 * ring_transport_bytes(n_pad, W, 5, 2))
    reg.declare("linalg.lanczos[ring,e5m2,w8,s4]", _lanczos(2, 4),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: 4 * ring_transport_bytes(n_pad, W, 5, 2))
