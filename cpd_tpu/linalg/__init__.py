"""cpd_tpu.linalg — quantized distributed linear algebra (ISSUE 15).

A new workload class beside `parallel/` and `train/`: dense linear
algebra whose every GEMM runs through the quantized-Kahan eXmY
accumulator (`quant.quant_function.qgemm`) and whose every cross-device
reduction rides the ordered quantized transports of the gradient wire
(`parallel.ring` / `parallel.reduction` — ring or gather, with the
plain/Kahan/SR/block-scaled variants all plumbed through).  Each
algorithm ships with a single-device oracle reproducing the
distributed result BIT-FOR-BIT (shared numerics helpers; only the
transport differs — the `ring_oracle_sum` doctrine), plus measured
accuracy bounds vs fp64 oracles (docs/PERF.md "Quantized linalg").

Modules:

* `blockmm` — 2D block-cyclic sharded matmul (`block_matmul`).
* `qr`      — distributed CholeskyQR2 (`cholesky_qr2`).
* `eigen`   — power iteration / Lanczos top-k (`power_iteration`,
  `lanczos_topk`) and the `inv_root_psd` preconditioner root that
  Shampoo-lite (train/optim.py) applies to its quantized statistics.

Ground: PAPERS.md #3 (TPU distributed linear algebra) × #2 (EQuARX
quantized collectives).  Docs: docs/LINALG.md.
"""

from .blockmm import (BlockLayout, REL_ERROR_BOUNDS, block_matmul,
                      block_matmul_oracle, make_block_matmul_fn,
                      matmul_rel_error)
from .eigen import (EIG_REL_BOUNDS, inv_root_psd, lanczos_topk,
                    lanczos_topk_oracle, power_iteration,
                    power_iteration_oracle)
from .qr import (QR_ORTHO_BOUNDS, cholesky_qr2, cholesky_qr2_oracle,
                 qr_error_metrics)

__all__ = [
    "BlockLayout",
    "block_matmul",
    "block_matmul_oracle",
    "make_block_matmul_fn",
    "matmul_rel_error",
    "REL_ERROR_BOUNDS",
    "cholesky_qr2",
    "cholesky_qr2_oracle",
    "qr_error_metrics",
    "QR_ORTHO_BOUNDS",
    "power_iteration",
    "power_iteration_oracle",
    "lanczos_topk",
    "lanczos_topk_oracle",
    "inv_root_psd",
    "EIG_REL_BOUNDS",
]
