"""cpd_tpu.obs — unified tracing, metrics and the crash flight recorder.

The observability spine (L2.5: below train/serve, above utils;
docs/OBSERVABILITY.md).  Four legs, all pure host-side observation —
nothing here may touch a value that feeds a jitted program, which is
what makes "obs on == obs off, bitwise" a structural property rather
than a hope (pinned in tests/test_obs.py and the obs-smoke CI gate):

* `trace.Tracer` — nested spans + instant events on the step clock AND
  the wall clock; `NULL_TRACER` / ``tracer is None`` is the zero-cost
  disabled path.
* `registry.MetricsRegistry` — counters/gauges/histograms with labels,
  plus adapters that absorb every legacy telemetry surface
  (ResilienceMeter, ``prec_wire_*``/``reduce_*`` step metrics, the
  three supervisors, the serve engine counters) so every number has
  one home and one name.
* `export` — deterministic JSONL, Prometheus text exposition (+ the
  minimal `parse_prometheus` checker), and Chrome-trace-event JSON
  (Perfetto/TensorBoard-loadable); `write_all` is the one-call bundle.
* `flight.FlightRecorder` — a bounded ring of recent events dumped on
  watchdog fire, rollback, preemption and serve snapshots.
* `timing` — the ONE monotonic wall-clock helper every timer in the
  repo now rides (`now`, `Stopwatch`, `Timer`).

Stdlib-only on purpose: ``import cpd_tpu.obs`` must stay cheap enough
for CLIs to wire before jax loads (the same discipline as
cpd_tpu/utils).
"""

from .export import (export_chrome_trace, export_jsonl,
                     export_prometheus, merge_chrome_traces,
                     parse_prometheus, write_all)
from .flight import FlightRecorder
from .registry import MetricsRegistry
from .timing import Stopwatch, Timer, now
from .trace import NULL_TRACER, Span, Tracer

__all__ = ["Tracer", "Span", "NULL_TRACER", "MetricsRegistry",
           "FlightRecorder", "export_jsonl", "export_prometheus",
           "export_chrome_trace", "merge_chrome_traces",
           "parse_prometheus", "write_all",
           "now", "Stopwatch", "Timer"]
