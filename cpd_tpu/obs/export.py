"""Exporters — one tracer + registry, three artifact formats (ISSUE 11
tentpole, leg 3):

* **JSONL event stream** (``events.jsonl``) — one JSON object per
  span/event, in ``seq`` order: the machine-diffable ground truth the
  determinism gate compares byte-for-byte.
* **Prometheus text exposition** (``metrics.prom``) — the registry
  rendered in the text format scrapers ingest; `parse_prometheus` is
  the minimal in-repo checker the tests and the obs-smoke gate run
  over it.
* **Chrome trace-event JSON** (``trace.json``) — loadable in Perfetto /
  chrome://tracing / TensorBoard's trace viewer: spans as complete
  ("X") events, instants as "i", per-request serve timelines threaded
  by rid so one request reads as one lane.

Determinism contract (pinned in tests/test_obs.py): a deterministic
run exported with ``strip_wall=True`` yields byte-identical JSONL and
Chrome-trace files across runs — every wall-clock-derived field
(``wall``, ``dur_s``, ``ts``, ``dur``) is either dropped or replaced by
the deterministic ``seq``/step clock.  With ``strip_wall=False``
(default) the real timings ride along for humans.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

__all__ = ["export_jsonl", "export_prometheus", "export_chrome_trace",
           "merge_chrome_traces", "parse_prometheus", "write_all"]


# ---------------------------------------------------------------------------
# JSONL
# ---------------------------------------------------------------------------

def _jsonl_records(tracer, strip_wall: bool):
    # the header carries no wall field (Tracer.summary is counts only),
    # so it is identical with or without strip_wall
    yield {"t": "meta", "run": tracer.run, "meta": tracer.meta,
           **tracer.summary()}
    rows = []
    for seq, name, cat, step, t0, dur, depth, args in tracer.spans:
        r = {"t": "span", "seq": seq, "name": name, "cat": cat,
             "step": step, "depth": depth}
        if not strip_wall:
            r["wall"] = t0
            r["dur_s"] = dur
        if args:
            r["args"] = args
        rows.append((seq, r))
    for seq, name, cat, step, wall, args in tracer.events:
        r = {"t": "event", "seq": seq, "name": name, "cat": cat,
             "step": step}
        if not strip_wall:
            r["wall"] = wall
        if args:
            r["args"] = args
        rows.append((seq, r))
    for _seq, r in sorted(rows, key=lambda x: x[0]):
        yield r


def export_jsonl(tracer, path: str, *, strip_wall: bool = False) -> str:
    """Write the span+event stream as sorted JSONL; returns `path`."""
    with open(path, "w", encoding="utf-8") as fh:
        for rec in _jsonl_records(tracer, strip_wall):
            fh.write(json.dumps(rec, sort_keys=True,
                                separators=(",", ":")) + "\n")
    return path


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _esc(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"') \
                .replace("\n", "\\n")


def _fmt_labels(labels: tuple, extra: tuple = ()) -> str:
    pairs = list(labels) + list(extra)
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in pairs) + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    # the exposition-format spellings for non-finite samples (a
    # diverged run's NaN telemetry absorbed into a gauge must export,
    # not crash the end-of-run artifact write): int(inf)/int(nan)
    # raise, and repr() would emit 'inf'/'nan', which the format (and
    # our own parse_prometheus) rejects
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(int(f)) if f == int(f) and abs(f) < 2 ** 53 else repr(f)


def export_prometheus(registry, path: Optional[str] = None) -> str:
    """Render the registry in the text exposition format; write to
    `path` when given, return the text either way."""
    lines = []
    for name, kind, help_text, buckets, rows in registry.collect():
        if help_text:
            lines.append(f"# HELP {name} {_esc(help_text)}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            for labels, cell in rows:
                acc = 0
                for bound, n in zip(buckets, cell["buckets"]):
                    acc += n
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, (('le', repr(float(bound))),))}"
                        f" {acc}")
                lines.append(f"{name}_bucket"
                             f"{_fmt_labels(labels, (('le', '+Inf'),))}"
                             f" {cell['count']}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(cell['sum'])}")
                lines.append(f"{name}_count{_fmt_labels(labels)} "
                             f"{cell['count']}")
        else:
            for labels, value in rows:
                lines.append(f"{name}{_fmt_labels(labels)} "
                             f"{_fmt_value(value)}")
    text = "\n".join(lines) + ("\n" if lines else "")
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(text)
    return text


_SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'          # metric name
    r'(\{[^{}]*\})?'                         # optional label set
    r'\s+(-?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|\+?Inf|NaN))\s*$')
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> dict:
    """Minimal exposition-format checker (ISSUE 11 satellite): every
    line must be a comment, a ``# TYPE``/``# HELP`` directive, blank,
    or a well-formed sample; samples must belong to a declared TYPE.
    Returns ``{name: {"type": kind, "samples": [(labels_dict, value)]}}``
    and raises ValueError naming the first malformed line."""
    out: dict = {}
    types: dict = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in ("counter", "gauge",
                                                   "histogram",
                                                   "summary",
                                                   "untyped"):
                raise ValueError(f"line {lineno}: malformed TYPE "
                                 f"directive: {line!r}")
            types[parts[2]] = parts[3]
            out.setdefault(parts[2], {"type": parts[3], "samples": []})
            continue
        if line.startswith("#"):
            continue   # HELP and free comments
        m = _SAMPLE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample: "
                             f"{line!r}")
        name, labels_raw, value = m.group(1), m.group(2), m.group(3)
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        family = name if name in types else base
        if family not in types:
            raise ValueError(f"line {lineno}: sample {name!r} has no "
                             f"preceding # TYPE directive")
        labels = {}
        if labels_raw:
            body = labels_raw[1:-1]
            matched = _LABEL.findall(body)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in matched)
            if body and body.rstrip(",") != rebuilt:
                raise ValueError(f"line {lineno}: malformed labels: "
                                 f"{labels_raw!r}")
            labels = dict(matched)
        out.setdefault(family, {"type": types[family], "samples": []})
        out[family]["samples"].append(
            (labels, float(value.replace("+Inf", "inf"))))
    return out


# ---------------------------------------------------------------------------
# Chrome trace-event JSON
# ---------------------------------------------------------------------------

def _chrome_rows(tracer, pid: int, strip_wall: bool,
                 process_name: str) -> list:
    """One tracer's Chrome events on process lane ``pid`` — the shared
    body of the single-run export and the fleet merge.  Spans are
    complete ("X") events on tid 0; per-request serve events
    (cat="req") are instants on ``tid = rid + 1`` (offset past the
    span lane at tid 0) so each request reads as its own lane.
    ``strip_wall`` replaces every wall-derived ts/dur with the
    deterministic seq clock (1 µs per seq tick)."""
    events = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
               "args": {"name": process_name}}]
    rows = []
    for seq, name, cat, step, t0, dur, depth, args in tracer.spans:
        ev = {"ph": "X", "name": name, "cat": cat, "pid": pid, "tid": 0,
              "ts": seq if strip_wall else round(t0 * 1e6, 3),
              "dur": 1 if strip_wall else round(dur * 1e6, 3),
              "args": {**({"step": step} if step is not None else {}),
                       **args}}
        rows.append((seq, ev))
    for seq, name, cat, step, wall, args in tracer.events:
        a = dict(args)
        tid = int(a.get("rid", 0)) + 1 if cat == "req" else 0
        ev = {"ph": "i", "s": "t", "name": name, "cat": cat, "pid": pid,
              "tid": tid,
              "ts": seq if strip_wall else round(wall * 1e6, 3),
              "args": {**({"step": step} if step is not None else {}),
                       **a}}
        rows.append((seq, ev))
    events.extend(ev for _seq, ev in sorted(rows, key=lambda x: x[0]))
    return events


def export_chrome_trace(tracer, path: str, *,
                        strip_wall: bool = False) -> str:
    """Write the Perfetto/chrome://tracing-loadable trace
    (`_chrome_rows` has the lane layout)."""
    events = _chrome_rows(tracer, 1, strip_wall,
                          f"cpd_tpu:{tracer.run}")
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"run": tracer.run, **tracer.meta}}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, sort_keys=True, separators=(",", ":"))
    return path


def merge_chrome_traces(tracers, path: str, *, strip_wall: bool = False,
                        run: str = "fleet") -> str:
    """ONE merged timeline for a multi-engine fleet run (ISSUE 13):
    engine ``i``'s tracer becomes process lane ``pid = i + 1`` (named
    ``cpd_tpu:<run>:engine<i>``), with its per-request rid lanes
    nested inside — so a migrated session reads as an instant stream
    hopping between process lanes at the migration step.  The same
    ``strip_wall`` determinism contract as `export_chrome_trace`
    applies per lane (``ts`` falls back to each tracer's own seq
    clock)."""
    tracers = list(tracers)
    events = []
    for i, tracer in enumerate(tracers):
        events.extend(_chrome_rows(tracer, i + 1, strip_wall,
                                   f"cpd_tpu:{run}:engine{i}"))
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"run": run, "engines": len(tracers)}}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, sort_keys=True, separators=(",", ":"))
    return path


# ---------------------------------------------------------------------------
# the one-call artifact bundle
# ---------------------------------------------------------------------------

def write_all(obs_dir: str, tracer, registry=None, *,
              strip_wall: bool = False) -> dict:
    """Write every artifact into ``obs_dir`` and return the paths +
    summary block CLIs and bench.py embed in their output
    (docs/OBSERVABILITY.md "Artifact bundle")."""
    os.makedirs(obs_dir, exist_ok=True)
    artifacts = {
        "events_jsonl": export_jsonl(
            tracer, os.path.join(obs_dir, "events.jsonl"),
            strip_wall=strip_wall),
        "chrome_trace": export_chrome_trace(
            tracer, os.path.join(obs_dir, "trace.json"),
            strip_wall=strip_wall),
    }
    summary = dict(tracer.summary())
    if registry is not None:
        artifacts["prometheus"] = os.path.join(obs_dir, "metrics.prom")
        export_prometheus(registry, artifacts["prometheus"])
        summary["metrics"] = len(registry)
    return {"dir": os.path.abspath(obs_dir),
            "artifacts": {k: os.path.abspath(v)
                          for k, v in artifacts.items()},
            "summary": summary}
