"""FlightRecorder — a bounded ring of recent events, dumped at the
moment of death (ISSUE 11 tentpole, leg 4).

The post-incident question is always "what were the last N steps
doing?"; the answer must survive the four ways a run dies:

* a `StepWatchdog` fire (the step wedged — `StepWatchdog(on_trip=...)`
  dumps BEFORE the interrupt is sent, so even a hard-exit leaves the
  ring on disk),
* a `run_guarded` rollback or abort (loop.py dumps on every rollback
  and on any non-None abort),
* SIGINT / preemption (the trainer CLIs dump in their preempt paths),
* a serve-engine snapshot (`ServeEngine.snapshot` dumps alongside, so
  a crash-recovery restore has the pre-crash flight log next to it).

The ring holds (seq, wall, kind, step, fields) tuples; `record` is a
deque append + one clock read — cheap enough to call every step.  Each
`dump` APPENDS one self-describing block to the dump file (a header
line with the reason + the ring contents), so repeated incidents in
one run stay individually greppable:

    {"flight_dump": 3, "reason": "watchdog", ...}
    {"seq": 140, "kind": "step", "step": 140, "loss": 2.1, ...}
    ...

Dumping does NOT clear the ring: a rollback dump followed by a
watchdog dump both show the full recent window.
"""

from __future__ import annotations

import json
import os
import sys
from collections import deque
from typing import Optional

from .timing import now

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded in-memory event ring with crash-time JSONL dumps."""

    def __init__(self, path: Optional[str] = None, *,
                 capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.path = path
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self.dumps = 0

    def record(self, kind: str, *, step: Optional[int] = None,
               **fields) -> None:
        """Append one event; past `capacity` the oldest ages out.
        ``fields`` must be JSON-serializable (they are written verbatim
        at dump time — a dump must never raise)."""
        self._seq += 1
        self._ring.append((self._seq, now(), kind, step, fields))

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Append the ring (header + one line per event) to ``path`` or
        the constructor's path.  Best-effort by design: a recorder with
        no path, or an unwritable one, reports to stderr instead of
        raising — the crash being recorded must stay the headline."""
        target = path or self.path
        self.dumps += 1
        header = {"flight_dump": self.dumps, "reason": reason,
                  "wall": now(), "events": len(self._ring),
                  "capacity": self.capacity, "seq_high": self._seq}
        if target is None:
            print(f"=> flight recorder ({reason}): no dump path "
                  f"configured; {len(self._ring)} events lost",
                  file=sys.stderr)
            return None
        try:
            # snapshot FIRST: dump() runs on the watchdog timer thread
            # while the main thread may still be record()ing (a slow
            # step completing as the trip fires) — iterating the live
            # deque would raise "mutated during iteration" and lose
            # the dump at exactly the crash moment it exists for
            ring = list(self._ring)
            os.makedirs(os.path.dirname(os.path.abspath(target)),
                        exist_ok=True)
            with open(target, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(header, sort_keys=True) + "\n")
                for seq, wall, kind, step, fields in ring:
                    rec = {"seq": seq, "wall": wall, "kind": kind,
                           "step": step, **fields}
                    fh.write(json.dumps(rec, sort_keys=True,
                                        default=str) + "\n")
        except Exception as e:  # noqa: BLE001 — a dump must never
            # out-crash the crash it is recording (unserializable
            # field, concurrent mutation, OSError alike)
            print(f"=> flight recorder ({reason}): dump to {target} "
                  f"failed: {type(e).__name__}: {e}", file=sys.stderr)
            return None
        return os.path.abspath(target)

    def state(self) -> dict:
        return {"events": len(self._ring), "capacity": self.capacity,
                "dumps": self.dumps, "seq_high": self._seq,
                "path": self.path}
