"""MetricsRegistry — every number gets one home and one name (ISSUE 11
tentpole, leg 2).

The repo accreted five generations of telemetry, each with its own
container and spelling: `ResilienceMeter` counters, the in-jit
``prec_wire_*``/``reduce_*`` step metrics, the three supervisors'
``state_dict()``s, the serve engine's counter dict, and assorted
one-off floats in bench tools.  The registry absorbs all of them into
one labelled namespace so exporters (export.py) and dashboards see a
single coherent surface.

Naming scheme (docs/OBSERVABILITY.md):

    cpd_<subsystem>_<name>    e.g. cpd_train_rollbacks,
                                   cpd_step_prec_wire_sat,
                                   cpd_serve_tokens_generated,
                                   cpd_sup_transport_level

* **counter** — monotone, absorbed cumulatively (`inc`) or mirrored
  from a device-held cumulative total (`mirror` — the ResilienceMeter
  discipline: the device holds the truth, the host overwrites).
* **gauge** — last-write-wins scalar (`set_gauge`).
* **histogram** — fixed bucket bounds chosen at declaration, plus
  sum/count (`observe`); exposition follows the Prometheus cumulative-
  bucket convention.

Labels are sorted key=value tuples, so iteration order — and therefore
every export — is deterministic for a deterministic run.  The registry
is pure host-side bookkeeping: nothing here may touch a traced value.
"""

from __future__ import annotations

import bisect
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["MetricsRegistry"]

# step-metric keys the registry adopts from a train step's metric dict
# (the in-jit telemetry families; anything else in the dict is a loss/
# accuracy-style training metric that belongs to ScalarWriter, not here)
_STEP_FAMILIES = ("prec_wire_", "reduce_", "guard_", "faults_",
                  "aps_")

_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _valid_name(name: str) -> bool:
    return bool(name) and not name[0].isdigit() and \
        all(c in _NAME_OK for c in name)


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Series:
    __slots__ = ("kind", "help", "buckets", "series")

    def __init__(self, kind: str, help_text: str,
                 buckets: Optional[Sequence[float]] = None):
        self.kind = kind
        self.help = help_text
        self.buckets = tuple(buckets) if buckets is not None else None
        self.series: Dict[tuple, object] = {}


class MetricsRegistry:
    """One labelled namespace for every counter/gauge/histogram."""

    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

    def __init__(self):
        self._metrics: Dict[str, _Series] = {}

    # -- declaration (implicit on first touch, explicit for help text) ----

    def declare(self, name: str, kind: str, help_text: str = "",
                buckets: Optional[Sequence[float]] = None) -> None:
        if not _valid_name(name):
            raise ValueError(f"invalid metric name {name!r} (allowed: "
                             f"[a-zA-Z_:][a-zA-Z0-9_:]*)")
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown metric kind {kind!r}")
        existing = self._metrics.get(name)
        if existing is not None:
            if existing.kind != kind:
                raise ValueError(
                    f"metric {name!r} already declared as "
                    f"{existing.kind}, not {kind} — one home, one name")
            if help_text and not existing.help:
                existing.help = help_text
            return
        if kind == "histogram" and buckets is None:
            buckets = self.DEFAULT_BUCKETS
        self._metrics[name] = _Series(kind, help_text, buckets)  # cpd: disable=host-unbounded -- keyed by declared metric names: static, low-cardinality by the registry's own naming contract

    # -- writes -----------------------------------------------------------

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {name!r} cannot decrease "
                             f"(inc {value})")
        m = self._touch(name, "counter")
        key = _label_key(labels)
        m.series[key] = float(m.series.get(key, 0.0)) + float(value)

    def mirror(self, name: str, value: float, **labels) -> None:
        """Overwrite a counter with a device-held cumulative total (the
        ResilienceMeter MIRRORED discipline)."""
        m = self._touch(name, "counter")
        m.series[_label_key(labels)] = float(value)

    def set_gauge(self, name: str, value: float, **labels) -> None:
        m = self._touch(name, "gauge")
        m.series[_label_key(labels)] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        m = self._touch(name, "histogram")
        key = _label_key(labels)
        cell = m.series.get(key)
        if cell is None:
            cell = {"buckets": [0] * len(m.buckets), "sum": 0.0,
                    "count": 0}
            m.series[key] = cell
        i = bisect.bisect_left(m.buckets, float(value))
        if i < len(m.buckets):
            cell["buckets"][i] += 1
        cell["sum"] += float(value)
        cell["count"] += 1

    def _touch(self, name: str, kind: str) -> _Series:
        m = self._metrics.get(name)
        if m is None:
            self.declare(name, kind)
            m = self._metrics[name]
        elif m.kind != kind:
            raise ValueError(f"metric {name!r} is a {m.kind}, not a "
                             f"{kind} — one home, one name")
        return m

    # -- adapters: the five legacy telemetry surfaces ---------------------

    def absorb_resilience_meter(self, meter) -> None:
        """`train.metrics.ResilienceMeter` — every field becomes
        ``cpd_train_<field>`` (cumulative; mirrored, the meter already
        holds run totals)."""
        for field, value in meter.as_dict().items():
            self.mirror(f"cpd_train_{field}", value)

    def absorb_step_metrics(self, metrics: dict,
                            step: Optional[int] = None) -> None:
        """The in-jit telemetry families riding a step's metric dict
        (``prec_wire_*``, ``reduce_*``, ``guard_*``, ``aps_*``,
        ``faults_*``) — gauges named ``cpd_step_<key>`` holding the
        latest step's value (the cumulative ones are device-held run
        totals already)."""
        for key, value in metrics.items():
            if any(key.startswith(f) for f in _STEP_FAMILIES):
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                self.set_gauge(f"cpd_step_{key}", v)
        if step is not None:
            self.set_gauge("cpd_step_index", float(step))

    def absorb_supervisor(self, which: str, state: dict) -> None:
        """A supervisor ``state_dict()`` (transport / precision /
        serve): numeric scalars become ``cpd_sup_<which>_<key>``
        gauges; string/tuple-valued fields (mode, format, rung name)
        become one ``cpd_sup_<which>_info`` gauge carrying them as
        labels — the Prometheus *info-metric* idiom."""
        info = {}
        for key, value in sorted(state.items()):
            name = f"cpd_sup_{which}_{key}"
            if isinstance(value, bool):
                self.set_gauge(name, 1.0 if value else 0.0)
            elif isinstance(value, (int, float)):
                self.set_gauge(name, float(value))
            elif isinstance(value, str):
                info[key] = value
            elif isinstance(value, (list, tuple)):
                # structure (ladder rungs, transition logs): export the
                # size; the full value belongs to the JSONL stream
                self.set_gauge(f"{name}_len", float(len(value)))
            # nested dicts are supervisor-internal; JSONL carries them
        if info:
            self.set_gauge(f"cpd_sup_{which}_info", 1.0, **info)

    def absorb_serve_counters(self, counters: dict,
                              engine: Optional[int] = None) -> None:
        """The serve engine's counter dict — ``cpd_serve_<key>``,
        mirrored (the engine holds cumulative truth).  ``engine``
        labels the series with the fleet member index, so an N-engine
        fleet exports N distinguishable series per counter."""
        labels = {} if engine is None else {"engine": engine}
        for key, value in counters.items():
            self.mirror(f"cpd_serve_{key}", float(value), **labels)

    def absorb_serve_shards(self, cfg, engine: Optional[int] = None) -> None:
        """Per-shard KV pool pricing for a (possibly tp-sharded) engine
        (ISSUE 18): the ``shard`` label joins ``engine`` on the
        ``cpd_serve_*`` family — one gauge series per head-group shard,
        so a tp=4 engine exports four distinguishable pool slices.

        ``cfg`` is the engine's `KVCacheConfig`.  Gauges (rows in
        docs/OBSERVABILITY.md): ``cpd_serve_kv_shard_page_bytes`` — one
        layer's K+V bytes of one page on this shard (the blocked codec's
        per-shard sidecar makes this NOT page_bytes / tp); and
        ``cpd_serve_kv_shard_pool_bytes`` — the shard's whole resident
        pool slice.  A tp=1 engine exports the single shard-0 series,
        so dashboards sum over ``shard`` uniformly."""
        labels = {} if engine is None else {"engine": engine}
        page = float(cfg.shard_page_bytes if cfg.tp > 1 else
                     cfg.page_bytes)
        pool = float(cfg.n_layers) * float(cfg.n_pages) * page
        for s in range(cfg.tp):
            self.set_gauge("cpd_serve_kv_shard_page_bytes", page,
                           shard=s, **labels)
            self.set_gauge("cpd_serve_kv_shard_pool_bytes", pool,
                           shard=s, **labels)

    def absorb_linalg_counters(self, counters: dict,
                               algo: Optional[str] = None,
                               fmt: Optional[str] = None) -> None:
        """A linalg benchmark result block (tools/bench_linalg.py) —
        ``cpd_linalg_<key>`` gauges labelled by algorithm and eXmY
        format, so one capture exports the whole accuracy/bytes
        frontier as distinguishable series (ISSUE 15)."""
        labels = {}
        if algo is not None:
            labels["algo"] = algo
        if fmt is not None:
            labels["fmt"] = fmt
        for key, value in counters.items():
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            self.set_gauge(f"cpd_linalg_{key}", v, **labels)

    def absorb_fleet_counters(self, fleet) -> None:
        """A `cpd_tpu.fleet.Fleet` — the ``cpd_fleet_*`` family
        (ISSUE 13): the fleet's own counters (routing, retries,
        migrations, kills, recoveries, waves, spawns/retirements)
        mirrored unlabelled, plus every member engine's counters as
        engine-labelled ``cpd_serve_*`` series.  An attached
        autoscaler adds the ``cpd_fleet_scale_*`` family (ISSUE 17):
        its decision counters plus the live accepting-engine gauge —
        docs/OBSERVABILITY.md lists the rows."""
        for key, value in fleet.counters.items():
            self.mirror(f"cpd_fleet_{key}", float(value))
        self.set_gauge("cpd_fleet_engines", float(fleet.n_engines))
        self.set_gauge("cpd_fleet_step_index", float(fleet.step_index))
        scaler = getattr(fleet, "autoscaler", None)
        if scaler is not None:
            for key, value in scaler.counters.items():
                self.mirror(f"cpd_fleet_scale_{key}", float(value))
            self.set_gauge("cpd_fleet_scale_accepting",
                           float(sum(fleet.accepting)))
        shard_totals: Dict[int, float] = {}
        for i, eng in enumerate(fleet.engines):
            self.absorb_serve_counters(eng.counters, engine=i)
            cfg = getattr(eng, "cfg", None)
            if cfg is not None:
                self.absorb_serve_shards(cfg, engine=i)
                page = float(cfg.shard_page_bytes if cfg.tp > 1
                             else cfg.page_bytes)
                pool = float(cfg.n_layers) * float(cfg.n_pages) * page
                for s in range(cfg.tp):
                    shard_totals[s] = shard_totals.get(s, 0.0) + pool
        # fleet-level shard rows (ISSUE 18): resident KV bytes per head-
        # group shard index, summed over member engines.
        for s, total in sorted(shard_totals.items()):
            self.set_gauge("cpd_fleet_kv_shard_bytes", total, shard=s)

    def absorb_store_counters(self, store) -> None:
        """A `cpd_tpu.store.DurableStore` — the ``cpd_store_*`` family
        (ISSUE 20): the store tree's shared counters (publishes,
        retries, transient I/O errors, backoff steps, quarantines,
        tmp sweeps, GC collections, restores, fence refusals, fired
        storage chaos, read-time digest rejects) mirrored unlabelled,
        plus live gauges for the quarantine depth and the number of
        published generations under the root.  Sub-stores share one
        counter plane, so absorbing the root covers every surface
        riding it — docs/OBSERVABILITY.md lists the rows."""
        for key, value in store.counters.items():
            self.mirror(f"cpd_store_{key}", float(value))
        self.set_gauge("cpd_store_quarantine_depth",
                       float(len(store.quarantined())))
        self.set_gauge("cpd_store_generations",
                       float(len(store.generations())))

    def absorb_elastic(self, supervisor) -> None:
        """A `cpd_tpu.resilience.ElasticSupervisor` — the
        ``cpd_elastic_*`` family (ISSUE 19): the recovery-ladder
        decision counters (drains, rejoins, shrinks, regrows, hot
        steps, heartbeat misses, link retries/escalations) mirrored
        unlabelled, plus the live fleet-shape gauges: the current
        compute world, the home (full-fleet) world, the alive-host
        count, and a degraded flag — docs/OBSERVABILITY.md lists the
        rows."""
        for key, value in supervisor.counters.items():
            self.mirror(f"cpd_elastic_{key}", float(value))
        self.set_gauge("cpd_elastic_world", float(supervisor.world))
        self.set_gauge("cpd_elastic_home_world",
                       float(supervisor.home_world))
        self.set_gauge("cpd_elastic_alive",
                       float(sum(supervisor.alive)))
        self.set_gauge("cpd_elastic_degraded",
                       1.0 if supervisor.degraded else 0.0)

    # -- reads ------------------------------------------------------------

    def collect(self) -> list:
        """Deterministic flat view: ``(name, kind, help, [(labels,
        value), ...])`` sorted by name then labels — the exporters'
        input."""
        out = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            rows = sorted(m.series.items())
            out.append((name, m.kind, m.help, m.buckets, rows))
        return out

    def as_dict(self) -> dict:
        """JSON-ready snapshot (bench.py summaries, tests)."""
        out: dict = {}
        for name, kind, _help, _buckets, rows in self.collect():
            if len(rows) == 1 and rows[0][0] == ():
                val = rows[0][1]
            else:
                val = {";".join(f"{k}={v}" for k, v in key): value
                       for key, value in rows}
            out[name] = {"kind": kind, "value": val}
        return out

    def __len__(self) -> int:
        return len(self._metrics)

    def __contains__(self, name: str) -> bool:
        return name in self._metrics
