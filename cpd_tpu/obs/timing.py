"""The ONE wall-clock timer home (ISSUE 11 satellite: the repo had
three — `train/metrics.Timer`, ad-hoc ``time.monotonic()`` pairs in
`serve/loadgen.py`, and ``time.perf_counter()`` pairs sprinkled through
the bench tools).  Everything that measures host wall time routes
through here so the clock choice, and any future virtualization of it
(deterministic replay, frozen-clock tests), has one choke point.

``now()`` is a monotonic clock: immune to NTP steps, comparable only
within one process — exactly the contract latency metrics need.  The
serve timelines (obs/trace.py) and loadgen's published TTFT/TPOT use
the SAME ``now()``, which is what makes the timeline reconstruction
bit-exact against the published metrics (loadgen.timeline_metrics).
"""

from __future__ import annotations

import time

__all__ = ["now", "epoch", "Stopwatch", "Timer"]


def now() -> float:
    """Monotonic wall-clock seconds (one clock for the whole repo).

    `time.perf_counter`, not `time.monotonic`: both are monotonic, but
    perf_counter is the highest-resolution clock the platform offers —
    the bench tools time sub-millisecond kernels through this helper,
    and `time.monotonic`'s ~15.6 ms tick on Windows (< 3.13) would
    quantize those to garbage.  Every latency metric and the timeline-
    reconstruction parity contract only need one shared monotonic
    clock, which this remains."""
    return time.perf_counter()


def epoch() -> float:
    """Unix-epoch seconds — the ONE sanctioned ``time.time()`` read.

    For *timestamps* (log lines, scalar-stream ``ts`` fields, run
    metadata) where an absolute, cross-process time is the point.
    Never difference two ``epoch()`` reads to measure a duration — NTP
    can step it; that is what ``now()``/``Stopwatch`` are for.  The
    host-clock rule (docs/ANALYSIS.md v4) funnels every wall-clock
    read in the tree through these two helpers."""
    return time.time()


class Stopwatch:
    """The ``t0 = clock(); ...; dt = clock() - t0`` pair, named.

    `lap()` returns seconds since construction or the previous lap;
    `elapsed()` peeks without resetting the lap mark."""

    def __init__(self):
        self._t0 = now()
        self._mark = self._t0

    def lap(self) -> float:
        t = now()
        dt = t - self._mark
        self._mark = t
        return dt

    def elapsed(self) -> float:
        """Seconds since construction (independent of laps)."""
        return now() - self._t0


class Timer:
    """Incremental wall-clock timer (reference DavidNet/utils.py:28-38
    parity, moved here from train/metrics.py): each call returns the
    time since the previous call and accumulates total time.

    State is O(1) — only the previous mark is kept.  The reference
    appends every timestamp to a list, which on a long-lived loop is
    exactly the host-unbounded defect the analyzer flags; nothing ever
    read more than the last two entries."""

    def __init__(self):
        self._last = now()
        self.total_time = 0.0

    def __call__(self, include_in_total: bool = True) -> float:
        t = now()
        delta = t - self._last
        self._last = t
        if include_in_total:
            self.total_time += delta
        return delta
