"""Tracer — nested spans and instant events on the step clock AND the
wall clock (ISSUE 11 tentpole, leg 1).

Design constraints, in priority order:

1. **Provably free when off.**  Call sites hold ``tracer = None`` (or
   `NULL_TRACER`) and the hot loops guard with one ``is not None``
   check; nothing is allocated, formatted or timestamped.  The obs-on
   path only *observes* — it never touches values that feed a jitted
   program, so step outputs are bitwise identical either way (pinned in
   tests/test_obs.py and the obs-smoke gate).
2. **Two clocks per record.**  Every span/event carries the *step*
   (the deterministic logical clock every counter and fault plan runs
   on) and the *wall* time (`obs.timing.now`, the one monotonic clock).
   Exports can strip the wall fields to get byte-reproducible artifacts
   (export.py), while latency metrics keep the real timings.
3. **Bounded by construction.**  ``max_records`` caps both streams;
   past it the oldest records age out (counted, never silent) — a
   tracer left attached to a long-running engine cannot grow host
   memory without limit, same doctrine as `serve.ResultStore`.

Record shapes (plain tuples — export.py owns the serialization):

* span:  ``(seq, name, cat, step, wall_t0, dur_s, depth, args)``
* event: ``(seq, name, cat, step, wall, args)`` — instant occurrences;
  the serve per-request timeline rides these with ``cat="req"`` and
  ``args["rid"]`` (engine.py stamps submit/admit/first_chunk/
  first_token/complete/shed/deadline_miss plus verdict/SLA/ladder
  annotations; docs/OBSERVABILITY.md has the taxonomy).

``seq`` is a per-tracer monotone ordinal — the deterministic total
order exports sort by, so two runs of the same (trace, plan, seed)
produce identical streams modulo the wall fields.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from .timing import now

__all__ = ["Tracer", "Span", "NULL_TRACER", "NULL_SPAN"]


class Span:
    """Context manager handed out by `Tracer.span` — records on exit."""

    __slots__ = ("_tracer", "name", "cat", "step", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 step: Optional[int], args: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.step = step
        self.args = args
        self._t0 = 0.0

    def __enter__(self) -> "Span":
        self._tracer._depth += 1
        self._t0 = now()
        return self

    def __exit__(self, *exc) -> None:
        t1 = now()
        tr = self._tracer
        tr._depth -= 1
        tr._push(tr.spans, (tr._next_seq(), self.name, self.cat,
                            self.step, self._t0, t1 - self._t0,
                            tr._depth, self.args))


class _NullSpan:
    """Reusable no-op context manager — the disabled path allocates
    nothing per call.  Exported as `NULL_SPAN` so call sites that
    branch on ``tracer is None`` themselves (e.g. the serve engine's
    per-phase spans) share THE one null context instead of growing
    local copies."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL_SPAN = NULL_SPAN = _NullSpan()


class Tracer:
    """Span/event collector for one run (module docstring).

    Parameters
    ----------
    run : label stamped into exports ("train", "serve", "bench", ...).
    max_records : bound on EACH stream (spans, events); the oldest age
        out past it, counted in ``spans_dropped``/``events_dropped``.
    meta : free-form run metadata carried into the export headers
        (model shape, flags, world size) — keep it JSON-serializable.
    """

    def __init__(self, run: str = "run", *, max_records: int = 65536,
                 meta: Optional[dict] = None):
        if max_records < 1:
            raise ValueError(f"max_records must be >= 1, got "
                             f"{max_records}")
        self.run = run
        self.meta = dict(meta or {})
        self.max_records = int(max_records)
        self.spans: deque = deque()
        self.events: deque = deque()
        self.spans_dropped = 0
        self.events_dropped = 0
        self._seq = 0
        self._depth = 0

    # -- recording --------------------------------------------------------

    def span(self, name: str, *, step: Optional[int] = None,
             cat: str = "phase", **args) -> Span:
        """``with tracer.span("data", step=it): ...`` — nested spans
        carry their depth so exports reconstruct the hierarchy."""
        return Span(self, name, cat, step, args)

    def event(self, name: str, *, step: Optional[int] = None,
              cat: str = "mark", wall: Optional[float] = None,
              **args) -> None:
        """Instant occurrence.  ``wall`` lets a caller that already
        timestamped the moment (loadgen's step_wall, the engine's event
        log) record the SAME float — that shared value is what makes
        timeline reconstruction exact."""
        self._push(self.events,
                   (self._next_seq(), name, cat, step,
                    now() if wall is None else wall, args))

    def request_event(self, rid: int, kind: str, step: int, *,
                      wall: Optional[float] = None, **args) -> None:
        """One serve per-request timeline record (cat="req")."""
        self.event(kind, step=step, cat="req", wall=wall, rid=rid,
                   **args)

    # -- internals --------------------------------------------------------

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _push(self, stream: deque, record: tuple) -> None:
        stream.append(record)
        if len(stream) > self.max_records:
            stream.popleft()
            if stream is self.spans:
                self.spans_dropped += 1
            else:
                self.events_dropped += 1

    def summary(self) -> dict:
        return {"run": self.run, "spans": len(self.spans),
                "events": len(self.events),
                "spans_dropped": self.spans_dropped,
                "events_dropped": self.events_dropped}


class _NullTracer:
    """The zero-cost disabled tracer: every method is a no-op and
    `span` returns one shared reusable context manager.  Call sites
    that prefer not to branch on ``None`` can hold this instead."""

    run = "off"
    meta: dict = {}
    spans: tuple = ()
    events: tuple = ()
    spans_dropped = events_dropped = 0

    def span(self, name, *, step=None, cat="phase", **args):
        return _NULL_SPAN

    def event(self, name, *, step=None, cat="mark", wall=None, **args):
        return None

    def request_event(self, rid, kind, step, *, wall=None, **args):
        return None

    def summary(self) -> dict:
        return {"run": "off", "spans": 0, "events": 0,
                "spans_dropped": 0, "events_dropped": 0}

    def __bool__(self) -> bool:
        # `if tracer:` reads as "is tracing live?" at call sites
        return False


NULL_TRACER = _NullTracer()
