"""Device-mesh construction for all parallelism axes.

The reference's only parallelism is data parallelism over NCCL ranks
(SURVEY.md §2); its "mesh" is implicit in the process group
(dist_util.py:128).  TPU-natively the mesh is explicit and multi-axis:
data (dp), tensor (tp), sequence/context (sp), pipeline (pp) and expert (ep)
axes all live on one `jax.sharding.Mesh`, and shardings — not process ranks —
decide which collectives XLA emits and whether they ride ICI or DCN.

Axis order convention: ("dp", "pp", "sp", "tp", "ep")-major with dp
outermost, so dp collectives (the gradient all-reduce) cross the slowest
axis and tp collectives (per-layer all-gathers) stay on the innermost,
fastest ICI ring.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "data_parallel_mesh", "group_split",
           "AXIS_DATA", "AXIS_TENSOR",
           "AXIS_SEQ", "AXIS_PIPE", "AXIS_EXPERT"]

AXIS_DATA = "dp"
AXIS_TENSOR = "tp"
AXIS_SEQ = "sp"
AXIS_PIPE = "pp"
AXIS_EXPERT = "ep"

_CANONICAL_ORDER = (AXIS_DATA, AXIS_PIPE, AXIS_SEQ, AXIS_EXPERT, AXIS_TENSOR)


def make_mesh(dp: int = 1, tp: int = 1, sp: int = 1, pp: int = 1,
              ep: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh with the requested axis sizes (size-1 axes kept, so
    PartitionSpecs can always name every axis).

    If `dp` is 0, it absorbs all remaining devices (the common "shard batch
    over whatever is left" case)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = tp * sp * pp * ep
    if dp == 0:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by tp*sp*pp*ep={fixed}")
        dp = n // fixed
    total = dp * fixed
    if total != n:
        raise ValueError(
            f"mesh axes dp={dp} pp={pp} sp={sp} ep={ep} tp={tp} need {total} "
            f"devices, have {n}")
    sizes = {AXIS_DATA: dp, AXIS_PIPE: pp, AXIS_SEQ: sp, AXIS_EXPERT: ep,
             AXIS_TENSOR: tp}
    shape = tuple(sizes[a] for a in _CANONICAL_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, _CANONICAL_ORDER)


def data_parallel_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """Pure-DP mesh over all devices — the reference's implicit topology
    (one NCCL rank per GPU, dist_util.py:126-128)."""
    if devices is None:
        devices = jax.devices()
    return make_mesh(dp=len(devices), devices=devices)


def group_split(world_size: int, num_groups: int):
    """Sub-communicator groups — reference `simple_group_split`
    (train_util.py:11-18), which carves the world into `num_groups` NCCL
    groups of consecutive ranks.

    The XLA analog is `axis_index_groups` for collectives: pass the
    returned list to `lax.psum(..., axis_name, axis_index_groups=...)`
    (or pmax/all_gather) to reduce within each group only — no process
    groups to manage.
    """
    if world_size % num_groups:
        raise ValueError(f"world {world_size} not divisible into "
                         f"{num_groups} groups")
    per = world_size // num_groups
    return [list(range(g * per, (g + 1) * per)) for g in range(num_groups)]
