"""Distributed layer: mesh, low-precision collectives, APS, emulation.

TPU-native replacement for reference CPDtorch/utils/dist_util.py (NCCL /
torch.distributed) built on XLA collectives under shard_map/pjit."""

from .aps import (aps_max_exponents, aps_scale, aps_shift_factors,
                  aps_shift_factors_checked, aps_unscale)
from .dist import (all_reduce_mean, broadcast_from, dist_init,
                   make_sum_gradients_fn, replicate, sum_gradients)
from .emulate import emulate_node_reduce
from .integrity import (digest_agree, hop_tag, make_consensus_fns,
                        tree_digest, wire_digest)
from .mesh import (AXIS_DATA, AXIS_EXPERT, AXIS_PIPE, AXIS_SEQ, AXIS_TENSOR, group_split,
                   data_parallel_mesh, make_mesh)
from .overlap import (BucketPlan, bucket_layout, overlap_evidence,
                      overlapped_grads)
from .pipeline import pipeline_spmd
from .ring import (gather_transport_bytes, hierarchical_ring_sum,
                   ring_oracle_sum, ring_oracle_sum_multi,
                   ring_quantized_sum, ring_transport_bytes)
from .zero import Zero1State, zero1_sgd, zero2_sgd, zero3_sgd
from .reduction import (kahan_quantized_sum, ordered_quantized_sum,
                        quantized_sum)

__all__ = [
    "pipeline_spmd", "Zero1State", "zero1_sgd", "zero2_sgd", "zero3_sgd",
    "aps_max_exponents", "aps_scale", "aps_shift_factors",
    "aps_shift_factors_checked", "aps_unscale",
    "all_reduce_mean", "broadcast_from", "dist_init", "make_sum_gradients_fn",
    "replicate", "sum_gradients", "emulate_node_reduce",
    "AXIS_DATA", "AXIS_EXPERT", "AXIS_PIPE", "AXIS_SEQ", "AXIS_TENSOR",
    "data_parallel_mesh", "make_mesh",
    "kahan_quantized_sum", "ordered_quantized_sum", "quantized_sum",
    "ring_quantized_sum", "ring_oracle_sum", "ring_transport_bytes",
    "gather_transport_bytes", "hierarchical_ring_sum",
    "ring_oracle_sum_multi",
    "BucketPlan", "bucket_layout", "overlapped_grads", "overlap_evidence",
    "wire_digest", "tree_digest", "hop_tag", "digest_agree",
    "make_consensus_fns",
]
