"""APS — Auto Precision Scaling (the paper's core contribution).

TPU-native re-implementation of reference `sum_gradients`'s APS pre/post
scaling (CPDtorch/utils/dist_util.py:22-51).  Per gradient tensor:

    max_exp      = ceil(log2(max |g * world_size|))          (dist_util.py:26-28)
    max_exp      = all_reduce(max_exp, MAX)                  (dist_util.py:29-30)
    shift_factor = (2^(exp-1) - 1) - max_exp                 (dist_util.py:32-34)
    g            = quantize(g * 2^shift_factor, exp, man)    (dist_util.py:35-37)
    ... low-precision reduction ...
    g            = g / 2^shift_factor                        (dist_util.py:44-45)

Effect: the summed gradient's exponent range is shifted to the top of the
eXmY representable range so the low-precision sum loses no dynamic range.
Scaling by exact powers of two is lossless in binary floating point, so the
shift itself introduces no rounding.

Differences from the reference, by design:

* Vectorized: all per-parameter max-exponents are computed in one fused pass
  and reduced with ONE `pmax` collective, instead of the reference's Python
  loop with a host round-trip per parameter (dist_util.py:26-34).
* All-zero gradients: the reference computes log2(0) = -inf, giving an
  infinite shift and NaN gradients (dist_util.py:27 has no guard; the
  *emulate-node* path does guard, mix.py:267-268).  We adopt the guarded
  behavior everywhere: zero tensors get shift_factor = 0.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["aps_max_exponents", "aps_shift_factors",
           "aps_shift_factors_checked", "aps_scale", "aps_unscale"]


def aps_max_exponents(grads: Any, world_size) -> jnp.ndarray:
    """ceil(log2(max|g * W|)) per leaf, stacked into one (n_leaves,) vector.

    -inf marks an all-zero leaf (caller maps it to shift 0)."""
    leaves = jax.tree_util.tree_leaves(grads)
    w = jnp.float32(world_size)
    return jnp.stack(
        [jnp.ceil(jnp.log2(jnp.max(jnp.abs(g.astype(jnp.float32) * w))))
         for g in leaves])


def aps_shift_factors_checked(max_exp: jnp.ndarray,
                              grad_exp: int) -> tuple:
    """shift = (2^(exp-1)-1) - max_exp, distinguishing the two ways
    `max_exp` can be non-finite.

    * ``-inf`` — an all-zero leaf (log2(0)); shift 0 is CORRECT there
      (nothing to scale; the reference's guarded emulate-node path,
      mix.py:267-268).
    * ``+inf`` or ``NaN`` — the leaf itself contains Inf/NaN gradients.
      Shift 0 is merely *damage control*: the garbage value still rides
      the quantized reduce (the cast passes Inf/NaN through), so the
      condition must be SURFACED, not silently normalized away.

    Returns ``(shifts, bad)`` where ``bad`` is the int32 count of
    non-finite-gradient leaves (the ``+inf``/NaN case only — all-zero
    leaves are healthy).  `sum_gradients(stats=True)` exposes it as the
    ``aps_bad`` counter, which the grad guard's skip and the precision
    supervisor (resilience/precision.py) both see.  Call on the
    pmax-agreed vector: the verdict is then replicated by construction
    (pmax propagates +inf, and jnp.maximum propagates NaN)."""
    upper_bound = jnp.float32(2 ** (grad_exp - 1) - 1)
    shift = upper_bound - max_exp
    bad = jnp.sum((jnp.isnan(max_exp)
                   | (max_exp == jnp.inf)).astype(jnp.int32))
    return jnp.where(jnp.isfinite(shift), shift, jnp.float32(0.0)), bad


def aps_shift_factors(max_exp: jnp.ndarray, grad_exp: int) -> jnp.ndarray:
    """shift = (2^(exp-1)-1) - max_exp, with the all-zero guard (shift=0).

    Maps BOTH non-finite cases to shift 0 (see the checked variant for
    why they differ); callers that can report should prefer
    `aps_shift_factors_checked`."""
    return aps_shift_factors_checked(max_exp, grad_exp)[0]


def aps_scale(grads: Any, shifts: jnp.ndarray) -> Any:
    """g * 2^shift per leaf (lossless power-of-two scaling)."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    scaled = [g * jnp.exp2(shifts[i]) for i, g in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, scaled)


def aps_unscale(grads: Any, shifts: jnp.ndarray) -> Any:
    """g / 2^shift per leaf — a true fp32 divide like the reference
    (dist_util.py:45), NOT multiply-by-2^-shift: for shifts > 127 the
    reference's 2^shift overflows to inf and the divide flushes to 0, which
    a multiply by the subnormal 2^-shift would not reproduce."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    scaled = [g / jnp.exp2(shifts[i]) for i, g in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, scaled)


def pmax_scalar_vector(vec: jnp.ndarray, axis_name: str | Sequence[str]) -> jnp.ndarray:
    """One MAX collective over the stacked per-leaf exponent vector —
    the TPU replacement for dist.all_reduce(max_exp, MAX)
    (dist_util.py:29-30), one collective instead of a host sync."""
    return lax.pmax(vec, axis_name)
