"""APS — Auto Precision Scaling (the paper's core contribution).

TPU-native re-implementation of reference `sum_gradients`'s APS pre/post
scaling (CPDtorch/utils/dist_util.py:22-51).  Per gradient tensor:

    max_exp      = ceil(log2(max |g * world_size|))          (dist_util.py:26-28)
    max_exp      = all_reduce(max_exp, MAX)                  (dist_util.py:29-30)
    shift_factor = (2^(exp-1) - 1) - max_exp                 (dist_util.py:32-34)
    g            = quantize(g * 2^shift_factor, exp, man)    (dist_util.py:35-37)
    ... low-precision reduction ...
    g            = g / 2^shift_factor                        (dist_util.py:44-45)

Effect: the summed gradient's exponent range is shifted to the top of the
eXmY representable range so the low-precision sum loses no dynamic range.
Scaling by exact powers of two is lossless in binary floating point, so the
shift itself introduces no rounding.

Differences from the reference, by design:

* Vectorized: all per-parameter max-exponents are computed in one fused pass
  and reduced with ONE `pmax` collective, instead of the reference's Python
  loop with a host round-trip per parameter (dist_util.py:26-34).
* All-zero gradients: the reference computes log2(0) = -inf, giving an
  infinite shift and NaN gradients (dist_util.py:27 has no guard; the
  *emulate-node* path does guard, mix.py:267-268).  We adopt the guarded
  behavior everywhere: zero tensors get shift_factor = 0.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["aps_max_exponents", "aps_shift_factors",
           "aps_shift_factors_checked", "aps_scale", "aps_unscale",
           "exp2_exact"]


def exp2_exact(s: jnp.ndarray) -> jnp.ndarray:
    """The IEEE fp32 value of ``2.0 ** s`` for integer-valued float32
    ``s``, built by BIT ASSEMBLY — normals for s in [-126, 127], exact
    subnormals down to 2^-149, +inf above 127, +0.0 below -149.

    Exists because XLA:CPU lowers ``jnp.exp2`` to a polynomial that is
    off by an ulp for MOST negative integer inputs (measured: 221 of the
    254 integers in [-126, 127]), and the ulp it lands on can differ
    between compiled programs — so any bitwise contract between two
    programs that both scale by "2^shift" (the replicated reduce vs the
    ZeRO-2 shard, the monolith vs the overlap taps, a distributed path
    vs its single-device oracle) held only by luck.  Bit assembly is
    exact and program-independent by construction (ISSUE 12; the same
    doctrine as numerics._pow2 / the frexp-based blocked codec).
    Non-integer inputs are a caller error (truncated toward the
    assembled exponent)."""
    s = jnp.asarray(s, jnp.float32)
    si = jnp.clip(s, -150.0, 128.0).astype(jnp.int32)
    norm = lax.bitcast_convert_type(
        ((jnp.clip(si, -126, 127) + 127) << 23).astype(jnp.uint32),
        jnp.float32)
    sub = lax.bitcast_convert_type(
        (jnp.uint32(1) << jnp.clip(si + 149, 0, 22).astype(jnp.uint32)),
        jnp.float32)
    out = jnp.where(si >= -126, norm, sub)
    out = jnp.where(si >= 128, jnp.float32(jnp.inf), out)
    return jnp.where(si <= -150, jnp.float32(0.0), out)


def _ceil_log2_exact(m: jnp.ndarray) -> jnp.ndarray:
    """Exact ``ceil(log2(m))`` for a positive finite fp32 scalar, from
    the bit pattern (frexp): m = f·2^e with f in [0.5, 1) gives
    log2(m) in [e-1, e), so ceil = e unless m IS the power of two
    2^(e-1) (f == 0.5), where ceil = e-1.  Subnormal m pre-scales by
    2^24 exactly (frexp mis-reports subnormals on some backends).
    The transcendental it replaces could return either side of an
    integer boundary depending on the compiled program, and the
    downstream ``ceil`` turned that ulp into a whole shift unit."""
    is_sub = m < jnp.float32(2.0) ** -126
    mn = jnp.where(is_sub, m * jnp.float32(16777216.0), m)
    f, e = jnp.frexp(mn)
    ex = (e.astype(jnp.float32)
          - (f == jnp.float32(0.5)).astype(jnp.float32)
          - jnp.where(is_sub, jnp.float32(24.0), jnp.float32(0.0)))
    ex = jnp.where(m == 0, -jnp.inf, ex)
    ex = jnp.where(jnp.isinf(m), jnp.inf, ex)
    return jnp.where(jnp.isnan(m), jnp.nan, ex)


def aps_max_exponents(grads: Any, world_size) -> jnp.ndarray:
    """ceil(log2(max|g * W|)) per leaf, stacked into one (n_leaves,) vector
    (computed EXACTLY from the max's bit pattern — `_ceil_log2_exact` —
    so every program derives the same shift from the same max).

    -inf marks an all-zero leaf (caller maps it to shift 0)."""
    leaves = jax.tree_util.tree_leaves(grads)
    w = jnp.float32(world_size)
    return jnp.stack(
        [_ceil_log2_exact(jnp.max(jnp.abs(g.astype(jnp.float32) * w)))
         for g in leaves])


def aps_shift_factors_checked(max_exp: jnp.ndarray,
                              grad_exp: int) -> tuple:
    """shift = (2^(exp-1)-1) - max_exp, distinguishing the two ways
    `max_exp` can be non-finite.

    * ``-inf`` — an all-zero leaf (log2(0)); shift 0 is CORRECT there
      (nothing to scale; the reference's guarded emulate-node path,
      mix.py:267-268).
    * ``+inf`` or ``NaN`` — the leaf itself contains Inf/NaN gradients.
      Shift 0 is merely *damage control*: the garbage value still rides
      the quantized reduce (the cast passes Inf/NaN through), so the
      condition must be SURFACED, not silently normalized away.

    Returns ``(shifts, bad)`` where ``bad`` is the int32 count of
    non-finite-gradient leaves (the ``+inf``/NaN case only — all-zero
    leaves are healthy).  `sum_gradients(stats=True)` exposes it as the
    ``aps_bad`` counter, which the grad guard's skip and the precision
    supervisor (resilience/precision.py) both see.  Call on the
    pmax-agreed vector: the verdict is then replicated by construction
    (pmax propagates +inf, and jnp.maximum propagates NaN)."""
    upper_bound = jnp.float32(2 ** (grad_exp - 1) - 1)
    shift = upper_bound - max_exp
    bad = jnp.sum((jnp.isnan(max_exp)
                   | (max_exp == jnp.inf)).astype(jnp.int32))
    return jnp.where(jnp.isfinite(shift), shift, jnp.float32(0.0)), bad


def aps_shift_factors(max_exp: jnp.ndarray, grad_exp: int) -> jnp.ndarray:
    """shift = (2^(exp-1)-1) - max_exp, with the all-zero guard (shift=0).

    Maps BOTH non-finite cases to shift 0 (see the checked variant for
    why they differ); callers that can report should prefer
    `aps_shift_factors_checked`."""
    return aps_shift_factors_checked(max_exp, grad_exp)[0]


def aps_scale(grads: Any, shifts: jnp.ndarray) -> Any:
    """g * 2^shift per leaf (lossless power-of-two scaling — the scale
    is the EXACT `exp2_exact` power of two, program-independent)."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    scaled = [g * exp2_exact(shifts[i]) for i, g in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, scaled)


def aps_unscale(grads: Any, shifts: jnp.ndarray) -> Any:
    """g / 2^shift per leaf — a true fp32 divide like the reference
    (dist_util.py:45), NOT multiply-by-2^-shift: for shifts > 127 the
    reference's 2^shift overflows to inf and the divide flushes to 0, which
    a multiply by the subnormal 2^-shift would not reproduce.  The
    divisor is the EXACT `exp2_exact` power of two (shift > 127 still
    assembles +inf, so the documented flush-to-0 survives)."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    scaled = [g / exp2_exact(shifts[i]) for i, g in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, scaled)


def pmax_scalar_vector(vec: jnp.ndarray, axis_name: str | Sequence[str]) -> jnp.ndarray:
    """One MAX collective over the stacked per-leaf exponent vector —
    the TPU replacement for dist.all_reduce(max_exp, MAX)
    (dist_util.py:29-30), one collective instead of a host sync."""
    return lax.pmax(vec, axis_name)
