"""Ring-transport quantized all-reduce: reduce-scatter + all-gather rings
moving bit-packed eXmY payloads (EQuARX-style, PAPERS.md).

The faithful gather path (parallel/dist.py) ships every rank's FULL
gradient to every rank — (W-1)·n raw fp32 elements per device on the wire
and a (W, n) gathered stack resident — before the ordered requantizing
scan even starts.  The ring transport does the same class of ordered
quantized reduction while moving ~2·n·(W-1)/W elements per device (2/W of
the gather path's element count) at ``wire_bytes(exp, man)`` bytes each
(quant/numerics.pack_exmy), with O(n/W) peak transient memory: partial
sums — which are post-quantize and therefore always in the format's value
set, APS or not — are what rides the wire, never raw fp32.

Transport semantics (the documented per-chunk rank rotation)
-----------------------------------------------------------

The flat buffer is zero-padded to W·chunk and split into W chunks; device
d finishes owning chunk d.  Chunk c's partial starts on device (c+1) mod W
as ``q(0 + g_{c+1}[c])`` and hops rightward, each hop folding in the host
device's local contribution:

    hop t (t = 0..W-1): device (c+1+t) mod W applies
        res = q(res + g_{(c+1+t) mod W}[c])            (plain; sites 0)
        y = q(g - comp); tmp = q(res + y);              (Kahan; sites 0-3)
        comp = q(q(tmp - res) - y); res = tmp

so chunk c accumulates ranks in the ROTATED order (c+1, c+2, ..., c) mod
W — each chunk's order is a rotation of rank order, not rank order
itself.  A single unidirectional ring cannot give every chunk the
identical start rank while keeping all devices busy, so the rotation IS
the transport's semantics: deterministic, topology-independent, and
emulated bit-for-bit by the single-device `ring_oracle_sum` (the
correctness gate — tests assert bitwise equality distributed-vs-oracle
across formats, world sizes and rounding modes).  Versus the gather
path's single global rank order the result differs only by that
per-chunk rotation of the same ordered requantized sum; both are equally
faithful "some fixed documented order" reductions (the property psum
cannot give), and tests pin their statistical agreement.

Stochastic rounding composes transport-invariantly: per-element bits are
indexed by (key, hop step t, cast site, GLOBAL flat offset) — the same
(key, step, site, offset) scheme as reduction.py — so the oracle, the
distributed ring, and any resharding of the ring draw identical bits.

Kahan on a ring: the compensation term must ride along with the partial
(the next hop's casts need it), so the reduce-scatter phase ships 2
values per element; the all-gather phase ships only the result.  Still
~(W-1)·3/W elements per device vs the gather path's (W-1)·n.

The per-hop body is one fused quantize-accumulate kernel on TPU
(ops/quantize.quantize_add_pallas, sharing `cast_body` with everything
else); elsewhere the XLA composition of the same ops (bit-identical —
same body).

Wire integrity (ISSUE 4)
------------------------

``verify=True`` turns on the self-verifying transport: every hop
payload rides a tagged Fletcher checksum (parallel/integrity.hop_tag —
digest ^ hop-index ^ sender-rank, so flipped bits, dropped payloads AND
coherent stale self-echoes all fail at the receiving hop), the final
all-gather rows are tag-checked the same way, and the full reduced
vector's digest is pmin/pmax-agreed across replicas.  The function then
returns ``(vec, report)`` with replicated int32 scalars ``hop_bad`` /
``gather_bad`` (psum'd mismatch counts), ``agree`` and ``ok``.  The
scan-site checksums matter because a corrupted partial keeps hopping
and lands the SAME wrong sum on every replica — invisible to any
cross-replica comparison; the agreement digest matters because a
gather-site corruption diverges one replica — invisible to the hops it
never rode.

``fault=(code, rank)`` injects the matching deterministic wire faults
(resilience/inject.WIRE_KINDS: 1=flip one bit, 2=stale self-echo,
3=drop) into the first reduce-scatter hop AND the all-gather wire on
that rank — the attack exists independently of the defense, so a run
with ``verify=False`` silently computes a wrong (or divergent) sum,
which is exactly the EQuARX failure mode the checksums exist to catch.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..quant.numerics import (cast_to_format, cast_to_format_sr_at,
                              pack_exmy, sr_bits_at, unpack_exmy,
                              wire_bytes)

__all__ = ["ring_quantized_sum", "ring_oracle_sum", "ring_transport_bytes",
           "gather_transport_bytes", "transport_table", "pad_to_world",
           "ring_chunk_size", "hierarchical_ring_sum",
           "ring_oracle_sum_multi"]


def ring_chunk_size(n: int, world: int) -> int:
    """Elements per ring chunk: ceil(n / world) — one chunk per device.
    The same quantum parallel/zero.py shards its flat layouts by."""
    return math.ceil(n / world) if n else 0


def pad_to_world(flat: jnp.ndarray, world: int) -> jnp.ndarray:
    """Zero-pad a flat (n,) vector to world * ring_chunk_size(n, world).
    Exact zeros are rounding-invariant, so pad elements never perturb a
    quantized reduction (and are sliced off before returning)."""
    n = flat.shape[0]
    return jnp.pad(flat, (0, world * ring_chunk_size(n, world) - n))


def _make_hop_q(exp: int, man: int, key):
    """Per-hop quantizer ``q(x, step, site, offs)`` with reduction.py's
    exact bit-indexing contract: RTNE when key is None, else SR bits from
    (key, step, site, global offset).  Unlike reduction._make_q the
    offsets are a call argument — on the ring the chunk (hence its global
    offsets) a device is casting changes every hop."""
    if key is None:
        return lambda x, step, site, offs: cast_to_format(x, exp, man)

    def q(x, step, site, offs):
        k = jax.random.fold_in(jax.random.fold_in(key, step), site)
        return cast_to_format_sr_at(x, exp, man, k, offs)

    return q


def _hop_plain(q, res, g, t, offs, fp32_shortcut):
    """res = q(res + g) — one reduce-scatter hop.  At (8,23) non-Kahan the
    cast is skipped entirely (plain fp32 add), mirroring quantized_sum's
    reference-parity shortcut (dist_util.py:55-59): cast_to_format(8,23)
    would flush fp32-subnormal partials, which the reference's fp32 path
    never does."""
    if fp32_shortcut:
        return res + g
    return q(res + g, t, 0, offs)


def _hop_kahan(q, res, comp, g, t, offs):
    """One Kahan-compensated hop, sites 0-3 exactly as
    reduction.kahan_quantized_sum's scan body."""
    y = q(g - comp, t, 0, offs)
    tmp = q(res + y, t, 1, offs)
    comp = q(q(tmp - res, t, 2, offs) - y, t, 3, offs)
    return tmp, comp


def _flip_first_bit(x: jnp.ndarray) -> jnp.ndarray:
    """The minimal wire corruption: the lowest bit of the first word of
    a payload (uint8 code word or fp32 bit pattern) flipped."""
    flat = jnp.ravel(x)
    if flat.dtype == jnp.uint8:
        flat = flat.at[0].set(flat[0] ^ jnp.uint8(1))
    else:
        b = lax.bitcast_convert_type(flat, jnp.uint32)
        b = b.at[0].set(b[0] ^ jnp.uint32(1))
        flat = lax.bitcast_convert_type(b, x.dtype)
    return flat.reshape(x.shape)


def _apply_hop_fault(recv, rtag, sent, stag, code, active):
    """Corrupt a received (payload, tag) per the wire-fault code when
    `active` (resilience/inject.WIRE_KINDS).  ``stale`` replays this
    rank's own just-sent payload WITH its coherent tag — the corruption
    a bare payload checksum cannot catch (the tag's sender-rank fold
    does); ``flip``/``drop`` corrupt the payload under the ridden tag."""
    stale = active & (code == 2)
    recv = jnp.where(stale, sent, recv)
    rtag = jnp.where(stale, stag, rtag)
    recv = jnp.where(active & (code == 1), _flip_first_bit(recv), recv)
    recv = jnp.where(active & (code == 3), jnp.zeros_like(recv), recv)
    return recv, rtag


def _static_world(axis_name, world: Optional[int]) -> int:
    if world is not None:
        return int(world)
    w = lax.psum(1, axis_name)  # concrete int inside shard_map on jax 0.4
    try:
        return int(w)
    except (TypeError, jax.errors.TracerArrayConversionError) as e:
        raise ValueError(
            "ring transport needs the axis size as a static int at trace "
            "time; this JAX returned a traced psum — pass world= "
            "explicitly (e.g. mesh.shape[axis_name])") from e


def ring_quantized_sum(flat: jnp.ndarray, axis_name: str, exp: int, man: int,
                       *, use_kahan: bool = False, key=None,
                       offset_start: int = 0, packed: bool = True,
                       world: Optional[int] = None,
                       fused: Optional[bool] = None,
                       interpret: bool = False,
                       verify: bool = False,
                       fault: Optional[tuple] = None,
                       offsets: Optional[jnp.ndarray] = None):
    """Ordered quantized SUM of per-rank flat fp32 vectors over `axis_name`
    via a ppermute ring — call inside shard_map.

    Every rank passes its LOCAL (n,) fp32 contribution; every rank returns
    the full (n,) reduced vector (replicated).  Accumulation follows the
    per-chunk rank rotation documented in the module docstring, with every
    partial re-quantized to (exp, man) — `ring_oracle_sum` reproduces the
    result bit-for-bit on one device.

    packed       → ship hop payloads (and the final all-gather) as
                   bit-packed eXmY code words (pack_exmy) instead of fp32.
                   Lossless by construction — partials are post-cast, so
                   they live in the format's value set.  Auto-disabled for
                   formats the codec rejects (man < 2) and a no-op gain at
                   (8, 23) (4-byte code words).
    offset_start → global flat offset of flat[0] in the SR bit-index space
                   (parallel/dist.py's `_leaf_starts` space).
    offsets      → full per-element (n,) uint32 global offsets, for flats
                   that are NOT contiguous in the global space (a bucket
                   spanning non-adjacent leaves — parallel/dist.py's
                   bucketed ring).  Overrides ``offset_start``.  Pad
                   elements are exact zeros, whose cast is rounding-
                   invariant, so their (arbitrary) offsets never matter.
    world        → static axis size; default reads it from the axis.
    fused        → use the fused Pallas quantize-accumulate hop kernel
                   (ops/quantize.quantize_add_pallas; plain path only —
                   Kahan's 4-cast body stays XLA).  Default: TPU backend
                   only.  `interpret` runs that kernel in interpret mode
                   (CPU tests).
    verify       → self-verifying transport (module docstring): returns
                   ``(vec, report)`` with replicated int32 scalars
                   {hop_bad, gather_bad, agree, ok}.  The clean-path
                   result is BITWISE identical to verify=False — the
                   checksums observe the wire, they never touch it.
    fault        → ``(code, rank)`` int32 scalars injecting a
                   deterministic wire fault (inject.WIRE_KINDS; 0 = no
                   fault) into the first reduce-scatter hop and the
                   all-gather wire on that rank.  Applied whether or
                   not `verify` is on — the attack does not need the
                   defense's permission.
    """
    if isinstance(axis_name, (tuple, list)):
        raise ValueError("ring transport runs over exactly one mesh axis; "
                         f"got {axis_name!r}")
    w = _static_world(axis_name, world)
    n = flat.shape[0]
    flat = jnp.asarray(flat, jnp.float32)
    fp32_shortcut = exp == 8 and man == 23 and not use_kahan
    if man < 2 or (exp == 8 and man == 23):
        packed = packed and not (man < 2)
        packed = packed and not fp32_shortcut  # 4-byte words: skip the work
    if fused is None:
        fused = jax.default_backend() == "tpu"
    if fused and (use_kahan or fp32_shortcut):
        fused = False

    padded = pad_to_world(flat, w)
    chunk = padded.shape[0] // w if w else 0
    padded_offs = None
    if offsets is not None:
        if offsets.shape != (n,):
            raise ValueError(f"offsets must be shape ({n},), got "
                             f"{offsets.shape}")
        padded_offs = pad_to_world(offsets.astype(jnp.uint32), w)
    if n == 0:
        if verify:
            i0, i1 = jnp.zeros([], jnp.int32), jnp.ones([], jnp.int32)
            return flat, {"hop_bad": i0, "gather_bad": i0,
                          "agree": i1, "ok": i1}
        return flat
    rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % w) for i in range(w)]
    q = _make_hop_q(exp, man, key)

    def chunk_at(t):
        """Chunk index this device's partial holds after hop t."""
        return jnp.mod(rank.astype(jnp.int32) - 1 - t, w)

    def local_chunk(c):
        return lax.dynamic_slice_in_dim(padded, c * chunk, chunk)

    def offs_of(c):
        if padded_offs is not None:
            return lax.dynamic_slice_in_dim(
                padded_offs, c.astype(jnp.int32) * chunk, chunk)
        return (jnp.uint32(offset_start)
                + c.astype(jnp.uint32) * jnp.uint32(chunk)
                + jnp.arange(chunk, dtype=jnp.uint32))

    def accum(res, comp, t, c):
        g = local_chunk(c)
        offs = offs_of(c)
        if use_kahan:
            return _hop_kahan(q, res, comp, g, t, offs)
        if fused and key is None:
            from ..ops.quantize import quantize_add_pallas
            return quantize_add_pallas(res, g, exp, man,
                                       interpret=interpret), comp
        if fused:
            from ..ops.quantize import quantize_add_pallas_bits
            k = jax.random.fold_in(jax.random.fold_in(key, t), 0)
            return quantize_add_pallas_bits(res, g, exp, man,
                                            sr_bits_at(k, offs),
                                            interpret=interpret), comp
        return _hop_plain(q, res, g, t, offs, fp32_shortcut), comp

    def to_wire(res, comp):
        payload = jnp.stack([res, comp]) if use_kahan else res
        return pack_exmy(payload, exp, man) if packed else payload

    def from_wire(p):
        payload = unpack_exmy(p, exp, man) if packed else p
        if use_kahan:
            return payload[0], payload[1]
        return payload, jnp.zeros_like(payload)

    # hop 0: quantize the local chunk in place (res = q(0 + g)); no wire
    zero = jnp.zeros((chunk,), jnp.float32)
    res, comp = accum(zero, zero, jnp.int32(0), chunk_at(0))

    if not verify and fault is None:
        # the plain transport, untouched: zero checksum work, and the
        # oracle-parity tests gate this exact path bitwise
        def body(carry, t):
            res, comp = from_wire(lax.ppermute(carry, axis_name, perm))
            res, comp = accum(res, comp, t, chunk_at(t))
            return to_wire(res, comp), None

        carry, _ = lax.scan(body, to_wire(res, comp),
                            jnp.arange(1, w, dtype=jnp.int32))
        res, _ = from_wire(carry)
        # res is now the reduced chunk `rank`; ring all-gather of the
        # packed chunks rebuilds the full vector (XLA lowers all_gather
        # as a ring on the TPU torus, so the wire cost is the (W-1)
        # chunk hops accounted in ring_transport_bytes — with the
        # payload still bit-packed).
        wire = pack_exmy(res, exp, man) if packed else res
        gathered = lax.all_gather(wire, axis_name, axis=0, tiled=False)
        full = unpack_exmy(gathered, exp, man) if packed else gathered
        return full.reshape(-1)[:n]

    # --- verified / fault-injected transport (module docstring) ------
    from .integrity import digest_agree, hop_tag, wire_digest
    rank_i = rank.astype(jnp.int32)
    f_code = (jnp.asarray(fault[0], jnp.int32) if fault is not None
              else jnp.zeros([], jnp.int32))
    f_rank = (jnp.asarray(fault[1], jnp.int32) if fault is not None
              else jnp.zeros([], jnp.int32))
    on_me = (f_code > 0) & (rank_i == f_rank)

    def vbody(carry, t):
        wire, tag, bad = carry
        recv = lax.ppermute(wire, axis_name, perm)
        rtag = lax.ppermute(tag, axis_name, perm)
        recv, rtag = _apply_hop_fault(recv, rtag, wire, tag, f_code,
                                      on_me & (t == jnp.int32(1)))
        # the left neighbor built its tag for exactly this (hop, sender)
        bad = bad + (hop_tag(recv, t, jnp.mod(rank_i - 1, w))
                     != rtag).astype(jnp.int32)
        res, comp = from_wire(recv)
        res, comp = accum(res, comp, t, chunk_at(t))
        new_wire = to_wire(res, comp)
        return (new_wire, hop_tag(new_wire, t + 1, rank_i), bad), None

    wire0 = to_wire(res, comp)
    (wire_f, _, hop_bad), _ = lax.scan(
        vbody, (wire0, hop_tag(wire0, jnp.int32(1), rank_i),
                jnp.zeros([], jnp.int32)),
        jnp.arange(1, w, dtype=jnp.int32))
    res, _ = from_wire(wire_f)

    # all-gather wire, row-tagged: row i's tag is built by rank i with
    # hop index 0 (scan hops use t >= 1, so no aliasing)
    gwire = pack_exmy(res, exp, man) if packed else res
    gtag = hop_tag(gwire, jnp.int32(0), rank_i)
    gathered = lax.all_gather(gwire, axis_name, axis=0, tiled=False)
    gtags = lax.all_gather(gtag, axis_name, axis=0, tiled=False)
    # gather-site fault: rank k's RECEIVED copy of row (k+1) mod W is
    # corrupted — only that replica's rebuilt vector diverges, which is
    # the case the cross-replica agreement digest exists for
    j = jnp.mod(rank_i + 1, w)
    row = jnp.take(gathered, j, axis=0)
    new_row = jnp.where(f_code == 2, gwire, row)       # stale: own row
    new_row = jnp.where(f_code == 1, _flip_first_bit(row), new_row)
    new_row = jnp.where(f_code == 3, jnp.zeros_like(row), new_row)
    gathered = jnp.where(on_me, gathered.at[j].set(new_row), gathered)
    gtags = jnp.where(on_me & (f_code == 2), gtags.at[j].set(gtag),
                      gtags)
    row_tags = jax.vmap(
        lambda r, i: hop_tag(r, jnp.int32(0), i))(
            gathered, jnp.arange(w, dtype=jnp.int32))
    gather_bad = jnp.sum((row_tags != gtags).astype(jnp.int32))
    full = (unpack_exmy(gathered, exp, man) if packed
            else gathered).reshape(-1)[:n]
    if not verify:
        return full
    report = {
        "hop_bad": lax.psum(hop_bad, axis_name),
        "gather_bad": lax.psum(gather_bad, axis_name),
        "agree": digest_agree(wire_digest(full), axis_name),
    }
    report["ok"] = ((report["hop_bad"] == 0) & (report["gather_bad"] == 0)
                    & (report["agree"] == 1)).astype(jnp.int32)
    return full, report


def ring_oracle_sum(stacked: jnp.ndarray, exp: int, man: int, *,
                    use_kahan: bool = False, key=None,
                    offset_start: int = 0,
                    offsets: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Single-device oracle for the ring transport: given the stacked
    per-rank contributions (W, *shape), reproduce `ring_quantized_sum`'s
    result bit-for-bit — the per-chunk rank rotation, the per-hop casts
    with their (step, site, global-offset) SR bit indexing, the (8,23)
    fp32 shortcut, everything except the wire.

    The distributed path and this oracle share the hop-body functions
    (`_hop_plain` / `_hop_kahan` / `_make_hop_q`), so a divergence can
    only come from the transport itself — which is exactly what the
    oracle-parity tests gate."""
    w = stacked.shape[0]
    n = int(stacked[0].size)
    shape = stacked.shape[1:]
    if n == 0:
        return jnp.zeros(shape, jnp.float32)
    flat = jnp.reshape(jnp.asarray(stacked, jnp.float32), (w, n))
    chunk = ring_chunk_size(n, w)
    padded = jnp.pad(flat, ((0, 0), (0, w * chunk - n)))
    per_chunk = padded.reshape(w, w, chunk)        # [rank, chunk, elem]
    # contribution visiting chunk c at hop t comes from rank (c+1+t) mod w
    t_idx = jnp.arange(w)[:, None]
    c_idx = jnp.arange(w)[None, :]
    order = jnp.mod(c_idx + 1 + t_idx, w)          # [hop, chunk]
    hops = per_chunk[order, c_idx, :]              # [hop, chunk, elem]
    if offsets is not None:
        offs = jnp.pad(offsets.astype(jnp.uint32).reshape(-1),
                       (0, w * chunk - n)).reshape(w, chunk)
    else:
        offs = (jnp.uint32(offset_start)
                + (c_idx.astype(jnp.uint32) * jnp.uint32(chunk))[..., None]
                + jnp.arange(chunk, dtype=jnp.uint32)[None, None, :])[0]
    q = _make_hop_q(exp, man, key)
    fp32_shortcut = exp == 8 and man == 23 and not use_kahan

    def body(carry, xs):
        res, comp = carry
        t, g = xs
        if use_kahan:
            res, comp = _hop_kahan(q, res, comp, g, t, offs)
        else:
            res = _hop_plain(q, res, g, t, offs, fp32_shortcut)
        return (res, comp), None

    zero = jnp.zeros((w, chunk), jnp.float32)
    (res, _), _ = lax.scan(
        body, (zero, zero), (jnp.arange(w, dtype=jnp.int32), hops))
    return res.reshape(-1)[:n].reshape(shape)


def hierarchical_ring_sum(flat: jnp.ndarray, axis_names, exp: int, man: int,
                          *, use_kahan: bool = False, key=None,
                          offset_start: int = 0,
                          offsets: Optional[jnp.ndarray] = None,
                          packed: bool = True,
                          fused: Optional[bool] = None,
                          interpret: bool = False,
                          verify: bool = False,
                          fault: Optional[tuple] = None):
    """Ring all-reduce composed over one OR several mesh axes.

    A single axis (plain string, or a 1-tuple) is exactly
    `ring_quantized_sum` — same bits, same program.  For k > 1 axes the
    reduction runs as k sequential per-axis rings, INNERMOST (last-named)
    axis first: per the mesh convention (parallel/mesh.py) the last axis
    is the fastest ICI ring, so the large fan-in happens on the cheap
    wire and the outer axes ring over already-reduced partials — the
    hierarchical intra-axis-then-inter-axis reduce of the MLPerf TPU-pod
    recipe (PAPERS.md #4).  Stage ``s`` reduces over ``axes[-1-s]`` with
    SR key ``fold_in(key, s)`` (stages must draw independent bits — the
    same (hop, site, offset) indices recur at every stage), and the
    result is the per-axis composition of the documented per-chunk rank
    rotation — reproduced bit-for-bit by `ring_oracle_sum_multi`.

    verify → every stage runs the self-verifying transport; the merged
    report sums ``hop_bad`` / ``gather_bad`` across all rings of all
    stages (psum over the non-stage axes makes the totals replicated),
    ANDs the per-stage agreement verdicts, and adds a FINAL cross-mesh
    agreement digest over every axis at once — a divergence introduced
    between stages (or on the last gather wire) cannot hide in a
    single-axis check.

    fault → injected into stage 0 only, and only on the one stage-0 ring
    whose other-axes indices are all zero: exactly ONE corruption fires,
    so the chaos drills' exact counter expectations (one flip →
    hop_bad == 1) hold on any mesh shape.
    """
    axes = ((axis_names,) if isinstance(axis_names, str)
            else tuple(axis_names))
    if not axes:
        raise ValueError("hierarchical_ring_sum needs at least one axis")
    kw = dict(use_kahan=use_kahan, offset_start=offset_start,
              offsets=offsets, packed=packed, fused=fused,
              interpret=interpret)
    if len(axes) == 1:
        return ring_quantized_sum(flat, axes[0], exp, man, key=key,
                                  verify=verify, fault=fault, **kw)

    vec = flat
    stage_reports = []
    for s in range(len(axes)):
        ax = axes[-1 - s]
        k_s = None if key is None else jax.random.fold_in(key, s)
        f_s = None
        if fault is not None and s == 0:
            on_slice = jnp.int32(1)
            for other in axes[:-1]:
                on_slice = on_slice * (
                    lax.axis_index(other) == 0).astype(jnp.int32)
            f_s = (jnp.where(on_slice == 1,
                             jnp.asarray(fault[0], jnp.int32),
                             jnp.int32(0)),
                   jnp.asarray(fault[1], jnp.int32))
        out = ring_quantized_sum(vec, ax, exp, man, key=k_s,
                                 verify=verify, fault=f_s, **kw)
        if verify:
            vec, rep = out
            stage_reports.append((ax, rep))
        else:
            vec = out
    if not verify:
        return vec

    from .integrity import digest_agree, wire_digest
    hop_bad = jnp.zeros([], jnp.int32)
    gather_bad = jnp.zeros([], jnp.int32)
    agree = jnp.ones([], jnp.int32)
    for ax, rep in stage_reports:
        other = tuple(a for a in axes if a != ax)
        hop_bad = hop_bad + lax.psum(rep["hop_bad"], other)
        gather_bad = gather_bad + lax.psum(rep["gather_bad"], other)
        agree = jnp.minimum(agree, lax.pmin(rep["agree"], other))
    agree = jnp.minimum(agree, digest_agree(wire_digest(vec), axes))
    report = {"hop_bad": hop_bad, "gather_bad": gather_bad,
              "agree": agree}
    report["ok"] = ((hop_bad == 0) & (gather_bad == 0)
                    & (agree == 1)).astype(jnp.int32)
    return vec, report


def ring_oracle_sum_multi(stacked: jnp.ndarray, n_axes: int, exp: int,
                          man: int, *, use_kahan: bool = False, key=None,
                          offset_start: int = 0,
                          offsets: Optional[jnp.ndarray] = None
                          ) -> jnp.ndarray:
    """Single-device oracle for `hierarchical_ring_sum`: ``stacked`` has
    shape ``(W_0, ..., W_{k-1}, *leaf)`` with the leading dims in mesh
    AXIS-NAME order; the reduction folds the LAST leading axis first
    (the innermost mesh axis), stage ``s`` drawing SR bits from
    ``fold_in(key, s)`` — exactly the distributed composition.  With
    ``n_axes == 1`` this is `ring_oracle_sum` (unfolded key, the legacy
    single-axis bitstream)."""
    if n_axes < 1 or stacked.ndim < n_axes:
        raise ValueError(f"n_axes={n_axes} does not fit stacked shape "
                         f"{stacked.shape}")
    kw = dict(use_kahan=use_kahan, offset_start=offset_start,
              offsets=offsets)
    if n_axes == 1:
        return ring_oracle_sum(stacked, exp, man, key=key, **kw)
    vec = stacked
    for s in range(n_axes):
        k_s = None if key is None else jax.random.fold_in(key, s)
        lead = vec.shape[:n_axes - s]
        tail = vec.shape[n_axes - s:]
        rest = int(np.prod(lead[:-1])) if lead[:-1] else 1
        flat = vec.reshape((rest, lead[-1]) + tail)
        red = jax.vmap(lambda st, k=k_s: ring_oracle_sum(
            st, exp, man, key=k, **kw))(flat)
        vec = red.reshape(lead[:-1] + tail)
    return vec


def ring_transport_bytes(n: int, world: int, exp: int, man: int, *,
                         use_kahan: bool = False,
                         packed: bool = True) -> int:
    """Analytic per-device wire bytes for one ring all-reduce of n
    elements: (W-1) reduce-scatter hops of one chunk (×2 with Kahan — the
    compensation rides) plus (W-1) all-gather hops of one chunk."""
    if n == 0 or world <= 0:
        return 0
    chunk = ring_chunk_size(n, world)
    per_elem = wire_bytes(exp, man) if packed else 4
    reduce_phase = (world - 1) * chunk * per_elem * (2 if use_kahan else 1)
    gather_phase = (world - 1) * chunk * per_elem
    return reduce_phase + gather_phase


def gather_transport_bytes(n: int, world: int, exp: int, man: int, *,
                           compressed: bool = False) -> int:
    """Analytic per-device wire bytes for the faithful all_gather path:
    (W-1)·n elements, raw fp32 unless the APS-prequantized wire packing
    applies (`compressed`)."""
    if n == 0 or world <= 0:
        return 0
    per_elem = wire_bytes(exp, man) if compressed else 4
    return (world - 1) * n * per_elem


def transport_table(n: int, world: int, exp: int, man: int,
                    use_kahan: bool = False) -> dict:
    """Analytic per-device bytes-on-wire for every transport of one
    all-reduce of n elements — the payload of bench.py's `reduction`
    block and tools/bench_reduce.py.  One home for the comparison so the
    ledger, the tool and docs/PERF.md's table cannot drift."""
    compressible = man >= 2 and wire_bytes(exp, man) < 4
    gather = gather_transport_bytes(n, world, exp, man, compressed=False)
    table = {
        "faithful_gather_fp32": gather,
        "faithful_gather_packed": (
            gather_transport_bytes(n, world, exp, man, compressed=True)
            if compressible else None),  # needs APS pre-quantized values
        "ring_packed": ring_transport_bytes(n, world, exp, man,
                                            use_kahan=use_kahan,
                                            packed=compressible),
        # XLA lowers psum as a ring reduce-scatter + all-gather on the
        # TPU torus, but the payload stays fp32 (psum cannot know the
        # values fit a narrower format — EQuARX's whole point), so fast
        # mode's wire is exactly the UNPACKED ring: 2·(W-1)·(n/W)·4
        "fast_psum_fp32": ring_transport_bytes(n, world, 8, 23,
                                               packed=False),
    }
    table["ring_vs_gather_ratio"] = (
        round(gather / table["ring_packed"], 2) if table["ring_packed"]
        else None)
    return table
