"""Ring-transport quantized all-reduce: reduce-scatter + all-gather rings
moving bit-packed eXmY payloads (EQuARX-style, PAPERS.md).

The faithful gather path (parallel/dist.py) ships every rank's FULL
gradient to every rank — (W-1)·n raw fp32 elements per device on the wire
and a (W, n) gathered stack resident — before the ordered requantizing
scan even starts.  The ring transport does the same class of ordered
quantized reduction while moving ~2·n·(W-1)/W elements per device (2/W of
the gather path's element count) at ``wire_bytes(exp, man)`` bytes each
(quant/numerics.pack_exmy), with O(n/W) peak transient memory: partial
sums — which are post-quantize and therefore always in the format's value
set, APS or not — are what rides the wire, never raw fp32.

Transport semantics (the documented per-chunk rank rotation)
-----------------------------------------------------------

The flat buffer is zero-padded to W·chunk and split into W chunks; device
d finishes owning chunk d.  Chunk c's partial starts on device (c+1) mod W
as ``q(0 + g_{c+1}[c])`` and hops rightward, each hop folding in the host
device's local contribution:

    hop t (t = 0..W-1): device (c+1+t) mod W applies
        res = q(res + g_{(c+1+t) mod W}[c])            (plain; sites 0)
        y = q(g - comp); tmp = q(res + y);              (Kahan; sites 0-3)
        comp = q(q(tmp - res) - y); res = tmp

so chunk c accumulates ranks in the ROTATED order (c+1, c+2, ..., c) mod
W — each chunk's order is a rotation of rank order, not rank order
itself.  A single unidirectional ring cannot give every chunk the
identical start rank while keeping all devices busy, so the rotation IS
the transport's semantics: deterministic, topology-independent, and
emulated bit-for-bit by the single-device `ring_oracle_sum` (the
correctness gate — tests assert bitwise equality distributed-vs-oracle
across formats, world sizes and rounding modes).  Versus the gather
path's single global rank order the result differs only by that
per-chunk rotation of the same ordered requantized sum; both are equally
faithful "some fixed documented order" reductions (the property psum
cannot give), and tests pin their statistical agreement.

Stochastic rounding composes transport-invariantly: per-element bits are
indexed by (key, hop step t, cast site, GLOBAL flat offset) — the same
(key, step, site, offset) scheme as reduction.py — so the oracle, the
distributed ring, and any resharding of the ring draw identical bits.

Kahan on a ring: the compensation term must ride along with the partial
(the next hop's casts need it), so the reduce-scatter phase ships 2
values per element; the all-gather phase ships only the result.  Still
~(W-1)·3/W elements per device vs the gather path's (W-1)·n.

The per-hop body is one fused quantize-accumulate kernel on TPU
(ops/quantize.quantize_add_pallas, sharing `cast_body` with everything
else); elsewhere the XLA composition of the same ops (bit-identical —
same body).

Wire integrity (ISSUE 4)
------------------------

``verify=True`` turns on the self-verifying transport: every hop
payload rides a tagged Fletcher checksum (parallel/integrity.hop_tag —
digest ^ hop-index ^ sender-rank, so flipped bits, dropped payloads AND
coherent stale self-echoes all fail at the receiving hop), the final
all-gather rows are tag-checked the same way, and each rank's WHOLE
gathered wire digest — composed from the per-row digests it just
computed, via `integrity.digest_concat` (the reconstructed vector is a
deterministic function of those bytes, so wire agreement IS vector
agreement, without a second full-vector hash pass) — is pmin/pmax-
agreed across replicas.  On the fused wire path the per-hop digests
come out of the pack kernel itself (ops/quantize.hop_pack_pallas) —
verification is not a separate pass over the wire words.  The function
returns ``(vec, report)`` with replicated int32 scalars ``hop_bad`` /
``gather_bad`` (psum'd mismatch counts), ``agree`` and ``ok``.  The
scan-site checksums matter because a corrupted partial keeps hopping
and lands the SAME wrong sum on every replica — invisible to any
cross-replica comparison; the agreement digest matters because a
gather-site corruption diverges one replica — invisible to the hops it
never rode.

``fault=(code, rank)`` injects the matching deterministic wire faults
(resilience/inject.WIRE_KINDS: 1=flip one bit, 2=stale self-echo,
3=drop) into the first reduce-scatter hop AND the all-gather wire on
that rank — the attack exists independently of the defense, so a run
with ``verify=False`` silently computes a wrong (or divergent) sum,
which is exactly the EQuARX failure mode the checksums exist to catch.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..quant.numerics import (cast_body_blocked, cast_to_format,
                              cast_to_format_sr_at, pack_exmy,
                              pack_exmy_blocked, sr_bits_at,
                              unpack_exmy, unpack_exmy_blocked, wire_bytes,
                              wire_bytes_blocked)

__all__ = ["ring_quantized_sum", "ring_oracle_sum", "ring_transport_bytes",
           "gather_transport_bytes", "transport_table", "pad_to_world",
           "reflatten_to_world", "ring_chunk_size", "hierarchical_ring_sum",
           "ring_oracle_sum_multi"]


def ring_chunk_size(n: int, world: int) -> int:
    """Elements per ring chunk: ceil(n / world) — one chunk per device.
    The same quantum parallel/zero.py shards its flat layouts by."""
    return math.ceil(n / world) if n else 0


def pad_to_world(flat: jnp.ndarray, world: int) -> jnp.ndarray:
    """Zero-pad a flat (n,) vector to world * ring_chunk_size(n, world).
    Exact zeros are rounding-invariant, so pad elements never perturb a
    quantized reduction (and are sliced off before returning)."""
    n = flat.shape[0]
    return jnp.pad(flat, (0, world * ring_chunk_size(n, world) - n))


def reflatten_to_world(flat: jnp.ndarray, total: int,
                       world: int) -> jnp.ndarray:
    """Re-shard a world-padded flat layout for a DIFFERENT world size:
    trim the old pad (the real data is the first ``total`` elements —
    the invariant every padded flat layout here keeps, because exact-zero
    grads leave exact-zero momentum in the pad) and re-pad through
    `pad_to_world` at the new world.  Bitwise-faithful in both
    directions, for ANY world pair — including non-divisible shrinks
    (8 -> 3): only the pad length changes, never a data element.  The
    runtime half of the elastic-restart contract (ISSUE 4/19): the
    checkpoint layer re-flattens through this on a ``world=`` restore,
    and the elastic shrink/regrow path re-flattens live ZeRO state the
    same way."""
    if total > flat.shape[0]:
        raise ValueError(
            f"reflatten_to_world: flat layout holds {flat.shape[0]} "
            f"elements but total={total} are claimed as data — the "
            f"caller's layout and parameter count disagree")
    return pad_to_world(flat[:total], world)


def _make_hop_q(exp: int, man: int, key, block: Optional[int] = None):
    """Per-hop quantizer ``q(x, step, site, offs)`` with reduction.py's
    exact bit-indexing contract: RTNE when key is None, else SR bits from
    (key, step, site, global offset).  Unlike reduction._make_q the
    offsets are a call argument — on the ring the chunk (hence its global
    offsets) a device is casting changes every hop.

    ``block`` switches every cast site to the block-scaled cast
    (`numerics.cast_body_blocked`, blocks of ``block`` elements along the
    LAST axis): each block of the partial is power-of-2-shifted to the
    format's top exponent before the cast and shifted back after — the
    EQuARX-style wire.  The distributed ring and `ring_oracle_sum` share
    this one factory, so the blocked transport is oracle-gated exactly
    like the per-tensor one."""
    if key is None:
        if block is None:
            return lambda x, step, site, offs: cast_to_format(x, exp, man)
        return lambda x, step, site, offs: cast_body_blocked(
            x, exp, man, block)

    def q(x, step, site, offs):
        k = jax.random.fold_in(jax.random.fold_in(key, step), site)
        if block is None:
            return cast_to_format_sr_at(x, exp, man, k, offs)
        rbits = jnp.broadcast_to(sr_bits_at(k, offs), jnp.shape(x))
        return cast_body_blocked(x, exp, man, block, rbits=rbits)

    return q


def _hop_plain(q, res, g, t, offs, fp32_shortcut):
    """res = q(res + g) — one reduce-scatter hop.  At (8,23) non-Kahan the
    cast is skipped entirely (plain fp32 add), mirroring quantized_sum's
    reference-parity shortcut (dist_util.py:55-59): cast_to_format(8,23)
    would flush fp32-subnormal partials, which the reference's fp32 path
    never does."""
    if fp32_shortcut:
        return res + g
    return q(res + g, t, 0, offs)


def _hop_kahan(q, res, comp, g, t, offs):
    """One Kahan-compensated hop, sites 0-3 exactly as
    reduction.kahan_quantized_sum's scan body."""
    y = q(g - comp, t, 0, offs)
    tmp = q(res + y, t, 1, offs)
    comp = q(q(tmp - res, t, 2, offs) - y, t, 3, offs)
    return tmp, comp


def _flip_first_bit(x: jnp.ndarray) -> jnp.ndarray:
    """The minimal wire corruption: the lowest bit of the first word of
    a payload (uint8 code word or fp32 bit pattern) flipped."""
    flat = jnp.ravel(x)
    if flat.dtype == jnp.uint8:
        flat = flat.at[0].set(flat[0] ^ jnp.uint8(1))
    else:
        b = lax.bitcast_convert_type(flat, jnp.uint32)
        b = b.at[0].set(b[0] ^ jnp.uint32(1))
        flat = lax.bitcast_convert_type(b, x.dtype)
    return flat.reshape(x.shape)


def _apply_hop_fault(recv, sent, code, active):
    """Corrupt a received payload per the wire-fault code when `active`
    (resilience/inject.WIRE_KINDS).  ``stale`` replays this rank's own
    just-sent payload; ``flip`` flips one bit; ``drop`` zeroes.  The
    deferred tag compare (sender-side tag of what was actually sent vs
    receiver-side tag of what actually arrived) catches all three by
    CONTENT: any replay/flip/drop whose bytes differ from the genuine
    payload fails the end-to-end compare, and one whose bytes happen to
    be identical is by definition a no-op on the sum — there is nothing
    to detect."""
    stale = active & (code == 2)
    recv = jnp.where(stale, sent, recv)
    recv = jnp.where(active & (code == 1), _flip_first_bit(recv), recv)
    recv = jnp.where(active & (code == 3), jnp.zeros_like(recv), recv)
    return recv


def _static_world(axis_name, world: Optional[int]) -> int:
    if world is not None:
        return int(world)
    w = lax.psum(1, axis_name)  # concrete int inside shard_map on jax 0.4
    try:
        return int(w)
    except (TypeError, jax.errors.TracerArrayConversionError) as e:
        raise ValueError(
            "ring transport needs the axis size as a static int at trace "
            "time; this JAX returned a traced psum — pass world= "
            "explicitly (e.g. mesh.shape[axis_name])") from e


def ring_quantized_sum(flat: jnp.ndarray, axis_name: str, exp: int, man: int,
                       *, use_kahan: bool = False, key=None,
                       offset_start: int = 0, packed: bool = True,
                       world: Optional[int] = None,
                       fused: Optional[bool] = None,
                       interpret: bool = False,
                       verify: bool = False,
                       fault: Optional[tuple] = None,
                       offsets: Optional[jnp.ndarray] = None,
                       block_scale: bool = False,
                       block_size: int = 128):
    """Ordered quantized SUM of per-rank flat fp32 vectors over `axis_name`
    via a ppermute ring — call inside shard_map.

    Every rank passes its LOCAL (n,) fp32 contribution; every rank returns
    the full (n,) reduced vector (replicated).  Accumulation follows the
    per-chunk rank rotation documented in the module docstring, with every
    partial re-quantized to (exp, man) — `ring_oracle_sum` reproduces the
    result bit-for-bit on one device.

    packed       → ship hop payloads (and the final all-gather) as
                   bit-packed eXmY code words (pack_exmy) instead of fp32.
                   Lossless by construction — partials are post-cast, so
                   they live in the format's value set.  Auto-disabled for
                   formats the codec rejects (man < 2) and a no-op gain at
                   (8, 23) (4-byte code words).
    offset_start → global flat offset of flat[0] in the SR bit-index space
                   (parallel/dist.py's `_leaf_starts` space).
    offsets      → full per-element (n,) uint32 global offsets, for flats
                   that are NOT contiguous in the global space (a bucket
                   spanning non-adjacent leaves — parallel/dist.py's
                   bucketed ring).  Overrides ``offset_start``.  Pad
                   elements are exact zeros, whose cast is rounding-
                   invariant, so their (arbitrary) offsets never matter.
    world        → static axis size; default reads it from the axis.
    fused        → use the fused Pallas quantize-accumulate hop kernel
                   (ops/quantize.quantize_add_pallas; plain path only —
                   Kahan's 4-cast body stays XLA).  Default: TPU backend
                   only.  `interpret` runs that kernel in interpret mode
                   (CPU tests).
    verify       → self-verifying transport (module docstring): returns
                   ``(vec, report)`` with replicated int32 scalars
                   {hop_bad, gather_bad, agree, ok}.  The clean-path
                   result is BITWISE identical to verify=False — the
                   checksums observe the wire, they never touch it.
    fault        → ``(code, rank)`` int32 scalars injecting a
                   deterministic wire fault (inject.WIRE_KINDS; 0 = no
                   fault) into the first reduce-scatter hop and the
                   all-gather wire on that rank.  Applied whether or
                   not `verify` is on — the attack does not need the
                   defense's permission.
    block_scale  → block-scaled wire (EQuARX-style; quant/numerics.py
                   "Block-scaled eXmY codec"): every hop cast shares one
                   power-of-2 scale per ``block_size`` consecutive
                   elements (chunk-local blocks, odd tail included), and
                   the 1-byte-per-block shift sidecar rides the packed
                   wire next to the code words.  Different accumulation
                   NUMERICS than the per-tensor cast — gated by its own
                   extended oracle (`ring_oracle_sum(block_size=...)`),
                   NOT bitwise comparable to block_scale=False.
                   Requires a packable format (man >= 2, not (8, 23)).
    block_size   → elements per shared-scale block (static; default 128
                   — one fp32 cache line's worth per scale byte).
    """
    if isinstance(axis_name, (tuple, list)):
        raise ValueError("ring transport runs over exactly one mesh axis; "
                         f"got {axis_name!r}")
    w = _static_world(axis_name, world)
    n = flat.shape[0]
    flat = jnp.asarray(flat, jnp.float32)
    fp32_shortcut = exp == 8 and man == 23 and not use_kahan
    if block_scale:
        if exp == 8 and man == 23:
            raise ValueError("block_scale=True at (8, 23): the fp32 wire "
                             "has nothing to scale — drop block_scale or "
                             "pick a sub-fp32 format")
        if man < 2:
            raise ValueError(
                f"block_scale=True needs a packable format (man_bits >= 2 "
                f"for the codec's special codes), got ({exp}, {man})")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if not packed:
            raise ValueError("block_scale=True IS the packed sidecar wire; "
                             "packed=False contradicts it")
    if man < 2 or (exp == 8 and man == 23):
        packed = packed and not (man < 2)
        packed = packed and not fp32_shortcut  # 4-byte words: skip the work
    if fused is None:
        fused = jax.default_backend() == "tpu"
    if fused and (use_kahan or fp32_shortcut):
        fused = False
    # the single-kernel wire path (ops/quantize.hop_pack_pallas): packed
    # plain hops, and blocked hops whose blocks are whole kernel rows
    # (a multiple of the 128-lane width dividing the 64k-element tile —
    # the default block_size=128 qualifies); other shapes ride the XLA
    # composition of the same bodies
    fused_wire = (fused and packed and not use_kahan
                  and (not block_scale
                       or (block_size % 128 == 0
                           and 65536 % block_size == 0)))

    padded = pad_to_world(flat, w)
    chunk = padded.shape[0] // w if w else 0
    padded_offs = None
    if offsets is not None:
        if offsets.shape != (n,):
            raise ValueError(f"offsets must be shape ({n},), got "
                             f"{offsets.shape}")
        padded_offs = pad_to_world(offsets.astype(jnp.uint32), w)
    if n == 0:
        if verify:
            i0, i1 = jnp.zeros([], jnp.int32), jnp.ones([], jnp.int32)
            return flat, {"hop_bad": i0, "gather_bad": i0,
                          "agree": i1, "ok": i1}
        return flat
    rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % w) for i in range(w)]
    blk = block_size if block_scale else None
    q = _make_hop_q(exp, man, key, block=blk)

    def chunk_at(t):
        """Chunk index this device's partial holds after hop t."""
        return jnp.mod(rank.astype(jnp.int32) - 1 - t, w)

    def local_chunk(c):
        return lax.dynamic_slice_in_dim(padded, c * chunk, chunk)

    def offs_of(c):
        if padded_offs is not None:
            return lax.dynamic_slice_in_dim(
                padded_offs, c.astype(jnp.int32) * chunk, chunk)
        return (jnp.uint32(offset_start)
                + c.astype(jnp.uint32) * jnp.uint32(chunk)
                + jnp.arange(chunk, dtype=jnp.uint32))

    def hop_rbits(t, c):
        k = jax.random.fold_in(jax.random.fold_in(key, t), 0)
        return sr_bits_at(k, offs_of(c))

    def accum(res, comp, t, c):
        g = local_chunk(c)
        offs = offs_of(c)
        if use_kahan:
            return _hop_kahan(q, res, comp, g, t, offs)
        if fused and not fused_wire:
            # legacy fused hop (unpacked wires): add+cast only
            if key is None:
                from ..ops.quantize import quantize_add_pallas
                return quantize_add_pallas(res, g, exp, man,
                                           interpret=interpret), comp
            from ..ops.quantize import quantize_add_pallas_bits
            return quantize_add_pallas_bits(res, g, exp, man,
                                            hop_rbits(t, c),
                                            interpret=interpret), comp
        return _hop_plain(q, res, g, t, offs, fp32_shortcut), comp

    def to_wire(res, comp):
        payload = jnp.stack([res, comp]) if use_kahan else res
        if block_scale:
            return pack_exmy_blocked(payload, exp, man, block_size)
        return pack_exmy(payload, exp, man) if packed else payload

    def from_wire(p):
        if block_scale:
            payload = unpack_exmy_blocked(p, exp, man, chunk, block_size)
        else:
            payload = unpack_exmy(p, exp, man) if packed else p
        if use_kahan:
            return payload[0], payload[1]
        return payload, jnp.zeros_like(payload)

    def fused_hop(recv_wire, t, c, want_digest):
        """The single-kernel wire path: unpack + add + (scale+)cast +
        pack (+ Fletcher digest of both wire buffers) in ONE Pallas
        kernel (ops/quantize.hop_pack_pallas).  Bitwise identical to the
        XLA composition (same cast/pack bodies)."""
        from ..ops.quantize import hop_pack_pallas
        rb = None if key is None else hop_rbits(t, c)
        return hop_pack_pallas(recv_wire, local_chunk(c), exp, man,
                               rbits=rb, block_size=blk,
                               want_digest=want_digest,
                               interpret=interpret)

    def fused_first(c, want_digest):
        from ..ops.quantize import quantize_pack_pallas
        rb = None if key is None else hop_rbits(jnp.int32(0), c)
        return quantize_pack_pallas(local_chunk(c), exp, man, rbits=rb,
                                    block_size=blk,
                                    want_digest=want_digest,
                                    interpret=interpret)

    if not verify and fault is None:
        # the plain transport, untouched: zero checksum work, and the
        # oracle-parity tests gate this exact path bitwise
        if fused_wire:
            _, wire0 = fused_first(chunk_at(0), False)

            def body(carry, t):
                recv = lax.ppermute(carry, axis_name, perm)
                _, new_wire = fused_hop(recv, t, chunk_at(t), False)
                return new_wire, None

            carry, _ = lax.scan(body, wire0,
                                jnp.arange(1, w, dtype=jnp.int32))
            res, _ = from_wire(carry)
        else:
            zero = jnp.zeros((chunk,), jnp.float32)
            res, comp = accum(zero, zero, jnp.int32(0), chunk_at(0))

            def body(carry, t):
                res, comp = from_wire(lax.ppermute(carry, axis_name, perm))
                res, comp = accum(res, comp, t, chunk_at(t))
                return to_wire(res, comp), None

            carry, _ = lax.scan(body, to_wire(res, comp),
                                jnp.arange(1, w, dtype=jnp.int32))
            res, _ = from_wire(carry)
        # res is now the reduced chunk `rank`; ring all-gather of the
        # packed chunks rebuilds the full vector (XLA lowers all_gather
        # as a ring on the TPU torus, so the wire cost is the (W-1)
        # chunk hops accounted in ring_transport_bytes — with the
        # payload still bit-packed).  On the fused arm the scan's final
        # carry IS that packed wire (the kernel canonicalizes its code
        # bytes to the XLA re-pack's exactly), so no re-pack runs.
        if fused_wire:          # fused_wire already excludes Kahan
            wire = carry
        elif block_scale:
            wire = pack_exmy_blocked(res, exp, man, block_size)
        else:
            wire = pack_exmy(res, exp, man) if packed else res
        gathered = lax.all_gather(wire, axis_name, axis=0, tiled=False)
        if block_scale:
            full = jax.vmap(lambda r: unpack_exmy_blocked(
                r, exp, man, chunk, block_size))(gathered)
        else:
            full = (unpack_exmy(gathered, exp, man) if packed
                    else gathered)
        return full.reshape(-1)[:n]

    # --- verified / fault-injected transport (module docstring) ------
    #
    # Deferred end-to-end tag compare: the scan carry stays EXACTLY the
    # clean wire (no second per-hop collective — a tag ppermute inside
    # the scan measured 3-4x the whole clean reduce on the CPU mesh);
    # each hop instead RECORDS two uint32 tags as scan outputs — the
    # sender-side tag of what it actually sent, and the receiver-side
    # tag of what actually arrived — and ONE post-scan ppermute of the
    # stacked (W-1,) sent-tag vector lines them up for the compare.
    # Detection is content-complete: any corruption whose bytes differ
    # from the genuine payload mismatches, and one whose bytes are
    # identical is a no-op on the sum.
    from .integrity import hop_tag, wire_digest
    rank_i = rank.astype(jnp.int32)
    have_fault = fault is not None
    if have_fault:
        f_code = jnp.asarray(fault[0], jnp.int32)
        f_rank = jnp.asarray(fault[1], jnp.int32)
        on_me = (f_code > 0) & (rank_i == f_rank)
    left = jnp.mod(rank_i - 1, w)

    def tag_of(wire, t, src, digest=None):
        d = wire_digest(wire) if digest is None else digest
        from .integrity import tag_from_digest
        return tag_from_digest(d, t, src)

    def vbody(carry, t):
        wire = carry
        recv = lax.ppermute(wire, axis_name, perm)
        if have_fault:
            recv = _apply_hop_fault(recv, wire, f_code,
                                    on_me & (t == jnp.int32(1)))
        ys = ()
        if fused_wire:
            if verify:
                res, new_wire, d_in, d_out = fused_hop(
                    recv, t, chunk_at(t), True)
                # d_out also rides out raw: the LAST hop's out-digest is
                # the digest of this rank's gather wire (gwire == the
                # final carry), so the gather tag needs no XLA re-hash
                ys = (tag_of(recv, t, left, digest=d_in),
                      tag_of(new_wire, t + 1, rank_i, digest=d_out),
                      d_out)
            else:
                _, new_wire = fused_hop(recv, t, chunk_at(t), False)
        else:
            if verify:
                rtag = hop_tag(recv, t, left)
            res, comp = from_wire(recv)
            res, comp = accum(res, comp, t, chunk_at(t))
            new_wire = to_wire(res, comp)
            if verify:
                ys = (rtag, hop_tag(new_wire, t + 1, rank_i))
        return new_wire, ys

    if fused_wire:
        if verify:
            _, wire0, d0 = fused_first(chunk_at(0), True)
            tag0 = tag_of(wire0, jnp.int32(1), rank_i, digest=d0)
        else:
            _, wire0 = fused_first(chunk_at(0), False)
    else:
        zero = jnp.zeros((chunk,), jnp.float32)
        res, comp = accum(zero, zero, jnp.int32(0), chunk_at(0))
        wire0 = to_wire(res, comp)
        if verify:
            tag0 = hop_tag(wire0, jnp.int32(1), rank_i)
    wire_f, ys = lax.scan(vbody, wire0, jnp.arange(1, w, dtype=jnp.int32))
    res, _ = from_wire(wire_f)

    hop_bad = jnp.zeros([], jnp.int32)
    d_gwire = None
    if verify and fused_wire:
        d_gwire = d0  # w == 1: wire0 is the gather wire
    if verify and w > 1:
        if fused_wire:
            rtags, stags, douts = ys
            d_gwire = douts[-1]
        else:
            rtags, stags = ys
        # sent[k] = the tag of the wire delivered at hop k+1: wire0's
        # tag first, then each body-produced wire's (the last body
        # iteration's wire is never sent — its tag is dropped)
        sent = jnp.concatenate([tag0[None], stags[:-1]])
        remote_sent = lax.ppermute(sent, axis_name, perm)
        hop_bad = jnp.sum((remote_sent != rtags).astype(jnp.int32))

    # all-gather wire, row-tagged: row i's tag is built by rank i with
    # hop index 0 (scan hops use t >= 1, so no aliasing).  The fused arm
    # reuses the scan's final carry as the gather wire (kernel bytes ==
    # the XLA re-pack's, PR 9 parity) and its kernel digest for the tag.
    if fused_wire:
        gwire = wire_f
    elif block_scale:
        gwire = pack_exmy_blocked(res, exp, man, block_size)
    else:
        gwire = pack_exmy(res, exp, man) if packed else res
    gathered = lax.all_gather(gwire, axis_name, axis=0, tiled=False)
    if have_fault:
        # gather-site fault: rank k's RECEIVED copy of row (k+1) mod W
        # is corrupted — only that replica's rebuilt vector diverges,
        # which is the case the cross-replica agreement digest catches
        j = jnp.mod(rank_i + 1, w)
        row = jnp.take(gathered, j, axis=0)
        new_row = jnp.where(f_code == 2, gwire, row)   # stale: own row
        new_row = jnp.where(f_code == 1, _flip_first_bit(row), new_row)
        new_row = jnp.where(f_code == 3, jnp.zeros_like(row), new_row)
        gathered = jnp.where(on_me, gathered.at[j].set(new_row), gathered)
    if block_scale:
        full = jax.vmap(lambda r: unpack_exmy_blocked(
            r, exp, man, chunk, block_size))(gathered)
    else:
        full = (unpack_exmy(gathered, exp, man) if packed else gathered)
    full = full.reshape(-1)[:n]
    if not verify:
        return full

    # one tiny all_gather carries the whole report exchange: each rank's
    # gather-row tag, its gathered-wire digest, and its hop-bad count —
    # totals and the agreement verdict derive locally; only the
    # per-rank gather-row verdicts (which compare the LOCAL copies of
    # the gathered rows) still need one scalar psum.
    #
    # The agreement value is the digest of this rank's WHOLE gathered
    # wire, composed from the per-row digests via `digest_concat` — the
    # rows were just digested for the tag compare, so agreement costs
    # O(W) scalar folds instead of a second full-vector hash pass
    # (digesting the reconstructed fp32 vector measured as a dominant
    # verify cost, docs/PERF.md).  Coverage is unchanged: `full` is a
    # deterministic pure function of the gathered wire (`from_wire` is
    # shared code), so replicas agreeing on every gathered byte agree
    # on the reconstructed vector bit-for-bit.
    from .integrity import digest_concat, tag_from_digest
    if fused_wire:
        # no XLA-side wire digest on the fused arm (ISSUE 12 leg 4):
        # the sent gather wire's digest came out of the LAST hop's pack
        # kernel, and the RECEIVED rows are hashed by the one-pass
        # per-row digest kernel (ops/quantize.digest_rows_pallas)
        from ..ops.quantize import digest_rows_pallas
        gtag = tag_from_digest(d_gwire, jnp.int32(0), rank_i)
        row_digests = digest_rows_pallas(
            gathered.reshape(w, -1), interpret)
    else:
        gtag = hop_tag(gwire, jnp.int32(0), rank_i)
        row_digests = jax.vmap(wire_digest)(gathered)
    row_tags = jax.vmap(
        lambda d, i: tag_from_digest(d, jnp.int32(0), i))(
            row_digests, jnp.arange(w, dtype=jnp.int32))
    row_words = int(np.prod(gathered.shape[1:]))
    full_digest = row_digests[0]
    for i in range(1, w):
        full_digest = digest_concat(full_digest, i * row_words,
                                    row_digests[i])
    rep = lax.all_gather(
        jnp.stack([gtag, full_digest, hop_bad.astype(jnp.uint32)]),
        axis_name, axis=0, tiled=False)
    gather_bad = jnp.sum((row_tags != rep[:, 0]).astype(jnp.int32))
    report = {
        "hop_bad": jnp.sum(rep[:, 2].astype(jnp.int32)),
        "gather_bad": lax.psum(gather_bad, axis_name),
        "agree": jnp.all(rep[:, 1] == rep[0, 1]).astype(jnp.int32),
    }
    report["ok"] = ((report["hop_bad"] == 0) & (report["gather_bad"] == 0)
                    & (report["agree"] == 1)).astype(jnp.int32)
    return full, report


def ring_oracle_sum(stacked: jnp.ndarray, exp: int, man: int, *,
                    use_kahan: bool = False, key=None,
                    offset_start: int = 0,
                    offsets: Optional[jnp.ndarray] = None,
                    block_scale: bool = False,
                    block_size: int = 128) -> jnp.ndarray:
    """Single-device oracle for the ring transport: given the stacked
    per-rank contributions (W, *shape), reproduce `ring_quantized_sum`'s
    result bit-for-bit — the per-chunk rank rotation, the per-hop casts
    with their (step, site, global-offset) SR bit indexing, the (8,23)
    fp32 shortcut, and (``block_scale=True``) the block-scaled hop
    quantizer with its chunk-local block boundaries — everything except
    the wire.

    The distributed path and this oracle share the hop-body functions
    (`_hop_plain` / `_hop_kahan` / `_make_hop_q`, the latter carrying
    the blocked cast), so a divergence can only come from the transport
    itself — which is exactly what the oracle-parity tests gate."""
    w = stacked.shape[0]
    n = int(stacked[0].size)
    shape = stacked.shape[1:]
    if n == 0:
        return jnp.zeros(shape, jnp.float32)
    flat = jnp.reshape(jnp.asarray(stacked, jnp.float32), (w, n))
    chunk = ring_chunk_size(n, w)
    padded = jnp.pad(flat, ((0, 0), (0, w * chunk - n)))
    per_chunk = padded.reshape(w, w, chunk)        # [rank, chunk, elem]
    # contribution visiting chunk c at hop t comes from rank (c+1+t) mod w
    t_idx = jnp.arange(w)[:, None]
    c_idx = jnp.arange(w)[None, :]
    order = jnp.mod(c_idx + 1 + t_idx, w)          # [hop, chunk]
    hops = per_chunk[order, c_idx, :]              # [hop, chunk, elem]
    if offsets is not None:
        offs = jnp.pad(offsets.astype(jnp.uint32).reshape(-1),
                       (0, w * chunk - n)).reshape(w, chunk)
    else:
        offs = (jnp.uint32(offset_start)
                + (c_idx.astype(jnp.uint32) * jnp.uint32(chunk))[..., None]
                + jnp.arange(chunk, dtype=jnp.uint32)[None, None, :])[0]
    q = _make_hop_q(exp, man, key,
                    block=block_size if block_scale else None)
    fp32_shortcut = exp == 8 and man == 23 and not use_kahan

    def body(carry, xs):
        res, comp = carry
        t, g = xs
        if use_kahan:
            res, comp = _hop_kahan(q, res, comp, g, t, offs)
        else:
            res = _hop_plain(q, res, g, t, offs, fp32_shortcut)
        return (res, comp), None

    zero = jnp.zeros((w, chunk), jnp.float32)
    (res, _), _ = lax.scan(
        body, (zero, zero), (jnp.arange(w, dtype=jnp.int32), hops))
    return res.reshape(-1)[:n].reshape(shape)


def hierarchical_ring_sum(flat: jnp.ndarray, axis_names, exp: int, man: int,
                          *, use_kahan: bool = False, key=None,
                          offset_start: int = 0,
                          offsets: Optional[jnp.ndarray] = None,
                          packed: bool = True,
                          fused: Optional[bool] = None,
                          interpret: bool = False,
                          verify: bool = False,
                          fault: Optional[tuple] = None,
                          block_scale: bool = False,
                          block_size: int = 128):
    """Ring all-reduce composed over one OR several mesh axes.

    A single axis (plain string, or a 1-tuple) is exactly
    `ring_quantized_sum` — same bits, same program.  For k > 1 axes the
    reduction runs as k sequential per-axis rings, INNERMOST (last-named)
    axis first: per the mesh convention (parallel/mesh.py) the last axis
    is the fastest ICI ring, so the large fan-in happens on the cheap
    wire and the outer axes ring over already-reduced partials — the
    hierarchical intra-axis-then-inter-axis reduce of the MLPerf TPU-pod
    recipe (PAPERS.md #4).  Stage ``s`` reduces over ``axes[-1-s]`` with
    SR key ``fold_in(key, s)`` (stages must draw independent bits — the
    same (hop, site, offset) indices recur at every stage), and the
    result is the per-axis composition of the documented per-chunk rank
    rotation — reproduced bit-for-bit by `ring_oracle_sum_multi`.

    verify → every stage runs the self-verifying transport; the merged
    report sums ``hop_bad`` / ``gather_bad`` across all rings of all
    stages (psum over the non-stage axes makes the totals replicated),
    ANDs the per-stage agreement verdicts, and adds a FINAL cross-mesh
    agreement digest over every axis at once — a divergence introduced
    between stages (or on the last gather wire) cannot hide in a
    single-axis check.

    fault → injected into stage 0 only, and only on the one stage-0 ring
    whose other-axes indices are all zero: exactly ONE corruption fires,
    so the chaos drills' exact counter expectations (one flip →
    hop_bad == 1) hold on any mesh shape.
    """
    axes = ((axis_names,) if isinstance(axis_names, str)
            else tuple(axis_names))
    if not axes:
        raise ValueError("hierarchical_ring_sum needs at least one axis")
    kw = dict(use_kahan=use_kahan, offset_start=offset_start,
              offsets=offsets, packed=packed, fused=fused,
              interpret=interpret, block_scale=block_scale,
              block_size=block_size)
    if len(axes) == 1:
        return ring_quantized_sum(flat, axes[0], exp, man, key=key,
                                  verify=verify, fault=fault, **kw)

    vec = flat
    stage_reports = []
    for s in range(len(axes)):
        ax = axes[-1 - s]
        k_s = None if key is None else jax.random.fold_in(key, s)
        f_s = None
        if fault is not None and s == 0:
            on_slice = jnp.int32(1)
            for other in axes[:-1]:
                on_slice = on_slice * (
                    lax.axis_index(other) == 0).astype(jnp.int32)
            f_s = (jnp.where(on_slice == 1,
                             jnp.asarray(fault[0], jnp.int32),
                             jnp.int32(0)),
                   jnp.asarray(fault[1], jnp.int32))
        out = ring_quantized_sum(vec, ax, exp, man, key=k_s,
                                 verify=verify, fault=f_s, **kw)
        if verify:
            vec, rep = out
            stage_reports.append((ax, rep))
        else:
            vec = out
    if not verify:
        return vec

    from .integrity import digest_agree, wire_digest
    hop_bad = jnp.zeros([], jnp.int32)
    gather_bad = jnp.zeros([], jnp.int32)
    agree = jnp.ones([], jnp.int32)
    for ax, rep in stage_reports:
        other = tuple(a for a in axes if a != ax)
        hop_bad = hop_bad + lax.psum(rep["hop_bad"], other)
        gather_bad = gather_bad + lax.psum(rep["gather_bad"], other)
        agree = jnp.minimum(agree, lax.pmin(rep["agree"], other))
    agree = jnp.minimum(agree, digest_agree(wire_digest(vec), axes))
    report = {"hop_bad": hop_bad, "gather_bad": gather_bad,
              "agree": agree}
    report["ok"] = ((hop_bad == 0) & (gather_bad == 0)
                    & (agree == 1)).astype(jnp.int32)
    return vec, report


def ring_oracle_sum_multi(stacked: jnp.ndarray, n_axes: int, exp: int,
                          man: int, *, use_kahan: bool = False, key=None,
                          offset_start: int = 0,
                          offsets: Optional[jnp.ndarray] = None,
                          block_scale: bool = False,
                          block_size: int = 128) -> jnp.ndarray:
    """Single-device oracle for `hierarchical_ring_sum`: ``stacked`` has
    shape ``(W_0, ..., W_{k-1}, *leaf)`` with the leading dims in mesh
    AXIS-NAME order; the reduction folds the LAST leading axis first
    (the innermost mesh axis), stage ``s`` drawing SR bits from
    ``fold_in(key, s)`` — exactly the distributed composition.  With
    ``n_axes == 1`` this is `ring_oracle_sum` (unfolded key, the legacy
    single-axis bitstream)."""
    if n_axes < 1 or stacked.ndim < n_axes:
        raise ValueError(f"n_axes={n_axes} does not fit stacked shape "
                         f"{stacked.shape}")
    kw = dict(use_kahan=use_kahan, offset_start=offset_start,
              offsets=offsets, block_scale=block_scale,
              block_size=block_size)
    if n_axes == 1:
        return ring_oracle_sum(stacked, exp, man, key=key, **kw)
    vec = stacked
    for s in range(n_axes):
        k_s = None if key is None else jax.random.fold_in(key, s)
        lead = vec.shape[:n_axes - s]
        tail = vec.shape[n_axes - s:]
        rest = int(np.prod(lead[:-1])) if lead[:-1] else 1
        flat = vec.reshape((rest, lead[-1]) + tail)
        red = jax.vmap(lambda st, k=k_s: ring_oracle_sum(
            st, exp, man, key=k, **kw))(flat)
        vec = red.reshape(lead[:-1] + tail)
    return vec


def ring_transport_bytes(n: int, world: int, exp: int, man: int, *,
                         use_kahan: bool = False,
                         packed: bool = True,
                         block_size: Optional[int] = None) -> int:
    """Analytic per-device wire bytes for one ring all-reduce of n
    elements: (W-1) reduce-scatter hops of one chunk (×2 with Kahan — the
    compensation rides) plus (W-1) all-gather hops of one chunk.

    ``block_size`` prices the block-scaled wire: every chunk payload
    carries its sidecar lane (one shift byte per block, odd tail block
    included) next to the code words — the sidecar is EXPLICIT here, and
    tests pin this formula against real `pack_exmy_blocked` buffer
    sizes so the analytics can never silently under-report the wire."""
    if n == 0 or world <= 0:
        return 0
    chunk = ring_chunk_size(n, world)
    if block_size is not None:
        per_chunk = wire_bytes_blocked(exp, man, chunk, block_size)
    else:
        per_chunk = chunk * (wire_bytes(exp, man) if packed else 4)
    reduce_phase = (world - 1) * per_chunk * (2 if use_kahan else 1)
    gather_phase = (world - 1) * per_chunk
    return reduce_phase + gather_phase


def gather_transport_bytes(n: int, world: int, exp: int, man: int, *,
                           compressed: bool = False,
                           block_size: Optional[int] = None) -> int:
    """Analytic per-device wire bytes for the faithful all_gather path:
    (W-1)·n elements, raw fp32 unless the APS-prequantized wire packing
    applies (`compressed`).  ``block_size`` adds the sidecar bytes a
    block-scaled row would carry ((W-1) rows × one shift byte per
    block) — analytic only; the faithful gather ships per-tensor today,
    but the ledger must price the alternative honestly."""
    if n == 0 or world <= 0:
        return 0
    if block_size is not None:
        return (world - 1) * wire_bytes_blocked(exp, man, n, block_size)
    per_elem = wire_bytes(exp, man) if compressed else 4
    return (world - 1) * n * per_elem


def transport_table(n: int, world: int, exp: int, man: int,
                    use_kahan: bool = False,
                    block_size: Optional[int] = None) -> dict:
    """Analytic per-device bytes-on-wire for every transport of one
    all-reduce of n elements — the payload of bench.py's `reduction`
    block and tools/bench_reduce.py.  One home for the comparison so the
    ledger, the tool and docs/PERF.md's table cannot drift.  With
    ``block_size`` the table adds the block-scaled ring row (code words
    + sidecar lane, both counted)."""
    compressible = man >= 2 and wire_bytes(exp, man) < 4
    gather = gather_transport_bytes(n, world, exp, man, compressed=False)
    table = {
        "faithful_gather_fp32": gather,
        "faithful_gather_packed": (
            gather_transport_bytes(n, world, exp, man, compressed=True)
            if compressible else None),  # needs APS pre-quantized values
        "ring_packed": ring_transport_bytes(n, world, exp, man,
                                            use_kahan=use_kahan,
                                            packed=compressible),
        "ring_block_scaled": (
            ring_transport_bytes(n, world, exp, man, use_kahan=use_kahan,
                                 block_size=block_size)
            if block_size is not None and compressible else None),
        # XLA lowers psum as a ring reduce-scatter + all-gather on the
        # TPU torus, but the payload stays fp32 (psum cannot know the
        # values fit a narrower format — EQuARX's whole point), so fast
        # mode's wire is exactly the UNPACKED ring: 2·(W-1)·(n/W)·4
        "fast_psum_fp32": ring_transport_bytes(n, world, 8, 23,
                                               packed=False),
    }
    table["ring_vs_gather_ratio"] = (
        round(gather / table["ring_packed"], 2) if table["ring_packed"]
        else None)
    return table


def ir_programs(reg):
    """Program-contract declarations (analysis/ir/registry.py).

    The ring transport and the faithful gather are the wire the byte
    analytics above price — each registered arm carries a ``wire``
    contract equal to its analytic table entry, so a stray fp32 debug
    gather, an unpacked hop, or a dropped block sidecar fails the
    ``ir-wire-ledger`` rule instead of silently shipping unpriced
    bytes.  All arms are bitwise-gated (`ring_oracle_sum` parity is a
    cross-program bitwise claim)."""
    from jax.sharding import PartitionSpec as P

    from ..compat import shard_map
    from .mesh import data_parallel_mesh

    W, n = 8, 1000
    deps = ("cpd_tpu.quant.numerics", "cpd_tpu.parallel.ring",
            "cpd_tpu.parallel.reduction")

    def _ring(use_kahan=False, block=None, exp=5, man=2):
        def build():
            mesh = data_parallel_mesh()

            def body(x):
                return ring_quantized_sum(
                    x[0], "dp", exp, man, use_kahan=use_kahan,
                    world=W, block_scale=block is not None,
                    block_size=block if block is not None else 128)

            fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                           out_specs=P(), check_vma=False)
            return fn, (jax.ShapeDtypeStruct((W, n), jnp.float32),)
        return build

    reg.declare("ring.packed[e5m2,w8]", _ring(),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: ring_transport_bytes(n, W, 5, 2))
    reg.declare("ring.kahan[e5m2,w8]", _ring(use_kahan=True),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: ring_transport_bytes(n, W, 5, 2,
                                                  use_kahan=True))
    reg.declare("ring.blocked[e4m3,b32,w8]", _ring(block=32, exp=4,
                                                   man=3),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: ring_transport_bytes(n, W, 4, 3,
                                                  block_size=32))

    def _gather(use_aps):
        def build():
            from .dist import sum_gradients
            mesh = data_parallel_mesh()

            def body(g):
                return sum_gradients({"g": g[0]}, "dp", use_aps=use_aps,
                                     grad_exp=5, grad_man=2,
                                     mode="faithful")

            fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                           out_specs=P(), check_vma=False)
            return fn, (jax.ShapeDtypeStruct((W, n), jnp.float32),)
        return build

    gdeps = deps + ("cpd_tpu.parallel.dist", "cpd_tpu.parallel.aps")
    reg.declare("gather.fp32[e5m2,w8]", _gather(False),
                deps=gdeps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: gather_transport_bytes(n, W, 5, 2,
                                                    compressed=False))
    reg.declare("gather.packed[aps,e5m2,w8]", _gather(True),
                deps=gdeps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: gather_transport_bytes(n, W, 5, 2,
                                                    compressed=True))
