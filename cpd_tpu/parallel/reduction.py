"""Ordered low-precision reduction primitives (the emulation heart of L2).

The reference's key trick (CPDtorch/utils/dist_util.py:54-89) is to emulate a
low-precision all-reduce *deterministically*: gather full-precision values from
every rank, then accumulate them **in rank order**, re-quantizing to eXmY after
every addition (optionally Kahan-compensated, every intermediate quantized).
That makes the reduction's numerics independent of the network's reduction
tree — a property `psum` cannot give, since XLA's reduction order is opaque.

Here the primitive operates on a *stacked* array ``(W, ...)`` so that exactly
the same code runs in three contexts, bit-identically:

1. real collectives: ``lax.all_gather`` inside ``shard_map`` → (W, ...);
2. cluster emulation ("emulate node", reference mix.py:251-282): micro-batch
   gradients stacked on a leading axis;
3. unit tests on a single device.

Everything is a `lax.scan` over the leading axis — sequential by construction,
which is the point: order *is* the semantics being emulated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..quant.numerics import (cast_body_blocked, cast_to_format,
                              cast_to_format_sr_at, sr_bits_at)

__all__ = ["ordered_quantized_sum", "kahan_quantized_sum", "quantized_sum"]


def _make_q(exp: int, man: int, key, offsets=None, block=None):
    """Per-step quantizer factory.  key=None -> RTNE (reference semantics,
    ignores the step/site arguments).  With a PRNG key -> unbiased
    stochastic rounding with an independent bitstream per (step, site,
    element offset): the sequential accumulation stays ordered and
    deterministic-given-key, but each partial sum rounds up with
    probability equal to its discarded fraction — so sub-ulp/2
    contributions survive in expectation instead of being flushed (the
    failure mode of an un-APS'd low-precision sum).

    Per-element bits are OFFSET-indexed (numerics.sr_bits_at): `offsets`
    gives each element's global flat offset (default: leaf-local
    ``arange(size)``).  Bits therefore depend only on (key, step, site,
    offset), never on the array layout — callers that pass GLOBAL offsets
    (parallel/dist.py buckets, parallel/zero.py shards) get bitwise
    agreement with the per-leaf / replicated computation.

    ``block`` switches every cast site to the block-scaled cast
    (`numerics.cast_body_blocked`, blocks of ``block`` elements along
    the LAST axis) — the ordered-scan twin of the ring's
    `_make_hop_q(block=...)`, used by ZeRO-2's blocked reduce-scatter
    scan (parallel/zero.py) so the accumulation keeps the per-block
    dynamic range the blocked wire bought."""
    if key is None:
        if block is not None:
            return lambda x, step, site: cast_body_blocked(
                x, exp, man, block)
        rtne = functools.partial(cast_to_format, exp_bits=exp, man_bits=man)
        return lambda x, step, site: rtne(x)

    def q(x, step, site):
        k = jax.random.fold_in(jax.random.fold_in(key, step), site)
        offs = (jnp.arange(x.size, dtype=jnp.uint32).reshape(x.shape)
                if offsets is None else offsets)
        if block is not None:
            rbits = jnp.broadcast_to(sr_bits_at(k, offs), jnp.shape(x))
            return cast_body_blocked(x, exp, man, block, rbits=rbits)
        return cast_to_format_sr_at(x, exp, man, k, offs)

    return q


def ordered_quantized_sum(stacked: jnp.ndarray, exp: int, man: int,
                          key=None, offsets=None,
                          block_size=None) -> jnp.ndarray:
    """res = 0; for g in stacked: res = quantize(res + g)   — in order.

    Mirrors reference normal_sum_gradients' gather path
    (dist_util.py:60-69): accumulation starts from zeros, and every partial
    sum is re-cast to eXmY.  `stacked` has shape (W, *leaf_shape).
    `key` switches the per-step cast to stochastic rounding; `offsets`
    overrides the per-element bit indices; `block_size` switches every
    cast to the block-scaled cast (see _make_q).
    """
    q = _make_q(exp, man, key, offsets, block=block_size)

    def step(carry, xs):
        res, i = carry
        return (q(res + xs, i, 0), i + 1), None

    (res, _), _ = lax.scan(
        step, (jnp.zeros_like(stacked[0]), jnp.zeros([], jnp.int32)),
        stacked)
    return res


def kahan_quantized_sum(stacked: jnp.ndarray, exp: int, man: int,
                        key=None, offsets=None,
                        block_size=None) -> jnp.ndarray:
    """Rank-ordered Kahan-compensated sum with every intermediate quantized.

    Mirrors reference kahan_sum_gradients (dist_util.py:72-89):

        y = q(g - c); t = q(res + y); c = q(q(t - res) - y); res = t

    With `key`, each of the four casts draws its own SR bitstream per rank
    step (sites 0-3); `offsets` overrides the per-element bit indices;
    `block_size` switches every site to the block-scaled cast.
    """
    q = _make_q(exp, man, key, offsets, block=block_size)

    def step(carry, g):
        res, c, i = carry
        y = q(g - c, i, 0)
        t = q(res + y, i, 1)
        c = q(q(t - res, i, 2) - y, i, 3)
        return (t, c, i + 1), None

    zero = jnp.zeros_like(stacked[0])
    (res, _, _), _ = lax.scan(
        step, (zero, zero, jnp.zeros([], jnp.int32)), stacked)
    return res


def quantized_sum(stacked: jnp.ndarray, exp: int, man: int,
                  use_kahan: bool = False, key=None,
                  offsets=None, block_size=None) -> jnp.ndarray:
    """Dispatch between the plain and Kahan ordered quantized sums.

    The fp32 shortcut (exp==8, man==23 → plain sum) applies only to the
    non-Kahan path, exactly as the reference does (dist_util.py:55-59 has the
    shortcut; kahan_sum_gradients:72-89 does not).  The shortcut also makes
    `key` irrelevant there (SR at (8,23) is the identity).  ``block_size``
    (ZeRO-2's blocked reduce-scatter, parallel/zero.py) switches every
    cast site to the block-scaled cast; it is a caller error at (8,23),
    where the shortcut would silently ignore it."""
    if block_size is not None and exp == 8 and man == 23 and not use_kahan:
        raise ValueError("block_size at (8, 23): the fp32 shortcut has no "
                         "cast to block-scale")
    if use_kahan:
        return kahan_quantized_sum(stacked, exp, man, key=key,
                                   offsets=offsets, block_size=block_size)
    if exp == 8 and man == 23:
        return jnp.sum(stacked, axis=0)
    return ordered_quantized_sum(stacked, exp, man, key=key, offsets=offsets,
                                 block_size=block_size)


def ir_programs(reg):
    """Program-contract declarations (analysis/ir/registry.py): the
    ordered-scan primitives are the emulation heart every oracle gate
    leans on — register them bitwise-gated so an ulp-unstable
    primitive (the PR 12 exp2 class) sneaking into a cast body fails
    lint before it fails a bitwise test four layers up."""

    def _scan(use_kahan, block=None):
        def build():
            arg = jax.ShapeDtypeStruct((8, 256), jnp.float32)
            return (lambda st: quantized_sum(
                st, 5 if block is None else 4,
                2 if block is None else 3,
                use_kahan=use_kahan, block_size=block), (arg,))
        return build

    deps = ("cpd_tpu.quant.numerics", "cpd_tpu.parallel.reduction")
    reg.declare("reduce.ordered_scan[e5m2]", _scan(False),
                deps=deps, bitwise=True)
    reg.declare("reduce.kahan_scan[e5m2]", _scan(True),
                deps=deps, bitwise=True)
    reg.declare("reduce.ordered_scan[blocked-e4m3,b32]",
                _scan(False, block=32), deps=deps, bitwise=True)
