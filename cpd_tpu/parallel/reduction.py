"""Ordered low-precision reduction primitives (the emulation heart of L2).

The reference's key trick (CPDtorch/utils/dist_util.py:54-89) is to emulate a
low-precision all-reduce *deterministically*: gather full-precision values from
every rank, then accumulate them **in rank order**, re-quantizing to eXmY after
every addition (optionally Kahan-compensated, every intermediate quantized).
That makes the reduction's numerics independent of the network's reduction
tree — a property `psum` cannot give, since XLA's reduction order is opaque.

Here the primitive operates on a *stacked* array ``(W, ...)`` so that exactly
the same code runs in three contexts, bit-identically:

1. real collectives: ``lax.all_gather`` inside ``shard_map`` → (W, ...);
2. cluster emulation ("emulate node", reference mix.py:251-282): micro-batch
   gradients stacked on a leading axis;
3. unit tests on a single device.

Everything is a `lax.scan` over the leading axis — sequential by construction,
which is the point: order *is* the semantics being emulated.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

from ..quant.numerics import cast_to_format

__all__ = ["ordered_quantized_sum", "kahan_quantized_sum", "quantized_sum"]


def ordered_quantized_sum(stacked: jnp.ndarray, exp: int, man: int) -> jnp.ndarray:
    """res = 0; for g in stacked: res = quantize(res + g)   — in order.

    Mirrors reference normal_sum_gradients' gather path
    (dist_util.py:60-69): accumulation starts from zeros, and every partial
    sum is re-cast to eXmY.  `stacked` has shape (W, *leaf_shape).
    """
    q = functools.partial(cast_to_format, exp_bits=exp, man_bits=man)

    def step(res, g):
        return q(res + g), None

    res, _ = lax.scan(step, jnp.zeros_like(stacked[0]), stacked)
    return res


def kahan_quantized_sum(stacked: jnp.ndarray, exp: int, man: int) -> jnp.ndarray:
    """Rank-ordered Kahan-compensated sum with every intermediate quantized.

    Mirrors reference kahan_sum_gradients (dist_util.py:72-89):

        y = q(g - c); t = q(res + y); c = q(q(t - res) - y); res = t
    """
    q = functools.partial(cast_to_format, exp_bits=exp, man_bits=man)

    def step(carry, g):
        res, c = carry
        y = q(g - c)
        t = q(res + y)
        c = q(q(t - res) - y)
        return (t, c), None

    zero = jnp.zeros_like(stacked[0])
    (res, _), _ = lax.scan(step, (zero, zero), stacked)
    return res


def quantized_sum(stacked: jnp.ndarray, exp: int, man: int,
                  use_kahan: bool = False) -> jnp.ndarray:
    """Dispatch between the plain and Kahan ordered quantized sums.

    The fp32 shortcut (exp==8, man==23 → plain sum) applies only to the
    non-Kahan path, exactly as the reference does (dist_util.py:55-59 has the
    shortcut; kahan_sum_gradients:72-89 does not)."""
    if use_kahan:
        return kahan_quantized_sum(stacked, exp, man)
    if exp == 8 and man == 23:
        return jnp.sum(stacked, axis=0)
    return ordered_quantized_sum(stacked, exp, man)
