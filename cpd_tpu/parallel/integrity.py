"""In-jit wire/replica integrity: checksums for the quantized collectives.

PR 3's ring transport moves bit-packed eXmY code words over
``lax.ppermute`` — and until now nothing verified that what arrives is
what was sent.  A single corrupted hop silently leaves replicas holding
*different* gradient sums (the EQuARX failure mode, PAPERS.md; row 3 of
docs/RESILIENCE.md), and because the ring's partials keep hopping, a
corrupted partial can also land the SAME wrong sum on every replica —
which no cross-replica comparison can see.  Two complementary checks,
both pure jnp (they run *inside* the jitted step):

* **per-wire checksums** — :func:`wire_digest`, a Fletcher-style
  position-weighted double sum mod 65521 over the payload's words
  (uint8 code words for packed eXmY — sidecar scale bytes included on
  the block-scaled wire — the raw fp32 bit patterns otherwise).  The
  ring tags every hop payload with :func:`hop_tag`(digest ^ hop-index ^
  sender-rank) on BOTH ends of the wire — the sender tags what it
  actually sent, the receiver tags what actually arrived — and compares
  the two vectors after the scan (one extra (W-1)-tag ppermute for the
  whole reduce, parallel/ring.py), so a flipped bit, a dropped payload,
  AND a stale self-echo all fail the end-to-end compare — catching
  exactly the corruption class cross-replica agreement cannot.  On TPU
  the payload digest comes out of the fused pack kernel as a second
  output (ops/quantize.py), so verification is not a separate pass over
  the wire words at all.
* **cross-replica agreement** — :func:`digest_agree`: pmin == pmax of
  the per-replica :func:`tree_digest`/:func:`wire_digest` of the
  reduced result, so every replica learns whether *any* replica
  disagrees (one tiny collective, two int32 scalars on the wire).

On top of those, the **parameter-consensus check**
(:func:`make_consensus_fns`) is the after-the-fact repair: a cheap
jitted digest comparison run every N steps, and — only when it
disagrees — a rank-0 broadcast re-sync that restores bitwise
replication (`parallel/dist.py` broadcast_from semantics).

`parallel/ring.py` consumes the checksums inside its scan body;
`parallel/dist.py` threads the verdict out of ``sum_gradients(...,
verify=True)``; `resilience/transport.py` turns repeated failures into
transport downgrades.  This module imports nothing from its siblings so
all of them can import it freely.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["wire_digest", "tree_digest", "hop_tag", "tag_from_digest",
           "digest_agree", "digest_concat", "make_consensus_fns",
           "DIGEST_MOD"]

# Largest prime below 2^16 (Adler-32's modulus): keeps both running sums
# in uint16 range so the pair packs into one uint32 digest, and keeps
# every intermediate product/sum below 2^32 (proof at each site below).
# Plain Python ints here (NOT jnp constants): this module is imported
# lazily from inside jitted code, and a module-level jnp array created
# mid-trace would be a leaked tracer.
DIGEST_MOD = 65521
# Knuth/Murmur odd constants for the hop/sender tag mixing — any odd
# multiplier is a bijection mod 2^32, so distinct (hop, sender) pairs
# perturb the tag distinctly.
_GOLD_HOP = 0x9E3779B9
_GOLD_SRC = 0x85EBCA6B


def _mod65521(x: jnp.ndarray) -> jnp.ndarray:
    """x % 65521 for the full uint32 range using only shifts/masks/adds
    (2^16 ≡ 15 mod 65521) — exact, and DIVISION-FREE: a per-word ``%``
    lowers to integer divides, which measured as the dominant cost of
    the verified ring on XLA:CPU (docs/PERF.md "Block-scaled wire").
    Same arithmetic as the fused pack kernel's `fletcher_mod65521`
    (ops/quantize.py — kept separate so this module stays import-leaf);
    both are pinned against ``%`` in tests."""
    f = jnp.uint32(15)
    x = (x & jnp.uint32(0xFFFF)) + (x >> 16) * f      # < 2^20
    x = (x & jnp.uint32(0xFFFF)) + (x >> 16) * f      # < 65761
    m = jnp.uint32(DIGEST_MOD)
    return jnp.where(x >= m, x - m, x)


def _mod_sum(v: jnp.ndarray) -> jnp.ndarray:
    """Sum of uint32 values (< DIGEST_MOD each) mod DIGEST_MOD, chunked
    so no intermediate overflows: 4096 summands < 65521 stay under
    4096 * 65520 < 2^28 < 2^32.  Static shapes only — jit-safe."""
    while v.size > 1:
        pad = (-v.size) % 4096
        if pad:
            v = jnp.concatenate([v, jnp.zeros((pad,), jnp.uint32)])
        v = _mod65521(jnp.sum(v.reshape(-1, 4096), axis=1))
    return v[0] if v.size else jnp.uint32(0)


def _digest_words(flat: jnp.ndarray) -> jnp.ndarray:
    """Uint32 hash words for a flat payload — always the BIT PATTERN,
    never a value cast: a value cast would truncate every |x| < 1 of a
    bf16/f16 leaf to the same word (drift-blind digest), and
    negative-float/signed->unsigned value conversion is
    implementation-defined in XLA.  Sub-32-bit types bitcast to their
    same-width unsigned then zero-extend (well-defined); 64-bit floats
    (rare here — x64 is off repo-wide) hash their float32 narrowing,
    deterministic though blind to sub-f32 drift."""
    dt = flat.dtype
    if jnp.issubdtype(dt, jnp.floating):
        if dt.itemsize == 4:
            return lax.bitcast_convert_type(flat, jnp.uint32)
        if dt.itemsize == 2:
            return lax.bitcast_convert_type(flat, jnp.uint16).astype(
                jnp.uint32)
        return lax.bitcast_convert_type(flat.astype(jnp.float32),
                                        jnp.uint32)
    if jnp.issubdtype(dt, jnp.signedinteger) and dt.itemsize <= 4:
        unsigned = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[dt.itemsize]
        return lax.bitcast_convert_type(flat, unsigned).astype(jnp.uint32)
    return flat.astype(jnp.uint32)    # unsigned/bool: zero-extend


def wire_digest(x: jnp.ndarray) -> jnp.ndarray:
    """Fletcher-style uint32 digest of any payload array (jit-pure).

    Words are the payload's own transport units: uint8 code words for a
    bit-packed eXmY wire, the fp32 *bit patterns* (bitcast, so -0.0/NaN
    payloads are first-class) for an unpacked wire, the raw integer
    values otherwise.  digest = (sum2 << 16) | sum1 with
    sum1 = Σ wᵢ and sum2 = Σ (i+1)·wᵢ, both mod 65521 — sum1 catches
    any changed word, the position weight in sum2 catches reorderings
    and moved corruption that a plain sum cannot."""
    flat = jnp.ravel(x)
    if flat.dtype == jnp.uint8 and flat.size > 4096:
        # fast path for the packed-wire case (bytes < 256): chunk 4096
        # words and hoist the position weight's chunk offset out of the
        # inner product — global position (c·4096 + l) splits as
        # (l+1) + c·4096, so s2 = Σ_c [Σ_l w·(l+1)] + (c·4096)·[Σ_l w],
        # with every inner sum overflow-free in uint32 (4096·255·4096 <
        # 2^32).  ~1.5x fewer passes than the generic path on the hot
        # verified-ring wires; bitwise the SAME digest (pinned in
        # tests/test_integrity.py)
        n = flat.size
        pad = (-n) % 4096
        w = jnp.pad(flat, (0, pad)).astype(jnp.uint32).reshape(-1, 4096)
        pos_l = jnp.arange(4096, dtype=jnp.uint32) + jnp.uint32(1)
        c1 = jnp.sum(w, axis=1)                        # < 2^20
        c2 = _mod65521(jnp.sum(w * pos_l, axis=1))     # < 2^32
        off = _mod65521(jnp.arange(w.shape[0], dtype=jnp.uint32)
                        * jnp.uint32(4096 % DIGEST_MOD))
        s1 = _mod_sum(_mod65521(c1))
        s2 = _mod_sum(_mod65521(c2 + _mod65521(off * _mod65521(c1))))
        return (s2 << 16) | s1
    words = _digest_words(flat)
    w = _mod65521(words)
    # weights cycle 1..DIGEST_MOD; each product < 65521^2 < 2^32
    pos = _mod65521(jnp.arange(w.size, dtype=jnp.uint32)) + jnp.uint32(1)
    s1 = _mod_sum(w)
    s2 = _mod_sum(_mod65521(w * pos))
    return (s2 << 16) | s1


def tree_digest(tree: Any) -> jnp.ndarray:
    """One uint32 digest over a whole pytree (FNV-style fold of the
    per-leaf :func:`wire_digest`s in tree-flatten order) — the replica
    fingerprint the parameter-consensus check compares."""
    d = jnp.uint32(0x811C9DC5)
    for leaf in jax.tree.leaves(tree):
        d = (d * jnp.uint32(0x01000193)) ^ wire_digest(leaf)
    return d


def tag_from_digest(digest: jnp.ndarray, hop: jnp.ndarray,
                    src_rank: jnp.ndarray) -> jnp.ndarray:
    """Mix a precomputed payload digest with its (hop, sender)
    provenance — the tag algebra of :func:`hop_tag`, split out so a
    digest produced elsewhere (the fused Pallas pack kernel's second
    output, ops/quantize.py) can be tagged without re-hashing."""
    return (digest
            ^ (jnp.asarray(hop).astype(jnp.uint32)
               * jnp.uint32(_GOLD_HOP))
            ^ (jnp.asarray(src_rank).astype(jnp.uint32)
               * jnp.uint32(_GOLD_SRC)))


def hop_tag(payload: jnp.ndarray, hop: jnp.ndarray,
            src_rank: jnp.ndarray) -> jnp.ndarray:
    """The per-hop wire checksum: digest ^ mix(hop index) ^ mix(sender
    rank).  The ring compares the SENDER's tag of what it actually sent
    against the RECEIVER's tag of what actually arrived (deferred to one
    post-scan ppermute of the stacked tag vector, parallel/ring.py) —
    content-complete detection: a flip, a drop, AND a stale replay all
    change the received bytes, and a corruption that leaves the bytes
    identical is by definition a no-op on the reduction."""
    return tag_from_digest(wire_digest(payload), hop, src_rank)


def digest_concat(d_a: jnp.ndarray, len_a, d_b: jnp.ndarray) -> jnp.ndarray:
    """Fletcher digest of the CONCATENATION of two payloads from their
    individual digests: with (s1, s2) packed as (s2 << 16) | s1,
    ``s1 = s1a + s1b`` and ``s2 = s2a + s2b + len_a * s1b`` (mod 65521 —
    the position weights of the second payload shift by len_a, and
    (i mod m)+1 ≡ i+1 mod m makes the shift additive).  Lets the fused
    pack kernel digest the code-word lane and XLA digest the tiny
    sidecar lane, composing to EXACTLY `wire_digest(concat(a, b))`."""
    m = jnp.uint32(DIGEST_MOD)
    s1a, s2a = d_a & jnp.uint32(0xFFFF), d_a >> 16
    s1b, s2b = d_b & jnp.uint32(0xFFFF), d_b >> 16
    la = jnp.asarray(len_a).astype(jnp.uint32) % m
    s1 = (s1a + s1b) % m
    # each term < m, la*s1b < m^2 < 2^32: no intermediate overflow
    s2 = (s2a + s2b + (la * s1b) % m) % m
    return (s2 << 16) | s1


def digest_agree(digest: jnp.ndarray, axis_name) -> jnp.ndarray:
    """int32 1/0: do all replicas along `axis_name` (a name or a tuple
    of names) hold this same digest?  pmin == pmax — every replica
    learns whether ANY replica disagrees, for two scalars on the wire."""
    d = lax.bitcast_convert_type(digest, jnp.int32)
    return (lax.pmin(d, axis_name) == lax.pmax(d, axis_name)).astype(
        jnp.int32)


def _bcast(x: jnp.ndarray, axis_name: str, src: int = 0) -> jnp.ndarray:
    # dist.broadcast_from, inlined so this module stays import-leaf
    return lax.all_gather(x, axis_name, axis=0, tiled=False)[src]


def make_consensus_fns(mesh, axis_name: str = "dp") -> Tuple:
    """Build the periodic parameter-consensus pair ``(check_fn,
    resync_fn)`` over a replicated pytree (a TrainState, a param tree).

    ``check_fn(tree) -> int32 1/0``: every device digests ITS local
    copy of the nominally-replicated tree; agreement is the pmin==pmax
    of those digests.  Cheap: O(bytes) local hashing, two scalars on
    the wire.

    ``resync_fn(tree) -> tree``: rank 0's bytes broadcast to every
    replica (one all_gather per leaf) — after it, the replicas are
    bitwise identical regardless of how far they had drifted.  Call it
    only when ``check_fn`` disagreed (or after a detected wire fault);
    the split into two jitted programs is what keeps the healthy-path
    cost at the digest alone."""
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    def check(tree):
        return digest_agree(tree_digest(tree), axis_name)

    def resync(tree):
        return jax.tree.map(lambda x: _bcast(x, axis_name, 0), tree)

    check_fn = jax.jit(shard_map(check, mesh=mesh, in_specs=(P(),),
                                 out_specs=P(), check_vma=False))
    resync_fn = jax.jit(shard_map(resync, mesh=mesh, in_specs=(P(),),
                                  out_specs=P(), check_vma=False))
    return check_fn, resync_fn
