"""Overlapped backward-reduce: bucketed, dependency-scheduled gradient
transport (ISSUE 8; MLPerf TPU-pod bucketed gradient summation,
PAPERS.md #4).

Every reduction mode used to fire only after the ENTIRE backward pass had
produced every gradient: the micro-batch ``lax.scan`` in the step builders
emits all grads together, and the ring path additionally concatenates the
whole tree into ONE flat vector — both are hard barriers, so XLA could
never start a single collective hop while backward compute was still
running.  This module removes the barrier:

* :func:`bucket_layout` — the ONE greedy bucket-capping function, shared
  with ``dist._bucketed_quantized_sum`` so the overlapped and the
  post-backward bucketed paths can never disagree about the layout;
* :class:`BucketPlan` — the static layout (leaf sizes, global flat
  offsets in parallel/dist.py's `_leaf_starts` space, bucket membership)
  plus a hashable ``key()`` for step-table cache keys
  (resilience/precision.ladder_step_key's ``overlap`` coordinate);
* :func:`overlapped_grads` — ``value_and_grad`` with per-bucket
  ``jax.custom_vjp`` taps on the parameters: each bucket's tap is an
  identity on the forward pass, and its BACKWARD rule runs that bucket's
  quantized all-reduce (`dist.sum_gradients` on the bucket's sub-tree,
  with the bucket's GLOBAL flat offsets) the moment autodiff closes the
  bucket's last cotangent.  Late-layer buckets therefore finish their
  reduction work while early-layer backward compute is still pending —
  the dependency structure XLA's scheduler needs to overlap ring hops
  with backward compute.  Verification / telemetry reports ride OUT of
  the backward through the tap-cotangent channel (the
  quant_function.quantizer_stats idiom): a zeros ``(n_buckets, R)``
  input whose "gradient" is defined by the tap's bwd rule to be the
  bucket's report vector;
* :func:`overlap_evidence` — the crude overlap-actually-happened
  assertion for CI: walks the traced step's jaxpr and counts matmul/conv
  equations scheduled AFTER the first reduction collective.  The
  monolithic step has none (every collective postdates all compute); the
  tapped step interleaves them — a structural property of the emitted
  program, not a timing flake.

Bitwise contract: the overlapped result equals the non-overlapped one
bit for bit.  The ordered quantized accumulation is elementwise across
ranks, SR bits are indexed by GLOBAL flat offset, and Kahan compensation
is per-element — so faithful/fast results are invariant to ANY bucket
layout, and ring results are invariant to overlap on/off at a FIXED
layout (``sum_gradients(mode="ring", bucket_elems=...)`` runs the same
per-bucket rings post-backward; tests/test_overlap.py gates all of it).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["bucket_layout", "BucketPlan", "overlapped_grads",
           "overlap_evidence", "evidence_from_prims",
           "extract_bucket_shards", "REPORT_FIELDS",
           "DEFAULT_BUCKET_ELEMS"]

# One home for the default per-bucket element cap (dist.py re-exports it
# as the faithful path's historical `_BUCKET_ELEMS`): W x 4M x 4B =
# 128 MiB of gathered fp32 at W=8 — large enough to amortize collective
# launch overhead, small enough that a bucket never rivals model memory
# AND late buckets close early enough in the backward to overlap.
DEFAULT_BUCKET_ELEMS = 4 * 1024 * 1024

# Fixed slot order of the per-bucket report vector that rides the
# tap-cotangent channel (float32; ints ride exactly up to 2^24).  The
# wire layout prepends one internal "ran" slot (always 1 when the tap's
# bwd executed): a bucket whose parameters the loss never touches has
# its tap dead-code-eliminated by autodiff — its gradients are zeros
# either way (reducing zeros yields zeros bitwise, so the data path is
# unaffected), but its report row stays all-zero, and without the
# sentinel the merged `agree` verdict would read a never-run bucket as
# a cross-replica DISAGREEMENT (a permanent false-positive that would
# livelock the transport ladder).
REPORT_FIELDS = ("hop_bad", "gather_bad", "agree", "wire_sat",
                 "wire_underflow", "wire_nan", "wire_total", "aps_bad")


def bucket_layout(sizes: Sequence[int], bucket_elems: int,
                  group_ids: Optional[Sequence] = None) -> list:
    """Greedy bucket capping: split leaf indices into buckets of at most
    ``bucket_elems`` total elements (a single leaf larger than the cap
    forms its own bucket), preserving leaf order.  ``group_ids`` (e.g.
    dtypes) force a bucket break between unequal neighbors — the faithful
    gather path buckets per dtype because the gathered stack must be one
    array.  This is THE layout function: `dist._bucketed_quantized_sum`,
    the bucketed ring and the overlap taps all call it, so their bucket
    boundaries cannot drift."""
    if bucket_elems < 1:
        raise ValueError(f"bucket_elems must be >= 1, got {bucket_elems}")
    buckets: list = []
    cur: list = []
    cur_n = 0
    cur_gid = None
    for i, n in enumerate(sizes):
        gid = None if group_ids is None else group_ids[i]
        if cur and (cur_n + n > bucket_elems or gid != cur_gid):
            buckets.append(cur)
            cur, cur_n = [], 0
        cur.append(i)
        cur_n += int(n)
        cur_gid = gid
    if cur:
        buckets.append(cur)
    return buckets


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Static bucket layout over one gradient pytree.

    ``starts`` are GLOBAL flat offsets in tree_flatten order — the same
    index space `dist._leaf_starts` defines and the SR bitstream is
    indexed by, so a bucket's reduction draws exactly the bits the
    whole-tree reduction would."""
    sizes: tuple
    starts: tuple
    buckets: tuple          # tuple of tuples of leaf indices
    bucket_elems: int

    @classmethod
    def for_tree(cls, tree: Any, bucket_elems: Optional[int] = None,
                 group_by_dtype: bool = False) -> "BucketPlan":
        be = (DEFAULT_BUCKET_ELEMS if bucket_elems is None
              else int(bucket_elems))
        if be < 1:
            # fail HERE, at plan construction, not from bucket_layout
            # deep inside jit tracing of a per-bucket reduce
            raise ValueError(f"bucket_elems must be >= 1, got {be}")
        leaves = jax.tree_util.tree_leaves(tree)
        sizes = tuple(int(l.size) for l in leaves)
        starts = tuple(int(s) for s in
                       np.concatenate([[0], np.cumsum(sizes[:-1])])
                       ) if sizes else ()
        gids = ([str(jnp.dtype(l.dtype)) for l in leaves]
                if group_by_dtype else None)
        buckets = tuple(tuple(b) for b in bucket_layout(sizes, be, gids))
        return cls(sizes=sizes, starts=starts, buckets=buckets,
                   bucket_elems=be)

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def key(self) -> tuple:
        """Hashable layout fingerprint for step-table cache keys: a step
        traced for one layout must never be served for another (the PR 5
        half-keyed-table bug class, now with a bucket coordinate)."""
        return (self.bucket_elems, self.buckets)


def _f0(x):
    """A float0 zero cotangent for a non-differentiable (integer) tap
    input — the tangent type JAX requires for int-dtype primals."""
    return np.zeros(np.shape(x), jax.dtypes.float0)


def _make_bucket_tap(reduce_bucket: Callable, n_leaves: int):
    """One identity tap per bucket: ``tap(z, keys, aux, *leaves,
    *extras)`` returns the leaves unchanged; its bwd rule reduces the
    leaf cotangents with `reduce_bucket` and returns the bucket's report
    vector as ``z``'s cotangent.  ``keys`` ((2, 2) uint32 — the [sum,
    emulate] PRNG key pair, possibly dummies) and ``aux`` (float32
    [sat_scale, wf_code, wf_rank]) are traced per-step values that must
    ride as ARGUMENTS — custom_vjp cannot close over tracers.  The
    optional per-leaf ``extras`` (the emulate-node path's stacked prior
    micro-batch gradients, ISSUE 12 leg 3) ride the same way: pass-through
    residuals consumed by the bwd rule's local reduce, zero cotangents
    out (they are data, not params)."""

    @jax.custom_vjp
    def tap(z, keys, aux, *operands):
        return tuple(operands[:n_leaves])

    def fwd(z, keys, aux, *operands):
        return tuple(operands[:n_leaves]), (keys, aux,
                                            operands[n_leaves:])

    def bwd(res, cots):
        keys, aux, extras = res
        reduced, report = reduce_bucket(list(cots), list(extras), keys,
                                        aux)
        # slot 0 is the "ran" sentinel (see REPORT_FIELDS comment): it
        # distinguishes a clean all-zero report from a tap autodiff
        # never executed (all-unused bucket)
        report = jnp.concatenate([jnp.ones((1,), jnp.float32), report])
        return (report, _f0(keys), jnp.zeros_like(aux), *reduced,
                *[jnp.zeros_like(e) for e in extras])

    tap.defvjp(fwd, bwd)
    return tap


def extract_bucket_shards(reduced: Any, plan: "BucketPlan",
                          chunks: Sequence[int]) -> jnp.ndarray:
    """Pull the per-bucket reduce-scattered shards back out of the
    embedded leaf-cotangent encoding a ZeRO-2 tap collective emits
    (`parallel.zero._Zero2.make_tap_reduce`: bucket b's (c_b,) shard
    sits in the first c_b flat slots of its leaves, zeros after) and
    concatenate them into the rank's (S,) shard vector the updater's
    ``pre_sharded`` path consumes."""
    leaves = jax.tree_util.tree_leaves(reduced)
    segs = []
    for idxs, c in zip(plan.buckets, chunks):
        flat = (leaves[idxs[0]].reshape(-1) if len(idxs) == 1 else
                jnp.concatenate([leaves[i].reshape(-1) for i in idxs]))
        segs.append(flat[:c])
    return (jnp.concatenate(segs) if segs
            else jnp.zeros((0,), jnp.float32))


def overlapped_grads(loss_fn: Callable, params: Any, *,
                     axis_name, plan: BucketPlan,
                     reduce_kw: dict, key=None,
                     sat_factor=None, wire_fault=None,
                     verify: bool = False, stats: bool = False,
                     leaf_pre: Optional[Callable] = None,
                     collective: Optional[Callable] = None,
                     extras: Optional[Sequence] = None,
                     emulate_reduce: Optional[Callable] = None,
                     emulate_key=None):
    """``value_and_grad`` with per-bucket reduce-in-backward taps.

    loss_fn(params) -> (loss, aux) — the scalar loss and auxiliary
    outputs, exactly what the step builders pass to value_and_grad.
    Returns ``((loss, aux), reduced_grads, report)`` where
    ``reduced_grads`` is the FULLY REDUCED gradient pytree (bitwise equal
    to ``sum_gradients(local_grads, ...)`` of the non-overlapped step)
    and ``report`` is the merged verification/telemetry dict (None when
    both ``verify`` and ``stats`` are off).

    reduce_kw   → the `sum_gradients` precision/mode kwargs
                  (use_aps/grad_exp/grad_man/use_kahan/mode/rounding/
                  block_scale/block_size — the block-scaled ring wire
                  threads through unchanged, and because blocks are
                  chunk-local the per-bucket taps reproduce the
                  monolith's block boundaries exactly: overlap on/off
                  stays bitwise identical with block scaling on).
    key         → the shared reduction SR key (grad_sr_key site 1); the
                  same key reaches every bucket — bits are global-offset
                  indexed, so per-bucket draws equal the whole-tree draw.
    sat_factor  → traced 2^k saturation-pressure scale applied to each
                  cotangent BEFORE its bucket reduce (None = off; the
                  FaultPlan ``sat_pressure`` attack keeps firing under
                  the overlapped schedule).
    wire_fault  → traced ``(code, rank)`` ring wire fault.  Injected
                  into bucket 0 ONLY, so the deterministic chaos drills
                  keep their exact expected counter values (one flip →
                  hop_bad == 1) whatever the bucket count.
    leaf_pre    → optional ``fn(cotangent, leaf_index)`` run on each leaf
                  cotangent before the bucket reduce — the LM step's
                  sp/tp psums, which in the monolithic step run between
                  backward and the dp reduce.
    collective  → optional per-bucket collective override replacing the
                  `sum_gradients` call (ISSUE 12 leg 3: ZeRO-2's
                  per-bucket reduce-scatter, `zero._Zero2.make_tap_reduce`):
                  ``fn(bucket_index, leaf_indices, gs, key) -> outputs``
                  with outputs shaped like the bucket's leaves (the
                  shard-embedding contract).  Mutually exclusive with
                  verify/stats — the ZeRO updaters thread no reports.
    extras      → optional per-leaf operand list (aligned with the FULL
                  flattened param leaves): the emulate-node path's
                  stacked (N-1, *leaf) prior micro-batch gradients,
                  threaded through each tap as pass-through residuals so
                  the bwd-rule reduce can see them without closing over
                  tracers.
    emulate_reduce → optional ``fn(cotangent, extra, leaf_index,
                  emu_key) -> local_grad`` run per leaf AFTER leaf_pre
                  and the sat scale and BEFORE the bucket collective —
                  the rank-local emulate-node ordered reduce (stacks the
                  last micro-batch's cotangent under the prior ones).
                  Requires ``extras``.
    emulate_key → the rank-folded emulate-node SR key (site 0); rides
                  the taps next to `key` (slot 1 of the key pair).
    """
    from .dist import sum_gradients

    leaves_t, treedef = jax.tree_util.tree_flatten(params)
    if len(leaves_t) != len(plan.sizes):
        raise ValueError(f"BucketPlan built for {len(plan.sizes)} leaves, "
                         f"params have {len(leaves_t)}")
    if collective is not None and (verify or stats):
        raise ValueError("a custom bucket collective threads no "
                         "verify/stats report — the ZeRO paths reject "
                         "them upstream (make_train_step)")
    if emulate_reduce is not None and extras is None:
        raise ValueError("emulate_reduce needs the prior micro-batches' "
                         "stacked gradients via extras=")
    if extras is not None and len(extras) != len(leaves_t):
        raise ValueError(f"extras must align with the {len(leaves_t)} "
                         f"param leaves, got {len(extras)}")
    n_rep = len(REPORT_FIELDS)
    has_key = key is not None
    has_emu_key = emulate_key is not None
    want_report = verify or stats

    def make_reduce(b: int, idxs: tuple):
        fault_armed = wire_fault is not None and b == 0

        def reduce_bucket(gs, extras_b, keys, aux):
            # order matters and mirrors the monolith exactly: the sp/tp
            # psums FIRST, the 2^k sat-pressure scale on the post-psum
            # gradients second (scaling before the psum could overflow
            # a per-rank value whose psum'd sum the monolith keeps
            # finite — a bitwise divergence at the fp32 range edge),
            # the rank-local emulate-node reduce third (its input is
            # the scaled post-psum micro grads, mix.py:251-282), the
            # cross-device collective last
            if leaf_pre is not None:
                gs = [leaf_pre(g, i) for g, i in zip(gs, idxs)]
            if sat_factor is not None:
                gs = [g * aux[0] for g in gs]
            if emulate_reduce is not None:
                gs = [emulate_reduce(g, e, i,
                                     keys[1] if has_emu_key else None)
                      for g, e, i in zip(gs, extras_b, idxs)]
            sum_key = keys[0] if has_key else None
            if collective is not None:
                out = collective(b, idxs, gs, sum_key)
                return list(out), jnp.zeros((n_rep,), jnp.float32)
            wf = ((aux[1].astype(jnp.int32), aux[2].astype(jnp.int32))
                  if fault_armed else None)
            out = sum_gradients(
                list(gs), axis_name,
                key=sum_key,
                verify=verify, stats=stats, wire_fault=wf,
                offset_starts=[plan.starts[i] for i in idxs],
                **reduce_kw)
            if want_report:
                out, rep = out
                report = jnp.stack([
                    rep.get(f, jnp.zeros([], jnp.float32))
                    .astype(jnp.float32) for f in REPORT_FIELDS])
            else:
                report = jnp.zeros((n_rep,), jnp.float32)
            return out, report

        return reduce_bucket

    taps = [_make_bucket_tap(make_reduce(b, idxs), len(idxs))
            for b, idxs in enumerate(plan.buckets)]
    dummy = jnp.zeros((2,), jnp.uint32)
    keys = jnp.stack([jnp.asarray(key) if has_key else dummy,
                      jnp.asarray(emulate_key) if has_emu_key else dummy])
    aux = jnp.stack([
        (jnp.asarray(sat_factor, jnp.float32) if sat_factor is not None
         else jnp.float32(1.0)),
        (wire_fault[0].astype(jnp.float32) if wire_fault is not None
         else jnp.float32(0.0)),
        (wire_fault[1].astype(jnp.float32) if wire_fault is not None
         else jnp.float32(0.0))])

    def inner(p, z):
        leaves = list(jax.tree_util.tree_flatten(p)[0])
        for b, idxs in enumerate(plan.buckets):
            ext = ([extras[i] for i in idxs] if extras is not None
                   else [])
            outs = taps[b](z[b], keys, aux,
                           *[leaves[i] for i in idxs], *ext)
            for j, i in enumerate(idxs):
                leaves[i] = outs[j]
        return loss_fn(jax.tree_util.tree_unflatten(treedef, leaves))

    z0 = jnp.zeros((plan.n_buckets, n_rep + 1), jnp.float32)
    (loss, aux_out), (g_params, g_z) = jax.value_and_grad(
        inner, argnums=(0, 1), has_aux=True)(params, z0)

    report = None
    if want_report and plan.n_buckets == 0:
        report = {"hop_bad": jnp.zeros([], jnp.int32),
                  "gather_bad": jnp.zeros([], jnp.int32),
                  "agree": jnp.ones([], jnp.int32),
                  "ok": jnp.ones([], jnp.int32)} if verify else {}
        if stats:
            report.update({f: jnp.zeros([], jnp.float32)
                           for f in ("wire_sat", "wire_underflow",
                                     "wire_nan", "wire_total")})
            report["aps_bad"] = jnp.zeros([], jnp.int32)
    elif want_report:
        ran = g_z[:, 0]
        cols = {f: g_z[:, i + 1] for i, f in enumerate(REPORT_FIELDS)}
        report = {}
        if verify:
            hop_bad = jnp.sum(cols["hop_bad"]).astype(jnp.int32)
            gather_bad = jnp.sum(cols["gather_bad"]).astype(jnp.int32)
            # a never-run bucket (ran == 0) reduced nothing — its wire
            # is vacuously clean, not a disagreement
            agree = jnp.min(jnp.where(ran > 0, cols["agree"], 1.0)
                            ).astype(jnp.int32)
            report.update(
                hop_bad=hop_bad, gather_bad=gather_bad, agree=agree,
                ok=((hop_bad == 0) & (gather_bad == 0)
                    & (agree == 1)).astype(jnp.int32))
        if stats:
            for f in ("wire_sat", "wire_underflow", "wire_nan",
                      "wire_total"):
                report[f] = jnp.sum(cols[f])
            # a never-run bucket's gradients are exact zeros; the
            # monolith's probe still CASTS and COUNTS them (zeros fit
            # every format: 0 sat/underflow/nan, n*W total).  Credit the
            # dead buckets' element counts so wire_total — the
            # precision supervisor's rate denominator — is identical
            # under either schedule.
            from jax import lax
            sizes_b = jnp.asarray(
                [sum(plan.sizes[i] for i in idxs)
                 for idxs in plan.buckets], jnp.float32)
            world = lax.psum(jnp.float32(1.0), axis_name)
            report["wire_total"] = report["wire_total"] + world * jnp.sum(
                jnp.where(ran > 0, 0.0, sizes_b))
            report["aps_bad"] = jnp.sum(cols["aps_bad"]).astype(jnp.int32)
    return (loss, aux_out), g_params, report


# ---------------------------------------------------------------------------
# overlap evidence (CI's crude "overlap actually happened" assertion)
# ---------------------------------------------------------------------------

# the gradient-TRANSPORT collectives: ppermute (ring hops), all_gather
# (gather path / ring rebuild) and all_to_all (ZeRO-2's per-bucket
# reduce-scatter, ISSUE 12).  psum is deliberately absent — scalar
# bookkeeping (world size, loss metrics) and the LM's FORWARD
# tensor-parallel psums would otherwise read as transport.
_COLLECTIVE_PRIMS = {"ppermute", "all_gather", "all_to_all"}
_COMPUTE_PRIMS = {"conv_general_dilated", "dot_general"}


def _walk_eqns(jaxpr, out: list):
    """Flatten a jaxpr's equations depth-first in emission order —
    equations are topologically ordered as traced, so relative positions
    reflect the dependency structure XLA schedules from.  Each entry is
    ``(primitive_name, max_operand_elems)``."""
    for eqn in jaxpr.eqns:
        size = 0
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                size = max(size, int(np.prod(aval.shape))
                           if aval.shape else 1)
        out.append((eqn.primitive.name, size))
        for v in eqn.params.values():
            if isinstance(v, jax.core.ClosedJaxpr):
                _walk_eqns(v.jaxpr, out)
            elif isinstance(v, jax.core.Jaxpr):
                _walk_eqns(v, out)
            elif isinstance(v, (tuple, list)):
                for w in v:
                    if isinstance(w, jax.core.ClosedJaxpr):
                        _walk_eqns(w.jaxpr, out)
                    elif isinstance(w, jax.core.Jaxpr):
                        _walk_eqns(w, out)
    return out


def evidence_from_prims(prims: Sequence,
                        min_collective_elems: int = 2) -> dict:
    """The ONE interleaving-count implementation, over an emission-order
    ``(primitive_name, max_operand_elems)`` stream (`_walk_eqns`'s
    output shape — the IR analyzer's program tracer feeds its own walk
    through here, analysis/ir/trace.py, so the CI gate and the lint
    rule cannot drift).  Collectives moving fewer than
    ``min_collective_elems`` elements are ignored — the world-size
    psum, loss/metric psums and the APS per-leaf exponent pmax are
    scalar bookkeeping, not gradient transport."""
    first_coll = None
    compute_positions = []
    n_coll = 0
    for i, (p, size) in enumerate(prims):
        if p in _COLLECTIVE_PRIMS and size >= min_collective_elems:
            n_coll += 1
            if first_coll is None:
                first_coll = i
        elif p in _COMPUTE_PRIMS:
            compute_positions.append(i)
    after = (0 if first_coll is None else
             sum(1 for i in compute_positions if i > first_coll))
    return {"collectives": n_coll,
            "compute_eqns": len(compute_positions),
            "compute_after_first_collective": after,
            "interleaved": after > 0}


def overlap_evidence(fn: Callable, *args,
                     min_collective_elems: int = 2) -> dict:
    """Trace ``fn(*args)`` and report how much matmul/conv compute the
    program is free to schedule AFTER its first payload-bearing
    reduction collective.

    ``compute_after_first_collective == 0`` means every gradient
    collective postdates all compute — the post-backward monolith (no
    overlap possible).  A positive count is the structural signature of
    the bucketed schedule: bucket k's ring hops are emitted while bucket
    k+1's backward matmuls are still pending, so the compiler MAY
    overlap them.  This checks the emitted dependency order, not
    wall-clock — a loaded CI box cannot flake it.  Every
    overlap-configured REGISTERED program is additionally gated on this
    verdict in CI by the ``ir-overlap`` analyzer rule
    (analysis/ir/rules.py), which shares `evidence_from_prims`."""
    prims = _walk_eqns(jax.make_jaxpr(fn)(*args).jaxpr, [])
    return evidence_from_prims(prims,
                               min_collective_elems=min_collective_elems)


def ir_programs(reg):
    """Program-contract declarations (analysis/ir/registry.py): a toy
    two-bucket overlapped_grads program and its post-backward monolith
    — the minimal schedule twins.  They claim bitwise parity
    (tests/test_overlap.py's whole matrix), so the `ir-schedule` rule
    pins their collective multisets equal; the `ir-overlap` rule pins
    the structural verdicts (taps interleave, monolith does not) — the
    registry-generalized form of `overlap_evidence`, gated in CI for
    every overlap-configured program rather than where a bench script
    happened to call the probe."""
    from jax.sharding import PartitionSpec as P

    from ..compat import shard_map
    from .mesh import data_parallel_mesh
    from .ring import ring_transport_bytes

    W, d = 8, 64
    n_leaf = d * d
    deps = ("cpd_tpu.parallel.overlap", "cpd_tpu.parallel.dist",
            "cpd_tpu.parallel.ring", "cpd_tpu.quant.numerics")
    reduce_kw = dict(mode="ring", grad_exp=5, grad_man=2)

    def _params():
        return {"w1": jnp.zeros((d, d), jnp.float32),
                "w2": jnp.zeros((d, d), jnp.float32)}

    def _wire():
        # two buckets (one per dxd leaf at cap n_leaf), each ringing
        # its own n_leaf-element flat — identical for taps and monolith
        return 2 * ring_transport_bytes(n_leaf, W, 5, 2)

    def _overlapped():
        def build():
            mesh = data_parallel_mesh()
            plan = BucketPlan.for_tree(_params(), n_leaf)

            def body(x):
                params = _params()

                def loss(p):
                    return jnp.sum((x[0] @ p["w1"]) @ p["w2"]), None

                (_, _), reduced, _ = overlapped_grads(
                    loss, params, axis_name="dp", plan=plan,
                    reduce_kw=dict(reduce_kw))
                return reduced

            fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                           out_specs=P(), check_vma=False)
            return fn, (jax.ShapeDtypeStruct((W, 4, d), jnp.float32),)
        return build

    def _monolith():
        def build():
            from .dist import sum_gradients
            mesh = data_parallel_mesh()

            def body(x):
                params = _params()

                def loss(p):
                    return jnp.sum((x[0] @ p["w1"]) @ p["w2"])

                grads = jax.grad(loss)(params)
                return sum_gradients(grads, "dp",
                                     bucket_elems=n_leaf, **reduce_kw)

            fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                           out_specs=P(), check_vma=False)
            return fn, (jax.ShapeDtypeStruct((W, 4, d), jnp.float32),)
        return build

    reg.declare("overlap.taps[ring,e5m2,w8]", _overlapped(),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                twin="overlap.toy", overlap=True, wire=_wire)
    reg.declare("overlap.monolith[ring,e5m2,w8]", _monolith(),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                twin="overlap.toy", overlap=False, wire=_wire)
