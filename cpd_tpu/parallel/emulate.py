"""Cluster-size emulation ("emulate node") — N virtual nodes per real device.

TPU-native re-implementation of the reference's `--emulate_node` mechanism
(reference: example/ResNet18/tools/mix.py:224-285, example/ResNet50/
main.py:156-202): each real process runs N micro-batches, buffers per-param
gradients, then performs a *local* APS shift + quantize + ordered quantized
accumulation — "as we use a single node to emulate multi-node, we should
first accumulate gradients within a single node and then communicate them"
(mix.py:275-277) — before the cross-process `sum_gradients`.

Here the micro-batch loop is vectorized: the trainer computes per-micro-batch
grads with `jax.vmap`/`lax.scan` (leaf shape ``(N, *shape)``) and this module
reduces the leading axis with the same ordered primitives as the collectives,
so emulated-node numerics are bit-identical to the reference's recipe.

Faithful quirks preserved (mix.py:251-282):
* N == 1 shortcut: the single grad is used as-is, NO quantization
  (mix.py:254-256).
* The quantize step runs even when APS is off (shift is just 0)
  (mix.py:267-271: `shift_factor = 0 if not use_APS`, quantize regardless).
* All-zero guard: max_exp == -100 sentinel → shift 0 (mix.py:267-268).
* The local shift uses only the *local* micro-batch max — the global pmax
  happens later inside `sum_gradients`.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..quant.numerics import cast_to_format, cast_to_format_sr
from .aps import aps_max_exponents, aps_shift_factors, exp2_exact
from .reduction import ordered_quantized_sum

__all__ = ["emulate_node_reduce", "reduce_stacked_leaf",
           "make_overlap_emulate_fn"]


def _reduce_leaf(g: jnp.ndarray, n: int, use_aps: bool,
                 grad_exp: int, grad_man: int, key=None) -> jnp.ndarray:
    """Reduce one stacked leaf (N, *shape) -> (*shape,)."""
    if n == 1:
        return g[0]  # mix.py:254-256 — no quantization for a single grad
    if use_aps:
        max_exp = aps_max_exponents([g], n)
        shift = aps_shift_factors(max_exp, grad_exp)[0]
    else:
        shift = jnp.float32(0.0)  # quantize still runs (mix.py:267-271)
    scale = exp2_exact(shift)
    if key is None:
        g = cast_to_format(g * scale, grad_exp, grad_man)
        res = ordered_quantized_sum(g, grad_exp, grad_man)
    else:
        k_pre, k_sum = jax.random.split(key)
        g = cast_to_format_sr(g * scale, grad_exp, grad_man, k_pre)
        res = ordered_quantized_sum(g, grad_exp, grad_man, key=k_sum)
    return res / exp2_exact(shift)  # true divide, as mix.py:280 does


def reduce_stacked_leaf(g: jnp.ndarray, n: int, use_aps: bool = False,
                        grad_exp: int = 5, grad_man: int = 2,
                        key=None) -> jnp.ndarray:
    """Public per-leaf emulate-node reduce: one stacked (N, *shape) leaf
    -> its locally-accumulated (*shape,) gradient, with EXACTLY
    `emulate_node_reduce`'s per-leaf semantics (N==1 shortcut, quantize
    even without APS, local-max shift).

    For callers that reduce one leaf at a time — the overlapped
    backward-reduce taps (parallel/overlap.py `emulate_reduce` hook,
    ISSUE 12), whose bwd rules see a single leaf's cotangent.  The SR
    `key` must already be folded by the leaf's GLOBAL tree index
    (`fold_in(emu_key, leaf_index)`) to reproduce
    `emulate_node_reduce`'s per-leaf streams bit for bit."""
    return _reduce_leaf(g, n, use_aps, grad_exp, grad_man, key=key)


def make_overlap_emulate_fn(n: int, use_aps: bool, grad_exp: int,
                            grad_man: int, sr: bool):
    """The ONE `overlapped_grads(emulate_reduce=...)` hook body, shared
    by both step builders (train/step.py, train/lm.py) so the SR-key
    contract — `fold_in(emu_key, GLOBAL leaf index)` feeding
    `reduce_stacked_leaf`, exactly `emulate_node_reduce`'s per-leaf
    streams — cannot drift between them.

    Returns ``fn(cotangent, extra, leaf_index, emu_key)``: stacks the
    LAST micro-batch's cotangent under the prior micro-batches' stacked
    gradients (`extra`, (N-1, *leaf)) and runs the rank-local
    emulate-node ordered reduce on the (N, *leaf) result."""

    def emulate_fn(g, extra, i, ekey):
        stacked_leaf = jnp.concatenate([extra, g[None]], 0)
        return reduce_stacked_leaf(
            stacked_leaf, n, use_aps, grad_exp, grad_man,
            key=(jax.random.fold_in(ekey, i) if sr else None))

    return emulate_fn


def emulate_node_reduce(stacked_grads: Any, emulate_node: int,
                        use_aps: bool = False, grad_exp: int = 5,
                        grad_man: int = 2, key=None,
                        rounding: str = "nearest") -> Any:
    """Locally reduce N stacked micro-batch gradients per leaf.

    stacked_grads: pytree with leaves shaped (emulate_node, *param_shape).
    Returns the locally-accumulated gradient pytree (leaf shape
    (*param_shape,)), ready for the cross-device `sum_gradients`.

    rounding='stochastic' with `key` (beyond-reference) switches every
    cast — the local pre-quantize and each ordered-accumulation step — to
    unbiased stochastic rounding, one independent bitstream per leaf.
    The key/rounding contract matches `sum_gradients`: a key with
    'nearest' raises (it would be silently ignored), 'stochastic' without
    a key raises."""
    if rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown rounding {rounding!r}")
    if rounding == "stochastic" and key is None:
        raise ValueError("rounding='stochastic' requires a PRNG key")
    if rounding == "nearest" and key is not None:
        raise ValueError("a PRNG key was passed but rounding='nearest' "
                         "would ignore it; pass rounding='stochastic' "
                         "(matching sum_gradients' contract)")
    if key is None:
        return jax.tree.map(
            lambda g: _reduce_leaf(g, emulate_node, use_aps, grad_exp,
                                   grad_man),
            stacked_grads)
    leaves, treedef = jax.tree_util.tree_flatten(stacked_grads)
    out = [_reduce_leaf(g, emulate_node, use_aps, grad_exp, grad_man,
                        key=jax.random.fold_in(key, i))
           for i, g in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)
