"""ZeRO-1/2: optimizer-state (and reduction) sharding over the data axis.

New capability beyond the reference (SURVEY.md §2 strategy inventory:
"ZeRO/FSDP sharding — Absent").  Stage-1 ZeRO: params stay replicated,
but the optimizer state (the torch-SGD momentum buffer — as large as the
model) is sharded 1/W per data rank, cutting optimizer memory by the dp
world size.  The TPU-native realisation under `shard_map`:

    1. the quantized all-reduce (parallel/dist.py) leaves every rank with
       the full gradient sum, exactly as before — APS/ordered/Kahan
       semantics are untouched;
    2. gradients and params are flattened to ONE fp32 vector, padded to a
       multiple of W; each rank dynamic-slices its 1/W shard;
    3. the torch-SGD update rule (train/optim.py's semantics, bit-equal)
       runs on the shard against the rank's momentum shard;
    4. one tiled `all_gather` rebuilds the full flat params, unflattened
       back to the pytree — the ZeRO "param broadcast".

Memory: momentum goes from NxP to NxP/W per chip; wire cost is one (P/W)
all_gather per step, riding ICI.  Usage:

    z = zero1_sgd(schedule, world=mesh.shape["dp"], momentum=0.9, ...)
    state = TrainState(..., opt_state=z.init(params))
    step = make_train_step(model, tx=None, mesh, update_fn=z.update_fn,
                           opt_state_spec=z.state_spec())

Stage-2 ZeRO (`zero2_sgd`) additionally shards the *reduction*: instead of
every rank gathering the full (W, P) gradient stack and each computing the
whole ordered quantized sum (parallel/dist.py faithful mode), one
`all_to_all` hands rank r the (W, P/W) stack of every rank's r-th slice,
and the rank-ordered requantized scan runs only on that shard.  The scan
is elementwise over ranks in rank order, so the shard-local sum is
bit-identical to the corresponding slice of the replicated faithful
reduction — APS scaling (global pmax), Kahan compensation, and the
e5m2/fp16/bf16 wire compression all compose unchanged.  Peak reduction
memory drops from W x P to P per chip (the gathered stack equals one
model's gradients), wire bytes are identical.  Usage is the same as
zero1_sgd, with the train step told to skip its own reduction (the step
forwards its use_aps/grad_exp/grad_man/use_kahan/mode to the updater, so
precision has one source of truth):

    z = zero2_sgd(schedule, world)
    step = make_train_step(model, None, mesh, use_aps=True, grad_exp=5,
                           grad_man=2, update_fn=z.update_fn,
                           opt_state_spec=z.state_spec(),
                           reduce_in_update=True)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..quant.numerics import (cast_body_blocked, pack_exmy,
                              pack_exmy_blocked, sr_bits_at, unpack_exmy,
                              unpack_exmy_blocked)
from .aps import (aps_max_exponents, aps_scale, aps_shift_factors,
                  exp2_exact, pmax_scalar_vector)
from .dist import _flat_axis_index, _wire_format, quantize_tree_sr
from .reduction import quantized_sum
from .ring import pad_to_world, ring_chunk_size

__all__ = ["Zero1State", "zero1_sgd", "zero2_sgd", "zero3_sgd",
           "zero1_lars", "zero2_lars", "zero3_lars",
           "zero2_oracle_flat", "zero2_transport_bytes"]


@dataclasses.dataclass(frozen=True)
class _ZeroLayout:
    """Static flat layout of one ZeRO updater over one param/grad tree.

    The legacy whole-tree layout is the single-bucket special case
    (``bucket_elems=None``): one span covering every leaf, padded to
    ``world * ceil(total / world)`` — bit-identical to the pre-ISSUE-12
    code.  With a bucket cap (`zero2_sgd(bucket_elems=...)`, shared with
    the overlap taps' `overlap.bucket_layout`), each bucket's span is
    padded to ``world * c_b`` independently and rank r's shard is the
    CONCATENATION of its per-bucket slices — the layout that lets a
    per-bucket `custom_vjp` tap reduce-scatter one bucket the moment its
    backward closes (ISSUE 12 leg 3), with the update consuming bucket
    shards.

    ``meta[b] = (a_b, m_b, c_b)``: the bucket's GLOBAL flat start in the
    unpadded tree layout (the SR bitstream's index space,
    parallel/dist._leaf_starts), its element count, and its per-rank
    chunk ``ceil(m_b / world)``.  ``shard_size = Σ c_b``."""
    world: int
    total: int
    sizes: tuple          # per-leaf element counts, tree_flatten order
    starts: tuple         # per-leaf global flat starts
    buckets: tuple        # tuple of tuples of leaf indices
    meta: tuple           # per bucket: (a_b, m_b, c_b)

    @property
    def shard_size(self) -> int:
        return sum(c for _, _, c in self.meta)

    @property
    def padded_total(self) -> int:
        return self.world * self.shard_size

    def shard_offsets(self, rank) -> jnp.ndarray:
        """(S,) uint32 GLOBAL flat offset of each element of rank
        ``rank``'s shard; world-size-pad elements get the sentinel
        ``total`` (one past the last real element — they hold exact
        zeros, whose cast is rounding-invariant, and the sentinel maps
        them to the pad bucket in every leaf lookup)."""
        segs = []
        r = jnp.asarray(rank).astype(jnp.uint32)
        for a, m, c in self.meta:
            local = r * jnp.uint32(c) + jnp.arange(c, dtype=jnp.uint32)
            g = jnp.uint32(a) + local
            segs.append(jnp.where(local < jnp.uint32(m), g,
                                  jnp.uint32(self.total)))
        return jnp.concatenate(segs) if segs else jnp.zeros((0,),
                                                            jnp.uint32)


class Zero1State(NamedTuple):
    """Flat ZeRO optimizer state.

    Elastic-restart invariant (ISSUE 4): elements of ``momentum`` in the
    world-size pad (past the total parameter count) hold EXACT zeros,
    forever — `pad_to_world` zero-fills them and the update rule keeps
    them there (pad gradients are exact zeros, so ``m*0 + 0 == 0``).
    `train/checkpoint.py::restore_latest_valid(world=W')` relies on it:
    trimming the pad and re-padding through `pad_to_world` at a NEW
    world size is then bitwise-faithful, so a checkpoint written at
    world W resumes at W' (`export_state`'s portable trim is the same
    contract, applied eagerly)."""
    step: jnp.ndarray          # replicated scalar int32
    momentum: jnp.ndarray      # flat fp32, global (W*S,), per-rank (S,)


class _Zero1:
    # per-bucket element cap for the BUCKETED flat layout (ZeRO-2 only;
    # None = the legacy single whole-tree bucket, bit-identical to the
    # pre-ISSUE-12 layout)
    bucket_elems: Optional[int] = None
    # whether this updater's update_fn consumes pre_sharded tap output
    # (ZeRO-2 only; mesh_layout wires tap_reduce= iff this is True, so
    # ZeRO-3 — which inherits make_tap_reduce but not the pre_sharded
    # update path — cannot advertise an overlap hook it does not honor)
    supports_tap_reduce = False

    def __init__(self, schedule: Callable, world: int, momentum: float,
                 weight_decay: float, nesterov: bool,
                 wd_mask: Optional[Callable], axis_name: str):
        self.schedule = schedule
        self.world = world
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_mask = wd_mask
        self.axis_name = axis_name

    # ---- flat layout ----
    def _layout(self, template) -> _ZeroLayout:
        """The static flat layout over `template` — one whole-tree
        bucket unless this updater was built with a bucket cap
        (`_Zero2(bucket_elems=...)`), in which case the buckets come
        from the ONE shared capping function (`overlap.bucket_layout`)
        so the updater, the step's overlap taps, and the bucketed ring
        can never disagree about boundaries.  Chunks use the ring
        transport's quantum (`ring_chunk_size`) per bucket."""
        from .overlap import bucket_layout
        leaves = jax.tree.leaves(template)
        sizes = tuple(int(l.size) for l in leaves)
        starts = tuple(int(s) for s in
                       np.concatenate([[0], np.cumsum(sizes[:-1])])
                       ) if sizes else ()
        total = int(sum(sizes))
        if self.bucket_elems is None:
            buckets = (tuple(range(len(sizes))),) if sizes else ()
        else:
            buckets = tuple(tuple(b) for b in
                            bucket_layout(sizes, self.bucket_elems))
        meta = []
        for b in buckets:
            a = starts[b[0]]
            m = sum(sizes[i] for i in b)
            meta.append((a, m, ring_chunk_size(m, self.world)))
        return _ZeroLayout(world=self.world, total=total, sizes=sizes,
                           starts=starts, buckets=buckets,
                           meta=tuple(meta))

    def _shard_size(self, params) -> int:
        # the ring transport's chunk quantum (parallel/ring.py) — ZeRO
        # shards and ring chunks slice the same padded flat layout
        return self._layout(params).shard_size

    def _shard_leaf_values(self, lay: _ZeroLayout, values, rank,
                           pad: float = 0.0) -> jnp.ndarray:
        """Expand a per-LEAF value vector to this rank's (S,) per-element
        shard of the flat layout.

        Built from the static leaf-offset table: each shard element's
        GLOBAL offset maps to its leaf via searchsorted, then to that
        leaf's value.  O(S) per rank — never the full (W*S,) flat
        vector, which the round-2 code materialized on every rank before
        slicing (ADVICE r2).  Elements in the world-size pad get `pad`."""
        leaf_idx = self._shard_leaf_index(lay, rank)
        padded = jnp.concatenate([jnp.asarray(values, jnp.float32),
                                  jnp.full((1,), pad, jnp.float32)])
        return jnp.take(padded, leaf_idx)

    def _shard_leaf_index(self, lay: _ZeroLayout, rank) -> jnp.ndarray:
        """(S,) map from shard element to its leaf index in tree-leaves
        order; elements in the world-size pad map to n_leaves (one past
        the last real leaf — the `shard_offsets` sentinel lands there)."""
        ends = np.cumsum(lay.sizes)  # static end offsets
        # uint32 index space, same rationale as the SR offsets: int32
        # would wrap negative past 2^31 elements and searchsorted would
        # map those shard elements to leaf 0 (ADVICE r4 follow-up)
        return jnp.searchsorted(jnp.asarray(ends, np.uint32),
                                lay.shard_offsets(rank), side="right")

    def _shard_mask(self, params, lay: _ZeroLayout, rank) -> jnp.ndarray:
        """This rank's (S,) slice of the per-element weight-decay mask
        (per-leaf bools are static, so the value vector is a host-side
        constant of n_leaves floats, not a 100MB per-element literal)."""
        mask = (self.wd_mask(params) if self.wd_mask is not None
                else jax.tree.map(lambda _: True, params))
        vals = np.array([float(bool(m)) for m in jax.tree.leaves(mask)],
                        np.float32)
        return self._shard_leaf_values(lay, vals, rank)

    def _shard_flat(self, flat: jnp.ndarray, lay: _ZeroLayout,
                    rank) -> jnp.ndarray:
        """Rank `rank`'s (S,) shard of the UNPADDED (total,) flat vector:
        per bucket, pad the span to ``world * c_b`` and dynamic-slice
        the rank's chunk.  Single bucket == the legacy pad+slice."""
        segs = []
        for a, m, c in lay.meta:
            span = pad_to_world(lax.slice_in_dim(flat, a, a + m),
                                self.world)
            segs.append(lax.dynamic_slice(
                span, (rank * c,), (c,)))
        return (jnp.concatenate(segs) if segs
                else jnp.zeros((0,), jnp.float32))

    def _unflatten_gathered(self, full: jnp.ndarray, template,
                            lay: _ZeroLayout):
        """Inverse of `_shard_flat` ∘ all_gather: the tiled (W*S,)
        gather of per-rank shards back to the param pytree.  Rank-major:
        ``full.reshape(W, S)`` holds each rank's concatenated bucket
        chunks, so bucket b's unpadded span is the flattened (W, c_b)
        column block trimmed to m_b."""
        stacked = full.reshape(self.world, lay.shard_size)
        spans, off = [], 0
        for a, m, c in lay.meta:
            spans.append(lax.slice_in_dim(stacked, off, off + c,
                                          axis=1).reshape(-1)[:m])
            off += c
        flat = (jnp.concatenate(spans) if spans
                else jnp.zeros((0,), jnp.float32))
        return self._unflatten(flat, template)

    @staticmethod
    def _flatten(tree) -> jnp.ndarray:
        return jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1)
             for l in jax.tree.leaves(tree)])

    @staticmethod
    def _unflatten(flat: jnp.ndarray, template):
        leaves = jax.tree.leaves(template)
        out, off = [], 0
        for l in leaves:
            out.append(flat[off:off + l.size].reshape(l.shape)
                       .astype(l.dtype))
            off += l.size
        return jax.tree.unflatten(jax.tree.structure(template), out)

    # ---- optimizer surface ----
    def init(self, params) -> Zero1State:
        """Global-shaped opt state: momentum (W*S,) — device_put with
        `state_spec()` (or the train step's out sharding) splits it 1/W
        per rank."""
        s = self._shard_size(params)
        return Zero1State(jnp.zeros([], jnp.int32),
                          jnp.zeros((self.world * s,), jnp.float32))

    def state_spec(self) -> Zero1State:
        return Zero1State(P(), P(self.axis_name))

    def _grad_shard(self, grads, state, axis_name: str,
                    **quant_kw) -> jnp.ndarray:
        """This rank's (S,) gradient slice.  ZeRO-1: slice the replicated
        reduced grads; ZeRO-2 overrides with the sharded reduce-scatter."""
        if quant_kw:
            raise ValueError(
                "ZeRO-1 expects pre-reduced gradients; "
                "reduce_in_update=True is a ZeRO-2 (zero2_sgd) contract")
        lay = self._layout(state.params)
        rank = lax.axis_index(axis_name)
        return self._shard_flat(self._flatten(grads), lay, rank)

    requires_reduce_in_update = False

    def update_fn(self, grads, state, axis_name: str,
                  pre_sharded: bool = False, **quant_kw):
        """Inside shard_map: `grads` per the subclass's _grad_shard
        contract, LOCAL (S,) momentum shard.  Returns (new full params,
        new opt state).  `quant_kw` is forwarded by the train step when it
        delegates the reduction (reduce_in_update) so precision settings
        have one source of truth.  ``pre_sharded=True`` (ZeRO-2 overlap,
        ISSUE 12): `grads` is ALREADY this rank's (S,) reduce-scattered
        shard — the per-bucket custom_vjp taps ran the collective inside
        the backward (`make_tap_reduce`) and the update just consumes the
        bucket shards."""
        if self.requires_reduce_in_update and not (quant_kw or pre_sharded):
            raise ValueError(
                "this ZeRO stage folds the collective into the update: "
                "build the step with make_train_step(..., "
                "reduce_in_update=True) — without it the step pre-reduces "
                "and the sharded reduce-scatter would double-count by W")
        params = state.params
        opt: Zero1State = state.opt_state
        lay = self._layout(params)
        rank = lax.axis_index(axis_name)
        lr = self.schedule(opt.step)

        if pre_sharded:
            g_sh = jnp.asarray(grads, jnp.float32)
            if g_sh.shape != (lay.shard_size,):
                raise ValueError(
                    f"pre_sharded gradients have shape {g_sh.shape}, "
                    f"expected ({lay.shard_size},) — the tap plan's "
                    f"bucket layout must come from this updater "
                    f"(make_tap_reduce)")
        else:
            g_sh = self._grad_shard(grads, state, axis_name, **quant_kw)
        p_sh = self._shard_flat(self._flatten(params), lay, rank)
        new_p_sh, new_buf = self._shard_update(g_sh, p_sh, params, rank,
                                               lay, opt.momentum, lr,
                                               axis_name)

        full = lax.all_gather(new_p_sh, axis_name, axis=0, tiled=True)
        new_params = self._unflatten_gathered(full, params, lay)
        return new_params, Zero1State(opt.step + 1, new_buf)

    def _shard_update(self, g_sh, p_sh, template, rank, lay, buf, lr,
                      axis_name):
        """Optimizer rule on the flat shard — overridden by the LARS
        variants (`_LarsRule`); the default is the torch-SGD rule."""
        m_sh = self._shard_mask(template, lay, rank)
        return self._shard_sgd(g_sh, p_sh, m_sh, buf, lr)

    # ---- portable checkpoints (round 5; the ZeRO-3 analogs are its
    # own export_state/portable_template, which also convert params) ----
    def export_state(self, state):
        """Padded (W*S,) momentum -> PORTABLE (total,) layout: the
        world-size pad is trimmed so the checkpoint restores at ANY
        device count (and its momentum reads as the plain flat vector
        by any non-ZeRO consumer).

        A PADDED snapshot (e.g. a preemption save that skipped this
        conversion) is equally world-portable now: `CheckpointManager`
        records the padded length in the sidecar and
        `restore_latest_valid(world=W')` performs this same trim +
        re-pad lazily at restore (the Zero1State elastic invariant) —
        for the LEGACY single-bucket layout; a bucketed updater
        (`zero2_sgd(bucket_elems=...)`) must save through this method,
        which interleaves the per-bucket pad trim."""
        opt: Zero1State = state.opt_state
        lay = self._layout(state.params)
        mom = jnp.asarray(opt.momentum)
        if len(lay.meta) <= 1:
            return state.replace(opt_state=Zero1State(
                opt.step, mom[:lay.total]))
        stacked = mom.reshape(self.world, lay.shard_size)
        spans, off = [], 0
        for a, m, c in lay.meta:
            spans.append(stacked[:, off:off + c].reshape(-1)[:m])
            off += c
        return state.replace(opt_state=Zero1State(
            opt.step, jnp.concatenate(spans)))

    def portable_template(self, state):
        """Restore template in the portable layout (pass to
        `CheckpointManager.restore` before `import_state`)."""
        total = sum(l.size for l in jax.tree.leaves(state.params))
        return state.replace(opt_state=Zero1State(
            jnp.zeros([], jnp.int32), jnp.zeros((total,), jnp.float32)))

    def import_state(self, state):
        """Portable layout -> THIS updater's padded (W*S,) layout (the
        bucketed inverse of `export_state`'s interleaved trim)."""
        opt: Zero1State = state.opt_state
        lay = self._layout(state.params)
        mom = jnp.asarray(opt.momentum)
        if len(lay.meta) <= 1:
            return state.replace(opt_state=Zero1State(
                opt.step, pad_to_world(mom, self.world)))
        cols = []
        for a, m, c in lay.meta:
            cols.append(pad_to_world(mom[a:a + m],
                                     self.world).reshape(self.world, c))
        stacked = jnp.concatenate(cols, axis=1)     # (W, S)
        return state.replace(opt_state=Zero1State(opt.step,
                                                  stacked.reshape(-1)))

    def mesh_layout(self, state, mesh):
        """Lay a pytree-params TrainState (whose opt_state is this
        updater's `init(...)`) out on `mesh` — everything replicated
        except the dp-sharded flat momentum — and return
        ``(state, step_kwargs)`` with the `make_train_step` hooks wired
        (`update_fn`, `opt_state_spec`, plus `reduce_in_update` for the
        stages that shard the reduction).  The ONE copy of the ZeRO-1/2
        CLI wiring (the ZeRO-3 analog is `make_state`, whose packed
        params need the extra `params_spec`/`unpack_params` hooks)."""
        from jax.sharding import NamedSharding

        spec_tree = state.replace(step=P(), params=P(), batch_stats=P(),
                                  opt_state=self.state_spec())
        laid = jax.device_put(
            state, jax.tree.map(lambda sp: NamedSharding(mesh, sp),
                                spec_tree,
                                is_leaf=lambda sp: isinstance(sp, P)))
        kw = {"update_fn": self.update_fn,
              "opt_state_spec": self.state_spec()}
        if self.requires_reduce_in_update:
            kw["reduce_in_update"] = True
        if self.supports_tap_reduce:
            # the ZeRO-2 overlap hook (make_tap_reduce): lets
            # make_train_step(overlap_reduce=True) run the per-bucket
            # reduce-scatter inside the backward taps (ISSUE 12 leg 3)
            kw["tap_reduce"] = self.make_tap_reduce
        return laid, kw

    def _shard_sgd(self, g_sh, p_sh, m_sh, buf, lr):
        """The torch-SGD rule on a flat shard (train/optim.py:65-69,
        bit-equal) — the ONE copy every ZeRO stage's update uses."""
        d = g_sh + (self.weight_decay * p_sh * m_sh
                    if self.weight_decay else 0.0)
        new_buf = self.momentum * buf + d
        step_dir = d + self.momentum * new_buf if self.nesterov else new_buf
        return p_sh - lr * step_dir, new_buf


def zero1_sgd(schedule: Callable, world: int, momentum: float = 0.9,
              weight_decay: float = 0.0, nesterov: bool = False,
              wd_mask: Optional[Callable] = None,
              axis_name: str = "dp") -> _Zero1:
    """ZeRO-1 torch-SGD: momentum sharded 1/`world` over `axis_name`."""
    return _Zero1(schedule, world, momentum, weight_decay, nesterov,
                  wd_mask, axis_name)


class _Zero2(_Zero1):
    """ZeRO-2: sharded faithful quantized reduction + sharded update.

    `update_fn` receives the rank's LOCAL (unreduced, post-emulate-node)
    gradients — build the train step with ``reduce_in_update=True`` so it
    skips `sum_gradients`.  Precision settings (use_aps/grad_exp/grad_man/
    use_kahan/mode, and the blocked wire's block_scale/block_size) are NOT
    stored here: the step forwards its own, so the emulate-node
    quantization and the cross-device reduction cannot drift apart.

    ``bucket_elems`` switches the flat layout to per-bucket spans
    (`overlap.bucket_layout` boundaries): each bucket reduce-scatters
    independently, which is what lets `make_tap_reduce`'s per-bucket
    custom_vjp taps run the collective INSIDE the backward (ISSUE 12 leg
    3) — overlap on/off is bitwise identical at a fixed layout because
    monolith and taps run the SAME `_bucket_reduce_scatter` per bucket."""

    # update_fn must see LOCAL grads; _Zero1.update_fn enforces this by
    # refusing to run when the step did not forward its precision settings
    # (i.e. reduce_in_update was off and grads are already reduced —
    # reduce-scattering those would double-count by W)
    requires_reduce_in_update = True
    supports_tap_reduce = True

    def __init__(self, schedule, world, momentum, weight_decay, nesterov,
                 wd_mask, axis_name, bucket_elems: Optional[int] = None):
        super().__init__(schedule, world, momentum, weight_decay, nesterov,
                         wd_mask, axis_name)
        if bucket_elems is not None and bucket_elems < 1:
            raise ValueError(f"bucket_elems must be >= 1, got "
                             f"{bucket_elems}")
        self.bucket_elems = bucket_elems

    def _bucket_shift_values(self, lay: _ZeroLayout, b: int, values,
                             rank, pad: float = 0.0) -> jnp.ndarray:
        """Per-element expansion of a per-LEAF value vector over bucket
        b's (c_b,) shard — the bucket-local sibling of
        `_shard_leaf_values` (values indexed by position WITHIN the
        bucket's leaf list; world-size-pad elements get `pad`)."""
        _, m, c = lay.meta[b]
        idxs = lay.buckets[b]
        ends = np.cumsum([lay.sizes[i] for i in idxs])
        local = (jnp.asarray(rank).astype(jnp.uint32) * jnp.uint32(c)
                 + jnp.arange(c, dtype=jnp.uint32))
        leaf_idx = jnp.searchsorted(jnp.asarray(ends, np.uint32), local,
                                    side="right")
        padded = jnp.concatenate([jnp.asarray(values, jnp.float32),
                                  jnp.full((1,), pad, jnp.float32)])
        return jnp.take(padded, leaf_idx)

    @staticmethod
    def _validate_precision(mode, rounding, key, block_scale, block_size,
                            grad_exp, grad_man):
        """The ONE precision-contract validator of the sharded reduce —
        shared by the post-backward `_grad_shard` and the overlap hook
        `make_tap_reduce`, so the two entry points cannot drift."""
        if mode != "faithful":
            raise ValueError(
                f"ZeRO-2 shards the faithful ordered reduction; mode="
                f"{mode!r} is not supported here (the fast psum path "
                f"keeps the full gradient resident anyway, and the ring "
                f"transport's per-chunk rotation order is a different "
                f"reduction semantics from the rank-order slices ZeRO-2 "
                f"reproduces)")
        if rounding == "stochastic" and key is None:
            raise ValueError("rounding='stochastic' requires a PRNG key")
        if rounding == "nearest" and key is not None:
            raise ValueError("a PRNG key was passed but rounding='nearest' "
                             "would ignore it (sum_gradients' contract)")
        if block_scale:
            if grad_exp == 8 and grad_man == 23:
                raise ValueError(
                    "block_scale=True at (8, 23): the fp32 wire has "
                    "nothing to scale — drop block_scale or pick a "
                    "sub-fp32 format")
            if grad_man < 2:
                raise ValueError(
                    f"block_scale=True needs a packable format (man_bits "
                    f">= 2 for the codec's special codes), got "
                    f"({grad_exp}, {grad_man})")
            if block_size < 1:
                raise ValueError(f"block_size must be >= 1, got "
                                 f"{block_size}")

    def _bucket_reduce_scatter(self, lay: _ZeroLayout, b: int, leaves_b,
                               axis_name: str, *, use_aps, grad_exp,
                               grad_man, use_kahan, key,
                               block_scale=False,
                               block_size=128) -> jnp.ndarray:
        """Bucket b's (c_b,) reduce-scattered shard — the ONE collective
        body shared by the post-backward monolith (`_grad_shard`'s loop)
        and the in-backward overlap taps (`make_tap_reduce`), so overlap
        on/off cannot drift.

        Per-tensor wire (block_scale=False): APS pre-scale+quantize, the
        (W, c_b) all_to_all of bit-packed code words, the rank-ordered
        requantized scan with GLOBAL-offset SR bits, divide-unscale —
        exactly `sum_gradients` faithful-mode semantics on the bucket's
        slice (the scan is elementwise over ranks, so slicing before
        summing is bit-identical to summing then slicing).

        Blocked wire (block_scale=True, ISSUE 12 leg 1): the all_to_all
        payload rides `pack_exmy_blocked` — each (c_b,) payload row is
        block-scaled-cast with CHUNK-LOCAL blocks (every sender derives
        identical boundaries for slice j, so the shift sidecar is
        sharded consistently with the slice layout; the odd tail block
        of a non-divisible c_b is handled by the codec), the
        1-byte-per-block sidecar rides after the code bytes, and the
        ordered scan requantizes partials with the SAME blocked cast
        (`reduction.quantized_sum(block_size=...)`) so accumulation
        keeps the per-block dynamic range the wire bought.  A DIFFERENT
        documented accumulation numerics than per-tensor — gated by its
        own single-device oracle in tests/test_zero.py, with the
        pack→all_to_all→unpack wire trip bitwise lossless on the
        blocked-cast values (the codec's fixed-point idempotence, the
        'existing lossless path' gate)."""
        a, m, c = lay.meta[b]
        idxs = lay.buckets[b]
        k_pre = k_sum = None
        if key is not None:
            # same derivation as sum_gradients: shared scan key, rank-
            # decorrelated pre-quantize key (coherent-rounding argument
            # in parallel/dist.py)
            k_pre, k_sum, _ = jax.random.split(key, 3)
            k_pre = jax.random.fold_in(k_pre, _flat_axis_index(axis_name))
        rank = lax.axis_index(axis_name)
        g = list(leaves_b)
        shifts = None
        if use_aps:
            max_exp = aps_max_exponents(g, float(self.world))
            max_exp = pmax_scalar_vector(max_exp, axis_name)
            shifts = aps_shift_factors(max_exp, grad_exp)
            g = aps_scale(g, shifts)
            if not block_scale:
                g = quantize_tree_sr(g, grad_exp, grad_man, k_pre,
                                     starts=[lay.starts[i] for i in idxs])
        flat = jnp.concatenate([l.astype(jnp.float32).reshape(-1)
                                for l in g])
        payload = pad_to_world(flat, self.world).reshape(self.world, c)
        # uint32 offset space throughout: int32 intermediates would rely
        # on signed overflow wrapping to agree with _leaf_offsets for
        # element counts in (2^31, 2^32] (ADVICE r4)
        if block_scale:
            if k_pre is not None:
                # blocked SR pre-quantize: bits indexed by the SAME
                # (k_pre, global offset) convention as quantize_tree_sr,
                # then the lossless blocked pack (idempotent on the
                # blocked-cast output — numerics.pack_exmy_blocked)
                local_idx = (jnp.arange(self.world * c, dtype=jnp.uint32)
                             .reshape(self.world, c))
                row_offs = jnp.where(local_idx < jnp.uint32(m),
                                     jnp.uint32(a) + local_idx,
                                     jnp.uint32(lay.total))
                rbits = sr_bits_at(k_pre, row_offs)
                payload = cast_body_blocked(payload, grad_exp, grad_man,
                                            block_size, rbits=rbits)
            wire_rows = pack_exmy_blocked(payload, grad_exp, grad_man,
                                          block_size)
            stacked = lax.all_to_all(wire_rows, axis_name,
                                     split_axis=0, concat_axis=0)
            stacked = unpack_exmy_blocked(stacked, grad_exp, grad_man, c,
                                          block_size)
        else:
            wire = _wire_format(grad_exp, grad_man) if use_aps else None
            if wire is not None:
                # bit-packed eXmY wire (quant.numerics.pack_exmy): the
                # APS pre-quantize above put the values in the format
                # set, so the all_to_all ships wire_bytes(exp, man)
                # bytes/element lossless
                payload = pack_exmy(payload, *wire)
            stacked = lax.all_to_all(payload, axis_name,
                                     split_axis=0, concat_axis=0)
            if wire is not None:
                stacked = unpack_exmy(stacked, *wire)
        # (W, c): row j after all_to_all = rank j's slice of OUR shard,
        # rank-ordered — the gather side of a reduce_scatter
        offs = None
        if k_sum is not None:
            local = (rank.astype(jnp.uint32) * jnp.uint32(c)
                     + jnp.arange(c, dtype=jnp.uint32))
            offs = jnp.where(local < jnp.uint32(m), jnp.uint32(a) + local,
                             jnp.uint32(lay.total))
        red = quantized_sum(stacked, grad_exp, grad_man, use_kahan,
                            key=k_sum, offsets=offs,
                            block_size=(block_size if block_scale
                                        else None))
        if use_aps:
            # true divide, aps_unscale semantics (pad shift 0 -> 2^0=1)
            red = red / exp2_exact(
                self._bucket_shift_values(lay, b, shifts, rank))
        return red

    def _grad_shard(self, local_grads, state, axis_name: str,
                    use_aps: bool = False, grad_exp: int = 8,
                    grad_man: int = 23, use_kahan: bool = False,
                    mode: str = "faithful", rounding: str = "nearest",
                    key=None, block_scale: bool = False,
                    block_size: int = 128) -> jnp.ndarray:
        """This rank's (S,) slice of the faithful quantized gradient sum:
        the per-bucket `_bucket_reduce_scatter` shards concatenated in
        bucket order (ONE whole-tree bucket unless this updater was built
        with ``bucket_elems`` — the legacy layout, bit-identical to the
        pre-ISSUE-12 code).

        rounding='stochastic' composes bitwise: the SR bitstream is
        indexed by GLOBAL flat offset (numerics.sr_bits_at) and the key
        schedule mirrors sum_gradients' split exactly (k_pre rank-folded
        for the local pre-quantize, k_sum shared for the ordered scan),
        so each rank's shard reproduces the very bits the replicated
        faithful path would give that slice — the semantics target is
        the reference's ordered requantized sum (dist_util.py:60-69)
        with SR in place of RTNE.  Elements in the world-size pad hold
        exact zeros, whose cast is rounding-independent."""
        self._validate_precision(mode, rounding, key, block_scale,
                                 block_size, grad_exp, grad_man)
        lay = self._layout(local_grads)
        leaves = jax.tree.leaves(local_grads)
        shards = [self._bucket_reduce_scatter(
                      lay, b, [leaves[i] for i in lay.buckets[b]],
                      axis_name, use_aps=use_aps, grad_exp=grad_exp,
                      grad_man=grad_man, use_kahan=use_kahan, key=key,
                      block_scale=block_scale, block_size=block_size)
                  for b in range(len(lay.buckets))]
        return (jnp.concatenate(shards) if shards
                else jnp.zeros((0,), jnp.float32))

    def make_tap_reduce(self, params, axis_name: str, quant_kw: dict):
        """The ZeRO-2 overlap hook (ISSUE 12 leg 3): build the
        ``(plan, chunks, collective)`` triple `make_train_step` hands to
        `overlap.overlapped_grads` when ``overlap_reduce`` composes with
        ``reduce_in_update``.

        ``plan`` is the BucketPlan over THIS updater's bucket layout
        (boundaries from the shared `overlap.bucket_layout`, so the taps
        close exactly the buckets the update consumes); ``chunks`` the
        per-bucket shard sizes c_b; ``collective(b, idxs, gs, key)``
        runs bucket b's `_bucket_reduce_scatter` inside the tap's bwd
        rule and returns the (c_b,) shard EMBEDDED in the bucket's
        leaf-cotangent shapes (shard in the first c_b flat slots, zeros
        elsewhere — c_b <= m_b always, since c_b = ceil(m_b / W)).  The
        step extracts the shards (`overlap.extract_bucket_shards`) and
        calls ``update_fn(shard_vec, ..., pre_sharded=True)``."""
        from .overlap import BucketPlan
        lay = self._layout(params)
        self._validate_precision(
            quant_kw.get("mode", "faithful"),
            quant_kw.get("rounding", "nearest"),
            # the SR key is traced per-step; validate the static
            # contract with a placeholder
            (object() if quant_kw.get("rounding") == "stochastic"
             else None),
            quant_kw.get("block_scale", False),
            quant_kw.get("block_size", 128),
            quant_kw.get("grad_exp", 8), quant_kw.get("grad_man", 23))
        prec = dict(use_aps=quant_kw.get("use_aps", False),
                    grad_exp=quant_kw.get("grad_exp", 8),
                    grad_man=quant_kw.get("grad_man", 23),
                    use_kahan=quant_kw.get("use_kahan", False),
                    block_scale=quant_kw.get("block_scale", False),
                    block_size=quant_kw.get("block_size", 128))
        plan = BucketPlan(sizes=lay.sizes, starts=lay.starts,
                          buckets=lay.buckets,
                          bucket_elems=(self.bucket_elems
                                        if self.bucket_elems is not None
                                        else max(lay.total, 1)))
        chunks = tuple(c for _, _, c in lay.meta)

        def collective(b, idxs, gs, key):
            _, m, c = lay.meta[b]
            shard = self._bucket_reduce_scatter(lay, b, list(gs),
                                                axis_name, key=key,
                                                **prec)
            flat = jnp.zeros((m,), jnp.float32).at[:c].set(shard)
            outs, off = [], 0
            for j, i in enumerate(idxs):
                n_i = lay.sizes[i]
                outs.append(flat[off:off + n_i].reshape(np.shape(gs[j])))
                off += n_i
            return outs

        return plan, chunks, collective


def zero2_sgd(schedule: Callable, world: int, momentum: float = 0.9,
              weight_decay: float = 0.0, nesterov: bool = False,
              wd_mask: Optional[Callable] = None,
              axis_name: str = "dp",
              bucket_elems: Optional[int] = None) -> _Zero2:
    """ZeRO-2 torch-SGD: momentum AND the faithful quantized reduction
    sharded 1/`world`; pair with ``make_train_step(...,
    reduce_in_update=True)``, which forwards its precision settings.
    ``bucket_elems`` opts into the per-bucket flat layout (the overlap
    taps' boundaries; None keeps the legacy whole-tree span)."""
    return _Zero2(schedule, world, momentum, weight_decay, nesterov,
                  wd_mask, axis_name, bucket_elems)


def zero2_oracle_flat(stacked, world: int, *, use_aps: bool = False,
                      grad_exp: int = 8, grad_man: int = 23,
                      use_kahan: bool = False, key=None,
                      block_scale: bool = False, block_size: int = 128,
                      bucket_elems: Optional[int] = None) -> jnp.ndarray:
    """Single-device oracle for ZeRO-2's sharded reduce: given the
    STACKED per-rank local gradients (leaves shaped ``(W, *leaf)``),
    reproduce every rank's `_Zero2._grad_shard` output bit for bit and
    return them concatenated in rank order — shape ``(W * S,)``,
    ``S = Σ_b ceil(m_b / W)`` over the (optionally bucketed) layout.

    Everything except the `all_to_all` wire is shared code: the APS
    scaling/shift derivation, the per-tensor (`quantize_tree_sr`) and
    blocked (`cast_body_blocked` + the codec's lossless pack round-trip)
    pre-quantizers, the ordered `quantized_sum` scan with GLOBAL-offset
    SR bits and the blocked scan casts, and the layout/shift-shard
    helpers — so a divergence can only come from the transport itself,
    exactly the `ring_oracle_sum` philosophy.  This is the fp32-oracle
    gate of ISSUE 12 leg 1 (tests/test_zero.py + the reduce-smoke CI
    arm in tools/bench_reduce.py)."""
    z = _Zero2(lambda s: 0.0, world, 0.9, 0.0, False, None, "dp",
               bucket_elems)
    z._validate_precision("faithful",
                          "stochastic" if key is not None else "nearest",
                          key, block_scale, block_size, grad_exp,
                          grad_man)
    leaves = [jnp.asarray(l, jnp.float32) for l in jax.tree.leaves(stacked)]
    template = [l[0] for l in leaves]
    lay = z._layout(template)
    k_pre = k_sum = None
    if key is not None:
        k_pre, k_sum, _ = jax.random.split(key, 3)
    per_rank = []
    for r in range(world):
        segs = []
        for b, idxs in enumerate(lay.buckets):
            a, m, c = lay.meta[b]
            shifts_b = None
            if use_aps:
                # max over the stacked (W, *leaf) leaves == the pmax of
                # per-rank maxes the distributed path agrees on
                max_exp = aps_max_exponents([leaves[i] for i in idxs],
                                            float(world))
                shifts_b = aps_shift_factors(max_exp, grad_exp)
            rows_r = []
            for j in range(world):
                g_j = [leaves[i][j] for i in idxs]
                k_pre_j = (jax.random.fold_in(k_pre, j)
                           if k_pre is not None else None)
                if use_aps:
                    g_j = aps_scale(g_j, shifts_b)
                    if not block_scale:
                        g_j = quantize_tree_sr(
                            g_j, grad_exp, grad_man, k_pre_j,
                            starts=[lay.starts[i] for i in idxs])
                flat = jnp.concatenate([l.reshape(-1) for l in g_j])
                rows = pad_to_world(flat, world).reshape(world, c)
                if block_scale:
                    if k_pre_j is not None:
                        local_idx = (jnp.arange(world * c,
                                                dtype=jnp.uint32)
                                     .reshape(world, c))
                        row_offs = jnp.where(local_idx < jnp.uint32(m),
                                             jnp.uint32(a) + local_idx,
                                             jnp.uint32(lay.total))
                        rbits = sr_bits_at(k_pre_j, row_offs)
                        rows = cast_body_blocked(rows, grad_exp,
                                                 grad_man, block_size,
                                                 rbits=rbits)
                    else:
                        rows = cast_body_blocked(rows, grad_exp,
                                                 grad_man, block_size)
                rows_r.append(rows[r])
            stack_r = jnp.stack(rows_r)
            offs = None
            if k_sum is not None:
                local = (jnp.uint32(r) * jnp.uint32(c)
                         + jnp.arange(c, dtype=jnp.uint32))
                offs = jnp.where(local < jnp.uint32(m),
                                 jnp.uint32(a) + local,
                                 jnp.uint32(lay.total))
            red = quantized_sum(stack_r, grad_exp, grad_man, use_kahan,
                                key=k_sum, offsets=offs,
                                block_size=(block_size if block_scale
                                            else None))
            if use_aps:
                red = red / exp2_exact(
                    z._bucket_shift_values(lay, b, shifts_b, r))
            segs.append(red)
        per_rank.append(jnp.concatenate(segs) if segs
                        else jnp.zeros((0,), jnp.float32))
    return jnp.concatenate(per_rank)


class _Zero3(_Zero2):
    """ZeRO-3 (FSDP-style): parameters themselves sharded at rest.

    TrainState.params holds this rank's flat fp32 (S,) shard — the full
    model exists only transiently inside the step: one tiled `all_gather`
    + unflatten materializes the pytree for forward/backward, the ZeRO-2
    reduce-scatter shards the gradients, the update runs on the shard,
    and the step returns the shard.  Per-chip param memory drops from P
    to P/W (plus the transient gather, which XLA frees after the last
    use); the extra wire cost over ZeRO-2 is one P all_gather per step.

    Built for the train step's ``params_spec``/``unpack_params`` hooks:

        z = zero3_sgd(schedule, world, template=params_pytree)
        state = TrainState(..., params=z.pack(params), opt_state=z.init())
        step = make_train_step(model, None, mesh, update_fn=z.update_fn,
                               opt_state_spec=z.state_spec(),
                               params_spec=z.param_spec(),
                               unpack_params=z.unpack,
                               reduce_in_update=True, ...)

    ``template`` fixes the pytree structure/shapes (arrays or
    ShapeDtypeStructs); `to_pytree` recovers the pytree from the global
    flat array for eval/checkpoint interop.
    """

    # ZeRO-3's update_fn has no pre_sharded path (its params ARE the
    # shard; the gather/unflatten contract differs) — the inherited
    # ZeRO-2 overlap hook must not be advertised or callable
    supports_tap_reduce = False

    def __init__(self, schedule, world, momentum, weight_decay, nesterov,
                 wd_mask, axis_name, template):
        super().__init__(schedule, world, momentum, weight_decay, nesterov,
                         wd_mask, axis_name)
        self.template = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l), l.dtype), template)
        if wd_mask is not None:
            # ZeRO-3 evaluates the mask on the shape-only template (real
            # params exist only transiently inside the step) — fail fast
            # with a clear contract error for value-inspecting masks
            try:
                wd_mask(self.template)
            except TypeError as e:
                raise TypeError(
                    "zero3_sgd wd_mask must be shape/path-based: it is "
                    "evaluated on a ShapeDtypeStruct pytree, not real "
                    f"arrays (got: {e})") from e

    # ---- host-side layout converters ----
    def pack(self, params) -> jnp.ndarray:
        """Pytree -> global flat (W*S,) fp32 (device_put with
        `param_spec()`'s NamedSharding, or the step's out sharding,
        splits it 1/W)."""
        return pad_to_world(self._flatten(params), self.world)

    def to_pytree(self, flat_global: jnp.ndarray):
        """Global flat array -> param pytree (for eval / checkpoints)."""
        return self._unflatten(flat_global, self.template)

    # ---- step hooks ----
    def param_spec(self) -> P:
        return P(self.axis_name)

    def unpack(self, flat_shard: jnp.ndarray, axis_name: str):
        """Inside shard_map: rank's (S,) shard -> full param pytree."""
        full = lax.all_gather(flat_shard, axis_name, axis=0, tiled=True)
        return self._unflatten(full, self.template)

    def init(self) -> Zero1State:
        return super().init(self.template)

    def _total(self) -> int:
        return sum(l.size for l in jax.tree.leaves(self.template))

    def make_state(self, state, mesh):
        """Pytree-params TrainState -> packed ZeRO-3 TrainState laid out
        on `mesh` (params + momentum dp-sharded) — the ONE copy of the
        spec-tree/device_put wiring.

        `state.opt_state` may be any fresh optimizer state (replaced by
        zeroed flat momentum) or a PORTABLE `Zero1State` from
        `export_state` (trimmed momentum, re-padded for THIS world size —
        checkpoints stay readable across device counts)."""
        from jax.sharding import NamedSharding

        opt = state.opt_state
        if isinstance(opt, Zero1State):
            # the shared portable->padded re-pad (idempotent: a state
            # already padded for THIS world size pads by zero bytes)
            new_opt = self.import_state(state).opt_state
        else:
            new_opt = self.init()
        packed = state.replace(params=self.pack(state.params),
                               opt_state=new_opt)
        spec = state.replace(step=P(), params=self.param_spec(),
                             batch_stats=P(), opt_state=self.state_spec())
        return jax.device_put(packed, jax.tree.map(
            lambda sp: NamedSharding(mesh, sp), spec,
            is_leaf=lambda sp: isinstance(sp, P)))

    def export_state(self, state):
        """Packed layout -> PORTABLE checkpoint layout: pytree params and
        the flat momentum trimmed of the world-size pad, so the
        checkpoint is readable at any device count (and its params by any
        non-ZeRO-3 consumer)."""
        opt: Zero1State = state.opt_state
        return state.replace(
            params=self.to_pytree(jnp.asarray(state.params)),
            opt_state=Zero1State(opt.step,
                                 jnp.asarray(opt.momentum)[:self._total()]))

    def portable_template(self, state):
        """Restore template in the portable layout (for
        `CheckpointManager.restore` before `make_state`)."""
        return state.replace(
            params=jax.tree.map(lambda l: jnp.zeros(l.shape, l.dtype),
                                self.template),
            opt_state=Zero1State(jnp.zeros([], jnp.int32),
                                 jnp.zeros((self._total(),), jnp.float32)))

    def make_tap_reduce(self, params, axis_name, quant_kw):
        raise NotImplementedError(
            "ZeRO-3 has no overlap tap hook: its update consumes the "
            "param SHARD, not pre_sharded bucket gradients — run "
            "without overlap_reduce (the ZeRO-2 updaters support it)")

    def update_fn(self, local_grads, state, axis_name: str, **quant_kw):
        """`state.params` is the (S,) flat shard; `local_grads` the local
        post-emulate grad pytree.  Returns (new shard, new opt state)."""
        if not quant_kw:
            raise ValueError(
                "ZeRO-3 folds the collective into the update: build the "
                "step with make_train_step(..., reduce_in_update=True)")
        opt: Zero1State = state.opt_state
        lay = self._layout(self.template)
        rank = lax.axis_index(axis_name)
        lr = self.schedule(opt.step)

        g_sh = self._grad_shard(local_grads, state, axis_name, **quant_kw)
        p_sh = state.params
        new_p_sh, new_buf = self._shard_update(g_sh, p_sh, self.template,
                                               rank, lay, opt.momentum, lr,
                                               axis_name)
        return new_p_sh, Zero1State(opt.step + 1, new_buf)


def zero3_sgd(schedule: Callable, world: int, template,
              momentum: float = 0.9, weight_decay: float = 0.0,
              nesterov: bool = False, wd_mask: Optional[Callable] = None,
              axis_name: str = "dp") -> _Zero3:
    """ZeRO-3 torch-SGD: params, momentum AND the faithful quantized
    reduction all sharded 1/`world` (see _Zero3 for the wiring)."""
    return _Zero3(schedule, world, momentum, weight_decay, nesterov,
                  wd_mask, axis_name, template)


class _LarsRule:
    """LARS update on the flat shard (round 5, VERDICT r4 ask #5).

    LARS needs PER-LAYER norms (train/optim.py:85-117, the reference's
    mix.py:297-310), which the flat shard layout does not expose per
    rank: a shard spans pieces of many leaves and no rank sees a whole
    leaf.  The rule here recovers exact per-leaf norms with one
    segment-sum + one tiny psum:

      1. `_shard_leaf_index` maps each shard element to its leaf (static
         cumsum table + searchsorted — the `_shard_leaf_values`
         machinery);
      2. segment-sum of p² and g² over that map gives this rank's
         per-leaf partial sums of squares (n_leaves+1 floats, the +1
         catching the world-size pad);
      3. `lax.psum` over the dp axis completes them globally — the only
         collective, 2·(n_leaves+1) floats;
      4. the reference trust-ratio formula runs per leaf and is gathered
         back per element (constant within a leaf).

    Semantics match `lars` exactly — epsilon-free formula, trust ratio
    on the UN-decayed gradient norm, lr folded into the momentum buffer,
    no nesterov, no wd mask; zero-norm quirks (0/0 → nan) are preserved
    for REAL leaves, only the pad bucket is forced to 0.  Numerics: the
    replicated `lars` sums each leaf's squares in one XLA reduction; the
    sharded rule sums per-shard segments then across ranks — a different
    (still deterministic) association, so norms agree to fp32 round-off,
    not bitwise; the ZeRO×LARS parity test pins the resulting params at
    ulp-scale tolerance (tests/test_zero.py).
    """

    coefficient = 0.001

    def _shard_update(self, g_sh, p_sh, template, rank, lay, buf, lr,
                      axis_name):
        leaves = jax.tree.leaves(template)
        n = len(leaves)
        leaf_idx = self._shard_leaf_index(lay, rank).astype(jnp.int32)
        w_sq = jax.ops.segment_sum(p_sh * p_sh, leaf_idx,
                                   num_segments=n + 1)
        g_sq = jax.ops.segment_sum(g_sh * g_sh, leaf_idx,
                                   num_segments=n + 1)
        w_norm = jnp.sqrt(lax.psum(w_sq, axis_name))      # (n+1,)
        g_norm = jnp.sqrt(lax.psum(g_sq, axis_name))
        local_lr = (w_norm / (g_norm + self.weight_decay * w_norm)
                    * self.coefficient)
        local_lr = local_lr.at[n].set(0.0)   # pad bucket (0/0 guard)
        lr_e = jnp.take(local_lr, leaf_idx)               # (S,)
        new_buf = (self.momentum * buf
                   + lr * lr_e * (g_sh + self.weight_decay * p_sh))
        return p_sh - new_buf, new_buf


class _Zero1Lars(_LarsRule, _Zero1):
    pass


class _Zero2Lars(_LarsRule, _Zero2):
    pass


class _Zero3Lars(_LarsRule, _Zero3):
    pass


def _lars_factory(cls, schedule, world, momentum, weight_decay,
                  coefficient, axis_name, template=None,
                  bucket_elems=None):
    args = (schedule, world, momentum, weight_decay, False, None,
            axis_name)
    if template is not None:
        z = cls(*args, template)
    elif bucket_elems is not None:
        z = cls(*args, bucket_elems)     # _Zero2Lars's layout cap
    else:
        z = cls(*args)
    z.coefficient = coefficient
    return z


def zero1_lars(schedule: Callable, world: int, momentum: float = 0.9,
               weight_decay: float = 0.0, coefficient: float = 0.001,
               axis_name: str = "dp") -> _Zero1Lars:
    """ZeRO-1 LARS: momentum sharded 1/`world`, per-layer trust ratios
    recovered via segment-sum + psum (`_LarsRule`)."""
    return _lars_factory(_Zero1Lars, schedule, world, momentum,
                         weight_decay, coefficient, axis_name)


def zero2_lars(schedule: Callable, world: int, momentum: float = 0.9,
               weight_decay: float = 0.0, coefficient: float = 0.001,
               axis_name: str = "dp",
               bucket_elems: Optional[int] = None) -> _Zero2Lars:
    """ZeRO-2 LARS: momentum + faithful reduction sharded; pair with
    ``make_train_step(..., reduce_in_update=True)``.  ``bucket_elems``
    opts into the per-bucket flat layout (the overlap taps'
    boundaries, as on `zero2_sgd`)."""
    return _lars_factory(_Zero2Lars, schedule, world, momentum,
                         weight_decay, coefficient, axis_name,
                         bucket_elems=bucket_elems)


def zero3_lars(schedule: Callable, world: int, template,
               momentum: float = 0.9, weight_decay: float = 0.0,
               coefficient: float = 0.001,
               axis_name: str = "dp") -> _Zero3Lars:
    """ZeRO-3 LARS: params, momentum AND reduction sharded, LARS trust
    ratios from the sharded per-leaf norms."""
    return _lars_factory(_Zero3Lars, schedule, world, momentum,
                         weight_decay, coefficient, axis_name, template)


def zero2_transport_bytes(n: int, world: int, exp: int, man: int, *,
                          use_aps: bool = True,
                          block_size: Optional[int] = None) -> int:
    """Analytic per-device wire bytes of ZeRO-2's sharded faithful
    reduction of ONE ``n``-element bucket: the ``all_to_all`` ships the
    (W, c) payload (c = ``ring_chunk_size(n, world)``) and keeps 1/W
    local — (W-1)·c rows' worth leave each device.  A multi-bucket
    `_ZeroLayout` (``bucket_elems``) prices as the sum of this over its
    per-bucket element counts ``m_b``.

    The row cost mirrors `_bucket_reduce_scatter`'s wire exactly: the
    bit-packed eXmY code words when the APS pre-quantize applies
    (`dist._wire_format`), the blocked code-words-plus-sidecar wire
    with ``block_size`` (`numerics.wire_bytes_blocked` — the sidecar is
    EXPLICIT, as on `ring_transport_bytes`), raw fp32 otherwise.  The
    sibling of `ring_transport_bytes`/`gather_transport_bytes` for the
    third transport; the IR wire-ledger rule (analysis/ir) pins the
    traced `all_to_all` payloads against this formula."""
    from ..quant.numerics import wire_bytes, wire_bytes_blocked
    if n == 0 or world <= 0:
        return 0
    c = ring_chunk_size(n, world)
    if block_size is not None:
        per_shard = wire_bytes_blocked(exp, man, c, block_size)
    elif use_aps and man >= 2 and wire_bytes(exp, man) < 4:
        per_shard = c * wire_bytes(exp, man)
    else:
        per_shard = c * 4
    return (world - 1) * per_shard


def ir_programs(reg):
    """Program-contract declarations (analysis/ir/registry.py): the
    ZeRO-2 sharded reduce is the third wire transport — its all_to_all
    payloads are pinned against `zero2_transport_bytes` (blocked
    sidecar included) and the scan body is bitwise-gated (it claims
    slice-parity with the replicated faithful reduction)."""
    from jax.sharding import PartitionSpec as P

    from ..compat import shard_map
    from .mesh import data_parallel_mesh

    W, n = 8, 1000
    deps = ("cpd_tpu.quant.numerics", "cpd_tpu.parallel.zero",
            "cpd_tpu.parallel.dist", "cpd_tpu.parallel.reduction",
            "cpd_tpu.parallel.aps")

    def _rs(block=None, exp=5, man=2):
        def build():
            mesh = data_parallel_mesh()
            z = zero2_sgd(lambda step: 0.1, W)

            def body(g):
                return z._grad_shard(
                    {"w": g[0]}, None, "dp", use_aps=True,
                    grad_exp=exp, grad_man=man,
                    block_scale=block is not None,
                    block_size=block if block is not None else 128)

            fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                           out_specs=P("dp"), check_vma=False)
            return fn, (jax.ShapeDtypeStruct((W, n), jnp.float32),)
        return build

    reg.declare("zero2.reduce_scatter[aps,e5m2,w8]", _rs(),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: zero2_transport_bytes(n, W, 5, 2))
    reg.declare("zero2.reduce_scatter[blocked-e4m3,b32,w8]",
                _rs(block=32, exp=4, man=3),
                deps=deps, axis_sizes={"dp": W}, bitwise=True,
                wire=lambda: zero2_transport_bytes(n, W, 4, 3,
                                                   block_size=32))
