"""ZeRO-1: optimizer-state sharding over the data axis.

New capability beyond the reference (SURVEY.md §2 strategy inventory:
"ZeRO/FSDP sharding — Absent").  Stage-1 ZeRO: params stay replicated,
but the optimizer state (the torch-SGD momentum buffer — as large as the
model) is sharded 1/W per data rank, cutting optimizer memory by the dp
world size.  The TPU-native realisation under `shard_map`:

    1. the quantized all-reduce (parallel/dist.py) leaves every rank with
       the full gradient sum, exactly as before — APS/ordered/Kahan
       semantics are untouched;
    2. gradients and params are flattened to ONE fp32 vector, padded to a
       multiple of W; each rank dynamic-slices its 1/W shard;
    3. the torch-SGD update rule (train/optim.py's semantics, bit-equal)
       runs on the shard against the rank's momentum shard;
    4. one tiled `all_gather` rebuilds the full flat params, unflattened
       back to the pytree — the ZeRO "param broadcast".

Memory: momentum goes from NxP to NxP/W per chip; wire cost is one (P/W)
all_gather per step, riding ICI.  Usage:

    z = zero1_sgd(schedule, world=mesh.shape["dp"], momentum=0.9, ...)
    state = TrainState(..., opt_state=z.init(params))
    step = make_train_step(model, tx=None, mesh, update_fn=z.update_fn,
                           opt_state_spec=z.state_spec())
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["Zero1State", "zero1_sgd"]


class Zero1State(NamedTuple):
    step: jnp.ndarray          # replicated scalar int32
    momentum: jnp.ndarray      # flat fp32, global (W*S,), per-rank (S,)


class _Zero1:
    def __init__(self, schedule: Callable, world: int, momentum: float,
                 weight_decay: float, nesterov: bool,
                 wd_mask: Optional[Callable], axis_name: str):
        self.schedule = schedule
        self.world = world
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_mask = wd_mask
        self.axis_name = axis_name

    # ---- flat layout ----
    def _shard_size(self, params) -> int:
        total = sum(l.size for l in jax.tree.leaves(params))
        return math.ceil(total / self.world)

    def _flat_mask(self, params) -> jnp.ndarray:
        """Flat wd mask as broadcast ops (jnp.full), NOT a materialized
        numpy literal: a 25M-param model would otherwise embed a 100MB
        constant into the compiled executable."""
        mask = (self.wd_mask(params) if self.wd_mask is not None
                else jax.tree.map(lambda _: True, params))
        parts = [jnp.full((l.size,), float(bool(m)), jnp.float32)
                 for l, m in zip(jax.tree.leaves(params),
                                 jax.tree.leaves(mask))]
        flat = (jnp.concatenate(parts) if parts
                else jnp.zeros((0,), jnp.float32))
        s = self._shard_size(params)
        return jnp.pad(flat, (0, self.world * s - flat.shape[0]))

    @staticmethod
    def _flatten(tree) -> jnp.ndarray:
        return jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1)
             for l in jax.tree.leaves(tree)])

    @staticmethod
    def _unflatten(flat: jnp.ndarray, template):
        leaves = jax.tree.leaves(template)
        out, off = [], 0
        for l in leaves:
            out.append(flat[off:off + l.size].reshape(l.shape)
                       .astype(l.dtype))
            off += l.size
        return jax.tree.unflatten(jax.tree.structure(template), out)

    # ---- optimizer surface ----
    def init(self, params) -> Zero1State:
        """Global-shaped opt state: momentum (W*S,) — device_put with
        `state_spec()` (or the train step's out sharding) splits it 1/W
        per rank."""
        s = self._shard_size(params)
        return Zero1State(jnp.zeros([], jnp.int32),
                          jnp.zeros((self.world * s,), jnp.float32))

    def state_spec(self) -> Zero1State:
        return Zero1State(P(), P(self.axis_name))

    def update_fn(self, grads, state, axis_name: str):
        """Inside shard_map: full replicated `grads`/params, LOCAL (S,)
        momentum shard.  Returns (new full params, new opt state)."""
        params = state.params
        opt: Zero1State = state.opt_state
        s = self._shard_size(params)
        rank = lax.axis_index(axis_name)
        lr = self.schedule(opt.step)

        flat_g = self._flatten(grads)
        flat_p = self._flatten(params)
        pad = self.world * s - flat_g.size
        flat_g = jnp.pad(flat_g, (0, pad))
        flat_p = jnp.pad(flat_p, (0, pad))
        g_sh = lax.dynamic_slice(flat_g, (rank * s,), (s,))
        p_sh = lax.dynamic_slice(flat_p, (rank * s,), (s,))
        m_sh = lax.dynamic_slice(
            self._flat_mask(params), (rank * s,), (s,))

        # torch-SGD rule on the shard (train/optim.py:65-69, bit-equal)
        d = g_sh + (self.weight_decay * p_sh * m_sh
                    if self.weight_decay else 0.0)
        new_buf = self.momentum * opt.momentum + d
        step_dir = d + self.momentum * new_buf if self.nesterov else new_buf
        new_p_sh = p_sh - lr * step_dir

        full = lax.all_gather(new_p_sh, axis_name, axis=0, tiled=True)
        new_params = self._unflatten(full, params)
        return new_params, Zero1State(opt.step + 1, new_buf)


def zero1_sgd(schedule: Callable, world: int, momentum: float = 0.9,
              weight_decay: float = 0.0, nesterov: bool = False,
              wd_mask: Optional[Callable] = None,
              axis_name: str = "dp") -> _Zero1:
    """ZeRO-1 torch-SGD: momentum sharded 1/`world` over `axis_name`."""
    return _Zero1(schedule, world, momentum, weight_decay, nesterov,
                  wd_mask, axis_name)
