"""ZeRO-1/2: optimizer-state (and reduction) sharding over the data axis.

New capability beyond the reference (SURVEY.md §2 strategy inventory:
"ZeRO/FSDP sharding — Absent").  Stage-1 ZeRO: params stay replicated,
but the optimizer state (the torch-SGD momentum buffer — as large as the
model) is sharded 1/W per data rank, cutting optimizer memory by the dp
world size.  The TPU-native realisation under `shard_map`:

    1. the quantized all-reduce (parallel/dist.py) leaves every rank with
       the full gradient sum, exactly as before — APS/ordered/Kahan
       semantics are untouched;
    2. gradients and params are flattened to ONE fp32 vector, padded to a
       multiple of W; each rank dynamic-slices its 1/W shard;
    3. the torch-SGD update rule (train/optim.py's semantics, bit-equal)
       runs on the shard against the rank's momentum shard;
    4. one tiled `all_gather` rebuilds the full flat params, unflattened
       back to the pytree — the ZeRO "param broadcast".

Memory: momentum goes from NxP to NxP/W per chip; wire cost is one (P/W)
all_gather per step, riding ICI.  Usage:

    z = zero1_sgd(schedule, world=mesh.shape["dp"], momentum=0.9, ...)
    state = TrainState(..., opt_state=z.init(params))
    step = make_train_step(model, tx=None, mesh, update_fn=z.update_fn,
                           opt_state_spec=z.state_spec())

Stage-2 ZeRO (`zero2_sgd`) additionally shards the *reduction*: instead of
every rank gathering the full (W, P) gradient stack and each computing the
whole ordered quantized sum (parallel/dist.py faithful mode), one
`all_to_all` hands rank r the (W, P/W) stack of every rank's r-th slice,
and the rank-ordered requantized scan runs only on that shard.  The scan
is elementwise over ranks in rank order, so the shard-local sum is
bit-identical to the corresponding slice of the replicated faithful
reduction — APS scaling (global pmax), Kahan compensation, and the
e5m2/fp16/bf16 wire compression all compose unchanged.  Peak reduction
memory drops from W x P to P per chip (the gathered stack equals one
model's gradients), wire bytes are identical.  Usage is the same as
zero1_sgd, with the train step told to skip its own reduction (the step
forwards its use_aps/grad_exp/grad_man/use_kahan/mode to the updater, so
precision has one source of truth):

    z = zero2_sgd(schedule, world)
    step = make_train_step(model, None, mesh, use_aps=True, grad_exp=5,
                           grad_man=2, update_fn=z.update_fn,
                           opt_state_spec=z.state_spec(),
                           reduce_in_update=True)
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..quant.numerics import pack_exmy, unpack_exmy
from .aps import (aps_max_exponents, aps_scale, aps_shift_factors,
                  pmax_scalar_vector)
from .dist import _flat_axis_index, _wire_format, quantize_tree_sr
from .reduction import quantized_sum
from .ring import pad_to_world, ring_chunk_size

__all__ = ["Zero1State", "zero1_sgd", "zero2_sgd", "zero3_sgd",
           "zero1_lars", "zero2_lars", "zero3_lars"]


class Zero1State(NamedTuple):
    """Flat ZeRO optimizer state.

    Elastic-restart invariant (ISSUE 4): elements of ``momentum`` in the
    world-size pad (past the total parameter count) hold EXACT zeros,
    forever — `pad_to_world` zero-fills them and the update rule keeps
    them there (pad gradients are exact zeros, so ``m*0 + 0 == 0``).
    `train/checkpoint.py::restore_latest_valid(world=W')` relies on it:
    trimming the pad and re-padding through `pad_to_world` at a NEW
    world size is then bitwise-faithful, so a checkpoint written at
    world W resumes at W' (`export_state`'s portable trim is the same
    contract, applied eagerly)."""
    step: jnp.ndarray          # replicated scalar int32
    momentum: jnp.ndarray      # flat fp32, global (W*S,), per-rank (S,)


class _Zero1:
    def __init__(self, schedule: Callable, world: int, momentum: float,
                 weight_decay: float, nesterov: bool,
                 wd_mask: Optional[Callable], axis_name: str):
        self.schedule = schedule
        self.world = world
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_mask = wd_mask
        self.axis_name = axis_name

    # ---- flat layout ----
    def _shard_size(self, params) -> int:
        # the ring transport's chunk quantum (parallel/ring.py) — ZeRO
        # shards and ring chunks slice the same padded flat layout
        total = sum(l.size for l in jax.tree.leaves(params))
        return ring_chunk_size(total, self.world)

    def _shard_leaf_values(self, template, values, rank,
                           s: int, pad: float = 0.0) -> jnp.ndarray:
        """Expand a per-LEAF value vector to this rank's (S,) per-element
        shard of the flat layout.

        Built from the static leaf-offset table: each shard element index
        maps to its leaf via searchsorted, then to that leaf's value.
        O(S) per rank — never the full (W*S,) flat vector, which the
        round-2 code materialized on every rank before slicing (ADVICE
        r2).  Elements past the last leaf (flat padding) get `pad`."""
        leaf_idx = self._shard_leaf_index(template, rank, s)
        padded = jnp.concatenate([jnp.asarray(values, jnp.float32),
                                  jnp.full((1,), pad, jnp.float32)])
        return jnp.take(padded, leaf_idx)

    def _shard_leaf_index(self, template, rank, s: int) -> jnp.ndarray:
        """(S,) map from shard element to its leaf index in tree-leaves
        order; elements in the world-size pad map to n_leaves (one past
        the last real leaf)."""
        leaves = jax.tree.leaves(template)
        ends = np.cumsum([l.size for l in leaves])  # static end offsets
        # uint32 index space, same rationale as the SR offsets below:
        # int32 would wrap negative past 2^31 elements and searchsorted
        # would map those shard elements to leaf 0 (ADVICE r4 follow-up)
        idx = rank.astype(jnp.uint32) * jnp.uint32(s) + jnp.arange(
            s, dtype=jnp.uint32)
        return jnp.searchsorted(jnp.asarray(ends, np.uint32), idx,
                                side="right")

    def _shard_mask(self, params, rank, s: int) -> jnp.ndarray:
        """This rank's (S,) slice of the per-element weight-decay mask
        (per-leaf bools are static, so the value vector is a host-side
        constant of n_leaves floats, not a 100MB per-element literal)."""
        mask = (self.wd_mask(params) if self.wd_mask is not None
                else jax.tree.map(lambda _: True, params))
        vals = np.array([float(bool(m)) for m in jax.tree.leaves(mask)],
                        np.float32)
        return self._shard_leaf_values(params, vals, rank, s)

    @staticmethod
    def _flatten(tree) -> jnp.ndarray:
        return jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1)
             for l in jax.tree.leaves(tree)])

    @staticmethod
    def _unflatten(flat: jnp.ndarray, template):
        leaves = jax.tree.leaves(template)
        out, off = [], 0
        for l in leaves:
            out.append(flat[off:off + l.size].reshape(l.shape)
                       .astype(l.dtype))
            off += l.size
        return jax.tree.unflatten(jax.tree.structure(template), out)

    # ---- optimizer surface ----
    def init(self, params) -> Zero1State:
        """Global-shaped opt state: momentum (W*S,) — device_put with
        `state_spec()` (or the train step's out sharding) splits it 1/W
        per rank."""
        s = self._shard_size(params)
        return Zero1State(jnp.zeros([], jnp.int32),
                          jnp.zeros((self.world * s,), jnp.float32))

    def state_spec(self) -> Zero1State:
        return Zero1State(P(), P(self.axis_name))

    def _grad_shard(self, grads, state, axis_name: str,
                    **quant_kw) -> jnp.ndarray:
        """This rank's (S,) gradient slice.  ZeRO-1: slice the replicated
        reduced grads; ZeRO-2 overrides with the sharded reduce-scatter."""
        if quant_kw:
            raise ValueError(
                "ZeRO-1 expects pre-reduced gradients; "
                "reduce_in_update=True is a ZeRO-2 (zero2_sgd) contract")
        s = self._shard_size(state.params)
        rank = lax.axis_index(axis_name)
        flat_g = pad_to_world(self._flatten(grads), self.world)
        return lax.dynamic_slice(flat_g, (rank * s,), (s,))

    requires_reduce_in_update = False

    def update_fn(self, grads, state, axis_name: str, **quant_kw):
        """Inside shard_map: `grads` per the subclass's _grad_shard
        contract, LOCAL (S,) momentum shard.  Returns (new full params,
        new opt state).  `quant_kw` is forwarded by the train step when it
        delegates the reduction (reduce_in_update) so precision settings
        have one source of truth."""
        if self.requires_reduce_in_update and not quant_kw:
            raise ValueError(
                "this ZeRO stage folds the collective into the update: "
                "build the step with make_train_step(..., "
                "reduce_in_update=True) — without it the step pre-reduces "
                "and the sharded reduce-scatter would double-count by W")
        params = state.params
        opt: Zero1State = state.opt_state
        s = self._shard_size(params)
        rank = lax.axis_index(axis_name)
        lr = self.schedule(opt.step)

        g_sh = self._grad_shard(grads, state, axis_name, **quant_kw)
        flat_p = pad_to_world(self._flatten(params), self.world)
        p_sh = lax.dynamic_slice(flat_p, (rank * s,), (s,))
        new_p_sh, new_buf = self._shard_update(g_sh, p_sh, params, rank, s,
                                               opt.momentum, lr, axis_name)

        full = lax.all_gather(new_p_sh, axis_name, axis=0, tiled=True)
        new_params = self._unflatten(full, params)
        return new_params, Zero1State(opt.step + 1, new_buf)

    def _shard_update(self, g_sh, p_sh, template, rank, s, buf, lr,
                      axis_name):
        """Optimizer rule on the flat shard — overridden by the LARS
        variants (`_LarsRule`); the default is the torch-SGD rule."""
        m_sh = self._shard_mask(template, rank, s)
        return self._shard_sgd(g_sh, p_sh, m_sh, buf, lr)

    # ---- portable checkpoints (round 5; the ZeRO-3 analogs are its
    # own export_state/portable_template, which also convert params) ----
    def export_state(self, state):
        """Padded (W*S,) momentum -> PORTABLE (total,) layout: the
        world-size pad is trimmed so the checkpoint restores at ANY
        device count (and its momentum reads as the plain flat vector
        by any non-ZeRO consumer).

        A PADDED snapshot (e.g. a preemption save that skipped this
        conversion) is equally world-portable now: `CheckpointManager`
        records the padded length in the sidecar and
        `restore_latest_valid(world=W')` performs this same trim +
        re-pad lazily at restore (the Zero1State elastic invariant)."""
        opt: Zero1State = state.opt_state
        total = sum(l.size for l in jax.tree.leaves(state.params))
        return state.replace(opt_state=Zero1State(
            opt.step, jnp.asarray(opt.momentum)[:total]))

    def portable_template(self, state):
        """Restore template in the portable layout (pass to
        `CheckpointManager.restore` before `import_state`)."""
        total = sum(l.size for l in jax.tree.leaves(state.params))
        return state.replace(opt_state=Zero1State(
            jnp.zeros([], jnp.int32), jnp.zeros((total,), jnp.float32)))

    def import_state(self, state):
        """Portable layout -> THIS updater's padded (W*S,) layout."""
        opt: Zero1State = state.opt_state
        mom = pad_to_world(jnp.asarray(opt.momentum), self.world)
        return state.replace(opt_state=Zero1State(opt.step, mom))

    def mesh_layout(self, state, mesh):
        """Lay a pytree-params TrainState (whose opt_state is this
        updater's `init(...)`) out on `mesh` — everything replicated
        except the dp-sharded flat momentum — and return
        ``(state, step_kwargs)`` with the `make_train_step` hooks wired
        (`update_fn`, `opt_state_spec`, plus `reduce_in_update` for the
        stages that shard the reduction).  The ONE copy of the ZeRO-1/2
        CLI wiring (the ZeRO-3 analog is `make_state`, whose packed
        params need the extra `params_spec`/`unpack_params` hooks)."""
        from jax.sharding import NamedSharding

        spec_tree = state.replace(step=P(), params=P(), batch_stats=P(),
                                  opt_state=self.state_spec())
        laid = jax.device_put(
            state, jax.tree.map(lambda sp: NamedSharding(mesh, sp),
                                spec_tree,
                                is_leaf=lambda sp: isinstance(sp, P)))
        kw = {"update_fn": self.update_fn,
              "opt_state_spec": self.state_spec()}
        if self.requires_reduce_in_update:
            kw["reduce_in_update"] = True
        return laid, kw

    def _shard_sgd(self, g_sh, p_sh, m_sh, buf, lr):
        """The torch-SGD rule on a flat shard (train/optim.py:65-69,
        bit-equal) — the ONE copy every ZeRO stage's update uses."""
        d = g_sh + (self.weight_decay * p_sh * m_sh
                    if self.weight_decay else 0.0)
        new_buf = self.momentum * buf + d
        step_dir = d + self.momentum * new_buf if self.nesterov else new_buf
        return p_sh - lr * step_dir, new_buf


def zero1_sgd(schedule: Callable, world: int, momentum: float = 0.9,
              weight_decay: float = 0.0, nesterov: bool = False,
              wd_mask: Optional[Callable] = None,
              axis_name: str = "dp") -> _Zero1:
    """ZeRO-1 torch-SGD: momentum sharded 1/`world` over `axis_name`."""
    return _Zero1(schedule, world, momentum, weight_decay, nesterov,
                  wd_mask, axis_name)


class _Zero2(_Zero1):
    """ZeRO-2: sharded faithful quantized reduction + sharded update.

    `update_fn` receives the rank's LOCAL (unreduced, post-emulate-node)
    gradients — build the train step with ``reduce_in_update=True`` so it
    skips `sum_gradients`.  Precision settings (use_aps/grad_exp/grad_man/
    use_kahan/mode) are NOT stored here: the step forwards its own, so the
    emulate-node quantization and the cross-device reduction cannot drift
    apart."""

    # update_fn must see LOCAL grads; _Zero1.update_fn enforces this by
    # refusing to run when the step did not forward its precision settings
    # (i.e. reduce_in_update was off and grads are already reduced —
    # reduce-scattering those would double-count by W)
    requires_reduce_in_update = True

    def _shard_shifts(self, grads, shifts, rank, s: int) -> jnp.ndarray:
        """This rank's (S,) slice of the per-element APS shift factors
        (pad elements get shift 0 → factor exp2(0)=1)."""
        return jnp.exp2(self._shard_leaf_values(grads, shifts, rank, s))

    def _grad_shard(self, local_grads, state, axis_name: str,
                    use_aps: bool = False, grad_exp: int = 8,
                    grad_man: int = 23, use_kahan: bool = False,
                    mode: str = "faithful", rounding: str = "nearest",
                    key=None) -> jnp.ndarray:
        """This rank's (S,) slice of the faithful quantized gradient sum.

        Replicates parallel/dist.py `sum_gradients` faithful-mode semantics
        exactly (APS pre-scale+quantize, rank-ordered requantized scan,
        divide-unscale), but on 1/W of the elements: the scan is
        elementwise over ranks, so slicing before summing is bit-identical
        to summing then slicing.  The precision arguments come from the
        train step (reduce_in_update forwards them).

        rounding='stochastic' composes bitwise too: the SR bitstream is
        indexed by GLOBAL flat offset (numerics.sr_bits_at) and the key
        schedule mirrors sum_gradients' split exactly (k_pre rank-folded
        for the local pre-quantize, k_sum shared for the ordered scan), so
        each rank's shard reproduces the very bits the replicated faithful
        path would give that slice — the semantics target is the
        reference's ordered requantized sum (dist_util.py:60-69) with SR
        in place of RTNE.  Elements in the world-size pad hold exact
        zeros, whose cast is rounding-independent."""
        if mode != "faithful":
            raise ValueError(
                f"ZeRO-2 shards the faithful ordered reduction; mode="
                f"{mode!r} is not supported here (the fast psum path "
                f"keeps the full gradient resident anyway, and the ring "
                f"transport's per-chunk rotation order is a different "
                f"reduction semantics from the rank-order slices ZeRO-2 "
                f"reproduces)")
        if rounding == "stochastic" and key is None:
            raise ValueError("rounding='stochastic' requires a PRNG key")
        if rounding == "nearest" and key is not None:
            raise ValueError("a PRNG key was passed but rounding='nearest' "
                             "would ignore it (sum_gradients' contract)")
        k_pre = k_sum = None
        if key is not None:
            # same derivation as sum_gradients: shared scan key, rank-
            # decorrelated pre-quantize key (coherent-rounding argument in
            # parallel/dist.py)
            k_pre, k_sum, _ = jax.random.split(key, 3)
            k_pre = jax.random.fold_in(k_pre, _flat_axis_index(axis_name))
        s = self._shard_size(local_grads)
        g = local_grads
        shifts = None
        if use_aps:
            max_exp = aps_max_exponents(g, float(self.world))
            max_exp = pmax_scalar_vector(max_exp, axis_name)
            shifts = aps_shift_factors(max_exp, grad_exp)
            g = aps_scale(g, shifts)
            g = quantize_tree_sr(g, grad_exp, grad_man, k_pre)

        flat = pad_to_world(self._flatten(g), self.world)
        wire = _wire_format(grad_exp, grad_man) if use_aps else None
        payload = flat.reshape(self.world, s)
        if wire is not None:
            # bit-packed eXmY wire (quant.numerics.pack_exmy): the APS
            # pre-quantize above put the values in the format set, so the
            # all_to_all ships wire_bytes(exp, man) bytes/element lossless
            payload = pack_exmy(payload, *wire)
        # (W, S): row j after all_to_all = rank j's slice of OUR shard,
        # rank-ordered — the gather side of a reduce_scatter
        stacked = lax.all_to_all(payload, axis_name,
                                 split_axis=0, concat_axis=0)
        if wire is not None:
            stacked = unpack_exmy(stacked, *wire)
        rank = lax.axis_index(axis_name)
        # uint32 throughout: int32 intermediates would rely on signed
        # overflow wrapping to agree with _leaf_offsets for element
        # counts in (2^31, 2^32] (ADVICE r4)
        offs = (None if k_sum is None
                else (rank.astype(jnp.uint32) * jnp.uint32(s)
                      + jnp.arange(s, dtype=jnp.uint32)))
        red = quantized_sum(stacked, grad_exp, grad_man, use_kahan,
                            key=k_sum, offsets=offs)
        if use_aps:
            shift_sh = self._shard_shifts(local_grads, shifts, rank, s)
            red = red / shift_sh   # true divide, aps_unscale semantics
        return red


def zero2_sgd(schedule: Callable, world: int, momentum: float = 0.9,
              weight_decay: float = 0.0, nesterov: bool = False,
              wd_mask: Optional[Callable] = None,
              axis_name: str = "dp") -> _Zero2:
    """ZeRO-2 torch-SGD: momentum AND the faithful quantized reduction
    sharded 1/`world`; pair with ``make_train_step(...,
    reduce_in_update=True)``, which forwards its precision settings."""
    return _Zero2(schedule, world, momentum, weight_decay, nesterov,
                  wd_mask, axis_name)


class _Zero3(_Zero2):
    """ZeRO-3 (FSDP-style): parameters themselves sharded at rest.

    TrainState.params holds this rank's flat fp32 (S,) shard — the full
    model exists only transiently inside the step: one tiled `all_gather`
    + unflatten materializes the pytree for forward/backward, the ZeRO-2
    reduce-scatter shards the gradients, the update runs on the shard,
    and the step returns the shard.  Per-chip param memory drops from P
    to P/W (plus the transient gather, which XLA frees after the last
    use); the extra wire cost over ZeRO-2 is one P all_gather per step.

    Built for the train step's ``params_spec``/``unpack_params`` hooks:

        z = zero3_sgd(schedule, world, template=params_pytree)
        state = TrainState(..., params=z.pack(params), opt_state=z.init())
        step = make_train_step(model, None, mesh, update_fn=z.update_fn,
                               opt_state_spec=z.state_spec(),
                               params_spec=z.param_spec(),
                               unpack_params=z.unpack,
                               reduce_in_update=True, ...)

    ``template`` fixes the pytree structure/shapes (arrays or
    ShapeDtypeStructs); `to_pytree` recovers the pytree from the global
    flat array for eval/checkpoint interop.
    """

    def __init__(self, schedule, world, momentum, weight_decay, nesterov,
                 wd_mask, axis_name, template):
        super().__init__(schedule, world, momentum, weight_decay, nesterov,
                         wd_mask, axis_name)
        self.template = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l), l.dtype), template)
        if wd_mask is not None:
            # ZeRO-3 evaluates the mask on the shape-only template (real
            # params exist only transiently inside the step) — fail fast
            # with a clear contract error for value-inspecting masks
            try:
                wd_mask(self.template)
            except TypeError as e:
                raise TypeError(
                    "zero3_sgd wd_mask must be shape/path-based: it is "
                    "evaluated on a ShapeDtypeStruct pytree, not real "
                    f"arrays (got: {e})") from e

    # ---- host-side layout converters ----
    def pack(self, params) -> jnp.ndarray:
        """Pytree -> global flat (W*S,) fp32 (device_put with
        `param_spec()`'s NamedSharding, or the step's out sharding,
        splits it 1/W)."""
        return pad_to_world(self._flatten(params), self.world)

    def to_pytree(self, flat_global: jnp.ndarray):
        """Global flat array -> param pytree (for eval / checkpoints)."""
        return self._unflatten(flat_global, self.template)

    # ---- step hooks ----
    def param_spec(self) -> P:
        return P(self.axis_name)

    def unpack(self, flat_shard: jnp.ndarray, axis_name: str):
        """Inside shard_map: rank's (S,) shard -> full param pytree."""
        full = lax.all_gather(flat_shard, axis_name, axis=0, tiled=True)
        return self._unflatten(full, self.template)

    def init(self) -> Zero1State:
        return super().init(self.template)

    def _total(self) -> int:
        return sum(l.size for l in jax.tree.leaves(self.template))

    def make_state(self, state, mesh):
        """Pytree-params TrainState -> packed ZeRO-3 TrainState laid out
        on `mesh` (params + momentum dp-sharded) — the ONE copy of the
        spec-tree/device_put wiring.

        `state.opt_state` may be any fresh optimizer state (replaced by
        zeroed flat momentum) or a PORTABLE `Zero1State` from
        `export_state` (trimmed momentum, re-padded for THIS world size —
        checkpoints stay readable across device counts)."""
        from jax.sharding import NamedSharding

        opt = state.opt_state
        if isinstance(opt, Zero1State):
            # the shared portable->padded re-pad (idempotent: a state
            # already padded for THIS world size pads by zero bytes)
            new_opt = self.import_state(state).opt_state
        else:
            new_opt = self.init()
        packed = state.replace(params=self.pack(state.params),
                               opt_state=new_opt)
        spec = state.replace(step=P(), params=self.param_spec(),
                             batch_stats=P(), opt_state=self.state_spec())
        return jax.device_put(packed, jax.tree.map(
            lambda sp: NamedSharding(mesh, sp), spec,
            is_leaf=lambda sp: isinstance(sp, P)))

    def export_state(self, state):
        """Packed layout -> PORTABLE checkpoint layout: pytree params and
        the flat momentum trimmed of the world-size pad, so the
        checkpoint is readable at any device count (and its params by any
        non-ZeRO-3 consumer)."""
        opt: Zero1State = state.opt_state
        return state.replace(
            params=self.to_pytree(jnp.asarray(state.params)),
            opt_state=Zero1State(opt.step,
                                 jnp.asarray(opt.momentum)[:self._total()]))

    def portable_template(self, state):
        """Restore template in the portable layout (for
        `CheckpointManager.restore` before `make_state`)."""
        return state.replace(
            params=jax.tree.map(lambda l: jnp.zeros(l.shape, l.dtype),
                                self.template),
            opt_state=Zero1State(jnp.zeros([], jnp.int32),
                                 jnp.zeros((self._total(),), jnp.float32)))

    def update_fn(self, local_grads, state, axis_name: str, **quant_kw):
        """`state.params` is the (S,) flat shard; `local_grads` the local
        post-emulate grad pytree.  Returns (new shard, new opt state)."""
        if not quant_kw:
            raise ValueError(
                "ZeRO-3 folds the collective into the update: build the "
                "step with make_train_step(..., reduce_in_update=True)")
        opt: Zero1State = state.opt_state
        s = self._shard_size(self.template)
        rank = lax.axis_index(axis_name)
        lr = self.schedule(opt.step)

        g_sh = self._grad_shard(local_grads, state, axis_name, **quant_kw)
        p_sh = state.params
        new_p_sh, new_buf = self._shard_update(g_sh, p_sh, self.template,
                                               rank, s, opt.momentum, lr,
                                               axis_name)
        return new_p_sh, Zero1State(opt.step + 1, new_buf)


def zero3_sgd(schedule: Callable, world: int, template,
              momentum: float = 0.9, weight_decay: float = 0.0,
              nesterov: bool = False, wd_mask: Optional[Callable] = None,
              axis_name: str = "dp") -> _Zero3:
    """ZeRO-3 torch-SGD: params, momentum AND the faithful quantized
    reduction all sharded 1/`world` (see _Zero3 for the wiring)."""
    return _Zero3(schedule, world, momentum, weight_decay, nesterov,
                  wd_mask, axis_name, template)


class _LarsRule:
    """LARS update on the flat shard (round 5, VERDICT r4 ask #5).

    LARS needs PER-LAYER norms (train/optim.py:85-117, the reference's
    mix.py:297-310), which the flat shard layout does not expose per
    rank: a shard spans pieces of many leaves and no rank sees a whole
    leaf.  The rule here recovers exact per-leaf norms with one
    segment-sum + one tiny psum:

      1. `_shard_leaf_index` maps each shard element to its leaf (static
         cumsum table + searchsorted — the `_shard_leaf_values`
         machinery);
      2. segment-sum of p² and g² over that map gives this rank's
         per-leaf partial sums of squares (n_leaves+1 floats, the +1
         catching the world-size pad);
      3. `lax.psum` over the dp axis completes them globally — the only
         collective, 2·(n_leaves+1) floats;
      4. the reference trust-ratio formula runs per leaf and is gathered
         back per element (constant within a leaf).

    Semantics match `lars` exactly — epsilon-free formula, trust ratio
    on the UN-decayed gradient norm, lr folded into the momentum buffer,
    no nesterov, no wd mask; zero-norm quirks (0/0 → nan) are preserved
    for REAL leaves, only the pad bucket is forced to 0.  Numerics: the
    replicated `lars` sums each leaf's squares in one XLA reduction; the
    sharded rule sums per-shard segments then across ranks — a different
    (still deterministic) association, so norms agree to fp32 round-off,
    not bitwise; the ZeRO×LARS parity test pins the resulting params at
    ulp-scale tolerance (tests/test_zero.py).
    """

    coefficient = 0.001

    def _shard_update(self, g_sh, p_sh, template, rank, s, buf, lr,
                      axis_name):
        leaves = jax.tree.leaves(template)
        n = len(leaves)
        leaf_idx = self._shard_leaf_index(template, rank, s).astype(
            jnp.int32)
        w_sq = jax.ops.segment_sum(p_sh * p_sh, leaf_idx,
                                   num_segments=n + 1)
        g_sq = jax.ops.segment_sum(g_sh * g_sh, leaf_idx,
                                   num_segments=n + 1)
        w_norm = jnp.sqrt(lax.psum(w_sq, axis_name))      # (n+1,)
        g_norm = jnp.sqrt(lax.psum(g_sq, axis_name))
        local_lr = (w_norm / (g_norm + self.weight_decay * w_norm)
                    * self.coefficient)
        local_lr = local_lr.at[n].set(0.0)   # pad bucket (0/0 guard)
        lr_e = jnp.take(local_lr, leaf_idx)               # (S,)
        new_buf = (self.momentum * buf
                   + lr * lr_e * (g_sh + self.weight_decay * p_sh))
        return p_sh - new_buf, new_buf


class _Zero1Lars(_LarsRule, _Zero1):
    pass


class _Zero2Lars(_LarsRule, _Zero2):
    pass


class _Zero3Lars(_LarsRule, _Zero3):
    pass


def _lars_factory(cls, schedule, world, momentum, weight_decay,
                  coefficient, axis_name, template=None):
    args = (schedule, world, momentum, weight_decay, False, None,
            axis_name)
    z = cls(*args, template) if template is not None else cls(*args)
    z.coefficient = coefficient
    return z


def zero1_lars(schedule: Callable, world: int, momentum: float = 0.9,
               weight_decay: float = 0.0, coefficient: float = 0.001,
               axis_name: str = "dp") -> _Zero1Lars:
    """ZeRO-1 LARS: momentum sharded 1/`world`, per-layer trust ratios
    recovered via segment-sum + psum (`_LarsRule`)."""
    return _lars_factory(_Zero1Lars, schedule, world, momentum,
                         weight_decay, coefficient, axis_name)


def zero2_lars(schedule: Callable, world: int, momentum: float = 0.9,
               weight_decay: float = 0.0, coefficient: float = 0.001,
               axis_name: str = "dp") -> _Zero2Lars:
    """ZeRO-2 LARS: momentum + faithful reduction sharded; pair with
    ``make_train_step(..., reduce_in_update=True)``."""
    return _lars_factory(_Zero2Lars, schedule, world, momentum,
                         weight_decay, coefficient, axis_name)


def zero3_lars(schedule: Callable, world: int, template,
               momentum: float = 0.9, weight_decay: float = 0.0,
               coefficient: float = 0.001,
               axis_name: str = "dp") -> _Zero3Lars:
    """ZeRO-3 LARS: params, momentum AND reduction sharded, LARS trust
    ratios from the sharded per-leaf norms."""
    return _lars_factory(_Zero3Lars, schedule, world, momentum,
                         weight_decay, coefficient, axis_name, template)
