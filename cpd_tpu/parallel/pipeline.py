"""Pipeline parallelism (the `pp` mesh axis) — GPipe-style, TPU-native.

New capability beyond the reference (SURVEY.md §2 marks PP "Absent"); the
round-1 review flagged the `pp` mesh axis as a placeholder, and this module
makes it real.

Design: SPMD all the way down.  Under `shard_map` every pp rank runs the
SAME program; what differs is the slice of stage parameters it holds
(layer-stacked params sharded on their leading axis, `P("pp", ...)`) and
its `lax.axis_index(pp_axis)`.  Microbatches stream through stages with a
single rotating `lax.ppermute` per pipeline tick:

    tick t:  stage 0 ingests microbatch t (while t < M);
             every stage applies its local layer stack to its buffer;
             the last stage records the finished microbatch t-(P-1);
             every stage hands its activation to the next (ppermute).

M microbatches over P stages take M + P - 1 ticks — the classic GPipe
schedule with bubble fraction (P-1)/(M+P-1).  The whole schedule is ONE
`lax.scan`, so `jax.grad` through it yields the reverse pipeline schedule
automatically: the transpose of a rotating ppermute is the reverse
rotation, which is exactly backward pipelining.  No hand-written backward
pass, no Python-level stage loop — XLA sees a static single program and
overlaps the permute with stage compute.

The activation shape must be preserved by the stage function (true of
transformer blocks), because every stage's buffer is the same array shape.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

__all__ = ["pipeline_spmd"]


def pipeline_spmd(stage_fn: Callable, microbatches: jnp.ndarray,
                  pp_axis: str, pp_size: int) -> jnp.ndarray:
    """Stream `microbatches` (M, ...) through the pp pipeline.

    stage_fn: activation (...) -> activation (...), closing over THIS
    rank's stage parameters (shape-preserving).
    Returns (M, ...) where entry m is stage P-1's output for microbatch m —
    valid ON THE LAST STAGE ONLY (other ranks hold garbage; mask with
    `lax.axis_index(pp_axis) == pp_size - 1`).

    Must be called inside shard_map with `pp_axis` bound.  pp_size == 1
    degenerates to a plain scan of stage_fn over microbatches.
    """
    m_count = microbatches.shape[0]
    if pp_size == 1:
        def plain(_, x):
            return None, stage_fn(x)
        _, outs = lax.scan(plain, None, microbatches)
        return outs

    stage = lax.axis_index(pp_axis)
    last = pp_size - 1
    perm = [(i, (i + 1) % pp_size) for i in range(pp_size)]

    def tick(carry, t):
        buf, outs = carry
        # stage 0 ingests the next microbatch; everyone else continues the
        # activation received last tick
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m_count - 1), 0, keepdims=False)
        cur = jnp.where(stage == 0, inject, buf)
        y = stage_fn(cur)
        # the last stage completes microbatch t-(P-1) at this tick
        out_idx = t - last
        outs = lax.cond(
            out_idx >= 0,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(out_idx, 0, m_count - 1), 0),
            lambda o: o, outs)
        nxt = lax.ppermute(y, pp_axis, perm)
        return (nxt, outs), None

    buf0 = jnp.zeros_like(microbatches[0])
    outs0 = jnp.zeros_like(microbatches)
    (_, outs), _ = lax.scan(tick, (buf0, outs0),
                            jnp.arange(m_count + pp_size - 1))
    return outs
