"""Pipeline parallelism (the `pp` mesh axis) — GPipe-style, TPU-native.

New capability beyond the reference (SURVEY.md §2 marks PP "Absent"); the
round-1 review flagged the `pp` mesh axis as a placeholder, and this module
makes it real.

Design: SPMD all the way down.  Under `shard_map` every pp rank runs the
SAME program; what differs is the slice of stage parameters it holds
(layer-stacked params sharded on their leading axis, `P("pp", ...)`) and
its `lax.axis_index(pp_axis)`.  Microbatches stream through stages with a
single rotating `lax.ppermute` per pipeline tick:

    tick t:  stage 0 ingests microbatch t (while t < M);
             every stage applies its local layer stack to its buffer;
             the last stage records the finished microbatch t-(P-1);
             every stage hands its activation to the next (ppermute).

M microbatches over P stages take M + P - 1 ticks — the classic GPipe
schedule with bubble fraction (P-1)/(M+P-1).  The whole schedule is ONE
`lax.scan`, so `jax.grad` through it yields the reverse pipeline schedule
automatically: the transpose of a rotating ppermute is the reverse
rotation, which is exactly backward pipelining.  No hand-written backward
pass, no Python-level stage loop — XLA sees a static single program and
overlaps the permute with stage compute.

The activation shape must be preserved by the stage function (true of
transformer blocks), because every stage's buffer is the same array shape.

Memory profile of the scan backward (and where this design stops scaling):

* ``jax.grad`` through the tick scan stores each tick's residuals until
  the reverse sweep.  WITHOUT stage remat that is (M+P-1) ticks x the
  full internal residuals of stage_fn (every matmul input inside L/P
  layers, per microbatch) per rank — linear in M, the classic GPipe
  memory wall.
* WITH ``remat_stages=True`` (``jax.checkpoint`` around stage_fn) each
  tick stores only its boundary carry — the (B/M, T, d) activation —
  and the stage recomputes its internals in the backward tick.  Total
  boundary memory per rank is (M+P-1) x (B/M)·T·d ≈ (1 + (P-1)/M) x
  B·T·d, i.e. roughly ONE full-batch boundary activation regardless of
  M; the transient recompute peak adds one microbatch's stage residuals.
  Memory is then flat in M, so the bubble (P-1)/(M+P-1) can be driven
  down with more microbatches without hitting HBM — the remat forward
  replay (~1/3 extra stage FLOPs) is the price.
* What scan-GPipe cannot express is 1F1B/interleaved scheduling: AD
  generates the backward as the transpose of the WHOLE forward scan, so
  every forward tick completes before the first backward tick — fwd and
  bwd of different microbatches never interleave.  1F1B's win over
  remat-GPipe is holding ≤P (not M) boundary activations while skipping
  the replay; expressing it in JAX requires a hand-scheduled
  custom_vjp pipeline (both directions inside one scan with explicit
  stashes).  Measured against that: remat-GPipe already removes the
  M-scaling, so 1F1B here would buy only the replay FLOPs back — a
  deliberate non-goal until a profile shows the replay on the critical
  path (docs/PERF.md).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pipeline_spmd", "pipeline_ticks", "bubble_fraction"]


def pipeline_ticks(n_microbatches: int, pp_size: int) -> int:
    """Scan length of the GPipe schedule: M + P - 1."""
    return n_microbatches + pp_size - 1


def bubble_fraction(n_microbatches: int, pp_size: int) -> float:
    """Idle fraction of the schedule: (P-1)/(M+P-1).  Every rank executes
    stage_fn once per tick; only M of the M+P-1 executions act on real
    data, so compute overhead vs the unpipelined model is exactly
    1/(1-bubble) — the tick count is asserted on the traced program's
    scan length in tests/test_pipeline.py."""
    return (pp_size - 1) / pipeline_ticks(n_microbatches, pp_size)


def pipeline_spmd(stage_fn: Callable, microbatches: jnp.ndarray,
                  pp_axis: str, pp_size: int,
                  remat_stages: bool = False) -> jnp.ndarray:
    """Stream `microbatches` (M, ...) through the pp pipeline.

    stage_fn: activation (...) -> activation (...), closing over THIS
    rank's stage parameters (shape-preserving).
    Returns (M, ...) where entry m is stage P-1's output for microbatch m —
    valid ON THE LAST STAGE ONLY (other ranks hold garbage; mask with
    `lax.axis_index(pp_axis) == pp_size - 1`).

    remat_stages: checkpoint each stage application — backward memory
    drops from (M+P-1) x stage residuals to (M+P-1) x boundary
    activations (see module docstring).  Bitwise-neutral on values.

    Must be called inside shard_map with `pp_axis` bound.  pp_size == 1
    degenerates to a plain scan of stage_fn over microbatches.
    """
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    m_count = microbatches.shape[0]
    if pp_size == 1:
        def plain(_, x):
            return None, stage_fn(x)
        _, outs = lax.scan(plain, None, microbatches)
        return outs

    stage = lax.axis_index(pp_axis)
    last = pp_size - 1
    perm = [(i, (i + 1) % pp_size) for i in range(pp_size)]

    def tick(carry, t):
        buf, outs = carry
        # stage 0 ingests the next microbatch; everyone else continues the
        # activation received last tick
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m_count - 1), 0, keepdims=False)
        cur = jnp.where(stage == 0, inject, buf)
        y = stage_fn(cur)
        # the last stage completes microbatch t-(P-1) at this tick
        out_idx = t - last
        outs = lax.cond(
            out_idx >= 0,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(out_idx, 0, m_count - 1), 0),
            lambda o: o, outs)
        nxt = lax.ppermute(y, pp_axis, perm)
        return (nxt, outs), None

    buf0 = jnp.zeros_like(microbatches[0])
    outs0 = jnp.zeros_like(microbatches)
    (_, outs), _ = lax.scan(tick, (buf0, outs0),
                            jnp.arange(m_count + pp_size - 1))
    return outs
