"""Distributed layer (L2): low-precision gradient all-reduce over a mesh axis.

TPU-native re-implementation of reference CPDtorch/utils/dist_util.py on top
of XLA collectives.  The reference runs one NCCL op per parameter from a
Python loop; here everything is traced once under `shard_map`/`pjit` so XLA
schedules the collectives on ICI back-to-back (and can overlap them).  On
TPU the faithful gathers are fused into few large per-dtype buckets
(`_bucketed_quantized_sum`), and when APS has pre-quantized the values the
wire carries the bit-packed eXmY code words (`quant.numerics.pack_exmy`,
1-3 bytes per element for any sub-fp32 format) — both bit-identical to
the per-leaf fp32 path.

Semantics map (reference → here):

    dist_init()                 → `dist_init()` (jax.distributed/env-driven;
                                  no SLURM hostname surgery — the TPU runtime
                                  provides coordination)         dist_util.py:96-131
    DistModule/broadcast_params → `replicate(tree, mesh)` (replicated
                                  sharding *is* the broadcast) + in-graph
                                  `broadcast_from(x, axis_name, src)`
                                                                 dist_util.py:8-19,92-94
    sum_gradients(...)          → `sum_gradients(grads, axis_name=...)`
                                  (pytree-in/pytree-out, pure)   dist_util.py:22-51
    normal/kahan_sum_gradients  → all_gather + ordered scan (reduction.py)
                                                                 dist_util.py:54-89

Reduction modes:

* ``faithful`` (default): bit-faithful emulation — `all_gather` the fp32
  gradients, then rank-ordered requantized accumulation.  Costs W× bandwidth
  exactly like the reference's all_gather (dist_util.py:62-64); order *is*
  the semantics.
* ``fast``: quantize → `psum` → no dequantize-step emulation.  The
  deployment path (EQuARX-style): same precision at the wire, but XLA's
  reduction tree order, so not bit-identical to the reference.  New
  capability beyond the reference.
* ``ring``: chunked ppermute reduce-scatter + all-gather moving bit-packed
  eXmY partials (parallel/ring.py) — the ordered requantized reduction at
  ~2/W of the gather path's wire elements and O(n/W) peak transient
  memory, in the documented per-chunk rank-rotation order (bitwise-gated
  by `ring.ring_oracle_sum`).  New capability beyond the reference.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..quant.numerics import (cast_to_format, cast_to_format_sr_at,
                              pack_exmy, unpack_exmy, wire_bytes)
from ..quant.quant_function import tree_quant_health
from .aps import (aps_max_exponents, aps_scale, aps_shift_factors_checked,
                  aps_unscale, pmax_scalar_vector)
from .overlap import DEFAULT_BUCKET_ELEMS, bucket_layout
from .reduction import quantized_sum
from .ring import hierarchical_ring_sum

__all__ = [
    "dist_init", "sum_gradients", "broadcast_from", "replicate",
    "all_reduce_mean", "host_batch_to_global", "quantize_tree_sr",
    "grad_sr_key",
]


def dist_init(coordinator_address: Optional[str] = None,
              num_processes: Optional[int] = None,
              process_id: Optional[int] = None) -> tuple[int, int]:
    """Initialize multi-host JAX and return (rank, world_size).

    Replaces reference `dist_init` (dist_util.py:96-131).  The reference
    hand-parses SLURM_NODELIST to find a TCP master and hardcodes port 12345;
    `jax.distributed.initialize` auto-detects SLURM / OpenMPI / TPU-pod
    environments, so the hostname surgery disappears.  Single-process runs
    (no cluster env) are a no-op returning (0, 1) — unlike the reference,
    which raises outside SLURM (dist_util.py:97-98)."""
    import os
    explicit = coordinator_address is not None
    in_cluster = any(v in os.environ for v in
                     ("SLURM_PROCID", "OMPI_COMM_WORLD_RANK",
                      "COORDINATOR_ADDRESS", "TPU_WORKER_ID"))
    if explicit or in_cluster:
        if not jax.distributed.is_initialized():
            # No blanket except here: a coordinator failure must surface,
            # not silently degrade an N-host job to N independent trainings.
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)
    return jax.process_index(), jax.process_count()


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree fully-replicated on every device of `mesh`.

    The functional equivalent of reference `broadcast_params`
    (dist_util.py:92-94) + `DistModule.__init__` (dist_util.py:8-12): with a
    replicated NamedSharding, every device holds rank-0's bytes — the
    broadcast happens in the transfer."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def broadcast_from(x: jnp.ndarray, axis_name: str, src: int = 0) -> jnp.ndarray:
    """In-graph broadcast of `src`'s shard to all ranks along `axis_name`.

    For use inside shard_map when parity with an explicit
    `dist.broadcast(p, 0)` (dist_util.py:94) is wanted mid-computation."""
    return lax.all_gather(x, axis_name, axis=0, tiled=False)[src]


def host_batch_to_global(x, mesh: Mesh, axis_name: str = "dp"):
    """Assemble each host's local batch slice into one global jax.Array
    sharded over `axis_name`.

    Multi-controller JAX feeds data per process (the analog of the
    reference's per-rank DataLoader, main.py:111-120): each host loads
    global_batch / process_count consecutive samples and this stitches them
    into the global batch.  Single-process: a plain device_put.  The
    host-order convention matches the contiguous per-rank blocks of
    DistributedGivenIterationSampler (train_util.py:212-215)."""
    x = np.asarray(x)
    sharding = NamedSharding(mesh, P(axis_name))
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, x)


def all_reduce_mean(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Mean across an axis — the loss/metric averaging the examples do with
    all_reduce + divide (mix.py:240-242, main.py:167-169)."""
    return lax.pmean(x, axis_name)


def _flat_axis_index(axis_name) -> jnp.ndarray:
    """This rank's flat index along one axis name or a sequence of them
    (row-major over the sequence), for per-rank SR key decorrelation."""
    if isinstance(axis_name, str):
        return lax.axis_index(axis_name)
    idx = jnp.zeros([], jnp.int32)
    for a in axis_name:
        idx = idx * lax.psum(jnp.int32(1), a) + lax.axis_index(a)
    return idx


def _leaf_starts(tree) -> list[int]:
    """Static global flat offset of each leaf (tree_flatten order) — the
    index space the SR bitstream is defined on.  parallel/zero.py flattens
    the same tree in the same order, so its shard offsets index the same
    space and reproduce the same bits."""
    sizes = [l.size for l in jax.tree_util.tree_leaves(tree)]
    return [0] + list(np.cumsum(sizes[:-1]).astype(np.int64)) if sizes else []


def _leaf_offsets(start: int, leaf) -> jnp.ndarray:
    """Global flat offsets for one leaf, shaped like the leaf."""
    return (jnp.uint32(start)
            + jnp.arange(leaf.size, dtype=jnp.uint32)).reshape(leaf.shape)


def quantize_tree_sr(tree, grad_exp: int, grad_man: int, key,
                     starts: Optional[Sequence[int]] = None) -> Any:
    """Per-leaf eXmY cast of a pytree: RTNE when `key` is None, otherwise
    stochastic rounding with GLOBAL-offset-indexed bits (one bitstream over
    the concatenated flat layout, so the draw is identical however the
    tree is later flattened, bucketed, or sharded).  ``starts`` overrides
    each leaf's global flat offset — for callers whose ``tree`` is a
    SLICE of a larger layout (the overlap taps reduce one bucket at a
    time, parallel/overlap.py) and must draw that layout's bits."""
    if key is None:
        return jax.tree.map(
            lambda g: cast_to_format(g, grad_exp, grad_man), tree)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    starts = _leaf_starts(tree) if starts is None else list(starts)
    out = [cast_to_format_sr_at(g, grad_exp, grad_man, key,
                                _leaf_offsets(st, g))
           for st, g in zip(starts, leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def grad_sr_key(grad_seed: int, step, site: int) -> jax.Array:
    """The ONE derivation of gradient-pipeline SR keys, shared by every
    train-step builder (train/step.py, lm.py, pp.py, moe.py).

    Depends only on (grad_seed, step, site) — NEVER a rank index: the
    same key must reach every sp/tp/pp/ep copy so replicated leaves
    round identically (desynchronized bits would silently diverge
    optimizer state across copies).  `sum_gradients` itself folds the
    dp rank into its pre-quantize subkey where decorrelation is wanted.
    Site convention: 0 = the rank-local pre-reduce cast (emulate-node;
    callers fold their dp rank in AFTER this), 1 = the cross-device
    `sum_gradients` reduction."""
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(grad_seed), step), site)


def _wire_format(grad_exp: int, grad_man: int):
    """(exp, man) when shipping the bit-packed eXmY code words pays, else
    None.

    When the gathered values are ALREADY quantized to the format (the APS
    path quantizes before the reduction, dist_util.py:35-37),
    `pack_exmy`'s re-encoding is lossless and the wire carries
    ``wire_bytes(exp, man)`` (1-3) bytes/element instead of 4.  This
    replaces the old 3-entry hardware-dtype table: ANY sub-fp32 format
    with man >= 2 compresses now — including (4,3), which float8_e4m3fn
    (finite-only) could never carry because the reference cast saturates
    to ±inf."""
    if grad_man >= 2 and wire_bytes(grad_exp, grad_man) < 4:
        return (grad_exp, grad_man)
    return None


def _gather_leaf(g: jnp.ndarray, axis_name, wire=None) -> jnp.ndarray:
    """all_gather one leaf; `wire` is an (exp, man) tuple to bit-pack the
    payload (values must already be in that format's value set)."""
    if wire is not None:
        packed = pack_exmy(g, *wire)
        out = lax.all_gather(packed, axis_name, axis=0, tiled=False)
        return unpack_exmy(out, *wire)
    return lax.all_gather(g, axis_name, axis=0, tiled=False)


# Per-bucket element cap for the faithful path (one home for the number:
# parallel/overlap.py, which the overlapped transport shares the layout
# with).  W x 4M x 4B = 128 MiB of gathered fp32 at W=8 — large enough to
# amortize collective launch overhead, small enough that the gathered
# stack never rivals model memory.
_BUCKET_ELEMS = DEFAULT_BUCKET_ELEMS


def _bucketed_quantized_sum(grads: Any, axis_name, grad_exp: int,
                            grad_man: int, use_kahan: bool,
                            bucket_elems: int = _BUCKET_ELEMS,
                            wire=None, key=None, starts=None) -> Any:
    """Faithful ordered reduction over few large buckets instead of one
    collective per parameter (SURVEY.md §7 hard-part 4).

    Leaves are flattened and concatenated per dtype into buckets of at most
    `bucket_elems` elements (`overlap.bucket_layout` — the ONE capping
    function, shared with the bucketed ring and the overlap taps); each
    bucket is all_gathered ONCE and reduced with ONE rank-ordered
    requantizing scan, then split back.  The quantized accumulation is
    elementwise, so concatenation changes nothing about any element's
    value — results are bit-identical to the per-leaf path (the
    reference's per-parameter loop, dist_util.py:60-89), with W x leaf_count
    collective launches collapsed to W x bucket_count.

    With stochastic rounding (`key` given) the per-element bits are indexed
    by GLOBAL flat offset (numerics.sr_bits_at), so bucketed and per-leaf
    reductions draw the SAME bits — bit-identical results, invariant to the
    bucket layout (and to ZeRO sharding, parallel/zero.py).  ``starts``
    overrides the leaves' global offsets (overlap taps reducing a bucket
    of a larger layout).
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    starts = _leaf_starts(grads) if starts is None else list(starts)
    out = [None] * len(leaves)
    # group by dtype GLOBALLY (order of first appearance), then cap each
    # group with the shared layout function — an interleaved-dtype tree
    # still packs into few large per-dtype buckets instead of breaking a
    # bucket at every dtype change
    by_dtype: dict = {}
    for i, g in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(g.dtype), []).append(i)
    buckets = []
    for idxs in by_dtype.values():
        for local in bucket_layout([leaves[i].size for i in idxs],
                                   bucket_elems):
            buckets.append([idxs[j] for j in local])
    for bucket in buckets:
        flat = (leaves[bucket[0]].reshape(-1) if len(bucket) == 1 else
                jnp.concatenate([leaves[i].reshape(-1)
                                 for i in bucket]))
        gathered = _gather_leaf(flat, axis_name, wire=wire)
        offs = (None if key is None else jnp.concatenate(
            [_leaf_offsets(starts[i], leaves[i]).ravel()
             for i in bucket]))
        red = quantized_sum(gathered, grad_exp, grad_man, use_kahan,
                            key=key, offsets=offs)
        off = 0
        for i in bucket:
            n = leaves[i].size
            out[i] = lax.dynamic_slice_in_dim(red, off, n).reshape(
                leaves[i].shape)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def sum_gradients(grads: Any, axis_name: str | Sequence[str],
                  use_aps: bool = False, grad_exp: int = 5, grad_man: int = 2,
                  use_kahan: bool = False, mode: str = "faithful",
                  bucket: Optional[bool] = None,
                  rounding: str = "nearest", key=None,
                  verify: bool = False,
                  wire_fault: Optional[tuple] = None,
                  stats: bool = False,
                  bucket_elems: Optional[int] = None,
                  offset_starts: Optional[Sequence[int]] = None,
                  block_scale: bool = False,
                  block_size: int = 128) -> Any:
    """Low-precision gradient all-reduce (SUM) over `axis_name`.

    Pure pytree-in/pytree-out version of reference `sum_gradients`
    (dist_util.py:22-51); must be called inside shard_map/pjit with
    `axis_name` bound on the mesh's data axis.  Returns the *sum* (not mean)
    of per-rank gradients, like the reference — trainers pre-divide the loss
    by world_size so the sum is the mean (mix.py:239).

    use_aps     → APS exponent shifting around the reduction (aps.py).
    use_kahan   → Kahan-compensated ordered accumulation (dist_util.py:72-89).
    mode        → "faithful" (gather + ordered scan) | "fast" (quantize+psum)
                  | "ring" (chunked ppermute reduce-scatter + all-gather
                  with bit-packed eXmY partials on the wire — the ordered
                  requantized reduction at ~2/W of the gather wire bytes
                  and O(n/W) peak memory, in parallel/ring.py's documented
                  per-chunk rank-rotation order).  On a MULTI-axis
                  ``axis_name`` the ring composes hierarchically:
                  sequential per-axis rings, innermost (last-named) axis
                  first, bit-gated by `ring.ring_oracle_sum_multi`
                  (parallel/ring.hierarchical_ring_sum) — the old
                  multi-axis fail-fast is gone.
    bucket      → faithful mode only: fuse per-leaf gathers into few large
                  per-dtype buckets (bit-identical).  Default (None) =
                  auto: on for TPU — fewer collective launches riding ICI
                  — off elsewhere (on the CPU mesh the gather is a plain
                  memcpy and the bucket concat/split copies measured ~17%
                  slower on a ResNet-18-sized pytree).
    bucket_elems→ per-bucket element cap (default `_BUCKET_ELEMS`, 4M).
                  Setting it implies ``bucket=True`` for faithful mode.
                  RING mode is always bucketed at this cap via the same
                  greedy layout the overlapped backward-reduce emits
                  (`overlap.bucket_layout` / `BucketPlan.for_tree`), so
                  overlap on/off is bitwise identical at ANY value
                  including the default — a tree that fits one bucket
                  rings whole, exactly the pre-bucketing transport.
                  NOTE: different ``bucket_elems`` values are DIFFERENT
                  documented accumulation orders (chunk boundaries
                  move), each gated by its own per-bucket oracle.
                  Ignored by "fast" (psum is elementwise; layout-free).
    offset_starts→ per-leaf GLOBAL flat offsets overriding the tree's own
                  `_leaf_starts` — for callers reducing a SLICE of a
                  larger layout (the overlap taps, parallel/overlap.py)
                  whose SR bits must match the whole-layout draw.
    block_scale / block_size → ring mode only: the EQuARX-style
                  block-scaled wire (quant/numerics.py "Block-scaled
                  eXmY codec"): every hop cast shares one power-of-2
                  scale per `block_size` consecutive elements, the
                  1-byte-per-block shift sidecar riding the packed
                  wire.  Different accumulation NUMERICS than the
                  per-tensor cast — gated by its own extended oracle
                  (`ring.ring_oracle_sum(block_scale=True)`), and an
                  e4m3 blocked wire covers dynamic range a per-tensor
                  e5m7 cannot (tools/bench_reduce.py --block-sweep).
                  Needs a packable format (man >= 2, not (8, 23));
                  rejected outside mode="ring" — faithful/fast have no
                  sidecar wire to carry the scales.
    rounding    → "nearest" (reference semantics) | "stochastic": every
                  eXmY cast in the pipeline (the APS/fast pre-quantize,
                  each ordered-accumulation step, the fast post-quantize)
                  uses the unbiased SR cast driven by `key` (required) —
                  sub-ulp/2 gradient mass then survives the reduction in
                  expectation, the unbiased alternative to APS's exponent
                  shifting (beyond-reference; composes with it too).
                  Per-element bits are indexed by (key, scan step, cast
                  site, GLOBAL flat offset) — deterministic given key and
                  invariant to bucketing and to ZeRO reduce-scatter
                  sharding (parallel/zero.py reproduces these exact bits
                  on each shard); every rank derives identical bits, so
                  replicated outputs agree.
    verify      → self-verifying reduction (parallel/integrity.py):
                  returns ``(reduced, report)`` where report holds the
                  replicated int32 scalars {ok, hop_bad, gather_bad,
                  agree}.  Ring mode checks every hop payload and
                  all-gather row against tagged Fletcher checksums AND
                  pmin/pmax-agrees the result digest across replicas;
                  faithful/fast have no checksummable custom wire, so
                  their report is the cross-replica agreement digest
                  alone (hop_bad/gather_bad stay 0).  The clean-path
                  values are bitwise unchanged.
    wire_fault  → ``(code, rank)`` int32 scalars: inject a deterministic
                  wire fault (resilience/inject.WIRE_KINDS) into the
                  ring transport on that rank — ignored outside ring
                  mode, because the wire being attacked IS the ring's
                  (downgrading the transport is how a run escapes a
                  persistently faulty ring wire).
    stats       → numeric-health telemetry of the reduce-wire cast site
                  (quant.numerics.quant_health): returns ``(reduced,
                  report)`` where report gains the psum-agreed
                  float32 scalars {wire_sat, wire_underflow, wire_nan,
                  wire_total} plus ``aps_bad`` (count of leaves whose
                  APS max-exponent was +Inf/NaN — gradients already
                  non-finite BEFORE the wire, satellite of
                  aps_shift_factors_checked; 0 when use_aps is off).
                  With APS the counters observe the pre-reduce quantize
                  that already runs (zero extra casts); without APS the
                  local grads are probe-cast to the wire format once,
                  telemetry-only (RTNE regardless of `rounding` — the
                  probe measures format fit, not round direction; its
                  output is discarded).  The data path is bitwise
                  unchanged either way.  Composes with `verify`: one
                  merged report dict.
    """
    if mode not in ("faithful", "fast", "ring"):
        raise ValueError(f"unknown mode {mode!r}")
    if mode == "ring" and not isinstance(axis_name, str) \
            and not tuple(axis_name):
        raise ValueError("mode='ring' needs at least one mesh axis; got "
                         f"{tuple(axis_name)!r}")
    if rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown rounding {rounding!r}")
    if rounding == "stochastic" and key is None:
        raise ValueError("rounding='stochastic' requires a PRNG key "
                         "(fold in the step counter for fresh per-step "
                         "bits)")
    if rounding == "nearest" and key is not None:
        raise ValueError("a PRNG key was passed but rounding='nearest' "
                         "would ignore it; pass rounding='stochastic' "
                         "(matching float_quantize/quant_gemm's contract)")
    if bucket is False and bucket_elems is not None and mode == "faithful":
        raise ValueError("bucket=False contradicts an explicit "
                         "bucket_elems — drop one of them")
    if block_scale and mode != "ring":
        raise ValueError(
            f"block_scale=True needs mode='ring' (got {mode!r}): the "
            f"per-block shift sidecar rides the ring's packed wire — "
            f"faithful's gather and fast's psum have no lane to carry it")
    if bucket is None:
        bucket = (jax.default_backend() == "tpu"
                  or bucket_elems is not None)
    world = lax.psum(jnp.float32(1.0), axis_name)

    # Independent SR bitstreams for the three cast stages.  The pre-
    # quantize acts on each rank's OWN gradients, so its key folds in the
    # rank index — identical bits across ranks would round similar
    # gradients the same way and the summed rounding error would grow
    # coherently (~W*ulp) instead of averaging out (~sqrt(W)*ulp).  The
    # ordered-sum and post-psum casts act on data that is identical on
    # every rank (gathered / reduced), so THEIR keys must stay shared or
    # the replicated outputs would disagree.
    k_pre = k_sum = k_post = None
    if key is not None:
        k_pre, k_sum, k_post = jax.random.split(key, 3)
        k_pre = jax.random.fold_in(k_pre, _flat_axis_index(axis_name))

    def q_tree(t, k):
        return quantize_tree_sr(t, grad_exp, grad_man, k,
                                starts=offset_starts)

    shifts = None
    prec = None
    aps_bad = jnp.zeros([], jnp.int32)
    if use_aps:
        max_exp = aps_max_exponents(grads, world)
        max_exp = pmax_scalar_vector(max_exp, axis_name)
        # checked variant: a +Inf/NaN max-exponent means the leaf holds
        # non-finite gradients — shift 0 is damage control, the count is
        # the signal (computed on the pmax'd vector, so it is replicated)
        shifts, aps_bad = aps_shift_factors_checked(max_exp, grad_exp)
        scaled = aps_scale(grads, shifts)
        grads = q_tree(scaled, k_pre)
        if stats:
            # the exact values that hit the reduce wire, observed for
            # free: the APS pre-quantize above already ran, telemetry
            # just compares its (input, output) pair
            prec = tree_quant_health(scaled, grads)
    elif stats:
        # no pre-quantize on this path (faithful/ring cast inside the
        # ordered accumulation) — probe: cast the local grads, scaled by
        # the world size, to the wire format once; telemetry-only,
        # result discarded.  The ·W scale is APS's own worst-case bound
        # on the ordered accumulation (max|g·W|, dist_util.py:26-28): a
        # per-rank value can fit the format while the running W-rank sum
        # saturates mid-scan, and the supervisor must see THAT — the
        # failure the reduce actually hits — not just the per-element
        # cast.  This one extra elementwise cast is the measured
        # telemetry overhead of docs/PERF.md.
        scaled = jax.tree.map(lambda g: g.astype(jnp.float32) * world,
                              grads)
        probe = jax.tree.map(
            lambda g: cast_to_format(g, grad_exp, grad_man), scaled)
        prec = tree_quant_health(scaled, probe)

    if mode == "fast":
        if not use_aps and not (grad_exp == 8 and grad_man == 23):
            grads = q_tree(grads, k_pre)
        # fast mode IS the XLA-order psum by definition: same wire
        # precision, no order emulation (module docstring) — the one
        # place the unordered reduction is the documented intent.
        reduced = jax.tree.map(  # cpd: disable=kahan-ordering
            lambda g: lax.psum(g, axis_name), grads)
        if not (grad_exp == 8 and grad_man == 23):
            reduced = q_tree(reduced, k_post)
    elif mode == "ring":
        # Per-bucket rings over the flat gradient (ONE whole-tree ring
        # when bucket_elems is None — leaves concatenated in tree_flatten
        # order, SR offsets in the same global space as _leaf_starts).
        # Partial sums are post-quantize — always in the format value set
        # — so the wire is bit-packed whether or not APS pre-quantized
        # the inputs.  Multi-axis axis_name composes hierarchically
        # (ring.hierarchical_ring_sum); an injected wire fault hits
        # bucket 0 only, so chaos-drill counter expectations survive any
        # bucket count (resilience/inject.py wire_schedule).
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if leaves:
            starts = (_leaf_starts(grads) if offset_starts is None
                      else list(offset_starts))
            sizes = [l.size for l in leaves]
            # the ring is ALWAYS bucketed at the same default cap the
            # overlap taps use (BucketPlan.for_tree): a tree that fits
            # one bucket rings whole — the historical behavior — and a
            # larger tree gets the same per-bucket layout whether the
            # reduction runs post-backward or inside the taps, so
            # overlap on/off is bitwise identical at bucket_elems=None
            # too (not just at an explicit cap)
            buckets = bucket_layout(
                sizes, bucket_elems if bucket_elems is not None
                else _BUCKET_ELEMS)
            out = [None] * len(leaves)
            reports = []
            for b, idxs in enumerate(buckets):
                flat = (leaves[idxs[0]].astype(jnp.float32).reshape(-1)
                        if len(idxs) == 1 else
                        jnp.concatenate([leaves[i].astype(jnp.float32)
                                         .reshape(-1) for i in idxs]))
                # contiguous bucket -> cheap scalar offset_start; a
                # bucket spanning non-adjacent global offsets ships the
                # full per-element offset array instead
                contig = all(starts[i] + sizes[i] == starts[j]
                             for i, j in zip(idxs, idxs[1:]))
                off_kw = (dict(offset_start=int(starts[idxs[0]]))
                          if contig else
                          dict(offsets=jnp.concatenate(
                              [_leaf_offsets(starts[i], leaves[i]).ravel()
                               for i in idxs])))
                red = hierarchical_ring_sum(
                    flat, axis_name, grad_exp, grad_man,
                    use_kahan=use_kahan, key=k_sum, verify=verify,
                    fault=(wire_fault if b == 0 else None),
                    block_scale=block_scale, block_size=block_size,
                    **off_kw)
                if verify:
                    red, rep = red
                    reports.append(rep)
                off = 0
                for i in idxs:
                    out[i] = lax.dynamic_slice_in_dim(red, off, sizes[i]) \
                        .reshape(leaves[i].shape).astype(leaves[i].dtype)
                    off += sizes[i]
            reduced = jax.tree_util.tree_unflatten(treedef, out)
            if verify:
                report = _merge_verify_reports(reports)
        else:
            reduced = grads
            if verify:
                report = _clean_verify_report()
    else:
        # Wire compression: with APS the gathered values were quantized to
        # the (exp, man) value set just above, so the W x gather ships the
        # bit-packed code words — wire_bytes(exp, man) bytes per element —
        # losslessly (bit-identical results; tested).  Without APS the
        # reference gathers RAW fp32 grads (dist_util.py:62-64), so no
        # compression is possible without changing semantics.
        wire = _wire_format(grad_exp, grad_man) if use_aps else None
        if grad_exp == 8 and grad_man == 23 and not use_kahan:
            # fp32 fast path == plain all-reduce: the reference takes the
            # same shortcut at the identity format (dist_util.py:55-59),
            # so XLA-order psum here is reference parity, not a loss.
            reduced = jax.tree.map(  # cpd: disable=kahan-ordering
                lambda g: lax.psum(g, axis_name), grads)
        elif bucket:
            reduced = _bucketed_quantized_sum(
                grads, axis_name, grad_exp, grad_man, use_kahan,
                bucket_elems=(bucket_elems if bucket_elems is not None
                              else _BUCKET_ELEMS),
                wire=wire, key=k_sum, starts=offset_starts)
        else:
            leaves, treedef = jax.tree_util.tree_flatten(grads)
            starts = (_leaf_starts(grads) if offset_starts is None
                      else list(offset_starts))
            out = [quantized_sum(
                       _gather_leaf(g, axis_name, wire=wire),
                       grad_exp, grad_man, use_kahan, key=k_sum,
                       offsets=(None if k_sum is None
                                else _leaf_offsets(st, g)))
                   for st, g in zip(starts, leaves)]
            reduced = jax.tree_util.tree_unflatten(treedef, out)

    if use_aps:
        reduced = aps_unscale(reduced, shifts)
    if verify or stats:
        if verify:
            if mode != "ring":
                # psum / all_gather have no custom wire to checksum; the
                # cross-replica agreement digest is the whole verdict
                from .integrity import digest_agree, tree_digest
                agree = digest_agree(tree_digest(reduced), axis_name)
                report = _clean_verify_report()
                report["agree"] = agree
                report["ok"] = agree
        else:
            report = {}
        if stats:
            # SUM the per-rank counts so every replica reports the same
            # cluster-wide verdict (the supervisor's escalation decision
            # must agree across hosts); aps_bad is replicated already
            # (computed from the pmax'd vector)
            report.update({"wire_" + k: lax.psum(v, axis_name)
                           for k, v in prec.items()})
            report["aps_bad"] = aps_bad
        return reduced, report
    return reduced


def _clean_verify_report() -> dict:
    i0, i1 = jnp.zeros([], jnp.int32), jnp.ones([], jnp.int32)
    return {"hop_bad": i0, "gather_bad": i0, "agree": i1, "ok": i1}


def _merge_verify_reports(reports: list) -> dict:
    """Merge per-bucket ring verification reports into one verdict:
    mismatch COUNTS add, agreement ANDs, and ``ok`` is recomputed from
    the merged fields — one corrupt bucket fails the step exactly as a
    corrupt whole-tree ring did."""
    if not reports:
        return _clean_verify_report()
    hop = sum((r["hop_bad"] for r in reports[1:]),
              reports[0]["hop_bad"])
    gather = sum((r["gather_bad"] for r in reports[1:]),
                 reports[0]["gather_bad"])
    agree = reports[0]["agree"]
    for r in reports[1:]:
        agree = jnp.minimum(agree, r["agree"])
    return {"hop_bad": hop, "gather_bad": gather, "agree": agree,
            "ok": ((hop == 0) & (gather == 0)
                   & (agree == 1)).astype(jnp.int32)}


def make_sum_gradients_fn(mesh: Mesh, axis_name: str = "data", **kwargs):
    """Standalone jitted ``stacked_grads -> reduced`` over `mesh.axis_name`.

    Input: pytree whose leaves are stacked per-rank gradients ``(W, *shape)``
    (the multi-controller analog of "each rank holds its own grad").  Output:
    the reduced pytree with leaf shape ``(*shape,)``, replicated.

    This mirrors the reference's usage pattern of an explicit post-backward
    `sum_gradients(model)` call (mix.py:286-291).  Trainers that jit a whole
    train step should instead call `sum_gradients` inline inside their
    shard_map — one trace, no extra dispatch."""
    from ..compat import shard_map

    fn = functools.partial(sum_gradients, axis_name=axis_name, **kwargs)

    def body(stacked):
        local = jax.tree.map(lambda g: g[0], stacked)  # this rank's grad
        return fn(local)

    # Keyed by treedef so jit's trace cache is actually hit — and BOUNDED:
    # a long-lived reducer fed many distinct pytree structures (sweeps,
    # notebooks) must not grow a callable per structure forever.  Eviction
    # only costs a re-trace on the next call with that structure.
    from ..utils.cache import LRUCache
    jitted = LRUCache(maxsize=16)

    def reduced(stacked_grads):
        # the key carries the layout-affecting coordinates alongside the
        # structure: a cached callable traced for one (mode, bucket
        # layout) must never serve another (the PR 5 half-keyed-table
        # bug class, extended to the bucket coordinate) — today they are
        # per-instance constants, but the key is what guards tomorrow
        treedef = (jax.tree.structure(stacked_grads),
                   kwargs.get("mode", "faithful"),
                   kwargs.get("bucket_elems"),
                   kwargs.get("block_scale", False),
                   kwargs.get("block_size", 128))

        def build():
            in_spec = jax.tree.map(lambda _: P(axis_name), stacked_grads)
            out_spec = jax.tree.map(lambda _: P(), stacked_grads)
            return jax.jit(
                shard_map(body, mesh=mesh, in_specs=(in_spec,),
                          out_specs=out_spec, check_vma=False))

        return jitted.get_or_create(treedef, build)(stacked_grads)

    reduced._cache = jitted   # introspectable bound (tests assert on it)
    return reduced
