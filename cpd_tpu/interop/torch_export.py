"""Export cpd_tpu flax variables to torch state_dicts — the reverse of
torch_import, completing the migration story in both directions.

A user leaving the reference brings `.pth` files in (torch_import); a user
who trains here but must hand a model to a torch consumer (the reference's
own eval tooling, torchvision pipelines, ONNX-via-torch exporters) takes a
state_dict out.  Layout rules are the exact inverses of torch_import's:

  * nn.Conv kernel (kH, kW, I, O) -> Conv2d weight (O, I, kH, kW)
  * nn.Dense kernel (I, O)        -> Linear weight (O, I); bias as-is
  * BN scale/bias + mean/var      -> weight/bias + running_mean/running_var,
    plus `num_batches_tracked = 0` (torch creates it; strict load_state_dict
    requires it; flax has no counterpart so 0 is the honest value)

Export targets the same two architectures the importers cover: the
reference CIFAR ResNet-18 (reference example/ResNet18/models/
resnet18_cifar.py:48-87 — nn.Sequential children, so numeric keys) and
torchvision-style ResNets (example/ResNet50/main.py:67).  Round-tripping
import(export(v)) is bitwise (tested), and exported dicts load into live
torch modules with strict=True (tests/test_interop.py).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

__all__ = [
    "export_conv", "export_linear", "export_bn",
    "export_reference_resnet18_cifar", "export_torchvision_resnet",
    "save_torch_checkpoint",
]


def _np32(x) -> np.ndarray:
    return np.asarray(x, np.float32)


def export_conv(kernel) -> np.ndarray:
    """flax (kH, kW, I, O) -> Conv2d weight (O, I, kH, kW)."""
    k = np.asarray(kernel)
    if k.ndim != 4:
        raise ValueError(f"conv kernel must be 4-D, got {k.shape}")
    return np.ascontiguousarray(np.transpose(_np32(k), (3, 2, 0, 1)))


def export_linear(kernel) -> np.ndarray:
    """flax Dense kernel (I, O) -> Linear weight (O, I)."""
    k = np.asarray(kernel)
    if k.ndim != 2:
        raise ValueError(f"dense kernel must be 2-D, got {k.shape}")
    return np.ascontiguousarray(_np32(k).T)


def export_bn(params: Mapping[str, Any], stats: Mapping[str, Any],
              prefix: str, out: dict) -> None:
    """Write one BatchNorm's four tensors + num_batches_tracked at
    `prefix.` into `out`."""
    out[f"{prefix}.weight"] = _np32(params["scale"])
    out[f"{prefix}.bias"] = _np32(params["bias"])
    out[f"{prefix}.running_mean"] = _np32(stats["mean"])
    out[f"{prefix}.running_var"] = _np32(stats["var"])
    out[f"{prefix}.num_batches_tracked"] = np.asarray(0, np.int64)


def _variables(v: Mapping[str, Any]) -> tuple[Mapping, Mapping]:
    if "params" not in v:
        raise ValueError("expected a variables dict with a 'params' "
                        "collection (model.init / TrainState fields)")
    return v["params"], v.get("batch_stats", {})


def export_reference_resnet18_cifar(variables: Mapping[str, Any]) -> dict:
    """`models.resnet18_cifar()` variables -> the reference trainer's
    state_dict keyspace (inverse of import_reference_resnet18_cifar)."""
    params, stats = _variables(variables)
    sd: dict = {"conv1.0.weight": export_conv(params["stem_conv"]["kernel"])}
    export_bn(params["stem_bn"], stats["stem_bn"], "conv1.1", sd)

    for stage in range(1, 5):
        block = 0
        while f"layer{stage}_block{block}" in params:
            src = f"layer{stage}_block{block}"
            dst = f"layer{stage}.{block}"
            bp, bs = params[src], stats[src]
            sd[f"{dst}.left.0.weight"] = export_conv(bp["conv1"]["kernel"])
            export_bn(bp["bn1"], bs["bn1"], f"{dst}.left.1", sd)
            sd[f"{dst}.left.3.weight"] = export_conv(bp["conv2"]["kernel"])
            export_bn(bp["bn2"], bs["bn2"], f"{dst}.left.4", sd)
            if "shortcut_conv" in bp:
                sd[f"{dst}.shortcut.0.weight"] = export_conv(
                    bp["shortcut_conv"]["kernel"])
                export_bn(bp["shortcut_bn"], bs["shortcut_bn"],
                          f"{dst}.shortcut.1", sd)
            block += 1
        if block == 0:
            raise KeyError(f"layer{stage} missing from variables")

    sd["fc.weight"] = export_linear(params["fc"]["kernel"])
    sd["fc.bias"] = _np32(params["fc"]["bias"])
    return sd


def export_torchvision_resnet(variables: Mapping[str, Any]) -> dict:
    """`models.resnet{18,34,50,101}()` variables -> torchvision-style
    state_dict (inverse of import_torchvision_resnet)."""
    params, stats = _variables(variables)
    sd: dict = {"conv1.weight": export_conv(params["stem_conv"]["kernel"])}
    export_bn(params["stem_bn"], stats["stem_bn"], "bn1", sd)

    for stage in range(1, 5):
        block = 0
        while f"layer{stage}_block{block}" in params:
            src = f"layer{stage}_block{block}"
            dst = f"layer{stage}.{block}"
            bp, bs = params[src], stats[src]
            conv = 1
            while f"conv{conv}" in bp:
                sd[f"{dst}.conv{conv}.weight"] = export_conv(
                    bp[f"conv{conv}"]["kernel"])
                export_bn(bp[f"bn{conv}"], bs[f"bn{conv}"],
                          f"{dst}.bn{conv}", sd)
                conv += 1
            if "downsample_conv" in bp:
                sd[f"{dst}.downsample.0.weight"] = export_conv(
                    bp["downsample_conv"]["kernel"])
                export_bn(bp["downsample_bn"], bs["downsample_bn"],
                          f"{dst}.downsample.1", sd)
            block += 1
        if block == 0:
            raise KeyError(f"layer{stage} missing from variables")

    sd["fc.weight"] = export_linear(params["fc"]["kernel"])
    sd["fc.bias"] = _np32(params["fc"]["bias"])
    return sd


def save_torch_checkpoint(sd: Mapping[str, Any], path: str,
                          wrapper: str = "state_dict") -> None:
    """torch.save `sd` at `path`, wrapped the way the reference's loaders
    expect: wrapper="state_dict" (ResNet-18 trainer, train_util.py:269),
    "model" (ResNet-50 trainer, main.py:258-264), or "" for a bare dict."""
    import torch  # lazy, same policy as torch_import

    tensors = {k: torch.from_numpy(np.ascontiguousarray(v))
               for k, v in sd.items()}
    obj: Any = {wrapper: tensors} if wrapper else tensors
    torch.save(obj, path)
