"""Import torch checkpoints (CPDtorch reference / torchvision ResNets) into
cpd_tpu's flax models.

A reference user's trained artifacts are `.pth` files: torchvision-style
ImageNet ResNets (example/ResNet50/main.py:67 instantiates
`torchvision.models.resnet50()`) and the reference's own CIFAR ResNet-18
(example/ResNet18/models/resnet18_cifar.py), saved by
`save_checkpoint` as `{"state_dict": ..., ...}` with optional DDP
`module.` prefixes (utils/train_util.py:268-299).  These converters map
those state_dicts onto our NHWC flax pytrees so migration does not forfeit
trained models.

Layout rules (torch -> flax):
  * Conv2d weight  (O, I, kH, kW) -> nn.Conv kernel (kH, kW, I, O)
  * Linear weight  (O, I)         -> nn.Dense kernel (I, O); bias as-is
  * BatchNorm2d    weight/bias    -> scale/bias (params);
                   running_mean/var -> mean/var (batch_stats);
                   num_batches_tracked has no flax equivalent (dropped)

Everything takes/returns numpy — torch is only needed (lazily) to
`torch.load` a pickle; converted trees feed `model.apply` directly and are
verified by forward-parity tests against live torch modules
(tests/test_interop.py).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

__all__ = [
    "convert_conv", "convert_linear", "convert_bn", "strip_module_prefix",
    "import_reference_resnet18_cifar", "import_torchvision_resnet",
    "load_reference_checkpoint",
]


def _np(t) -> np.ndarray:
    """torch.Tensor | array-like -> float32/int numpy (host)."""
    if hasattr(t, "detach"):          # torch.Tensor without importing torch
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def convert_conv(weight) -> np.ndarray:
    """Conv2d (O, I, kH, kW) -> flax (kH, kW, I, O)."""
    w = _np(weight)
    if w.ndim != 4:
        raise ValueError(f"conv weight must be 4-D, got {w.shape}")
    return np.transpose(w, (2, 3, 1, 0))


def convert_linear(weight) -> np.ndarray:
    """Linear (O, I) -> flax Dense kernel (I, O)."""
    w = _np(weight)
    if w.ndim != 2:
        raise ValueError(f"linear weight must be 2-D, got {w.shape}")
    return w.T


def convert_bn(sd: Mapping[str, Any], prefix: str) -> tuple[dict, dict]:
    """BatchNorm2d at `prefix` -> ({scale, bias}, {mean, var})."""
    params = {"scale": _np(sd[f"{prefix}.weight"]),
              "bias": _np(sd[f"{prefix}.bias"])}
    stats = {"mean": _np(sd[f"{prefix}.running_mean"]),
             "var": _np(sd[f"{prefix}.running_var"])}
    return params, stats


def strip_module_prefix(sd: Mapping[str, Any]) -> dict:
    """Drop DDP's `module.` key prefix (train_util.py:286-299 does the same
    dance in both directions; import always wants it gone)."""
    if any(k.startswith("module.") for k in sd):
        return {k[len("module."):] if k.startswith("module.") else k: v
                for k, v in sd.items()}
    return dict(sd)


def load_reference_checkpoint(path: str) -> dict:
    """torch.load a reference `.pth` and return its bare state_dict
    (module-prefix stripped).  Accepts the reference's two wrapper
    flavors — `{"state_dict": ...}` (ResNet-18 trainer,
    train_util.py:269) and `{"model": ...}` (ResNet-50 trainer,
    example/ResNet50/main.py:258-264) — and a raw state_dict."""
    import torch  # lazy: converters themselves are torch-free

    ckpt = torch.load(path, map_location="cpu", weights_only=True)
    sd = ckpt
    if isinstance(ckpt, dict):
        for key in ("state_dict", "model"):
            if key in ckpt and isinstance(ckpt[key], dict):
                sd = ckpt[key]
                break
    return strip_module_prefix(sd)


def assert_compatible(converted: dict, init_vars: Mapping[str, Any]) -> None:
    """Raise a named error if a converted tree does not match the target
    model's freshly initialized variables (params + batch_stats) in
    structure and leaf shapes — an arch/num-classes mismatch must fail at
    import time, not deep inside the first sharded step."""
    import jax

    def _shape(leaf):
        # works for arrays AND jax.eval_shape's ShapeDtypeStructs
        return tuple(getattr(leaf, "shape", None) or np.shape(leaf))

    for col in ("params", "batch_stats"):
        want = jax.tree_util.tree_flatten_with_path(init_vars[col])[0]
        got = jax.tree_util.tree_flatten_with_path(converted[col])[0]
        want_map = {jax.tree_util.keystr(p): _shape(l) for p, l in want}
        got_map = {jax.tree_util.keystr(p): _shape(l) for p, l in got}
        if set(want_map) != set(got_map):
            missing = sorted(set(want_map) - set(got_map))
            extra = sorted(set(got_map) - set(want_map))
            raise ValueError(
                f"imported checkpoint does not match the model's {col} "
                f"tree (wrong --arch?): missing={missing[:5]} "
                f"extra={extra[:5]}")
        for key, shape in want_map.items():
            if got_map[key] != shape:
                raise ValueError(
                    f"imported {col}{key} has shape {got_map[key]}, model "
                    f"expects {shape} (wrong --arch/--num-classes?)")


def _bn_into(tree_params, tree_stats, name, sd, prefix):
    p, s = convert_bn(sd, prefix)
    tree_params[name] = p
    tree_stats[name] = s


def import_reference_resnet18_cifar(sd: Mapping[str, Any]) -> dict:
    """Reference CIFAR ResNet-18 state_dict -> variables for
    `models.resnet18_cifar()`.

    Key map (reference resnet18_cifar.py:48-87 builds everything from
    nn.Sequential, so children are numeric):
        conv1.0 / conv1.1                -> stem_conv / stem_bn
        layer{s}.{b}.left.0/.1/.3/.4     -> layer{s}_block{b}.conv1/bn1/conv2/bn2
        layer{s}.{b}.shortcut.0/.1       -> layer{s}_block{b}.shortcut_conv/_bn
        fc                               -> fc
    """
    sd = strip_module_prefix(sd)
    params: dict = {"stem_conv": {"kernel": convert_conv(sd["conv1.0.weight"])}}
    stats: dict = {}
    _bn_into(params, stats, "stem_bn", sd, "conv1.1")

    for stage in range(1, 5):
        block = 0
        while f"layer{stage}.{block}.left.0.weight" in sd:
            src = f"layer{stage}.{block}"
            dst = f"layer{stage}_block{block}"
            bp: dict = {
                "conv1": {"kernel": convert_conv(sd[f"{src}.left.0.weight"])},
                "conv2": {"kernel": convert_conv(sd[f"{src}.left.3.weight"])},
            }
            bs: dict = {}
            _bn_into(bp, bs, "bn1", sd, f"{src}.left.1")
            _bn_into(bp, bs, "bn2", sd, f"{src}.left.4")
            if f"{src}.shortcut.0.weight" in sd:
                bp["shortcut_conv"] = {
                    "kernel": convert_conv(sd[f"{src}.shortcut.0.weight"])}
                _bn_into(bp, bs, "shortcut_bn", sd, f"{src}.shortcut.1")
            params[dst] = bp
            stats[dst] = bs
            block += 1
        if block == 0:
            raise KeyError(f"layer{stage} missing from state_dict")

    params["fc"] = {"kernel": convert_linear(sd["fc.weight"]),
                    "bias": _np(sd["fc.bias"])}
    return {"params": params, "batch_stats": stats}


def import_torchvision_resnet(sd: Mapping[str, Any]) -> dict:
    """torchvision-style ResNet state_dict (resnet18/34/50/101 — the
    flagship `torchvision.models.resnet50()`, main.py:67) -> variables for
    the matching `models.resnet{18,34,50,101}()`.

    Key map:
        conv1 / bn1                       -> stem_conv / stem_bn
        layer{s}.{b}.conv{i}/bn{i}        -> layer{s}_block{b}.conv{i}/bn{i}
        layer{s}.{b}.downsample.0/.1      -> layer{s}_block{b}.downsample_conv/_bn
        fc                                -> fc
    """
    sd = strip_module_prefix(sd)
    params: dict = {"stem_conv": {"kernel": convert_conv(sd["conv1.weight"])}}
    stats: dict = {}
    _bn_into(params, stats, "stem_bn", sd, "bn1")

    for stage in range(1, 5):
        block = 0
        while f"layer{stage}.{block}.conv1.weight" in sd:
            src = f"layer{stage}.{block}"
            dst = f"layer{stage}_block{block}"
            bp: dict = {}
            bs: dict = {}
            conv = 1
            while f"{src}.conv{conv}.weight" in sd:
                bp[f"conv{conv}"] = {
                    "kernel": convert_conv(sd[f"{src}.conv{conv}.weight"])}
                _bn_into(bp, bs, f"bn{conv}", sd, f"{src}.bn{conv}")
                conv += 1
            if f"{src}.downsample.0.weight" in sd:
                bp["downsample_conv"] = {
                    "kernel": convert_conv(sd[f"{src}.downsample.0.weight"])}
                _bn_into(bp, bs, "downsample_bn", sd, f"{src}.downsample.1")
            params[dst] = bp
            stats[dst] = bs
            block += 1
        if block == 0:
            raise KeyError(f"layer{stage} missing from state_dict")

    params["fc"] = {"kernel": convert_linear(sd["fc.weight"]),
                    "bias": _np(sd["fc.bias"])}
    return {"params": params, "batch_stats": stats}
