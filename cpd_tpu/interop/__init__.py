"""Interop: import reference (CPDtorch/torchvision) checkpoints into
cpd_tpu models, and export trained cpd_tpu models back to torch."""

from .torch_import import (assert_compatible, convert_bn, convert_conv,
                           convert_linear, import_reference_resnet18_cifar,
                           import_torchvision_resnet,
                           load_reference_checkpoint, strip_module_prefix)
from .torch_export import (export_bn, export_conv, export_linear,
                           export_reference_resnet18_cifar,
                           export_torchvision_resnet, save_torch_checkpoint)
from .torch_lm import (build_torch_lm, export_transformer_lm,
                       import_transformer_lm)

__all__ = [
    "assert_compatible", "convert_bn", "convert_conv", "convert_linear",
    "import_reference_resnet18_cifar", "import_torchvision_resnet",
    "load_reference_checkpoint", "strip_module_prefix",
    "export_bn", "export_conv", "export_linear",
    "export_reference_resnet18_cifar", "export_torchvision_resnet",
    "save_torch_checkpoint",
    "build_torch_lm", "export_transformer_lm", "import_transformer_lm",
]
