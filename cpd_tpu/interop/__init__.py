"""Interop: import reference (CPDtorch/torchvision) checkpoints into
cpd_tpu models."""

from .torch_import import (assert_compatible, convert_bn, convert_conv,
                           convert_linear, import_reference_resnet18_cifar,
                           import_torchvision_resnet,
                           load_reference_checkpoint, strip_module_prefix)

__all__ = [
    "assert_compatible", "convert_bn", "convert_conv", "convert_linear",
    "import_reference_resnet18_cifar", "import_torchvision_resnet",
    "load_reference_checkpoint", "strip_module_prefix",
]
