"""Torch interop for the transformer-LM family (round 5).

The resnet importers/exporters cover the reference's CNN workloads
(torch_import/torch_export); this module completes the migration story
for the beyond-reference LM: a user who trains a `TransformerLM` here
and must hand it to a torch consumer (serving stack, ONNX-via-torch,
torch-side evaluation) gets

  * `export_transformer_lm(variables, ...)` — flax params -> a torch
    state_dict (plain `weight`/`bias` keys);
  * `TorchTransformerLM` — the "modeling file": a faithful torch
    `nn.Module` mirror of `models/transformer.py` (RoPE, pre-LN blocks
    with eps=1e-6, head-major fused qkv / GQA split projections, fp32
    softmax with the same -1e30 mask value, tanh-approx GELU, tied
    embedding head) that `load_state_dict(strict=True)`s the exported
    dict and reproduces the flax logits to fp32 tolerance
    (tests/test_interop.py);
  * `import_transformer_lm(sd, ...)` — the inverse, for bringing a
    torch-trained checkpoint of the same architecture in;
    `import(export(v))` round-trips bitwise (tested).

Layout rules follow torch_import/torch_export: flax Dense kernel (I, O)
<-> Linear weight (O, I); LayerNorm scale/bias <-> weight/bias;
Embedding rows as-is.  Both the unrolled (`block{i}`) and
`scan_layers` (stacked leading-axis) flax layouts are handled on
export/import; the state_dict is always per-layer (`blocks.{i}.*`).

Reference: the reference has no LM (SURVEY.md §5); this extends its
C18-C20 torch-interop contract (docs/MIGRATING.md) to the LM family.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

import numpy as np

from .torch_export import export_linear
from .torch_import import convert_linear

__all__ = ["export_transformer_lm", "import_transformer_lm",
           "build_torch_lm"]

_BLOCK_LINEARS_MHA = ("wqkv", "wo", "wi", "wo_mlp")
_BLOCK_LINEARS_GQA = ("wq", "wkv", "wo", "wi", "wo_mlp")


def _np32(x) -> np.ndarray:
    return np.asarray(x, np.float32)


def _layer_params(params: Mapping[str, Any], i: int) -> Mapping[str, Any]:
    """Layer i's param subtree in either flax layout (block{i} unrolled
    or 'blocks' stacked-by-nn.scan)."""
    if f"block{i}" in params:
        return params[f"block{i}"]
    if "blocks" in params:
        import jax

        return jax.tree.map(lambda l: l[i], params["blocks"])
    raise KeyError(f"no block{i} / blocks entry in params "
                   f"(keys: {sorted(params)})")


def _n_layers(params: Mapping[str, Any]) -> int:
    if "blocks" in params:
        import jax

        return int(jax.tree.leaves(params["blocks"])[0].shape[0])
    return sum(1 for k in params if k.startswith("block")
               and k[5:].isdigit())


def export_transformer_lm(variables: Mapping[str, Any]) -> dict:
    """TransformerLM flax variables -> torch state_dict (numpy fp32
    values; wrap with `save_torch_checkpoint` to write a .pth)."""
    params = variables.get("params", variables)
    out: dict = {"embed.weight": _np32(params["embed"]["embedding"])}
    n = _n_layers(params)
    for i in range(n):
        blk = _layer_params(params, i)
        p = f"blocks.{i}."
        gqa = "wq" in blk
        for ln in ("ln1", "ln2"):
            out[p + ln + ".weight"] = _np32(blk[ln]["scale"])
            out[p + ln + ".bias"] = _np32(blk[ln]["bias"])
        names = _BLOCK_LINEARS_GQA if gqa else _BLOCK_LINEARS_MHA
        for w in names:
            out[p + w + ".weight"] = export_linear(blk[w]["kernel"])
    out["ln_f.weight"] = _np32(params["ln_f"]["scale"])
    out["ln_f.bias"] = _np32(params["ln_f"]["bias"])
    return out


def import_transformer_lm(sd: Mapping[str, Any]) -> dict:
    """torch state_dict (this module's layout) -> {"params": ...} in the
    unrolled flax layout; exact inverse of `export_transformer_lm`."""
    sd = {k: np.asarray(v) for k, v in sd.items()}
    params: dict = {"embed": {"embedding": _np32(sd["embed.weight"])},
                    "ln_f": {"scale": _np32(sd["ln_f.weight"]),
                             "bias": _np32(sd["ln_f.bias"])}}
    n = 1 + max((int(k.split(".")[1]) for k in sd
                 if k.startswith("blocks.")), default=-1)
    for i in range(n):
        p = f"blocks.{i}."
        gqa = p + "wq.weight" in sd
        blk: dict = {}
        for ln in ("ln1", "ln2"):
            blk[ln] = {"scale": _np32(sd[p + ln + ".weight"]),
                       "bias": _np32(sd[p + ln + ".bias"])}
        names = _BLOCK_LINEARS_GQA if gqa else _BLOCK_LINEARS_MHA
        for w in names:
            blk[w] = {"kernel": convert_linear(sd[p + w + ".weight"])}
        params[f"block{i}"] = blk
    return {"params": params}


def build_torch_lm(vocab_size: int, d_model: int, n_layers: int,
                   n_heads: int, d_ff: Optional[int] = None,
                   n_kv_heads: Optional[int] = None):
    """The torch mirror of `models/transformer.py` TransformerLM
    (non-decode forward path; eval semantics — no dropout).

    Defined inside a builder so importing cpd_tpu never requires torch;
    returns an un-initialized module — `load_state_dict` it from
    `export_transformer_lm`'s output.
    """
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    d_ff = d_ff or 4 * d_model
    head_dim = d_model // n_heads

    half = head_dim // 2

    def rope_tables(t: int, device) -> tuple:
        # _rope (transformer.py:42-53) — computed ONCE per forward on
        # the input's device and shared by every block's q and k
        freqs = torch.exp(
            -torch.arange(half, dtype=torch.float32, device=device)
            * (np.log(10000.0) / half))
        angles = (torch.arange(t, dtype=torch.float32,
                               device=device)[:, None] * freqs[None, :])
        return (torch.cos(angles)[None, :, None, :],
                torch.sin(angles)[None, :, None, :])

    def rope(x: torch.Tensor, cos: torch.Tensor,
             sin: torch.Tensor) -> torch.Tensor:
        # (B, T, H, D), half-split layout
        x1, x2 = x[..., :half], x[..., half:]
        return torch.cat([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)

    class TorchBlock(nn.Module):
        def __init__(self):
            super().__init__()
            self.ln1 = nn.LayerNorm(d_model, eps=1e-6)
            self.ln2 = nn.LayerNorm(d_model, eps=1e-6)
            if n_kv_heads is None:
                self.wqkv = nn.Linear(d_model, 3 * d_model, bias=False)
            else:
                self.wq = nn.Linear(d_model, d_model, bias=False)
                self.wkv = nn.Linear(d_model,
                                     2 * n_kv_heads * head_dim,
                                     bias=False)
            self.wo = nn.Linear(d_model, d_model, bias=False)
            self.wi = nn.Linear(d_model, d_ff, bias=False)
            self.wo_mlp = nn.Linear(d_ff, d_model, bias=False)

        def forward(self, x, cos, sin, mask):
            h = self.ln1(x)
            if n_kv_heads is None:
                # head-major fused layout (transformer.py Block): (...,
                # n_heads, 3, head_dim) in the feature dim
                qkv = self.wqkv(h)
                qkv = qkv.reshape(*qkv.shape[:-1], n_heads, 3, head_dim)
                q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
            else:
                q = self.wq(h).reshape(*h.shape[:-1], n_heads, head_dim)
                kv = self.wkv(h).reshape(*h.shape[:-1], n_kv_heads, 2,
                                         head_dim)
                k, v = kv[..., 0, :], kv[..., 1, :]
            q = rope(q, cos, sin)
            k = rope(k, cos, sin)
            # grouped fp32 softmax attention, same mask constant as
            # ops/attention.py (_NEG_INF = -1e30)
            hkv = k.shape[2]
            rep = q.shape[2] // hkv
            b, t = q.shape[0], q.shape[1]
            qg = q.reshape(b, t, hkv, rep, head_dim)
            logits = torch.einsum("bqgrd,bkgd->bgrqk", qg.float(),
                                  k.float()) / float(head_dim) ** 0.5
            logits = torch.where(mask, logits,
                                 logits.new_tensor(-1e30))
            probs = torch.softmax(logits, dim=-1)
            attn = torch.einsum("bgrqk,bkgd->bqgrd", probs, v.float())
            attn = attn.reshape(b, t, n_heads * head_dim)
            x = x + self.wo(attn)
            h = self.ln2(x)
            return x + self.wo_mlp(F.gelu(self.wi(h),
                                          approximate="tanh"))

    class TorchTransformerLM(nn.Module):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(vocab_size, d_model)
            self.blocks = nn.ModuleList(TorchBlock()
                                        for _ in range(n_layers))
            self.ln_f = nn.LayerNorm(d_model, eps=1e-6)

        def forward(self, tokens):
            t = tokens.shape[1]
            dev = tokens.device
            cos, sin = rope_tables(t, dev)
            pos = torch.arange(t, device=dev)
            mask = (pos[:, None] >= pos[None, :])[None, None, None]
            x = self.embed(tokens)
            for blk in self.blocks:
                x = blk(x, cos, sin, mask)
            x = self.ln_f(x)
            return x @ self.embed.weight.T        # tied head

    return TorchTransformerLM()
