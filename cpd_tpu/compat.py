"""Version-bridging shims over the installed JAX.

The codebase targets the modern public surface (``jax.shard_map`` with
``check_vma=``, promoted in jax 0.6); older jaxlibs (>= 0.4.30) ship the
same primitive as ``jax.experimental.shard_map.shard_map`` with the flag
spelled ``check_rep=``.  Everything in cpd_tpu (and its tests/tools)
imports ``shard_map`` from here so the whole tree tracks one shim instead
of sprinkling try/except at every call site.

This file is the ONE sanctioned home of ``jax.experimental`` imports:
the ``compat-drift`` lint rule (docs/ANALYSIS.md) flags every use
outside it, which is the machine-checked precondition for the jax
un-pin (ROADMAP item 5) — when upstream renames or promotes an API,
exactly one file changes.  Besides ``shard_map`` that covers:

* ``pallas`` / ``pallas_tpu`` — still under jax.experimental on every
  supported jax; re-exported so the Pallas kernels (ops/) survive the
  eventual promotion to a stable namespace with a one-line edit here.
* ``multihost_utils`` — host-coordination helpers (checkpoint.py's
  preemption-flag agreement); experimental on 0.4.x.
* ``flash_attention_import()`` — the stock Pallas TPU flash kernel,
  imported LAZILY because the module pulls in TPU-kernel machinery that
  CPU-only processes (and old jaxlibs) may not have.

Stdlib-cheap rule: this module DOES import jax, so it must never be
imported from ``cpd_tpu/__init__.py`` eagerly (see the lazy-export note
there) — only from the L1/L2 modules that already depend on jax.
"""

from __future__ import annotations

__all__ = ["shard_map", "pallas", "pallas_tpu", "multihost_utils",
           "flash_attention_import"]

try:  # jax >= 0.6: public
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x/0.5.x: experimental
    from jax.experimental.shard_map import shard_map as _shard_map

class _MissingModule:
    """Placeholder for an optional surface the installed jax lacks.
    Import-time soft (every compat importer — trainers, checkpointing,
    shard_map users — must not hard-fail because Pallas moved), use-time
    loud: touching any attribute raises with the real story."""

    def __init__(self, name: str, err: Exception):
        self._name = name
        self._err = err

    def __getattr__(self, attr):
        raise ImportError(
            f"{self._name} is unavailable in the installed jax "
            f"({self._err}); cpd_tpu.compat could not locate it under "
            f"jax.experimental or a promoted spelling") from self._err


# Pallas: experimental namespace on every jax this tree currently
# supports; try the promoted spelling first so the eventual move is
# absorbed here, and degrade to a use-time error (never an import-time
# one) when neither exists — compat is imported by far more modules
# than the three Pallas kernels.
try:
    try:
        from jax import pallas  # promoted (future jax)
        from jax.pallas import tpu as pallas_tpu
    except ImportError:
        from jax.experimental import pallas
        from jax.experimental.pallas import tpu as pallas_tpu
except ImportError as _e:
    pallas = _MissingModule("pallas", _e)
    pallas_tpu = _MissingModule("pallas.tpu", _e)

try:
    from jax.experimental import multihost_utils
except ImportError as _e:
    multihost_utils = _MissingModule("multihost_utils", _e)


def flash_attention_import():
    """The stock Pallas TPU flash-attention kernel, resolved lazily.

    Returns the ``flash_attention`` callable.  Lazy because importing
    the kernel module is heavyweight and TPU-flavored; callers
    (ops/attention.py's ``impl="flash"`` path) only reach it when the
    user explicitly asks for the stock kernel."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention)
    return flash_attention


def _check_kw() -> str:
    """The replication-check flag's spelling in the installed JAX.

    Probed from the function's signature, not from which import
    succeeded — the public promotion of shard_map and the
    check_rep -> check_vma rename landed in different jax releases."""
    import inspect
    try:
        params = inspect.signature(_shard_map).parameters
    except (TypeError, ValueError):
        return "check_rep"  # unsignaturable wrapper: assume the old name
    return "check_vma" if "check_vma" in params else "check_rep"


_CHECK_KW = _check_kw()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` with the replication-check flag translated.

    Accepts the modern ``check_vma=`` spelling and forwards it under
    whatever name the installed JAX uses.  All other keywords pass
    through untouched."""
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
