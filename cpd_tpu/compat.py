"""Version-bridging shims over the installed JAX.

The codebase targets the modern public surface (``jax.shard_map`` with
``check_vma=``, promoted in jax 0.6); older jaxlibs (>= 0.4.30) ship the
same primitive as ``jax.experimental.shard_map.shard_map`` with the flag
spelled ``check_rep=``.  Everything in cpd_tpu (and its tests/tools)
imports ``shard_map`` from here so the whole tree tracks one shim instead
of sprinkling try/except at every call site.

Stdlib-cheap rule: this module DOES import jax, so it must never be
imported from ``cpd_tpu/__init__.py`` eagerly (see the lazy-export note
there) — only from the L1/L2 modules that already depend on jax.
"""

from __future__ import annotations

__all__ = ["shard_map"]

try:  # jax >= 0.6: public
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x/0.5.x: experimental
    from jax.experimental.shard_map import shard_map as _shard_map


def _check_kw() -> str:
    """The replication-check flag's spelling in the installed JAX.

    Probed from the function's signature, not from which import
    succeeded — the public promotion of shard_map and the
    check_rep -> check_vma rename landed in different jax releases."""
    import inspect
    try:
        params = inspect.signature(_shard_map).parameters
    except (TypeError, ValueError):
        return "check_rep"  # unsignaturable wrapper: assume the old name
    return "check_vma" if "check_vma" in params else "check_rep"


_CHECK_KW = _check_kw()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` with the replication-check flag translated.

    Accepts the modern ``check_vma=`` spelling and forwards it under
    whatever name the installed JAX uses.  All other keywords pass
    through untouched."""
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
