"""Load generation + serving metrics — the harness behind
`tools/bench_serve.py` and bench.py's ``serving`` block.

Traces are **step-indexed**, not wall-clock-indexed: a request's
``arrival`` is the engine step at which the load generator makes it
visible.  That keeps every run of a (seed, trace) pair bit-reproducible
— the scheduler's admissions, the chunk interleave, the sampled tokens
and all engine counters replay exactly (the serve-smoke determinism
gate) — while latency METRICS are still measured in wall time (TTFT =
first-token wall time minus the wall time at which the arrival step
began).

Reported metrics (the `bench.py` ``serving`` block schema):

* ``tok_per_s`` — generated tokens / wall duration of the drained trace;
* ``ttft_ms`` p50/p99 — time-to-first-token per request;
* ``tpot_ms`` p50/p99 — per-token latency after the first token;
* ``goodput_tok_per_s`` — generated tokens of only the requests meeting
  the SLA (TTFT <= ``sla_ttft_ms`` AND per-token <= ``sla_tpot_ms``)
  over the same duration — the number that actually answers "how much
  traffic is being served *well*";
* the engine counter dict, verbatim.

`serial_baseline` replays the same trace through sequential
`models.generate` calls (batch 1, the pre-serve inference surface) —
the continuous-batching speedup gate compares aggregate tok/s.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from .scheduler import Request

__all__ = ["poisson_trace", "bursty_trace", "mixed_trace", "run_trace",
           "serial_baseline"]


def poisson_trace(n_requests: int, vocab_size: int, *,
                  rate: float = 0.5, prompt_lens: Sequence[int] = (4, 8),
                  max_new: Sequence[int] = (8,), seed: int = 0,
                  eos_id: Optional[int] = None) -> list:
    """Poisson arrivals: exponential inter-arrival gaps (mean ``1/rate``
    engine steps), prompt/response sizes drawn from the given small sets
    (small ON PURPOSE: the serial baseline compiles one program per
    distinct (prompt_len, max_new) pair)."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for rid in range(n_requests):
        t += rng.exponential(1.0 / rate)
        out.append(Request(
            rid=rid,
            prompt=tuple(int(x) for x in rng.integers(
                0, vocab_size, int(rng.choice(list(prompt_lens))))),
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=int(t), eos_id=eos_id))
    return out


def bursty_trace(n_requests: int, vocab_size: int, *,
                 burst: int = 4, gap: int = 8,
                 prompt_lens: Sequence[int] = (4, 8),
                 max_new: Sequence[int] = (8,), seed: int = 0,
                 eos_id: Optional[int] = None) -> list:
    """Bursty arrivals: ``burst`` requests land simultaneously every
    ``gap`` steps — the flash-crowd shape that stresses admission and
    page reservation hardest."""
    rng = np.random.default_rng(seed)
    out = []
    for rid in range(n_requests):
        out.append(Request(
            rid=rid,
            prompt=tuple(int(x) for x in rng.integers(
                0, vocab_size, int(rng.choice(list(prompt_lens))))),
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=(rid // burst) * gap, eos_id=eos_id))
    return out


def mixed_trace(n_requests: int, vocab_size: int, *,
                prompt_lens: Sequence[int] = (4, 8, 12),
                max_new: Sequence[int] = (8,), seed: int = 0,
                eos_id: Optional[int] = None) -> list:
    """The acceptance-gate trace shape: a Poisson steady stream for the
    first half, then a flash-crowd burst landing on top of it — request
    ids stay globally unique and arrivals sorted."""
    half = n_requests // 2
    steady = poisson_trace(half, vocab_size, rate=2.0,
                           prompt_lens=prompt_lens, max_new=max_new,
                           seed=seed, eos_id=eos_id)
    crowd = bursty_trace(n_requests - half, vocab_size, burst=4, gap=3,
                         prompt_lens=prompt_lens, max_new=max_new,
                         seed=seed + 1, eos_id=eos_id)
    mid = steady[half // 2].arrival if steady else 0
    out = list(steady)
    for r in crowd:
        out.append(Request(rid=half + r.rid, prompt=r.prompt,
                           max_new_tokens=r.max_new_tokens,
                           arrival=r.arrival + mid, eos_id=r.eos_id))
    return sorted(out, key=lambda r: (r.arrival, r.rid))


def _pct(values: list, q: float) -> Optional[float]:
    return round(float(np.percentile(values, q)), 3) if values else None


def run_trace(engine, requests: list, *, sla_ttft_ms: float = 1000.0,
              sla_tpot_ms: float = 250.0,
              max_steps: int = 100000) -> dict:
    """Drive ``engine`` through ``requests`` until drained; -> metrics."""
    for r in requests:
        engine.submit(r)
    step_wall = {}
    t0 = time.monotonic()
    while not engine.drained():
        if engine.step_index >= max_steps:
            raise RuntimeError(f"trace not drained in {max_steps} steps")
        step_wall[engine.step_index] = time.monotonic()
        engine.step()
    duration = time.monotonic() - t0
    engine.report_unfired()

    first, done = {}, {}
    for kind, rid, _step, wall in engine.events:
        if kind == "first_token":
            first[rid] = wall
        elif kind == "complete":
            done[rid] = wall
    ttft, tpot, good_tokens = [], [], 0
    for r in requests:
        n_gen = len(engine.finished.get(r.rid, ()))
        if r.rid not in first:
            continue
        t_first = (first[r.rid] - step_wall[r.arrival]) * 1e3
        ttft.append(t_first)
        t_tok = None
        if r.rid in done and n_gen > 1:
            t_tok = (done[r.rid] - first[r.rid]) * 1e3 / (n_gen - 1)
            tpot.append(t_tok)
        if t_first <= sla_ttft_ms and (t_tok is None
                                       or t_tok <= sla_tpot_ms):
            good_tokens += n_gen

    gen = engine.counters["tokens_generated"]
    return {
        "requests": len(requests),
        "completed": engine.counters["completed"],
        "dropped": len(requests) - engine.counters["completed"],
        "engine_steps": engine.step_index,
        "duration_s": round(duration, 3),
        "tok_per_s": round(gen / duration, 1) if duration else None,
        "ttft_ms_p50": _pct(ttft, 50), "ttft_ms_p99": _pct(ttft, 99),
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "goodput_tok_per_s": (round(good_tokens / duration, 1)
                              if duration else None),
        "sla": {"ttft_ms": sla_ttft_ms, "tpot_ms": sla_tpot_ms},
        "counters": dict(engine.counters),
    }


def serial_baseline(model, params, requests: list, *,
                    warm: bool = True) -> dict:
    """The same trace through sequential batch-1 `generate` calls — the
    repo's pre-serve inference surface.  ``warm=True`` runs the trace
    once first so every (prompt_len, max_new) program is compiled before
    the measured pass (the engine gets the same courtesy from its warmup
    trace run)."""
    import jax.numpy as jnp

    from ..models.generate import generate

    def one_pass() -> int:
        toks = 0
        for r in requests:
            prompt = jnp.asarray([list(r.prompt)], jnp.int32)
            out = generate(model, params, prompt, r.max_new_tokens,
                           eos_id=r.eos_id)
            out.block_until_ready()
            # count like the engine does: tokens up to AND INCLUDING the
            # first eos (generate freezes after it — the frozen repeats
            # are not useful work and must not pad the baseline's tok/s)
            new = np.asarray(out)[0, len(r.prompt):]
            if r.eos_id is not None and (new == r.eos_id).any():
                toks += int(np.argmax(new == r.eos_id)) + 1
            else:
                toks += r.max_new_tokens
        return toks

    if warm:
        one_pass()
    t0 = time.monotonic()
    n = one_pass()
    duration = time.monotonic() - t0
    return {"tok_per_s": round(n / duration, 1) if duration else None,
            "duration_s": round(duration, 3), "tokens": n}
