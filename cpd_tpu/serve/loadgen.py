"""Load generation + serving metrics — the harness behind
`tools/bench_serve.py` and bench.py's ``serving`` block.

Traces are **step-indexed**, not wall-clock-indexed: a request's
``arrival`` is the engine step at which the load generator makes it
visible.  That keeps every run of a (seed, trace) pair bit-reproducible
— the scheduler's admissions, the chunk interleave, the sampled tokens
and all engine counters replay exactly (the serve-smoke determinism
gate) — while latency METRICS are still measured in wall time (TTFT =
first-token wall time minus the wall time at which the arrival step
began).

`run_trace` submits each request AT its arrival step (not all
up-front): the ACCEPT/QUEUE/SHED admission verdicts (ISSUE 10) are
computed against the LIVE backlog, which is what the structural TTFT
bound prices — submitting the whole future trace at step 0 would make
every later request look provably late.  For arrival-sorted traces
with no SLA fields this is behaviourally identical to the old
submit-everything-first driver (admission was already arrival-gated).
``burst_factory`` wires the ``req_burst@s:k`` chaos kind: each step the
driver pops the engine's due burst specs (`ServeEngine.take_due_bursts`)
and submits the factory's flash crowd — the burst is keyed into the
fault plan, so it replays deterministically.

Reported metrics (the `bench.py` ``serving`` block schema):

* ``tok_per_s`` — generated tokens / wall duration of the drained trace;
* ``ttft_ms`` p50/p99 — time-to-first-token per request;
* ``tpot_ms`` p50/p99 — per-token latency after the first token;
* ``goodput_tok_per_s`` — generated tokens of only the requests meeting
  the SLA (TTFT <= ``sla_ttft_ms`` AND per-token <= ``sla_tpot_ms``)
  over the same duration — the number that actually answers "how much
  traffic is being served *well*" — plus ``goodput_by_class`` (the same
  split per ``sla_class``);
* ``shed_rate`` / ``deadline_miss_rate`` — shed and cancelled fractions
  of everything submitted (trace + bursts) — the overload-frontier
  axes `tools/bench_serve.py --overload-sweep` tabulates;
* ``dropped`` — SILENT drops: submissions resolved by none of
  FINISHED/SHED/DEADLINE_MISS.  Zero is the structural contract.
* the engine counter dict, verbatim.

The per-request metrics (ttft/tpot percentiles, goodput splits) derive
from the engine's TIMELINE when a tracer is attached (ISSUE 13: a
``finished`` entry the bounded `ResultStore` evicted mid-run still has
its ``complete`` event in the timeline, so `timeline_metrics`'s
reconstruction stays float-for-float even with the store held at cap);
only a saturated tracer ring then truncates them.  Without a tracer
they read the BOUNDED stores, so a trace longer than ``finished_cap``
covers only the retained window.  Truncation is never silent either
way — ``metrics_truncated`` flags it (counter-derived numbers: tok/s,
counts, shed/miss rates stay exact regardless).

`serial_baseline` replays the same trace through sequential
`models.generate` calls (batch 1, the pre-serve inference surface) —
the continuous-batching speedup gate compares aggregate tok/s.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as np

from ..obs.timing import Stopwatch, now
from .scheduler import Request

__all__ = ["poisson_trace", "bursty_trace", "mixed_trace", "with_sla",
           "flash_crowd", "run_trace", "serial_baseline",
           "decode_tail_matches", "timeline_metrics",
           "shared_prefix_trace", "run_fleet_trace"]


def decode_tail_matches(original, mark: int, restored) -> int:
    """The ONE snapshot-restore comparison contract (shared by the
    tests, the serve-smoke gate and bench.py's serving block): the
    restored engine's full ``logits_log`` must reproduce the original's
    entries from index ``mark`` on — same (rid, position) schedule and
    BITWISE-identical logit rows — and the two engines must agree on
    ``finished`` and ``counters``.  Returns the compared row count
    (> 0; an empty tail would make the gate vacuous); raises ValueError
    naming the first divergence otherwise.  Both engines need
    ``record_logits=True`` and to be drained."""
    tail = original.logits_log[mark:]
    if len(tail) != len(restored.logits_log) or not tail:
        raise ValueError(
            f"restored decode stream length {len(restored.logits_log)} "
            f"!= original tail {len(tail)} (empty tails are vacuous)")
    for (ra, pa, la), (rb, pb, lb) in zip(tail, restored.logits_log):
        if (ra, pa) != (rb, pb):
            raise ValueError(f"restored decode schedule diverged: "
                             f"(rid {ra}, pos {pa}) vs (rid {rb}, "
                             f"pos {pb})")
        if not (la.view(np.uint32) == lb.view(np.uint32)).all():
            raise ValueError(f"restored logits not bitwise identical "
                             f"at rid={ra} pos={pa}")
    if original.finished != restored.finished:
        raise ValueError("restored `finished` store differs")
    if original.counters != restored.counters:
        raise ValueError("restored counters differ")
    return len(tail)


def poisson_trace(n_requests: int, vocab_size: int, *,
                  rate: float = 0.5, prompt_lens: Sequence[int] = (4, 8),
                  max_new: Sequence[int] = (8,), seed: int = 0,
                  eos_id: Optional[int] = None) -> list:
    """Poisson arrivals: exponential inter-arrival gaps (mean ``1/rate``
    engine steps), prompt/response sizes drawn from the given small sets
    (small ON PURPOSE: the serial baseline compiles one program per
    distinct (prompt_len, max_new) pair)."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for rid in range(n_requests):
        t += rng.exponential(1.0 / rate)
        out.append(Request(
            rid=rid,
            prompt=tuple(int(x) for x in rng.integers(
                0, vocab_size, int(rng.choice(list(prompt_lens))))),
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=int(t), eos_id=eos_id))
    return out


def bursty_trace(n_requests: int, vocab_size: int, *,
                 burst: int = 4, gap: int = 8,
                 prompt_lens: Sequence[int] = (4, 8),
                 max_new: Sequence[int] = (8,), seed: int = 0,
                 eos_id: Optional[int] = None) -> list:
    """Bursty arrivals: ``burst`` requests land simultaneously every
    ``gap`` steps — the flash-crowd shape that stresses admission and
    page reservation hardest."""
    rng = np.random.default_rng(seed)
    out = []
    for rid in range(n_requests):
        out.append(Request(
            rid=rid,
            prompt=tuple(int(x) for x in rng.integers(
                0, vocab_size, int(rng.choice(list(prompt_lens))))),
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=(rid // burst) * gap, eos_id=eos_id))
    return out


def mixed_trace(n_requests: int, vocab_size: int, *,
                prompt_lens: Sequence[int] = (4, 8, 12),
                max_new: Sequence[int] = (8,), seed: int = 0,
                eos_id: Optional[int] = None) -> list:
    """The acceptance-gate trace shape: a Poisson steady stream for the
    first half, then a flash-crowd burst landing on top of it — request
    ids stay globally unique and arrivals sorted."""
    half = n_requests // 2
    steady = poisson_trace(half, vocab_size, rate=2.0,
                           prompt_lens=prompt_lens, max_new=max_new,
                           seed=seed, eos_id=eos_id)
    crowd = bursty_trace(n_requests - half, vocab_size, burst=4, gap=3,
                         prompt_lens=prompt_lens, max_new=max_new,
                         seed=seed + 1, eos_id=eos_id)
    mid = steady[half // 2].arrival if steady else 0
    out = list(steady)
    for r in crowd:
        out.append(Request(rid=half + r.rid, prompt=r.prompt,
                           max_new_tokens=r.max_new_tokens,
                           arrival=r.arrival + mid, eos_id=r.eos_id))
    return sorted(out, key=lambda r: (r.arrival, r.rid))


def with_sla(requests: Sequence[Request], classes: Sequence[dict]) -> list:
    """Stamp SLA fields onto a trace: request ``i`` gets
    ``classes[i % len(classes)]``, each a dict of any of ``sla_class``,
    ``deadline_steps``, ``tpot_budget_steps`` — e.g.

        with_sla(trace, [dict(sla_class=0, deadline_steps=8),
                         dict(sla_class=1)])

    alternates premium deadline-bound traffic with best-effort."""
    if not classes:
        raise ValueError("with_sla needs at least one class dict")
    return [dataclasses.replace(r, **classes[i % len(classes)])
            for i, r in enumerate(requests)]


def flash_crowd(vocab_size: int, *, start_rid: int = 1_000_000,
                prompt_lens: Sequence[int] = (4, 8),
                max_new: Sequence[int] = (8,), seed: int = 0,
                sla: Optional[dict] = None,
                eos_id: Optional[int] = None) -> Callable:
    """A ``burst_factory`` for `run_trace`: given a fired
    ``req_burst@s:k`` spec it returns ``k`` (default 4) requests
    arriving at step ``s`` — rids allocated from ``start_rid`` up (far
    above trace rids), sizes drawn from a dedicated deterministic
    stream so the crowd is identical every replay."""
    rng = np.random.default_rng(seed)
    next_rid = [start_rid]

    def factory(spec) -> list:
        k = int(spec.arg) if spec.arg > 0 else 4
        out = []
        for _ in range(k):
            kw = dict(sla or {})
            out.append(Request(
                rid=next_rid[0],
                prompt=tuple(int(x) for x in rng.integers(
                    0, vocab_size,
                    int(rng.choice(list(prompt_lens))))),
                max_new_tokens=int(rng.choice(list(max_new))),
                arrival=spec.step, eos_id=eos_id, **kw))
            next_rid[0] += 1
        return out

    return factory


def _pct(values: list, q: float) -> Optional[float]:
    return round(float(np.percentile(values, q)), 3) if values else None


def timeline_metrics(tracer, *, sla_ttft_ms: float = 1000.0,
                     sla_tpot_ms: float = 250.0) -> dict:
    """Reconstruct the serving latency metrics from an `obs.Tracer`'s
    per-request timeline ALONE — no engine, no stores (ISSUE 11
    acceptance gate: on a drained, non-truncated `run_trace(engine
    with tracer=...)` run the reconstructed TTFT/TPOT percentiles,
    goodput and verdict/resolution counts equal the published metrics
    EXACTLY, float for float).

    The equality is structural, not approximate: the engine records
    each event's wall time once (`ServeEngine._event`) and hands the
    same float to both its host event log (which `run_trace` reads)
    and the tracer; `run_trace` likewise records its per-step wall
    into the tracer (``step_begin``).  Reconstruction then repeats the
    identical arithmetic on the identical floats.

    Parity holds even when the bounded `ResultStore` evicted finished
    entries mid-trace (ISSUE 13 satellite — the PR 11 caveat, closed):
    `run_trace` derives its published per-request numbers from the
    SAME timeline whenever a tracer is attached, so both sides see the
    evicted rids' true ``n_generated``.  The one remaining truncation
    is a saturated tracer ring (``timeline_truncated`` flags it)."""
    step_begin: dict = {}
    submits: list = []           # (seq, rid, args) in submission order
    first: dict = {}
    done: dict = {}              # rid -> (wall, n_generated)
    counts = {"completed": 0, "shed": 0, "deadline_misses": 0}
    verdicts: dict = {}
    tokens = 0
    t0 = t_end = None
    for _seq, name, cat, step, wall, args in sorted(tracer.events):
        if cat == "serve":
            if name == "step_begin":
                step_begin[step] = wall
            elif name == "trace_begin":
                t0 = wall
            elif name == "trace_end":
                t_end = wall
            continue
        if cat != "req":
            continue
        rid = args["rid"]
        if name == "submit":
            submits.append((rid, args))
            v = args.get("verdict")
            verdicts[v] = verdicts.get(v, 0) + 1
        elif name == "first_token":
            first[rid] = wall
        elif name == "complete":
            done[rid] = (wall, args["n_generated"])
            counts["completed"] += 1
            tokens += args["n_generated"]
        elif name == "shed":
            counts["shed"] += 1
        elif name == "deadline_miss":
            counts["deadline_misses"] += 1
            tokens += args.get("partial_tokens", 0)
    ttft, tpot, good_tokens = [], [], 0
    class_tokens: dict = {}
    for rid, args in submits:
        n_gen = done[rid][1] if rid in done else 0
        if rid not in first:
            continue
        if args["arrival"] not in step_begin:
            # no step_begin for this arrival: the engine was stepped
            # manually (only run_trace records the per-step walls), or
            # the tracer ring aged the early steps out — either way a
            # silent wrong TTFT would betray the exactness contract
            raise ValueError(
                f"timeline has no step_begin for arrival step "
                f"{args['arrival']} (rid {rid}): drive the engine "
                f"through run_trace with the tracer attached, and "
                f"size Tracer(max_records=) to the trace "
                f"(events_dropped={getattr(tracer, 'events_dropped', 0)})")
        t_first = (first[rid] - step_begin[args["arrival"]]) * 1e3
        ttft.append(t_first)
        t_tok = None
        if rid in done and n_gen > 1:
            t_tok = (done[rid][0] - first[rid]) * 1e3 / (n_gen - 1)
            tpot.append(t_tok)
        if t_first <= sla_ttft_ms and (t_tok is None
                                       or t_tok <= sla_tpot_ms):
            good_tokens += n_gen
            cls = args.get("sla_class", 0)
            class_tokens[cls] = class_tokens.get(cls, 0) + n_gen
    duration = (t_end - t0) if (t0 is not None
                                and t_end is not None) else None
    n_sub = len(submits)
    return {
        "submitted": n_sub,
        "verdicts": dict(sorted(verdicts.items())),
        **counts,
        "dropped": n_sub - sum(counts.values()),
        "shed_rate": (round(counts["shed"] / n_sub, 4)
                      if n_sub else 0.0),
        "deadline_miss_rate": (round(counts["deadline_misses"] / n_sub,
                                     4) if n_sub else 0.0),
        "tokens_generated": tokens,
        "duration_s": (round(duration, 3) if duration is not None
                       else None),
        "tok_per_s": (round(tokens / duration, 1) if duration
                      else None),
        "ttft_ms_p50": _pct(ttft, 50), "ttft_ms_p99": _pct(ttft, 99),
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "goodput_tok_per_s": (round(good_tokens / duration, 1)
                              if duration else None),
        "goodput_by_class": {str(k): (round(v / duration, 1)
                                      if duration else None)
                             for k, v in sorted(class_tokens.items())},
        # honesty flag (run_trace's metrics_truncated twin): a
        # saturated tracer ring aged out early events, so the
        # reconstruction covers only the surviving window
        "timeline_truncated": getattr(tracer, "events_dropped", 0) > 0,
        "sla": {"ttft_ms": sla_ttft_ms, "tpot_ms": sla_tpot_ms},
    }


def _latency_block(submitted, first, done, n_gen_of, step_wall,
                   duration, sla_ttft_ms, sla_tpot_ms) -> dict:
    """The ONE published per-request SLA-latency computation shared by
    `run_trace` and `run_fleet_trace` (so the goodput/TTFT/TPOT
    definitions cannot drift between engine and fleet reports).
    `timeline_metrics` deliberately does NOT use this helper: it is the
    independent reconstruction the parity gate cross-checks — folding
    it in would make that gate circular."""
    ttft, tpot, good_tokens = [], [], 0
    class_tokens: dict = {}
    for r in submitted:
        n_gen = n_gen_of.get(r.rid, 0)
        if r.rid not in first or r.arrival not in step_wall:
            continue
        t_first = (first[r.rid] - step_wall[r.arrival]) * 1e3
        ttft.append(t_first)
        t_tok = None
        if r.rid in done and n_gen > 1:
            t_tok = (done[r.rid] - first[r.rid]) * 1e3 / (n_gen - 1)
            tpot.append(t_tok)
        if t_first <= sla_ttft_ms and (t_tok is None
                                       or t_tok <= sla_tpot_ms):
            good_tokens += n_gen
            class_tokens[r.sla_class] = (class_tokens.get(r.sla_class, 0)
                                         + n_gen)
    return {
        "ttft_ms_p50": _pct(ttft, 50), "ttft_ms_p99": _pct(ttft, 99),
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "goodput_tok_per_s": (round(good_tokens / duration, 1)
                              if duration else None),
        "goodput_by_class": {str(k): (round(v / duration, 1)
                                      if duration else None)
                             for k, v in sorted(class_tokens.items())},
    }


def run_trace(engine, requests: list, *, sla_ttft_ms: float = 1000.0,
              sla_tpot_ms: float = 250.0,
              burst_factory: Optional[Callable] = None,
              max_steps: int = 100000) -> dict:
    """Drive ``engine`` through ``requests`` (submitted at their arrival
    steps, module docstring) until drained; -> metrics."""
    pending = sorted(requests, key=lambda r: (r.arrival, r.rid))
    submitted = []
    step_wall = {}

    def more_work() -> bool:
        # a req_burst scheduled past the current drain point must still
        # arrive: the step clock runs until every consumed-here spec fired
        if pending or not engine.drained():
            return True
        return burst_factory is not None and engine.has_pending_bursts()

    # NULL_TRACER is falsy by design (obs.trace) — normalize it to None
    # here so the disabled path cannot select the timeline-derived
    # metrics branch below and publish empty percentiles
    tracer = getattr(engine, "tracer", None) or None
    t0 = now()
    if tracer is not None:
        tracer.event("trace_begin", cat="serve", wall=t0)
    while more_work():
        if engine.step_index >= max_steps:
            raise RuntimeError(f"trace not drained in {max_steps} steps")
        while pending and pending[0].arrival <= engine.step_index:
            r = pending.pop(0)
            engine.submit(r)
            submitted.append(r)
        if burst_factory is not None:
            for spec in engine.take_due_bursts():
                for r in burst_factory(spec):
                    engine.submit(r)
                    submitted.append(r)
        w = now()
        step_wall[engine.step_index] = w
        if tracer is not None:
            # the SAME wall float the latency metrics below subtract —
            # recording it (not a re-read of the clock) is what makes
            # `timeline_metrics`' reconstruction bit-exact
            tracer.event("step_begin", step=engine.step_index,
                         cat="serve", wall=w)
        engine.step()
    t_end = now()
    duration = t_end - t0
    if tracer is not None:
        tracer.event("trace_end", cat="serve", wall=t_end)
    engine.report_unfired()

    first, done, n_gen_of = {}, {}, {}
    if tracer is not None:
        # ISSUE 13 satellite (the PR 11 parity caveat, closed): with a
        # tracer attached the published per-request metrics derive from
        # the TIMELINE, not the bounded stores — a `finished` entry the
        # `ResultStore` evicted mid-run still has its `complete` event
        # (wall + n_generated) in the timeline, so
        # `timeline_metrics`'s reconstruction stays float-for-float
        # even with the store held at cap (regression-tested).  The
        # walls are the SAME floats either way (`ServeEngine._event`
        # hands one `now()` to both sinks).
        for _seq, name, cat, _step, wall, args in tracer.events:
            if cat != "req":
                continue
            if name == "first_token":
                first[args["rid"]] = wall
            elif name == "complete":
                done[args["rid"]] = wall
                n_gen_of[args["rid"]] = int(args["n_generated"])
    else:
        for kind, rid, _step, wall in engine.events:
            if kind == "first_token":
                first[rid] = wall
            elif kind == "complete":
                done[rid] = wall
        n_gen_of = {r.rid: len(engine.finished.get(r.rid, ()))
                    for r in submitted}
    lat = _latency_block(submitted, first, done, n_gen_of, step_wall,
                         duration, sla_ttft_ms, sla_tpot_ms)

    c = engine.counters
    gen = c["tokens_generated"]
    n_sub = c["submitted"]
    resolved = c["completed"] + c["shed"] + c["deadline_misses"]
    return {
        "requests": len(requests),
        "submitted": n_sub,
        "completed": c["completed"],
        "shed": c["shed"],
        "deadline_misses": c["deadline_misses"],
        # SILENT drops — anything submitted that resolved to none of
        # FINISHED / SHED / DEADLINE_MISS; structurally zero
        "dropped": n_sub - resolved,
        "shed_rate": round(c["shed"] / n_sub, 4) if n_sub else 0.0,
        "deadline_miss_rate": (round(c["deadline_misses"] / n_sub, 4)
                               if n_sub else 0.0),
        "engine_steps": engine.step_index,
        "duration_s": round(duration, 3),
        "tok_per_s": round(gen / duration, 1) if duration else None,
        **lat,
        # bounded honesty flag (module docstring): with a tracer the
        # per-request numbers derive from the timeline, so only a
        # SATURATED tracer ring truncates them; without one they read
        # the bounded stores, so a mid-run eviction truncates
        "metrics_truncated": (
            getattr(tracer, "events_dropped", 0) > 0
            if tracer is not None else c["results_evicted"] > 0),
        "sla": {"ttft_ms": sla_ttft_ms, "tpot_ms": sla_tpot_ms},
        "counters": dict(engine.counters),
    }


def shared_prefix_trace(n_requests: int, vocab_size: int, *,
                        n_prefixes: int = 2, prefix_len: int = 16,
                        suffix_lens: Sequence[int] = (2, 4),
                        max_new: Sequence[int] = (8,),
                        rate: float = 2.0, seed: int = 0,
                        eos_id: Optional[int] = None,
                        sla: Optional[Sequence[dict]] = None) -> list:
    """The prefix-cache workload shape (ISSUE 13): Poisson arrivals
    whose prompts share one of ``n_prefixes`` common prefixes (system
    prompts / few-shot preambles) followed by a short per-request
    suffix — the trace `tools/bench_serve.py --fleet`'s prefix-hit-rate
    sweep replays.  ``sla`` stamps classes round-robin like
    `with_sla`."""
    if n_prefixes < 1 or prefix_len < 1:
        raise ValueError(f"n_prefixes/prefix_len must be >= 1, got "
                         f"({n_prefixes}, {prefix_len})")
    rng = np.random.default_rng(seed)
    prefixes = [tuple(int(x) for x in rng.integers(0, vocab_size,
                                                   prefix_len))
                for _ in range(n_prefixes)]
    t = 0.0
    out = []
    for rid in range(n_requests):
        t += rng.exponential(1.0 / rate)
        suffix = tuple(int(x) for x in rng.integers(
            0, vocab_size, int(rng.choice(list(suffix_lens)))))
        out.append(Request(
            rid=rid, prompt=prefixes[rid % n_prefixes] + suffix,
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=int(t), eos_id=eos_id))
    return with_sla(out, list(sla)) if sla else out


def run_fleet_trace(fleet, requests: list, *,
                    sla_ttft_ms: float = 1000.0,
                    sla_tpot_ms: float = 250.0,
                    max_steps: int = 100000) -> dict:
    """`run_trace` lifted to fleet scope: submit each request at its
    arrival step through the ROUTER (`Fleet.submit`), step the fleet
    (all engines in lockstep) until drained and every pending
    ``engine_kill`` fired, and report the fleet metric set.

    Resolution counts are rid-level fleet-scope truth, not engine-
    counter sums (a request shed by one engine and completed by the
    next after a router retry counts COMPLETED; engine counters keep
    the per-engine view in ``engine_counters``).  ``dropped`` is the
    fleet-scope silent-drop count — structurally zero.  Latency walls
    merge every engine's event log (a migrated session's first token
    and completion legitimately live on different engines)."""
    pending = sorted(requests, key=lambda r: (r.arrival, r.rid))
    submitted = []
    step_wall = {}

    def more_work() -> bool:
        return bool(pending) or not fleet.drained() \
            or fleet.has_pending_faults()

    t0 = now()
    while more_work():
        if fleet.step_index >= max_steps:
            raise RuntimeError(
                f"fleet trace not drained in {max_steps} steps")
        while pending and pending[0].arrival <= fleet.step_index:
            r = pending.pop(0)
            fleet.submit(r)
            submitted.append(r)
        step_wall[fleet.step_index] = now()
        fleet.step()
    duration = now() - t0
    fleet.report_unfired()

    first, done, n_gen_of = {}, {}, {}
    for e in fleet.engines:
        for kind, rid, _step, wall in e.events:
            if kind == "first_token":
                first[rid] = wall
            elif kind == "complete":
                done[rid] = wall
        for rid, toks in e.finished.items():
            n_gen_of[rid] = len(toks)

    lat = _latency_block(submitted, first, done, n_gen_of, step_wall,
                         duration, sla_ttft_ms, sla_tpot_ms)
    agg = fleet.aggregate_counters()
    n_sub = fleet.counters["submitted"]
    # fleet-scope resolution from COUNTERS, not the bounded stores
    # (eviction-immune, run_trace's discipline): a rid completes and
    # deadline-misses at most once however it moves; every router
    # retry leaves exactly one extra engine-level shed record for a
    # rid that resolved elsewhere, so subtracting retries yields the
    # rid-level shed count
    completed = agg.get("completed", 0)
    misses = agg.get("deadline_misses", 0)
    shed = agg.get("shed", 0) - fleet.counters["router_retries"]
    resolved = completed + shed + misses
    gen = agg.get("tokens_generated", 0)
    return {
        "n_engines": fleet.n_engines,
        "requests": len(requests),
        "submitted": n_sub,
        "completed": completed,
        "shed": shed,
        "deadline_misses": misses,
        "dropped": n_sub - resolved,       # fleet-scope SILENT drops
        "shed_rate": round(shed / n_sub, 4) if n_sub else 0.0,
        "deadline_miss_rate": (round(misses / n_sub, 4)
                               if n_sub else 0.0),
        "fleet_steps": fleet.step_index,
        "duration_s": round(duration, 3),
        "tok_per_s": round(gen / duration, 1) if duration else None,
        **lat,
        "metrics_truncated": agg.get("results_evicted", 0) > 0,
        "sla": {"ttft_ms": sla_ttft_ms, "tpot_ms": sla_tpot_ms},
        "fleet_counters": dict(fleet.counters),
        "engine_counters": [dict(e.counters) for e in fleet.engines],
    }


def serial_baseline(model, params, requests: list, *,
                    warm: bool = True) -> dict:
    """The same trace through sequential batch-1 `generate` calls — the
    repo's pre-serve inference surface.  ``warm=True`` runs the trace
    once first so every (prompt_len, max_new) program is compiled before
    the measured pass (the engine gets the same courtesy from its warmup
    trace run)."""
    import jax.numpy as jnp

    from ..models.generate import generate

    def one_pass() -> int:
        toks = 0
        for r in requests:
            prompt = jnp.asarray([list(r.prompt)], jnp.int32)
            out = generate(model, params, prompt, r.max_new_tokens,
                           eos_id=r.eos_id)
            out.block_until_ready()
            # count like the engine does: tokens up to AND INCLUDING the
            # first eos (generate freezes after it — the frozen repeats
            # are not useful work and must not pad the baseline's tok/s)
            new = np.asarray(out)[0, len(r.prompt):]
            if r.eos_id is not None and (new == r.eos_id).any():
                toks += int(np.argmax(new == r.eos_id)) + 1
            else:
                toks += r.max_new_tokens
        return toks

    if warm:
        one_pass()
    watch = Stopwatch()
    n = one_pass()
    duration = watch.elapsed()
    return {"tok_per_s": round(n / duration, 1) if duration else None,
            "duration_s": round(duration, 3), "tokens": n}
