"""Load generation + serving metrics — the harness behind
`tools/bench_serve.py` and bench.py's ``serving`` block.

Traces are **step-indexed**, not wall-clock-indexed: a request's
``arrival`` is the engine step at which the load generator makes it
visible.  That keeps every run of a (seed, trace) pair bit-reproducible
— the scheduler's admissions, the chunk interleave, the sampled tokens
and all engine counters replay exactly (the serve-smoke determinism
gate) — while latency METRICS are still measured in wall time (TTFT =
first-token wall time minus the wall time at which the arrival step
began).

`run_trace` submits each request AT its arrival step (not all
up-front): the ACCEPT/QUEUE/SHED admission verdicts (ISSUE 10) are
computed against the LIVE backlog, which is what the structural TTFT
bound prices — submitting the whole future trace at step 0 would make
every later request look provably late.  For arrival-sorted traces
with no SLA fields this is behaviourally identical to the old
submit-everything-first driver (admission was already arrival-gated).
``burst_factory`` wires the ``req_burst@s:k`` chaos kind: each step the
driver pops the engine's due burst specs (`ServeEngine.take_due_bursts`)
and submits the factory's flash crowd — the burst is keyed into the
fault plan, so it replays deterministically.

Reported metrics (the `bench.py` ``serving`` block schema):

* ``tok_per_s`` — generated tokens / wall duration of the drained trace;
* ``ttft_ms`` p50/p99 — time-to-first-token per request;
* ``tpot_ms`` p50/p99 — per-token latency after the first token;
* ``goodput_tok_per_s`` — generated tokens of only the requests meeting
  the SLA (TTFT <= ``sla_ttft_ms`` AND per-token <= ``sla_tpot_ms``)
  over the same duration — the number that actually answers "how much
  traffic is being served *well*" — plus ``goodput_by_class`` (the same
  split per ``sla_class``);
* ``shed_rate`` / ``deadline_miss_rate`` — shed and cancelled fractions
  of everything submitted (trace + bursts) — the overload-frontier
  axes `tools/bench_serve.py --overload-sweep` tabulates;
* ``dropped`` — SILENT drops: submissions resolved by none of
  FINISHED/SHED/DEADLINE_MISS.  Zero is the structural contract.
* the engine counter dict, verbatim.

The per-request metrics (ttft/tpot percentiles, goodput splits) derive
from the engine's TIMELINE when a tracer is attached (ISSUE 13: a
``finished`` entry the bounded `ResultStore` evicted mid-run still has
its ``complete`` event in the timeline, so `timeline_metrics`'s
reconstruction stays float-for-float even with the store held at cap);
only a saturated tracer ring then truncates them.  Without a tracer
they read the BOUNDED stores, so a trace longer than ``finished_cap``
covers only the retained window.  Truncation is never silent either
way — ``metrics_truncated`` flags it (counter-derived numbers: tok/s,
counts, shed/miss rates stay exact regardless).

`serial_baseline` replays the same trace through sequential
`models.generate` calls (batch 1, the pre-serve inference surface) —
the continuous-batching speedup gate compares aggregate tok/s.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np

from ..obs.timing import Stopwatch, now
from .scheduler import Request

__all__ = ["poisson_trace", "bursty_trace", "mixed_trace", "with_sla",
           "flash_crowd", "run_trace", "serial_baseline",
           "decode_tail_matches", "timeline_metrics",
           "shared_prefix_trace", "run_fleet_trace",
           "fleet_timeline_metrics", "steady_stream"]


def decode_tail_matches(original, mark: int, restored) -> int:
    """The ONE snapshot-restore comparison contract (shared by the
    tests, the serve-smoke gate and bench.py's serving block): the
    restored engine's full ``logits_log`` must reproduce the original's
    entries from index ``mark`` on — same (rid, position) schedule and
    BITWISE-identical logit rows — and the two engines must agree on
    ``finished`` and ``counters``.  Returns the compared row count
    (> 0; an empty tail would make the gate vacuous); raises ValueError
    naming the first divergence otherwise.  Both engines need
    ``record_logits=True`` and to be drained."""
    tail = original.logits_log[mark:]
    if len(tail) != len(restored.logits_log) or not tail:
        raise ValueError(
            f"restored decode stream length {len(restored.logits_log)} "
            f"!= original tail {len(tail)} (empty tails are vacuous)")
    for (ra, pa, la), (rb, pb, lb) in zip(tail, restored.logits_log):
        if (ra, pa) != (rb, pb):
            raise ValueError(f"restored decode schedule diverged: "
                             f"(rid {ra}, pos {pa}) vs (rid {rb}, "
                             f"pos {pb})")
        if not (la.view(np.uint32) == lb.view(np.uint32)).all():
            raise ValueError(f"restored logits not bitwise identical "
                             f"at rid={ra} pos={pa}")
    if original.finished != restored.finished:
        raise ValueError("restored `finished` store differs")
    if original.counters != restored.counters:
        raise ValueError("restored counters differ")
    return len(tail)


def poisson_trace(n_requests: int, vocab_size: int, *,
                  rate: float = 0.5, prompt_lens: Sequence[int] = (4, 8),
                  max_new: Sequence[int] = (8,), seed: int = 0,
                  eos_id: Optional[int] = None) -> list:
    """Poisson arrivals: exponential inter-arrival gaps (mean ``1/rate``
    engine steps), prompt/response sizes drawn from the given small sets
    (small ON PURPOSE: the serial baseline compiles one program per
    distinct (prompt_len, max_new) pair)."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for rid in range(n_requests):
        t += rng.exponential(1.0 / rate)
        out.append(Request(
            rid=rid,
            prompt=tuple(int(x) for x in rng.integers(
                0, vocab_size, int(rng.choice(list(prompt_lens))))),
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=int(t), eos_id=eos_id))
    return out


def bursty_trace(n_requests: int, vocab_size: int, *,
                 burst: int = 4, gap: int = 8,
                 prompt_lens: Sequence[int] = (4, 8),
                 max_new: Sequence[int] = (8,), seed: int = 0,
                 eos_id: Optional[int] = None) -> list:
    """Bursty arrivals: ``burst`` requests land simultaneously every
    ``gap`` steps — the flash-crowd shape that stresses admission and
    page reservation hardest."""
    rng = np.random.default_rng(seed)
    out = []
    for rid in range(n_requests):
        out.append(Request(
            rid=rid,
            prompt=tuple(int(x) for x in rng.integers(
                0, vocab_size, int(rng.choice(list(prompt_lens))))),
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=(rid // burst) * gap, eos_id=eos_id))
    return out


def mixed_trace(n_requests: int, vocab_size: int, *,
                prompt_lens: Sequence[int] = (4, 8, 12),
                max_new: Sequence[int] = (8,), seed: int = 0,
                eos_id: Optional[int] = None) -> list:
    """The acceptance-gate trace shape: a Poisson steady stream for the
    first half, then a flash-crowd burst landing on top of it — request
    ids stay globally unique and arrivals sorted."""
    half = n_requests // 2
    steady = poisson_trace(half, vocab_size, rate=2.0,
                           prompt_lens=prompt_lens, max_new=max_new,
                           seed=seed, eos_id=eos_id)
    crowd = bursty_trace(n_requests - half, vocab_size, burst=4, gap=3,
                         prompt_lens=prompt_lens, max_new=max_new,
                         seed=seed + 1, eos_id=eos_id)
    mid = steady[half // 2].arrival if steady else 0
    out = list(steady)
    for r in crowd:
        out.append(Request(rid=half + r.rid, prompt=r.prompt,
                           max_new_tokens=r.max_new_tokens,
                           arrival=r.arrival + mid, eos_id=r.eos_id))
    return sorted(out, key=lambda r: (r.arrival, r.rid))


def with_sla(requests: Sequence[Request], classes: Sequence[dict]) -> list:
    """Stamp SLA fields onto a trace: request ``i`` gets
    ``classes[i % len(classes)]``, each a dict of any of ``sla_class``,
    ``deadline_steps``, ``tpot_budget_steps`` — e.g.

        with_sla(trace, [dict(sla_class=0, deadline_steps=8),
                         dict(sla_class=1)])

    alternates premium deadline-bound traffic with best-effort."""
    if not classes:
        raise ValueError("with_sla needs at least one class dict")
    return [dataclasses.replace(r, **classes[i % len(classes)])
            for i, r in enumerate(requests)]


def flash_crowd(vocab_size: int, *, start_rid: int = 1_000_000,
                prompt_lens: Sequence[int] = (4, 8),
                max_new: Sequence[int] = (8,), seed: int = 0,
                sla: Optional[dict] = None,
                eos_id: Optional[int] = None) -> Callable:
    """A ``burst_factory`` for `run_trace`: given a fired
    ``req_burst@s:k`` spec it returns ``k`` (default 4) requests
    arriving at step ``s`` — rids allocated from ``start_rid`` up (far
    above trace rids), sizes drawn from a dedicated deterministic
    stream so the crowd is identical every replay."""
    rng = np.random.default_rng(seed)
    next_rid = [start_rid]

    def factory(spec) -> list:
        k = int(spec.arg) if spec.arg > 0 else 4
        out = []
        for _ in range(k):
            kw = dict(sla or {})
            out.append(Request(
                rid=next_rid[0],
                prompt=tuple(int(x) for x in rng.integers(
                    0, vocab_size,
                    int(rng.choice(list(prompt_lens))))),
                max_new_tokens=int(rng.choice(list(max_new))),
                arrival=spec.step, eos_id=eos_id, **kw))
            next_rid[0] += 1
        return out

    return factory


def _pct(values: list, q: float) -> Optional[float]:
    return round(float(np.percentile(values, q)), 3) if values else None


def timeline_metrics(tracer, *, sla_ttft_ms: float = 1000.0,
                     sla_tpot_ms: float = 250.0) -> dict:
    """Reconstruct the serving latency metrics from an `obs.Tracer`'s
    per-request timeline ALONE — no engine, no stores (ISSUE 11
    acceptance gate: on a drained, non-truncated `run_trace(engine
    with tracer=...)` run the reconstructed TTFT/TPOT percentiles,
    goodput and verdict/resolution counts equal the published metrics
    EXACTLY, float for float).

    The equality is structural, not approximate: the engine records
    each event's wall time once (`ServeEngine._event`) and hands the
    same float to both its host event log (which `run_trace` reads)
    and the tracer; `run_trace` likewise records its per-step wall
    into the tracer (``step_begin``).  Reconstruction then repeats the
    identical arithmetic on the identical floats.

    Parity holds even when the bounded `ResultStore` evicted finished
    entries mid-trace (ISSUE 13 satellite — the PR 11 caveat, closed):
    `run_trace` derives its published per-request numbers from the
    SAME timeline whenever a tracer is attached, so both sides see the
    evicted rids' true ``n_generated``.  The one remaining truncation
    is a saturated tracer ring (``timeline_truncated`` flags it)."""
    step_begin: dict = {}
    submits: list = []           # (seq, rid, args) in submission order
    first: dict = {}
    done: dict = {}              # rid -> (wall, n_generated)
    counts = {"completed": 0, "shed": 0, "deadline_misses": 0}
    verdicts: dict = {}
    tokens = 0
    t0 = t_end = None
    for _seq, name, cat, step, wall, args in sorted(tracer.events):
        if cat == "serve":
            if name == "step_begin":
                step_begin[step] = wall
            elif name == "trace_begin":
                t0 = wall
            elif name == "trace_end":
                t_end = wall
            continue
        if cat != "req":
            continue
        rid = args["rid"]
        if name == "submit":
            submits.append((rid, args))
            v = args.get("verdict")
            verdicts[v] = verdicts.get(v, 0) + 1
        elif name == "first_token":
            first[rid] = wall
        elif name == "complete":
            done[rid] = (wall, args["n_generated"])
            counts["completed"] += 1
            tokens += args["n_generated"]
        elif name == "shed":
            counts["shed"] += 1
        elif name == "deadline_miss":
            counts["deadline_misses"] += 1
            tokens += args.get("partial_tokens", 0)
    ttft, tpot, good_tokens = [], [], 0
    class_tokens: dict = {}
    for rid, args in submits:
        n_gen = done[rid][1] if rid in done else 0
        if rid not in first:
            continue
        if args["arrival"] not in step_begin:
            # no step_begin for this arrival: the engine was stepped
            # manually (only run_trace records the per-step walls), or
            # the tracer ring aged the early steps out — either way a
            # silent wrong TTFT would betray the exactness contract
            raise ValueError(
                f"timeline has no step_begin for arrival step "
                f"{args['arrival']} (rid {rid}): drive the engine "
                f"through run_trace with the tracer attached, and "
                f"size Tracer(max_records=) to the trace "
                f"(events_dropped={getattr(tracer, 'events_dropped', 0)})")
        t_first = (first[rid] - step_begin[args["arrival"]]) * 1e3
        ttft.append(t_first)
        t_tok = None
        if rid in done and n_gen > 1:
            t_tok = (done[rid][0] - first[rid]) * 1e3 / (n_gen - 1)
            tpot.append(t_tok)
        if t_first <= sla_ttft_ms and (t_tok is None
                                       or t_tok <= sla_tpot_ms):
            good_tokens += n_gen
            cls = args.get("sla_class", 0)
            class_tokens[cls] = class_tokens.get(cls, 0) + n_gen
    duration = (t_end - t0) if (t0 is not None
                                and t_end is not None) else None
    n_sub = len(submits)
    return {
        "submitted": n_sub,
        "verdicts": dict(sorted(verdicts.items())),
        **counts,
        "dropped": n_sub - sum(counts.values()),
        "shed_rate": (round(counts["shed"] / n_sub, 4)
                      if n_sub else 0.0),
        "deadline_miss_rate": (round(counts["deadline_misses"] / n_sub,
                                     4) if n_sub else 0.0),
        "tokens_generated": tokens,
        "duration_s": (round(duration, 3) if duration is not None
                       else None),
        "tok_per_s": (round(tokens / duration, 1) if duration
                      else None),
        "ttft_ms_p50": _pct(ttft, 50), "ttft_ms_p99": _pct(ttft, 99),
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "goodput_tok_per_s": (round(good_tokens / duration, 1)
                              if duration else None),
        "goodput_by_class": {str(k): (round(v / duration, 1)
                                      if duration else None)
                             for k, v in sorted(class_tokens.items())},
        # honesty flag (run_trace's metrics_truncated twin): a
        # saturated tracer ring aged out early events, so the
        # reconstruction covers only the surviving window
        "timeline_truncated": getattr(tracer, "events_dropped", 0) > 0,
        "sla": {"ttft_ms": sla_ttft_ms, "tpot_ms": sla_tpot_ms},
    }


def _latency_block(submitted, first, done, n_gen_of, step_wall,
                   duration, sla_ttft_ms, sla_tpot_ms) -> dict:
    """The ONE published per-request SLA-latency computation shared by
    `run_trace` and `run_fleet_trace` (so the goodput/TTFT/TPOT
    definitions cannot drift between engine and fleet reports).
    `timeline_metrics` deliberately does NOT use this helper: it is the
    independent reconstruction the parity gate cross-checks — folding
    it in would make that gate circular."""
    ttft, tpot, good_tokens = [], [], 0
    class_tokens: dict = {}
    for r in submitted:
        n_gen = n_gen_of.get(r.rid, 0)
        if r.rid not in first or r.arrival not in step_wall:
            continue
        t_first = (first[r.rid] - step_wall[r.arrival]) * 1e3
        ttft.append(t_first)
        t_tok = None
        if r.rid in done and n_gen > 1:
            t_tok = (done[r.rid] - first[r.rid]) * 1e3 / (n_gen - 1)
            tpot.append(t_tok)
        if t_first <= sla_ttft_ms and (t_tok is None
                                       or t_tok <= sla_tpot_ms):
            good_tokens += n_gen
            class_tokens[r.sla_class] = (class_tokens.get(r.sla_class, 0)
                                         + n_gen)
    return {
        "ttft_ms_p50": _pct(ttft, 50), "ttft_ms_p99": _pct(ttft, 99),
        "tpot_ms_p50": _pct(tpot, 50), "tpot_ms_p99": _pct(tpot, 99),
        "goodput_tok_per_s": (round(good_tokens / duration, 1)
                              if duration else None),
        "goodput_by_class": {str(k): (round(v / duration, 1)
                                      if duration else None)
                             for k, v in sorted(class_tokens.items())},
    }


def run_trace(engine, requests: list, *, sla_ttft_ms: float = 1000.0,
              sla_tpot_ms: float = 250.0,
              burst_factory: Optional[Callable] = None,
              max_steps: int = 100000) -> dict:
    """Drive ``engine`` through ``requests`` (submitted at their arrival
    steps, module docstring) until drained; -> metrics."""
    pending = sorted(requests, key=lambda r: (r.arrival, r.rid))
    submitted = []
    step_wall = {}

    def more_work() -> bool:
        # a req_burst scheduled past the current drain point must still
        # arrive: the step clock runs until every consumed-here spec fired
        if pending or not engine.drained():
            return True
        return burst_factory is not None and engine.has_pending_bursts()

    # NULL_TRACER is falsy by design (obs.trace) — normalize it to None
    # here so the disabled path cannot select the timeline-derived
    # metrics branch below and publish empty percentiles
    tracer = getattr(engine, "tracer", None) or None
    t0 = now()
    if tracer is not None:
        tracer.event("trace_begin", cat="serve", wall=t0)
    while more_work():
        if engine.step_index >= max_steps:
            raise RuntimeError(f"trace not drained in {max_steps} steps")
        while pending and pending[0].arrival <= engine.step_index:
            r = pending.pop(0)
            engine.submit(r)
            submitted.append(r)
        if burst_factory is not None:
            for spec in engine.take_due_bursts():
                for r in burst_factory(spec):
                    engine.submit(r)
                    submitted.append(r)
        w = now()
        step_wall[engine.step_index] = w
        if tracer is not None:
            # the SAME wall float the latency metrics below subtract —
            # recording it (not a re-read of the clock) is what makes
            # `timeline_metrics`' reconstruction bit-exact
            tracer.event("step_begin", step=engine.step_index,
                         cat="serve", wall=w)
        engine.step()
    t_end = now()
    duration = t_end - t0
    if tracer is not None:
        tracer.event("trace_end", cat="serve", wall=t_end)
    engine.report_unfired()

    first, done, n_gen_of = {}, {}, {}
    if tracer is not None:
        # ISSUE 13 satellite (the PR 11 parity caveat, closed): with a
        # tracer attached the published per-request metrics derive from
        # the TIMELINE, not the bounded stores — a `finished` entry the
        # `ResultStore` evicted mid-run still has its `complete` event
        # (wall + n_generated) in the timeline, so
        # `timeline_metrics`'s reconstruction stays float-for-float
        # even with the store held at cap (regression-tested).  The
        # walls are the SAME floats either way (`ServeEngine._event`
        # hands one `now()` to both sinks).
        for _seq, name, cat, _step, wall, args in tracer.events:
            if cat != "req":
                continue
            if name == "first_token":
                first[args["rid"]] = wall
            elif name == "complete":
                done[args["rid"]] = wall
                n_gen_of[args["rid"]] = int(args["n_generated"])
    else:
        for kind, rid, _step, wall in engine.events:
            if kind == "first_token":
                first[rid] = wall
            elif kind == "complete":
                done[rid] = wall
        n_gen_of = {r.rid: len(engine.finished.get(r.rid, ()))
                    for r in submitted}
    lat = _latency_block(submitted, first, done, n_gen_of, step_wall,
                         duration, sla_ttft_ms, sla_tpot_ms)

    c = engine.counters
    gen = c["tokens_generated"]
    n_sub = c["submitted"]
    resolved = c["completed"] + c["shed"] + c["deadline_misses"]
    return {
        "requests": len(requests),
        "submitted": n_sub,
        "completed": c["completed"],
        "shed": c["shed"],
        "deadline_misses": c["deadline_misses"],
        # SILENT drops — anything submitted that resolved to none of
        # FINISHED / SHED / DEADLINE_MISS; structurally zero
        "dropped": n_sub - resolved,
        "shed_rate": round(c["shed"] / n_sub, 4) if n_sub else 0.0,
        "deadline_miss_rate": (round(c["deadline_misses"] / n_sub, 4)
                               if n_sub else 0.0),
        "engine_steps": engine.step_index,
        "duration_s": round(duration, 3),
        "tok_per_s": round(gen / duration, 1) if duration else None,
        **lat,
        # bounded honesty flag (module docstring): with a tracer the
        # per-request numbers derive from the timeline, so only a
        # SATURATED tracer ring truncates them; without one they read
        # the bounded stores, so a mid-run eviction truncates
        "metrics_truncated": (
            getattr(tracer, "events_dropped", 0) > 0
            if tracer is not None else c["results_evicted"] > 0),
        "sla": {"ttft_ms": sla_ttft_ms, "tpot_ms": sla_tpot_ms},
        "counters": dict(engine.counters),
    }


def shared_prefix_trace(n_requests: int, vocab_size: int, *,
                        n_prefixes: int = 2, prefix_len: int = 16,
                        suffix_lens: Sequence[int] = (2, 4),
                        max_new: Sequence[int] = (8,),
                        rate: float = 2.0, seed: int = 0,
                        eos_id: Optional[int] = None,
                        sla: Optional[Sequence[dict]] = None) -> list:
    """The prefix-cache workload shape (ISSUE 13): Poisson arrivals
    whose prompts share one of ``n_prefixes`` common prefixes (system
    prompts / few-shot preambles) followed by a short per-request
    suffix — the trace `tools/bench_serve.py --fleet`'s prefix-hit-rate
    sweep replays.  ``sla`` stamps classes round-robin like
    `with_sla`."""
    if n_prefixes < 1 or prefix_len < 1:
        raise ValueError(f"n_prefixes/prefix_len must be >= 1, got "
                         f"({n_prefixes}, {prefix_len})")
    rng = np.random.default_rng(seed)
    prefixes = [tuple(int(x) for x in rng.integers(0, vocab_size,
                                                   prefix_len))
                for _ in range(n_prefixes)]
    t = 0.0
    out = []
    for rid in range(n_requests):
        t += rng.exponential(1.0 / rate)
        suffix = tuple(int(x) for x in rng.integers(
            0, vocab_size, int(rng.choice(list(suffix_lens)))))
        out.append(Request(
            rid=rid, prompt=prefixes[rid % n_prefixes] + suffix,
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=int(t), eos_id=eos_id))
    return with_sla(out, list(sla)) if sla else out


def _fleet_count_block(fleet, requests_seen: int, duration: float,
                       sla_ttft_ms: float, sla_tpot_ms: float) -> dict:
    """Fleet-scope resolution from COUNTERS, not the bounded stores
    (eviction-immune, run_trace's discipline): a rid completes and
    deadline-misses at most once however it moves; every router retry
    leaves exactly one extra engine-level shed record for a rid that
    resolved elsewhere, so subtracting retries yields the rid-level
    shed count.  Shared by the in-memory and streaming fleet drivers —
    the exact-count contract cannot drift between them."""
    agg = fleet.aggregate_counters()
    n_sub = fleet.counters["submitted"]
    completed = agg.get("completed", 0)
    misses = agg.get("deadline_misses", 0)
    shed = agg.get("shed", 0) - fleet.counters["router_retries"]
    resolved = completed + shed + misses
    gen = agg.get("tokens_generated", 0)
    return {
        "n_engines": fleet.n_engines,
        "requests": requests_seen,
        "submitted": n_sub,
        "completed": completed,
        "shed": shed,
        "deadline_misses": misses,
        "dropped": n_sub - resolved,       # fleet-scope SILENT drops
        "shed_rate": round(shed / n_sub, 4) if n_sub else 0.0,
        "deadline_miss_rate": (round(misses / n_sub, 4)
                               if n_sub else 0.0),
        "fleet_steps": fleet.step_index,
        "duration_s": round(duration, 3),
        "tok_per_s": round(gen / duration, 1) if duration else None,
        "sla": {"ttft_ms": sla_ttft_ms, "tpot_ms": sla_tpot_ms},
        "fleet_counters": dict(fleet.counters),
        "engine_counters": [dict(e.counters) for e in fleet.engines],
        "_results_evicted": agg.get("results_evicted", 0),
    }


def run_fleet_trace(fleet, requests, *,
                    sla_ttft_ms: float = 1000.0,
                    sla_tpot_ms: float = 250.0,
                    max_steps: int = 100000,
                    burst_factory: Optional[Callable] = None,
                    stream: Optional[bool] = None,
                    window_steps: int = 64,
                    tracer=None,
                    min_steps: int = 0,
                    lat_reservoir: int = 65536,
                    max_windows: int = 4096) -> dict:
    """`run_trace` lifted to fleet scope: submit each request at its
    arrival step through the ROUTER (`Fleet.submit`), step the fleet
    (all engines in lockstep) until drained and every pending fleet
    fault fired, and report the fleet metric set.

    Two drivers behind one front door (ISSUE 17):

    * **in-memory** (``requests`` is a list/tuple and ``stream`` unset)
      — the PR 13 behavior, bit-unchanged: the whole trace is held,
      per-request latency merges every engine's event log post-hoc.
    * **streaming** (``requests`` is any other iterable, or
      ``stream=True``) — arrivals are PULLED one at a time from a
      generator and every per-request record is dropped the moment the
      rid resolves, so RSS is bounded by the in-flight session count
      however long the trace runs (~10⁶ sessions; the stays-at-cap
      test pins it).  Latency lands in per-``window_steps`` windows
      (``windows``) plus capped whole-run reservoirs; ``tracer``
      (fleet-scope, records ``step_begin`` walls) + per-engine tracers
      enable the independent `fleet_timeline_metrics` reconstruction
      the parity gate cross-checks.

    Both drivers consume ``burst_factory`` flash crowds
    (``req_burst@s:k`` specs popped from EVERY engine's plan, submitted
    through the router) and honor ``min_steps`` (keep the step clock
    running through a drained quiet tail — what gives scale-down
    hysteresis room to fire at end of trace).

    Resolution counts are rid-level fleet-scope truth, not engine-
    counter sums (a request shed by one engine and completed by the
    next after a router retry counts COMPLETED; engine counters keep
    the per-engine view in ``engine_counters``).  ``dropped`` is the
    fleet-scope silent-drop count — structurally zero.  Latency walls
    merge every engine's event log (a migrated session's first token
    and completion legitimately live on different engines)."""
    if stream is None:
        stream = not isinstance(requests, (list, tuple))
    if stream:
        return _run_fleet_stream(
            fleet, requests, sla_ttft_ms=sla_ttft_ms,
            sla_tpot_ms=sla_tpot_ms, max_steps=max_steps,
            burst_factory=burst_factory, window_steps=window_steps,
            tracer=tracer, min_steps=min_steps,
            lat_reservoir=lat_reservoir, max_windows=max_windows)
    pending = sorted(requests, key=lambda r: (r.arrival, r.rid))
    submitted = []
    step_wall = {}

    def more_work() -> bool:
        if pending or not fleet.drained() or fleet.has_pending_faults():
            return True
        if burst_factory is not None and any(
                e.has_pending_bursts() for e in fleet.engines):
            return True
        return fleet.step_index < min_steps

    t0 = now()
    while more_work():
        if fleet.step_index >= max_steps:
            raise RuntimeError(
                f"fleet trace not drained in {max_steps} steps")
        while pending and pending[0].arrival <= fleet.step_index:
            r = pending.pop(0)
            fleet.submit(r)
            submitted.append(r)
        if burst_factory is not None:
            for e in fleet.engines:
                for spec in e.take_due_bursts(fleet.step_index):
                    for r in burst_factory(spec):
                        fleet.submit(r)
                        submitted.append(r)
        step_wall[fleet.step_index] = now()
        fleet.step()
    duration = now() - t0
    fleet.report_unfired()

    first, done, n_gen_of = {}, {}, {}
    for e in fleet.engines:
        for kind, rid, _step, wall in e.events:
            if kind == "first_token":
                first[rid] = wall
            elif kind == "complete":
                done[rid] = wall
        for rid, toks in e.finished.items():
            n_gen_of[rid] = len(toks)

    lat = _latency_block(submitted, first, done, n_gen_of, step_wall,
                         duration, sla_ttft_ms, sla_tpot_ms)
    out = _fleet_count_block(fleet, len(submitted), duration,
                             sla_ttft_ms, sla_tpot_ms)
    evicted = out.pop("_results_evicted")
    out.update(lat)
    out["metrics_truncated"] = evicted > 0
    return out


def _run_fleet_stream(fleet, requests, *, sla_ttft_ms, sla_tpot_ms,
                      max_steps, burst_factory, window_steps, tracer,
                      min_steps, lat_reservoir, max_windows) -> dict:
    """The streaming fleet driver (`run_fleet_trace` docstring).

    Per-request state is ONE bounded dict: ``rid -> [arrival_wall,
    sla_class, first_wall, ttft_ms]``, created when the router places
    the rid and popped the moment it resolves — its size is exactly the
    in-flight session count (the ResultStore doctrine at trace scope;
    ``stream.peak_tracked_rids`` reports the high-water mark).  Engine
    events are TAILED incrementally through the monotone
    ``ServeEngine.events_total`` cursor (never re-read, never
    double-counted; a kill-restored engine is re-anchored by object
    identity and the per-rid guards make replayed duplicates no-ops).
    Sheds are recognized by the placement sweep: a tracked rid no
    longer placed anywhere after a step, with no complete/miss event,
    resolved SHED that step — this covers admission sheds, supervisor
    purges and drain-requeue sheds through one rule.  Aggregate counts
    come from counters (`_fleet_count_block`, exact regardless of any
    window/reservoir truncation); only the latency detail is windowed
    and capped, and every cap is flagged, never silent."""
    it = iter(requests)
    nxt = next(it, None)
    meta: dict = {}
    peak_meta = 0
    n_seen = 0
    ttft_all: list = []
    tpot_all: list = []
    lat_dropped = 0
    good_tokens = 0
    class_tokens: dict = {}
    windows: deque = deque(maxlen=max_windows)
    windows_emitted = 0
    events_missed = 0
    tails: dict = {}       # engine row -> (object id, events cursor)
    win = {"submitted": 0, "completed": 0, "shed": 0,
           "deadline_misses": 0, "tokens": 0}
    win_ttft: list = []
    win_tpot: list = []
    win_start = fleet.step_index
    if window_steps < 1:
        raise ValueError(f"window_steps must be >= 1, got {window_steps}")

    def reservoir_add(store: list, value: float) -> bool:
        nonlocal lat_dropped
        if len(store) < lat_reservoir:
            store.append(value)
            return True
        lat_dropped += 1
        return False

    def flush_window(end_step: int) -> None:
        nonlocal win, win_ttft, win_tpot, win_start, windows_emitted
        windows.append({
            "start_step": win_start, "end_step": end_step,
            **win,
            "ttft_ms_p50": _pct(win_ttft, 50),
            "ttft_ms_p99": _pct(win_ttft, 99),
            "tpot_ms_p50": _pct(win_tpot, 50),
            "tpot_ms_p99": _pct(win_tpot, 99),
        })
        windows_emitted += 1
        win = {"submitted": 0, "completed": 0, "shed": 0,
               "deadline_misses": 0, "tokens": 0}
        win_ttft, win_tpot = [], []
        win_start = end_step

    def resolve_goodput(m: list, n_gen: int,
                        t_tok: Optional[float]) -> None:
        # the _latency_block SLA arithmetic, applied at resolution
        # time on the SAME floats (arrival/first walls recorded once)
        nonlocal good_tokens
        if m[2] is None:
            return
        if m[3] <= sla_ttft_ms and (t_tok is None
                                    or t_tok <= sla_tpot_ms):
            good_tokens += n_gen
            class_tokens[m[1]] = class_tokens.get(m[1], 0) + n_gen

    def handle_event(kind: str, rid: int, wall: float, eng) -> None:
        if kind == "first_token":
            m = meta.get(rid)
            if m is not None and m[2] is None and m[0] is not None:
                m[2] = wall
                m[3] = (wall - m[0]) * 1e3
                win_ttft.append(m[3])
                reservoir_add(ttft_all, m[3])
        elif kind == "complete":
            m = meta.pop(rid, None)
            if m is not None:
                n_gen = len(eng.finished.get(rid, ()))
                win["completed"] += 1
                win["tokens"] += n_gen
                t_tok = None
                if m[2] is not None and n_gen > 1:
                    t_tok = (wall - m[2]) * 1e3 / (n_gen - 1)
                    win_tpot.append(t_tok)
                    reservoir_add(tpot_all, t_tok)
                resolve_goodput(m, n_gen, t_tok)
        elif kind == "deadline_miss":
            m = meta.pop(rid, None)
            if m is not None:
                win["deadline_misses"] += 1
                resolve_goodput(m, 0, None)

    def consume_events() -> None:
        nonlocal events_missed
        for i, e in enumerate(fleet.engines):
            key = id(e)
            anchor = tails.get(i)
            if anchor is None or anchor[0] != key:
                # new or kill-restored engine: re-anchor at the start
                # of its retained ring (replayed duplicates are no-ops
                # through the per-rid guards above)
                tails[i] = (key, max(0, e.events_total - len(e.events)))
            seen = tails[i][1]
            fresh = e.events_total - seen
            if fresh <= 0:
                continue
            evs = list(e.events)
            if fresh > len(evs):
                events_missed += fresh - len(evs)
                fresh = len(evs)
            for kind, rid, _step, wall in evs[len(evs) - fresh:]:
                handle_event(kind, rid, wall, e)
            tails[i] = (key, e.events_total)

    def sweep_resolved() -> None:
        # any still-tracked rid no longer placed anywhere resolved
        # WITHOUT a complete/miss event this step: a shed (admission,
        # purge or drain-requeue) — one rule for every shed path
        gone = [rid for rid in meta if rid not in fleet.placement]
        for rid in gone:
            meta.pop(rid)
            win["shed"] += 1

    def submit_one(r, stamped: list) -> None:
        nonlocal n_seen
        n_seen += 1
        win["submitted"] += 1
        _verdict, idx = fleet.submit(r)
        if idx >= 0:
            meta[r.rid] = [None, r.sla_class, None, None]
            stamped.append(r.rid)
        else:
            win["shed"] += 1

    def more_work() -> bool:
        if nxt is not None or not fleet.drained() \
                or fleet.has_pending_faults():
            return True
        if burst_factory is not None and any(
                e.has_pending_bursts() for e in fleet.engines):
            return True
        return fleet.step_index < min_steps

    t0 = now()
    if tracer is not None:
        tracer.event("trace_begin", cat="serve", wall=t0)
    while more_work():
        if fleet.step_index >= max_steps:
            raise RuntimeError(
                f"fleet stream not drained in {max_steps} steps")
        stamped: list = []
        while nxt is not None and nxt.arrival <= fleet.step_index:
            if nxt.arrival < fleet.step_index:
                raise ValueError(
                    f"streaming arrivals must be sorted by arrival "
                    f"step: rid {nxt.rid} arrives at {nxt.arrival} "
                    f"but the fleet clock is at {fleet.step_index}")
            submit_one(nxt, stamped)
            nxt = next(it, None)
        if burst_factory is not None:
            for e in fleet.engines:
                for spec in e.take_due_bursts(fleet.step_index):
                    for r in burst_factory(spec):
                        submit_one(r, stamped)
        w = now()
        if tracer is not None:
            # the SAME wall float the TTFT subtraction below uses —
            # what makes `fleet_timeline_metrics` bit-exact
            tracer.event("step_begin", step=fleet.step_index,
                         cat="serve", wall=w)
        for rid in stamped:
            meta[rid][0] = w
        if len(meta) > peak_meta:
            peak_meta = len(meta)
        fleet.step()
        consume_events()
        sweep_resolved()
        if fleet.step_index % window_steps == 0:
            flush_window(fleet.step_index)
    t_end = now()
    if tracer is not None:
        tracer.event("trace_end", cat="serve", wall=t_end)
    duration = t_end - t0
    if fleet.step_index > win_start:
        flush_window(fleet.step_index)
    fleet.report_unfired()

    out = _fleet_count_block(fleet, n_seen, duration,
                             sla_ttft_ms, sla_tpot_ms)
    evicted = out.pop("_results_evicted")
    out.update({
        "ttft_ms_p50": _pct(ttft_all, 50),
        "ttft_ms_p99": _pct(ttft_all, 99),
        "tpot_ms_p50": _pct(tpot_all, 50),
        "tpot_ms_p99": _pct(tpot_all, 99),
        "goodput_tok_per_s": (round(good_tokens / duration, 1)
                              if duration else None),
        "goodput_by_class": {str(k): (round(v / duration, 1)
                                      if duration else None)
                             for k, v in sorted(class_tokens.items())},
        "windows": list(windows),
        "window_steps": window_steps,
        "metrics_truncated": (evicted > 0 or lat_dropped > 0
                              or events_missed > 0),
        "fleet_shape": {
            "rows": fleet.n_engines,
            "accepting": sum(fleet.accepting),
            "retired": sum(fleet.retired),
            "shape_log": list(fleet.shape_log),
        },
        "stream": {
            "peak_tracked_rids": peak_meta,
            "final_tracked_rids": len(meta),
            "lat_samples_dropped": lat_dropped,
            "events_missed": events_missed,
            "windows_emitted": windows_emitted,
            "windows_truncated": windows_emitted > len(windows),
        },
    })
    return out


def fleet_timeline_metrics(tracer, engine_tracers, *,
                           sla_ttft_ms: float = 1000.0,
                           sla_tpot_ms: float = 250.0,
                           window_steps: int = 64,
                           lat_reservoir: int = 65536) -> dict:
    """`timeline_metrics` lifted to fleet scope (ISSUE 17): rebuild the
    STREAMING driver's windowed + aggregate latency metrics from the
    fleet tracer (``step_begin``/``trace_begin``/``trace_end`` walls)
    and the per-engine tracers' request timelines ALONE — no fleet, no
    stores.  On a drained, non-truncated streaming run (reservoir under
    cap, tracer rings unsaturated, no kill replay, an accepting engine
    at every submit) the reconstruction equals the published
    ``windows`` and latency aggregates EXACTLY, float for float: the
    engines hand one wall per event to both sinks
    (`ServeEngine._event`), the driver records its per-step wall into
    the fleet tracer, and this function repeats the identical
    arithmetic on the identical floats.  Deliberately independent of
    the driver's accumulation code — it is the cross-check, and
    sharing the arithmetic would make the parity gate circular.

    Resolution rule per rid (mirrors the driver's event/sweep order):
    a ``complete`` event wins; else ``deadline_miss``; else the rid
    resolved SHED at its last ``shed`` event's step.  Window
    attribution: submissions at the first ``submit`` step, TTFT at the
    ``first_token`` step, completions/misses/sheds at their event
    steps — the same steps the streaming sweep observes them."""
    step_begin: dict = {}
    t0 = t_end = None
    for _seq, name, cat, step, wall, _args in sorted(tracer.events):
        if cat != "serve":
            continue
        if name == "step_begin":
            step_begin[step] = wall
        elif name == "trace_begin":
            t0 = wall
        elif name == "trace_end":
            t_end = wall
    if not step_begin:
        raise ValueError(
            "fleet timeline has no step_begin records: drive the fleet "
            "through run_fleet_trace(stream=True, tracer=...) — only "
            "the streaming driver records the fleet-scope walls")
    rids: dict = {}
    for tr in engine_tracers:
        for _seq, name, cat, step, wall, args in sorted(tr.events):
            if cat != "req":
                continue
            rec = rids.setdefault(args["rid"], {})
            if name == "submit":
                if "submit_step" not in rec:
                    rec["submit_step"] = step
                    rec["arrival"] = args["arrival"]
                    rec["sla_class"] = args.get("sla_class", 0)
                if args.get("verdict") != "shed":
                    rec["placed"] = True
            elif name == "first_token" and "first" not in rec:
                rec["first"] = (step, wall)
            elif name == "complete" and "done" not in rec:
                rec["done"] = (step, wall, int(args["n_generated"]))
            elif name == "deadline_miss" and "miss" not in rec:
                rec["miss"] = (step, wall)
            elif name == "shed":
                rec["last_shed_step"] = step
    n_steps = max(step_begin) + 1
    n_windows = -(-n_steps // window_steps)      # ceil
    wins = [{"start_step": i * window_steps,
             "end_step": min((i + 1) * window_steps, n_steps),
             "submitted": 0, "completed": 0, "shed": 0,
             "deadline_misses": 0, "tokens": 0,
             "_ttft": [], "_tpot": []} for i in range(n_windows)]
    ttft_all: list = []
    tpot_all: list = []
    good_tokens = 0
    class_tokens: dict = {}
    counts = {"completed": 0, "shed": 0, "deadline_misses": 0}
    tokens = 0

    def w_of(step: int) -> dict:
        return wins[min(step // window_steps, n_windows - 1)]

    for rid in sorted(rids):
        rec = rids[rid]
        w_of(rec["submit_step"])["submitted"] += 1
        t_first = None
        if "first" in rec and rec["arrival"] in step_begin:
            fstep, fwall = rec["first"]
            t_first = (fwall - step_begin[rec["arrival"]]) * 1e3
            w_of(fstep)["_ttft"].append(t_first)
            if len(ttft_all) < lat_reservoir:
                ttft_all.append(t_first)
        if not rec.get("placed"):
            # every submit shed: resolved at fleet scope the same step
            counts["shed"] += 1
            w_of(rec.get("last_shed_step", rec["submit_step"]))["shed"] \
                += 1
            continue
        if "done" in rec:
            dstep, dwall, n_gen = rec["done"]
            counts["completed"] += 1
            tokens += n_gen
            w = w_of(dstep)
            w["completed"] += 1
            w["tokens"] += n_gen
            t_tok = None
            if t_first is not None and n_gen > 1:
                t_tok = (dwall - rec["first"][1]) * 1e3 / (n_gen - 1)
                w["_tpot"].append(t_tok)
                if len(tpot_all) < lat_reservoir:
                    tpot_all.append(t_tok)
            if t_first is not None and t_first <= sla_ttft_ms \
                    and (t_tok is None or t_tok <= sla_tpot_ms):
                good_tokens += n_gen
                cls = rec["sla_class"]
                class_tokens[cls] = class_tokens.get(cls, 0) + n_gen
        elif "miss" in rec:
            mstep, _mwall = rec["miss"]
            counts["deadline_misses"] += 1
            w_of(mstep)["deadline_misses"] += 1
            if t_first is not None and t_first <= sla_ttft_ms:
                good_tokens += 0
                cls = rec["sla_class"]
                class_tokens[cls] = class_tokens.get(cls, 0)
        else:
            counts["shed"] += 1
            w_of(rec.get("last_shed_step", rec["submit_step"]))["shed"] \
                += 1
    windows = []
    for w in wins:
        t, p = w.pop("_ttft"), w.pop("_tpot")
        windows.append({**w,
                        "ttft_ms_p50": _pct(t, 50),
                        "ttft_ms_p99": _pct(t, 99),
                        "tpot_ms_p50": _pct(p, 50),
                        "tpot_ms_p99": _pct(p, 99)})
    duration = (t_end - t0) if (t0 is not None
                                and t_end is not None) else None
    return {
        "submitted": len(rids),
        **counts,
        "tokens_generated": tokens,
        "fleet_steps": n_steps,
        "windows": windows,
        "window_steps": window_steps,
        "duration_s": (round(duration, 3) if duration is not None
                       else None),
        "ttft_ms_p50": _pct(ttft_all, 50),
        "ttft_ms_p99": _pct(ttft_all, 99),
        "tpot_ms_p50": _pct(tpot_all, 50),
        "tpot_ms_p99": _pct(tpot_all, 99),
        "goodput_tok_per_s": (round(good_tokens / duration, 1)
                              if duration else None),
        "goodput_by_class": {str(k): (round(v / duration, 1)
                                      if duration else None)
                             for k, v in sorted(class_tokens.items())},
        "timeline_truncated": (
            getattr(tracer, "events_dropped", 0) > 0
            or any(getattr(tr, "events_dropped", 0) > 0
                   for tr in engine_tracers)),
        "sla": {"ttft_ms": sla_ttft_ms, "tpot_ms": sla_tpot_ms},
    }


def steady_stream(n_requests: int, vocab_size: int, *,
                  rate: float = 0.5,
                  prompt_lens: Sequence[int] = (4, 8),
                  max_new: Sequence[int] = (8,), seed: int = 0,
                  start_rid: int = 0,
                  sla: Optional[Sequence[dict]] = None,
                  eos_id: Optional[int] = None):
    """`poisson_trace` as a GENERATOR (ISSUE 17): yields requests one
    at a time in arrival order, so the streaming fleet driver holds at
    most one unsubmitted request — the arrival stream itself costs O(1)
    RSS at any ``n_requests`` (10⁶ sessions is just a bigger count).
    Same deterministic construction as `poisson_trace` seed-for-seed;
    ``sla`` stamps class dicts round-robin like `with_sla`."""
    rng = np.random.default_rng(seed)
    t = 0.0
    for i in range(n_requests):
        t += rng.exponential(1.0 / rate)
        kw = dict(sla[i % len(sla)]) if sla else {}
        yield Request(
            rid=start_rid + i,
            prompt=tuple(int(x) for x in rng.integers(
                0, vocab_size, int(rng.choice(list(prompt_lens))))),
            max_new_tokens=int(rng.choice(list(max_new))),
            arrival=int(t), eos_id=eos_id, **kw)


def serial_baseline(model, params, requests: list, *,
                    warm: bool = True) -> dict:
    """The same trace through sequential batch-1 `generate` calls — the
    repo's pre-serve inference surface.  ``warm=True`` runs the trace
    once first so every (prompt_len, max_new) program is compiled before
    the measured pass (the engine gets the same courtesy from its warmup
    trace run)."""
    import jax.numpy as jnp

    from ..models.generate import generate

    def one_pass() -> int:
        toks = 0
        for r in requests:
            prompt = jnp.asarray([list(r.prompt)], jnp.int32)
            out = generate(model, params, prompt, r.max_new_tokens,
                           eos_id=r.eos_id)
            out.block_until_ready()
            # count like the engine does: tokens up to AND INCLUDING the
            # first eos (generate freezes after it — the frozen repeats
            # are not useful work and must not pad the baseline's tok/s)
            new = np.asarray(out)[0, len(r.prompt):]
            if r.eos_id is not None and (new == r.eos_id).any():
                toks += int(np.argmax(new == r.eos_id)) + 1
            else:
                toks += r.max_new_tokens
        return toks

    if warm:
        one_pass()
    watch = Stopwatch()
    n = one_pass()
    duration = watch.elapsed()
    return {"tok_per_s": round(n / duration, 1) if duration else None,
            "duration_s": round(duration, 3), "tokens": n}
