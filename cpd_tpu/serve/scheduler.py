"""Request scheduler for continuous batching — all host-side, all int32.

The device side (serve/model.py) wants exactly two things per step: a
fixed-shape decode batch (one token per slot, free slots masked) and at
most one prefill chunk.  Everything stateful — admission verdicts, page
reservation, chunk bookkeeping, completion, eviction — lives here in
plain Python so the jitted programs stay pure and shape-stable.

Slot lifecycle (docs/SERVING.md state diagram):

    FREE ──admit──> PREFILL ──prompt done──> DECODE ──eos/max──> FREE
                        │  (one chunk per engine step,             ▲
                        │   OLDEST admitted slot first)            │
                        └──────────── repair re-prefill ───────────┘
                              (a corrupt page rewinds fed K/V;
                               state and tokens are kept)

Admission reserves the request's WORST-CASE page count —
``ceil((prompt + max_new) / page_size)`` — up front, so a request that
enters the batch can always finish: no mid-decode allocation exists to
fail, which is what makes "zero dropped requests" structural.  The
queue is FIFO with head-of-line blocking (a big request waits for pages
rather than being overtaken into starvation — FIFO-within-class is an
invariant, pinned by the starvation test).

SLA verdicts (ISSUE 10): `submit` no longer unconditionally enqueues —
it returns ``ACCEPT`` (a FREE slot + pages are available right now, the
request enters the batch at the next step), ``QUEUE`` (it waits behind
the backlog), or ``SHED`` (rejected at admission: the bounded queue is
full, the active degradation rung sheds its SLA class, or its TTFT
deadline is PROVABLY unmeetable — `ttft_bound_steps`).  A shed request
is never silently dropped: the engine records the verdict and resolves
the rid as SHED.

The TTFT bound is structural, not a timer: prefill dispatches at most
``prefill_chunk`` prompt tokens per engine step, admission is FIFO, and
the prefill dispatcher serves the OLDEST admitted slot first — so every
prompt token ahead of a new request must be fed before its own prompt
finishes.  With ``n = ceil((backlog + own_prompt) / prefill_chunk)``
required dispatches and the first one eligible to run in the current
step, the first token cannot exist before ``n - 1`` steps from now (or
``ceil(own_prompt / chunk) - 1`` steps after its arrival, whichever is
later).  A deadline tighter than that bound is unmeetable by
construction, whatever the decode load does.  (Oldest-first is load-
bearing: the previous round-robin prefill could serve a later short
prompt ahead of an earlier long one, which would make the aggregated
bound unsound.)  The bound counts the backlog present AT SUBMIT TIME
and is exact under the NO-CANCELLATION assumption: if everything
queued ahead is actually served, the deadline is provably missed.  A
later cancellation of counted backlog (a deadline expiry or rung
purge ahead of the request) removes work and can make real TTFT beat
the bound — so a shed can be PESSIMISTIC in that case, never the
reverse: a request the bound admits is never doomed by backlog the
bound failed to count.  Admission control sheds on the load actually
offered, not on hypothetical future cancellations.

The scheduler never touches the pool; it owns the free list and each
slot's page-id tuple, and renders them into the trash-padded
``(S, max_pages)`` int32 page-table rows the jitted gather consumes.

Page REFCOUNTS (ISSUE 13): every allocated page carries a reference
count (``page_refs``), because the fleet layer's content-addressed
prefix cache (cpd_tpu/fleet/prefix.py) shares identical prompt-prefix
pages copy-on-write across requests — a page may be held by several
slots AND the cache at once.  `retain` / `release` are the ONE
allocation discipline: a page returns to the free list exactly when its
last reference drops.  Without sharing every count is 1 and the
behaviour (including free-list order) is identical to the pre-refcount
scheduler.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import List, Optional

import numpy as np

from .kvcache import TRASH_PAGE

__all__ = ["Request", "Slot", "Scheduler", "FREE", "PREFILL", "DECODE",
           "ACCEPT", "QUEUE", "SHED"]

FREE, PREFILL, DECODE = "free", "prefill", "decode"
# admission verdicts (`Scheduler.submit` / `ServeEngine.submit` return)
ACCEPT, QUEUE, SHED = "accept", "queue", "shed"


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request.  ``prompt`` is a tuple of token ids;
    ``arrival`` is the engine-step index at which the load generator
    makes it visible (step-based so traces replay deterministically).

    SLA fields (ISSUE 10, all step-clock so drills replay exactly):
    ``sla_class`` orders traffic priority (0 = highest; the degradation
    ladder sheds the LARGEST classes first); ``deadline_steps`` is the
    TTFT deadline — the first token must be sampled no later than
    engine step ``arrival + deadline_steps``; ``tpot_budget_steps`` is
    the per-token budget after the first — generated token ``k`` must
    land by ``first_token_step + k * tpot_budget_steps``.  ``None``
    disables the respective deadline (the pre-SLA behaviour)."""
    rid: int
    prompt: tuple
    max_new_tokens: int
    arrival: int = 0
    eos_id: Optional[int] = None
    sla_class: int = 0
    deadline_steps: Optional[int] = None
    tpot_budget_steps: Optional[int] = None

    def __post_init__(self):
        if len(self.prompt) < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must "
                             f"be >= 1, got {self.max_new_tokens}")
        if self.sla_class < 0:
            raise ValueError(f"request {self.rid}: sla_class must be "
                             f">= 0, got {self.sla_class}")
        for name in ("deadline_steps", "tpot_budget_steps"):
            v = getattr(self, name)
            if v is not None and v < 1:
                raise ValueError(f"request {self.rid}: {name} must be "
                                 f">= 1, got {v}")

    @property
    def t_max(self) -> int:
        """Cache positions the request can occupy: prompt + all generated
        tokens except the last (which is sampled but never fed) — the
        same sizing rule as `models.generate` (t_p + max_new)."""
        return len(self.prompt) + self.max_new_tokens


@dataclasses.dataclass
class Slot:
    """One decode-batch lane and its cache bookkeeping."""
    index: int
    state: str = FREE
    req: Optional[Request] = None
    pages: tuple = ()        # reserved page ids, admission-ordered
    fed: int = 0             # positions whose K/V is in the cache
    next_token: int = -1     # token to feed at position `fed` (DECODE)
    generated: List[int] = dataclasses.field(default_factory=list)
    seq: int = -1            # admission sequence number (FIFO service)
    first_token_step: int = -1   # engine step of the first sampled token
    last_progress: int = -1      # engine step `fed` last advanced
    prefix_registered: int = 0   # full prompt pages already offered to
    #                              the prefix cache (a watermark, so
    #                              each prefill chunk registers only
    #                              NEWLY completed pages — not an
    #                              O(pages) re-walk per chunk)

    @property
    def history(self) -> tuple:
        """Every token whose K/V the cache holds (or will hold next) —
        the recompute source for corruption repair."""
        if self.req is None:
            return ()
        return self.req.prompt + tuple(self.generated)

    def reset(self) -> None:
        self.state = FREE
        self.req = None
        self.pages = ()
        self.fed = 0
        self.next_token = -1
        self.generated = []
        self.seq = -1
        self.first_token_step = -1
        self.last_progress = -1
        self.prefix_registered = 0


class Scheduler:
    """Admission + slot/page bookkeeping for a `ServeEngine`.

    ``n_slots`` is the decode batch's fixed shape; ``max_pages`` the
    static per-slot page-table width (capacity ``max_pages * page_size``
    positions per request); ``n_pages`` the pool's total page count
    (page 0 reserved as trash); ``prefill_chunk`` the engine's prompt
    tokens per prefill dispatch (the TTFT bound's throughput constant).

    Admission POLICY knobs — all host state the engine (and through it
    the `ServeSupervisor` degradation ladder) re-points every step:
    ``max_queue`` bounds the wait queue (None = unbounded; beyond it
    `submit` sheds — bounded-queue backpressure instead of head-of-line
    starvation during burst storms); ``shed_class_above`` sheds every
    request whose ``sla_class`` is >= it at admission time;
    ``admission_cap`` caps admissions per engine step."""

    def __init__(self, n_slots: int, n_pages: int, page_size: int,
                 max_pages: int, prefill_chunk: int = 16,
                 max_queue: Optional[int] = None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1 (or None), got "
                             f"{max_queue}")
        self.n_slots = n_slots
        self.page_size = page_size
        self.max_pages = max_pages
        self.prefill_chunk = prefill_chunk
        self.slots = [Slot(i) for i in range(n_slots)]
        # page 0 is the trash page; ascending ids keep runs reproducible
        self.total_pages = n_pages - 1
        self.free_pages = deque(range(1, n_pages))
        # page id -> reference count (absent = free); the prefix cache
        # and CoW sharing push counts above 1 (module docstring)
        self.page_refs: dict = {}
        self.queue: deque = deque()
        self._admit_seq = 0       # admission sequence (oldest-first prefill)
        # per-step policy (engine/supervisor-owned; see class docstring)
        self.max_queue = max_queue
        self.shed_class_above: Optional[int] = None
        self.admission_cap: Optional[int] = None

    # -- capacity ---------------------------------------------------------

    def pages_needed(self, req: Request) -> int:
        return -(-req.t_max // self.page_size)

    def capacity_positions(self) -> int:
        return self.max_pages * self.page_size

    def page_utilization(self) -> float:
        """Fraction of allocatable pages currently reserved — the
        supervisor's page-pressure signal."""
        if self.total_pages <= 0:
            return 1.0
        return 1.0 - len(self.free_pages) / self.total_pages

    def validate(self, req: Request) -> None:
        """Fail fast at submit time when a request can NEVER be served —
        the serving twin of `generate`'s t_max check.  Both limits are
        checked: the per-request position window AND the pool's
        allocatable page count (a custom small `n_pages` could otherwise
        admit a request to the queue that no amount of draining frees
        enough pages for — head-of-line deadlock, not a drop)."""
        if req.t_max > self.capacity_positions():
            raise ValueError(
                f"request {req.rid}: prompt ({len(req.prompt)}) + "
                f"max_new_tokens ({req.max_new_tokens}) = {req.t_max} "
                f"exceeds the per-request capacity "
                f"{self.capacity_positions()} (max_pages={self.max_pages}"
                f" x page_size={self.page_size})")
        if self.pages_needed(req) > self.total_pages:
            raise ValueError(
                f"request {req.rid}: needs {self.pages_needed(req)} "
                f"pages but the pool only has {self.total_pages} "
                "allocatable (n_pages minus the trash page) — it would "
                "deadlock the admission queue")

    # -- admission verdicts ----------------------------------------------

    def prefill_backlog_tokens(self) -> int:
        """Prompt tokens that MUST be prefill-dispatched before any new
        request's own prompt under FIFO admission + oldest-first prefill:
        the unfed remainder of every PREFILL slot plus every queued
        prompt."""
        backlog = sum(len(s.req.prompt) - s.fed for s in self.slots
                      if s.state == PREFILL)
        return backlog + sum(len(q.prompt) for q in self.queue)

    def ttft_bound_steps(self, req: Request) -> int:
        """Structural lower bound on the prefill-chunk DISPATCHES that
        must run before ``req``'s first token exists (module docstring):
        at most one chunk (<= ``prefill_chunk`` tokens) is dispatched
        per engine step, and under oldest-admitted-first prefill every
        token of the current backlog precedes every token of ``req``'s
        prompt.  The first of those dispatches can run in the CURRENT
        step (submission precedes the step's prefill phase), so the
        earliest first-token step is ``now + ttft_bound_steps - 1``."""
        need = self.prefill_backlog_tokens() + len(req.prompt)
        return -(-need // self.prefill_chunk)

    def deadline_unmeetable(self, req: Request, step: int) -> bool:
        """True when ``req``'s TTFT deadline is provably missed GIVEN
        the backlog ahead of it is served (module docstring — a later
        cancellation ahead can make a shed pessimistic, never let an
        admitted request be doomed by counted backlog): the backlog
        bound from now, or the request's own prompt-feed time from its
        arrival, lands past ``arrival + deadline_steps``.  Both bounds
        count dispatches, and dispatch 1 of ``n`` can run in its
        starting step — so ``n`` dispatches finish no earlier than
        ``start + n - 1``, and a first token landing exactly AT the
        deadline step is on time (the engine's expiry uses the same
        strict-past convention)."""
        if req.deadline_steps is None:
            return False
        latest = req.arrival + req.deadline_steps
        own = -(-len(req.prompt) // self.prefill_chunk)
        earliest = max(step + self.ttft_bound_steps(req) - 1,
                       req.arrival + own - 1)
        return earliest > latest

    def submit(self, req: Request, step: int = 0) -> str:
        """Admission verdict for ``req`` at engine step ``step``:
        ``SHED`` (rejected — degradation rung sheds its class, bounded
        queue full, or TTFT deadline provably unmeetable), ``ACCEPT``
        (enqueued with a FREE slot + pages available right now), or
        ``QUEUE`` (enqueued behind the backlog).  Impossible requests
        (over capacity / bigger than the pool) still raise."""
        self.validate(req)
        if (self.shed_class_above is not None
                and req.sla_class >= self.shed_class_above):
            return SHED
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            return SHED
        if self.deadline_unmeetable(req, step):
            return SHED
        immediate = (not self.queue and req.arrival <= step
                     and any(s.state == FREE for s in self.slots)
                     and len(self.free_pages) >= self.pages_needed(req))
        self.queue.append(req)
        return ACCEPT if immediate else QUEUE

    def shed_queued_class(self, shed_class_above: int) -> list:
        """Purge queued requests whose ``sla_class`` >= the rung's shed
        class (the 'shed lowest-SLA-class traffic' rung acting on work
        that was queued BEFORE the rung engaged).  Returns the shed
        requests in queue order; FIFO order of the survivors is
        untouched."""
        keep, shed = deque(), []
        for q in self.queue:
            (shed if q.sla_class >= shed_class_above else keep).append(q)
        self.queue = keep
        return shed

    def expire_queued(self, step: int) -> list:
        """Remove queued requests whose TTFT deadline has already passed
        (``step > arrival + deadline_steps`` — even an immediate
        admission could no longer produce the first token in time).
        Returns them in queue order for DEADLINE_MISS accounting."""
        keep, expired = deque(), []
        for q in self.queue:
            dead = (q.deadline_steps is not None
                    and step > q.arrival + q.deadline_steps)
            (expired if dead else keep).append(q)
        self.queue = keep
        return expired

    # -- page reference counting ------------------------------------------

    def retain(self, page_id: int) -> int:
        """Add one reference to an allocated (or just-popped) page;
        returns the new count.  The trash page is never refcounted."""
        if page_id == TRASH_PAGE:
            raise ValueError("the trash page is never retained")
        self.page_refs[page_id] = self.page_refs.get(page_id, 0) + 1
        return self.page_refs[page_id]

    def release(self, page_id: int) -> bool:
        """Drop one reference; at zero the page returns to the free
        list.  Returns True when the page was actually freed — the
        ``pages_freed`` counter counts pool returns, not reference
        drops (a shared page survives its first releases)."""
        n = self.page_refs.get(page_id, 0)
        if n <= 0:
            raise ValueError(f"release of unallocated page {page_id}")
        if n == 1:
            del self.page_refs[page_id]
            self.free_pages.append(page_id)
            return True
        self.page_refs[page_id] = n - 1
        return False

    def reserve_pages(self, n: int) -> tuple:
        """Pop ``n`` fresh pages off the free list at refcount 1 — the
        one allocation path (admission, watchdog reassignment, capsule
        adoption).  Raises if the free list is short; callers check (or
        make room) first."""
        if len(self.free_pages) < n:
            raise RuntimeError(
                f"page pool exhausted: need {n}, have "
                f"{len(self.free_pages)} free")
        pages = tuple(self.free_pages.popleft() for _ in range(n))
        for p in pages:
            self.retain(p)
        return pages

    def shared_pages(self) -> list:
        """Page ids whose refcount exceeds 1 — the dedup accounting the
        fleet analytics (`quant.numerics.kv_pool_bytes`) price."""
        return sorted(p for p, n in self.page_refs.items() if n > 1)

    # -- admission / eviction --------------------------------------------

    def admit(self, step: int) -> list:
        """Move arrived queue heads into FREE slots while pages last
        (and ``admission_cap`` allows).  Returns the newly admitted
        slots (FIFO; head-of-line blocking on page pressure — never a
        drop)."""
        admitted = []
        for slot in self.slots:
            if (self.admission_cap is not None
                    and len(admitted) >= self.admission_cap):
                break
            if slot.state != FREE:
                continue
            if not self.queue or self.queue[0].arrival > step:
                break
            req = self.queue[0]
            need = self.pages_needed(req)
            if len(self.free_pages) < need:
                break
            self.queue.popleft()
            slot.req = req
            slot.pages = self.reserve_pages(need)
            slot.state = PREFILL
            slot.fed = 0
            slot.generated = []
            slot.next_token = -1
            slot.seq = self._admit_seq
            slot.first_token_step = -1
            slot.last_progress = step
            self._admit_seq += 1
            admitted.append(slot)
        return admitted

    def evict(self, slot: Slot) -> int:
        """Release a finished slot's page references; -> pages actually
        FREED (== the page count unless the prefix cache or another
        slot still shares some)."""
        freed = sum(self.release(p) for p in slot.pages)
        slot.reset()
        return freed

    def reassign_pages(self, slot: Slot) -> int:
        """Watchdog eviction support: release the slot's page refs and
        reserve a FRESH private set of the same size.  Without sharing
        the slot's own pages just came back, so the reserve always
        succeeds; a slot holding SHARED pages returns fewer than it
        takes, and the engine makes room first — or skips the eviction
        when it cannot (prefix-cache eviction, `ServeEngine._make_room`
        / the watchdog's skip).  The request stays in its slot; the
        engine rebuilds the cache from history into the new pages.
        Returns the pages actually FREED (pool returns, like `evict` —
        a shared page survives its release); the reserved count is the
        slot's page width."""
        n = len(slot.pages)
        freed = sum(self.release(p) for p in slot.pages)
        slot.pages = self.reserve_pages(n)
        return freed

    # -- step composition -------------------------------------------------

    def decode_slots(self) -> list:
        return [s for s in self.slots if s.state == DECODE]

    def next_prefill_slot(self) -> Optional[Slot]:
        """OLDEST admitted PREFILL slot — strict FIFO service, one chunk
        per engine step.  This discipline is what makes
        `ttft_bound_steps` a true lower bound (module docstring): every
        backlog token is dispatched before any newer prompt's."""
        pre = [s for s in self.slots if s.state == PREFILL]
        if not pre:
            return None
        return min(pre, key=lambda s: s.seq)

    def page_row(self, slot: Slot) -> np.ndarray:
        """The slot's trash-padded (max_pages,) int32 page-table row."""
        row = np.full((self.max_pages,), TRASH_PAGE, np.int32)
        row[:len(slot.pages)] = slot.pages
        return row

    def page_table(self) -> np.ndarray:
        """(S, max_pages) int32 rows for the whole decode batch."""
        return np.stack([self.page_row(s) for s in self.slots])

    def owners_of_page(self, page_id: int) -> list:
        """EVERY live slot referencing the page — under prefix-cache
        CoW sharing a corrupt shared page has several owners, and the
        repair ladder must recompute all of them (slot-index order, so
        the repair sequence is deterministic)."""
        return [slot for slot in self.slots
                if slot.state != FREE and page_id in slot.pages]

    def live_pages(self) -> list:
        """Every page reserved by a slot that already HOLDS cached K/V
        (``fed > 0``), slot-index then reservation order — the ONE
        deterministic target list for the ``kv_storm`` multi-page
        corruption drill (`ServeEngine._fire_storm` consumes it;
        admitted-but-unfed slots are excluded because their pages hold
        nothing a flip could corrupt meaningfully)."""
        out = []
        for slot in self.slots:
            if slot.state != FREE and slot.fed > 0:
                out.extend(slot.pages)
        return out

    def drained(self) -> bool:
        return not self.queue and all(s.state == FREE for s in self.slots)
