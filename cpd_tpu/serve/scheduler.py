"""Request scheduler for continuous batching — all host-side, all int32.

The device side (serve/model.py) wants exactly two things per step: a
fixed-shape decode batch (one token per slot, free slots masked) and at
most one prefill chunk.  Everything stateful — admission, page
reservation, chunk bookkeeping, completion, eviction — lives here in
plain Python so the jitted programs stay pure and shape-stable.

Slot lifecycle (docs/SERVING.md state diagram):

    FREE ──admit──> PREFILL ──prompt done──> DECODE ──eos/max──> FREE
                        │  (one chunk per engine step,             ▲
                        │   round-robin across PREFILL slots)      │
                        └──────────── repair re-prefill ───────────┘
                              (a corrupt page rewinds fed K/V;
                               state and tokens are kept)

Admission reserves the request's WORST-CASE page count —
``ceil((prompt + max_new) / page_size)`` — up front, so a request that
enters the batch can always finish: no mid-decode allocation exists to
fail, which is what makes "zero dropped requests" structural.  The
queue is FIFO with head-of-line blocking (a big request waits for pages
rather than being overtaken into starvation).

The scheduler never touches the pool; it owns the free list and each
slot's page-id tuple, and renders them into the trash-padded
``(S, max_pages)`` int32 page-table rows the jitted gather consumes.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import List, Optional

import numpy as np

from .kvcache import TRASH_PAGE

__all__ = ["Request", "Slot", "Scheduler", "FREE", "PREFILL", "DECODE"]

FREE, PREFILL, DECODE = "free", "prefill", "decode"


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request.  ``prompt`` is a tuple of token ids;
    ``arrival`` is the engine-step index at which the load generator
    makes it visible (step-based so traces replay deterministically)."""
    rid: int
    prompt: tuple
    max_new_tokens: int
    arrival: int = 0
    eos_id: Optional[int] = None

    def __post_init__(self):
        if len(self.prompt) < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens must "
                             f"be >= 1, got {self.max_new_tokens}")

    @property
    def t_max(self) -> int:
        """Cache positions the request can occupy: prompt + all generated
        tokens except the last (which is sampled but never fed) — the
        same sizing rule as `models.generate` (t_p + max_new)."""
        return len(self.prompt) + self.max_new_tokens


@dataclasses.dataclass
class Slot:
    """One decode-batch lane and its cache bookkeeping."""
    index: int
    state: str = FREE
    req: Optional[Request] = None
    pages: tuple = ()        # reserved page ids, admission-ordered
    fed: int = 0             # positions whose K/V is in the cache
    next_token: int = -1     # token to feed at position `fed` (DECODE)
    generated: List[int] = dataclasses.field(default_factory=list)

    @property
    def history(self) -> tuple:
        """Every token whose K/V the cache holds (or will hold next) —
        the recompute source for corruption repair."""
        if self.req is None:
            return ()
        return self.req.prompt + tuple(self.generated)

    def reset(self) -> None:
        self.state = FREE
        self.req = None
        self.pages = ()
        self.fed = 0
        self.next_token = -1
        self.generated = []


class Scheduler:
    """Admission + slot/page bookkeeping for a `ServeEngine`.

    ``n_slots`` is the decode batch's fixed shape; ``max_pages`` the
    static per-slot page-table width (capacity ``max_pages * page_size``
    positions per request); ``n_pages`` the pool's total page count
    (page 0 reserved as trash)."""

    def __init__(self, n_slots: int, n_pages: int, page_size: int,
                 max_pages: int):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = n_slots
        self.page_size = page_size
        self.max_pages = max_pages
        self.slots = [Slot(i) for i in range(n_slots)]
        # page 0 is the trash page; ascending ids keep runs reproducible
        self.total_pages = n_pages - 1
        self.free_pages = deque(range(1, n_pages))
        self.queue: deque = deque()
        self._prefill_rr = 0      # round-robin cursor over PREFILL slots

    # -- capacity ---------------------------------------------------------

    def pages_needed(self, req: Request) -> int:
        return -(-req.t_max // self.page_size)

    def capacity_positions(self) -> int:
        return self.max_pages * self.page_size

    def validate(self, req: Request) -> None:
        """Fail fast at submit time when a request can NEVER be served —
        the serving twin of `generate`'s t_max check.  Both limits are
        checked: the per-request position window AND the pool's
        allocatable page count (a custom small `n_pages` could otherwise
        admit a request to the queue that no amount of draining frees
        enough pages for — head-of-line deadlock, not a drop)."""
        if req.t_max > self.capacity_positions():
            raise ValueError(
                f"request {req.rid}: prompt ({len(req.prompt)}) + "
                f"max_new_tokens ({req.max_new_tokens}) = {req.t_max} "
                f"exceeds the per-request capacity "
                f"{self.capacity_positions()} (max_pages={self.max_pages}"
                f" x page_size={self.page_size})")
        if self.pages_needed(req) > self.total_pages:
            raise ValueError(
                f"request {req.rid}: needs {self.pages_needed(req)} "
                f"pages but the pool only has {self.total_pages} "
                "allocatable (n_pages minus the trash page) — it would "
                "deadlock the admission queue")

    # -- admission / eviction --------------------------------------------

    def submit(self, req: Request) -> None:
        self.validate(req)
        self.queue.append(req)

    def admit(self, step: int) -> list:
        """Move arrived queue heads into FREE slots while pages last.
        Returns the newly admitted slots (FIFO; head-of-line blocking on
        page pressure — never a drop)."""
        admitted = []
        for slot in self.slots:
            if slot.state != FREE:
                continue
            if not self.queue or self.queue[0].arrival > step:
                break
            req = self.queue[0]
            need = self.pages_needed(req)
            if len(self.free_pages) < need:
                break
            self.queue.popleft()
            slot.req = req
            slot.pages = tuple(self.free_pages.popleft()
                               for _ in range(need))
            slot.state = PREFILL
            slot.fed = 0
            slot.generated = []
            slot.next_token = -1
            admitted.append(slot)
        return admitted

    def evict(self, slot: Slot) -> int:
        """Return a finished slot's pages to the free list; -> page count."""
        n = len(slot.pages)
        self.free_pages.extend(slot.pages)
        slot.reset()
        return n

    # -- step composition -------------------------------------------------

    def decode_slots(self) -> list:
        return [s for s in self.slots if s.state == DECODE]

    def next_prefill_slot(self) -> Optional[Slot]:
        """Round-robin over PREFILL slots: one chunk per engine step, so
        several long prompts make progress fairly while decode runs."""
        pre = [s for s in self.slots if s.state == PREFILL]
        if not pre:
            return None
        slot = pre[self._prefill_rr % len(pre)]
        self._prefill_rr += 1
        return slot

    def page_row(self, slot: Slot) -> np.ndarray:
        """The slot's trash-padded (max_pages,) int32 page-table row."""
        row = np.full((self.max_pages,), TRASH_PAGE, np.int32)
        row[:len(slot.pages)] = slot.pages
        return row

    def page_table(self) -> np.ndarray:
        """(S, max_pages) int32 rows for the whole decode batch."""
        return np.stack([self.page_row(s) for s in self.slots])

    def owner_of_page(self, page_id: int) -> Optional[Slot]:
        for slot in self.slots:
            if slot.state != FREE and page_id in slot.pages:
                return slot
        return None

    def drained(self) -> bool:
        return not self.queue and all(s.state == FREE for s in self.slots)
