"""cpd_tpu.serve — continuous-batching serving on the quantized substrate.

The serving layer (L5) over the whole stack (ROADMAP item 1): a request
scheduler with continuous batching and chunked prefill
(`scheduler.Scheduler`, `engine.ServeEngine`), a paged KV cache whose
pages are bit-packed eXmY code words via the PR 3 wire codec
(`kvcache`), per-page Fletcher digests with repair-by-recomputation
(`engine.ServeEngine.scrub`), and the load-generator harness
(`loadgen`, `tools/bench_serve.py`).  See docs/SERVING.md.
"""

from .engine import ServeEngine
from .kvcache import KVCacheConfig
from .loadgen import (bursty_trace, mixed_trace, poisson_trace,
                      run_trace, serial_baseline)
from .model import ModelSpec, spec_from_model
from .scheduler import Request, Scheduler

__all__ = ["ServeEngine", "KVCacheConfig", "Request", "Scheduler",
           "ModelSpec", "spec_from_model", "poisson_trace",
           "bursty_trace", "mixed_trace", "run_trace",
           "serial_baseline"]
