"""cpd_tpu.serve — SLA-guarded continuous batching on the quantized
substrate.

The serving layer (L5) over the whole stack (ROADMAP item 1): a request
scheduler with continuous batching, chunked prefill and
ACCEPT/QUEUE/SHED admission verdicts (`scheduler.Scheduler`,
`engine.ServeEngine`), a paged KV cache whose pages are bit-packed eXmY
code words via the PR 3 wire codec (`kvcache`), per-page Fletcher
digests with repair-by-recomputation (`engine.ServeEngine.scrub`), the
`supervisor.ServeSupervisor` degradation ladder + deadline cancellation
+ no-progress watchdog + crash-recovery snapshots (ISSUE 10), and the
load-generator harness (`loadgen`, `tools/bench_serve.py`).  See
docs/SERVING.md.
"""

from .engine import ResultStore, ServeEngine
from .kvcache import KVCacheConfig
from .loadgen import (bursty_trace, decode_tail_matches,
                      fleet_timeline_metrics, flash_crowd, mixed_trace,
                      poisson_trace, run_fleet_trace, run_trace,
                      serial_baseline, shared_prefix_trace,
                      steady_stream, timeline_metrics, with_sla)
from .model import ModelSpec, spec_from_model
from .scheduler import ACCEPT, QUEUE, Request, Scheduler, SHED
from .supervisor import Rung, ServeSupervisor, default_rungs

__all__ = ["ServeEngine", "ResultStore", "KVCacheConfig", "Request",
           "Scheduler", "ACCEPT", "QUEUE", "SHED", "ModelSpec",
           "spec_from_model", "Rung", "ServeSupervisor", "default_rungs",
           "poisson_trace", "bursty_trace", "mixed_trace", "with_sla",
           "flash_crowd", "run_trace", "serial_baseline",
           "decode_tail_matches", "timeline_metrics",
           "shared_prefix_trace", "run_fleet_trace",
           "fleet_timeline_metrics", "steady_stream"]
