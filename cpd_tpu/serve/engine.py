"""ServeEngine — continuous batching over the paged eXmY KV cache.

One engine step is at most three device dispatches, each jit-stable:

1. (every ``scrub_every`` steps) the **scrub** — recompute every page
   digest and compare to the maintained array; mismatches are corruption
   (docs/SERVING.md repair ladder): a page owned by a live request
   triggers **repair by recomputation** — the slot's cached K/V is
   rebuilt from its token history (prompt + generated so far, which the
   host always holds) through the same prefill program, synchronously,
   without dropping the request; a free page's corruption is absorbed
   (nothing will ever read it before it is rewritten).
2. one **prefill chunk** for one PREFILL slot (round-robin), so long
   prompts trickle in without ever stalling the decode batch.
3. one **decode step** for the whole fixed-shape batch — every DECODE
   slot feeds its pending token and samples the next; FREE/PREFILL
   slots ride along masked to the trash page.

Detection is **two-tier** because an append re-digests its page from
the post-write bytes (which would re-bless pre-existing corruption):
every jitted dispatch verifies the pages it is about to append to
BEFORE writing (`kvcache.check_digests`, the ``bad`` verdict riding out
of the step), and the periodic scrub covers pages no append touches.
A nonzero verdict discards that dispatch's results (`_checked`), runs
the scrub+repair on the intact pre-dispatch state, and re-dispatches —
so corruption can never be served OR blessed, whatever its timing
relative to the scrub period.

Fault injection rides the existing `resilience.FaultPlan` grammar: the
``kv_flip@s:k`` kind flips one byte in slot ``k``'s first page at step
``s`` (held until that slot actually has cached K/V), exactly the
corruption class the digests exist to catch.  Injection, detection,
repair and completion are all deterministic: two runs of the same
(model, trace, plan) produce identical counters — the serve-smoke gate.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np

from . import kvcache
from .kvcache import KVCacheConfig, TRASH_PAGE
from .model import make_decode_step, make_prefill_step, spec_from_model
from .scheduler import DECODE, FREE, PREFILL, Request, Scheduler

__all__ = ["ServeEngine"]

_COUNTERS = ("admitted", "completed", "prompt_tokens", "tokens_generated",
             "decode_steps", "prefill_chunks", "repair_chunks", "scrubs",
             "kv_flips_injected", "kv_inline_detects", "kv_pages_corrupt",
             "kv_corrupt_free_pages", "kv_repairs", "pages_reserved",
             "pages_freed", "kv_faults_unfired")


class ServeEngine:
    """Continuous-batching serving loop for one `TransformerLM`.

    Parameters
    ----------
    model, params : the trained module (single-device config) + pytree.
    n_slots : fixed decode-batch width.
    max_seq : per-request capacity (prompt + max_new); rounded up to
        whole pages.  Requests exceeding it are rejected at `submit` —
        fail-fast, the serving twin of `generate(t_max=...)`.
    page_size : token positions per KV page.
    n_pages : total pool pages (default: full capacity for every slot
        plus the trash page — allocation can then never starve).
    kv_format : (exp_bits, man_bits) eXmY cache codec; (8, 23) is the
        lossless byte split, e5m2/e4m3 the 4x-compressed formats.
    raw_cache : fp32 pool, no codec — the bitwise oracle for (8, 23).
    prefill_chunk : prompt tokens per prefill dispatch.
    scrub_every : digest-scrub period in engine steps (0 = only explicit
        `scrub()` calls).
    fault_plan : `resilience.FaultPlan`; only its ``kv_flip`` specs are
        consumed here.
    temperature / seed : 0 = greedy argmax; > 0 samples from
        softmax(logits / T) with a deterministic host RNG.
    """

    def __init__(self, model, params, *, n_slots: int = 4,
                 max_seq: int = 128, page_size: int = 16,
                 n_pages: Optional[int] = None,
                 kv_format: tuple = (8, 23), raw_cache: bool = False,
                 prefill_chunk: int = 16, scrub_every: int = 0,
                 fault_plan=None, temperature: float = 0.0,
                 seed: int = 0, record_logits: bool = False):
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        spec = spec_from_model(model)
        max_pages = -(-max_seq // page_size)
        if n_pages is None:
            n_pages = 1 + n_slots * max_pages
        exp_bits, man_bits = kv_format
        self.cfg = KVCacheConfig(
            n_layers=spec.n_layers, n_kv_heads=spec.kv_heads,
            head_dim=spec.head_dim, page_size=page_size, n_pages=n_pages,
            exp_bits=exp_bits, man_bits=man_bits, raw=raw_cache)
        self.spec = spec
        self.params = params
        self.sched = Scheduler(n_slots, n_pages, page_size, max_pages)
        self._prefill_chunk = prefill_chunk
        self._scrub_every = scrub_every
        self._temperature = float(temperature)
        self._rng = np.random.default_rng(seed)

        self._decode_fn = make_decode_step(spec, self.cfg)
        self._prefill_fn = make_prefill_step(spec, self.cfg, prefill_chunk)
        self._scrub_fn = jax.jit(kvcache.all_digests)
        self._pool = kvcache.alloc_pool(self.cfg)
        # initial state: digest-of-zero-page everywhere, via the same
        # compiled scrub program every later pass reuses
        self._digests = self._scrub_fn(self._pool)

        self._kv_pending = list(fault_plan.kv_faults()) if fault_plan \
            else []
        self.counters = {k: 0 for k in _COUNTERS}
        self.events: list = []     # (kind, rid, step, wall-clock seconds)
        self.finished: dict = {}   # rid -> list of generated token ids
        self.step_index = 0
        # (rid, position, np logits row) per sampled token — the bitwise
        # oracle gate compares these across cache codecs (tests only;
        # unbounded, so keep it off in long-running serving)
        self.record_logits = record_logits
        self.logits_log: list = []

    # -- public API -------------------------------------------------------

    def submit(self, req: Request) -> None:
        self.sched.submit(req)

    def drained(self) -> bool:
        return self.sched.drained()

    def run_until_drained(self, max_steps: int = 100000) -> None:
        while not self.drained():
            if self.step_index >= max_steps:
                raise RuntimeError(
                    f"serve loop not drained after {max_steps} steps "
                    f"({len(self.sched.queue)} queued, "
                    f"{sum(s.state != FREE for s in self.sched.slots)} "
                    "slots busy)")
            self.step()

    def report_unfired(self) -> list:
        """kv_flip specs that never found a live target (e.g. scheduled
        on a slot index the trace never filled) — the serving twin of
        `resilience.report_unfired`; counted, never silent."""
        self.counters["kv_faults_unfired"] = len(self._kv_pending)
        return list(self._kv_pending)

    # -- the step ---------------------------------------------------------

    def step(self) -> None:
        s = self.step_index
        self._fire_kv_faults(s)
        if self._scrub_every and s % self._scrub_every == 0:
            self.scrub()
        for slot in self.sched.admit(s):
            self.counters["admitted"] += 1
            self.counters["pages_reserved"] += len(slot.pages)
            self._event("admit", slot.req.rid, s)
        self._prefill_phase(s)
        self._decode_phase(s)
        self.step_index += 1

    # -- phases -----------------------------------------------------------

    def _checked(self, fn, *args):
        """Dispatch a jitted step; its pre-append integrity verdict
        (``bad`` > 0: a page this dispatch was about to append to — and
        whose digest the append would have re-blessed — holds corrupted
        bytes) DISCARDS the returned state, repairs through `scrub` on
        the intact pre-dispatch pool, and re-dispatches.  Two strikes on
        the same dispatch mean repair itself failed — loud, not silent."""
        for _ in range(2):
            pool, digests, out, bad = fn(self.params, self._pool,
                                         self._digests, *args)
            if int(bad) == 0:
                self._pool, self._digests = pool, digests
                return out
            self.counters["kv_inline_detects"] += 1
            self.scrub()
        raise RuntimeError(
            "KV page corruption persisted through scrub + repair "
            f"(counters: {self.counters})")

    def _prefill_phase(self, s: int) -> None:
        slot = self.sched.next_prefill_slot()
        if slot is None:
            return
        prompt = slot.req.prompt
        n = min(self._prefill_chunk, len(prompt) - slot.fed)
        buf = np.zeros((self._prefill_chunk,), np.int32)
        buf[:n] = prompt[slot.fed:slot.fed + n]
        last_logits = self._checked(
            self._prefill_fn, buf, np.int32(slot.fed), np.int32(n),
            self.sched.page_row(slot))
        slot.fed += n
        self.counters["prefill_chunks"] += 1
        self.counters["prompt_tokens"] += n
        if slot.fed == len(prompt):
            row = np.asarray(last_logits)
            if self.record_logits:
                self.logits_log.append((slot.req.rid, slot.fed - 1, row))
            tok = self._sample(row)
            slot.generated.append(tok)
            self.counters["tokens_generated"] += 1
            self._event("first_token", slot.req.rid, s)
            if not self._maybe_complete(slot, tok, s):
                slot.state = DECODE
                slot.next_token = tok

    def _decode_phase(self, s: int) -> None:
        dec = self.sched.decode_slots()
        if not dec:
            return
        slots = self.sched.slots
        tokens = np.asarray([max(sl.next_token, 0) for sl in slots],
                            np.int32)
        positions = np.asarray([sl.fed for sl in slots], np.int32)
        active = np.asarray([sl.state == DECODE for sl in slots], bool)
        logits = np.asarray(self._checked(
            self._decode_fn, tokens, positions, self.sched.page_table(),
            active))
        self.counters["decode_steps"] += 1
        for sl in dec:
            sl.fed += 1
            if self.record_logits:
                self.logits_log.append(
                    (sl.req.rid, sl.fed - 1, logits[sl.index]))
            tok = self._sample(logits[sl.index])
            sl.generated.append(tok)
            self.counters["tokens_generated"] += 1
            if not self._maybe_complete(sl, tok, s):
                sl.next_token = tok

    def _maybe_complete(self, slot, tok: int, s: int) -> bool:
        req = slot.req
        done = (len(slot.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id))
        if done:
            self.finished[req.rid] = list(slot.generated)
            self._event("complete", req.rid, s)
            self.counters["completed"] += 1
            self.counters["pages_freed"] += self.sched.evict(slot)
        return done

    def _sample(self, logits_row: np.ndarray) -> int:
        if self._temperature == 0.0:
            return int(np.argmax(logits_row))
        z = logits_row.astype(np.float64) / self._temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(logits_row.shape[0], p=p))

    # -- integrity: scrub + repair ---------------------------------------

    def scrub(self) -> list:
        """Recompute every page digest, repair any live corruption.
        Returns the corrupt (layer, page) pairs found."""
        self.counters["scrubs"] += 1
        cur = np.asarray(self._scrub_fn(self._pool))
        stored = np.asarray(self._digests)
        bad = np.argwhere(cur != stored)
        bad_pages = sorted({int(p) for _, p in bad if p != TRASH_PAGE})
        if not bad_pages:
            return []
        to_repair = []
        for p in bad_pages:
            self.counters["kv_pages_corrupt"] += 1
            owner = self.sched.owner_of_page(p)
            if owner is None:
                self.counters["kv_corrupt_free_pages"] += 1
            elif owner not in to_repair:
                to_repair.append(owner)
        for slot in to_repair:
            self._repair(slot)
        # repaired pages rewrote their digests; absorb the rest (free
        # pages and any corrupted-but-unwritten tail) by re-syncing the
        # stored digests to the pool's current bytes
        self._digests = self._scrub_fn(self._pool)
        return [(int(layer), int(p)) for layer, p in bad
                if int(p) != TRASH_PAGE]

    def _repair(self, slot) -> None:
        """Rebuild a slot's cached K/V from its token history through the
        prefill program — the request is never dropped; decode resumes
        from the same pending token.  The pre-append verdict is ignored
        HERE (a nonzero count is exactly the corruption being repaired);
        the rewrite itself re-syncs the touched pages' digests."""
        self.counters["kv_repairs"] += 1
        feed = slot.history[:slot.fed]
        row = self.sched.page_row(slot)
        done = 0
        while done < len(feed):
            n = min(self._prefill_chunk, len(feed) - done)
            buf = np.zeros((self._prefill_chunk,), np.int32)
            buf[:n] = feed[done:done + n]
            self._pool, self._digests, _, _bad = self._prefill_fn(
                self.params, self._pool, self._digests, buf,
                np.int32(done), np.int32(n), row)
            done += n
            self.counters["repair_chunks"] += 1

    # -- fault injection --------------------------------------------------

    def _fire_kv_faults(self, s: int) -> None:
        still = []
        for f in self._kv_pending:
            if f.step > s or not self._flip_page(int(f.arg)):
                still.append(f)
        self._kv_pending = still

    def _flip_page(self, slot_arg: int) -> bool:
        """Flip one byte in the target slot's first page (layer 0, K
        plane, position 0).  Returns False when the slot holds no cached
        K/V yet — the spec stays pending until it can actually fire."""
        slot = self.sched.slots[max(slot_arg, 0) % self.sched.n_slots]
        if slot.state == FREE or slot.fed == 0 or not slot.pages:
            return False
        pid = slot.pages[0]
        if self.cfg.raw:
            # a REAL bit flip (low mantissa byte XOR 0xFF), not an
            # arithmetic perturbation: `old + 1.0` would round back to
            # `old` for |old| >= 2^24 or non-finite values — a fault
            # counted as fired that attacked nothing
            old = np.float32(self._pool[0, pid, 0, 0, 0, 0])
            bits = old.view(np.uint32) ^ np.uint32(0xFF)
            self._pool = self._pool.at[0, pid, 0, 0, 0, 0].set(
                float(bits.view(np.float32)))
        else:
            old = self._pool[0, pid, 0, 0, 0, 0, 0]
            self._pool = self._pool.at[0, pid, 0, 0, 0, 0, 0].set(
                old ^ np.uint8(0xFF))
        self.counters["kv_flips_injected"] += 1
        return True

    # -- misc -------------------------------------------------------------

    def _event(self, kind: str, rid: int, step: int) -> None:
        self.events.append((kind, rid, step, time.monotonic()))
