"""ServeEngine — SLA-guarded continuous batching over the paged eXmY KV
cache.

One engine step is at most three device dispatches, each jit-stable:

1. (every effective-scrub-period steps) the **scrub** — recompute every
   page digest and compare to the maintained array; mismatches are
   corruption (docs/SERVING.md repair ladder): a page owned by a live
   request triggers **repair by recomputation** — the slot's cached K/V
   is rebuilt from its token history (prompt + generated so far, which
   the host always holds) through the same prefill program,
   synchronously, without dropping the request; a free page's
   corruption is absorbed (nothing will ever read it before it is
   rewritten).
2. one **prefill chunk** for the OLDEST admitted PREFILL slot, so long
   prompts trickle in without ever stalling the decode batch (oldest-
   first is what makes the admission-time TTFT bound provable —
   scheduler.py module docstring).
3. one **decode step** for the whole fixed-shape batch — every DECODE
   slot feeds its pending token and samples the next; FREE/PREFILL and
   stalled slots ride along masked to the trash page.

Detection is **two-tier** because an append re-digests its page from
the post-write bytes (which would re-bless pre-existing corruption):
every jitted dispatch verifies the pages it is about to append to
BEFORE writing (`kvcache.check_digests`, the ``bad`` verdict riding out
of the step), and the periodic scrub covers pages no append touches.
A nonzero verdict discards that dispatch's results (`_checked`), runs
the scrub+repair on the intact pre-dispatch state, and re-dispatches —
so corruption can never be served OR blessed, whatever its timing
relative to the scrub period.

SLA guard rails (ISSUE 10), all step-clock-deterministic:

* `submit` returns an ACCEPT/QUEUE/SHED **verdict** (scheduler.py): a
  request whose TTFT deadline is provably unmeetable from the current
  backlog, that the bounded queue has no room for, or whose SLA class
  the active degradation rung sheds, is rejected at admission and
  resolved SHED — never silently dropped.
* expired work is **cancelled**: a queued request past its TTFT
  deadline, a PREFILL slot that cannot have produced its first token in
  time, or a DECODE slot blowing its per-token budget is resolved
  DEADLINE_MISS with its partial output retained, its pages released to
  the pool.
* a **no-progress watchdog** catches a decode lane that stops advancing
  (the ``slot_stall`` chaos kind): after ``stall_patience`` stuck
  steps, the slot's pages are evicted and its cache re-prefilled from
  the host-held token history — the request resumes, never dropped.
* a `ServeSupervisor` (serve/supervisor.py) watches page pressure,
  corruption and deadline misses, and steps the engine down a
  degradation ladder (shrink the prefill chunk, cap admissions, tighten
  the scrub, shed low-SLA traffic), probating back up on clean windows.

Every submitted rid therefore resolves to exactly one of ``finished``,
``shed`` or ``missed`` (the zero-silent-drops contract, `unresolved()`),
all three stores are BOUNDED and drainable (`ResultStore`), and the
event log is a bounded deque — so sustained traffic cannot grow host
memory without limit (`logits_log` is the one exception, tests-only
and off by default).

Crash recovery: `snapshot(path)` serializes the FULL engine state —
scheduler slots/queue/page table, host token histories, supervisor +
counters, and the bit-packed u8 KV pool with its per-page digests —
with a `train.checkpoint.checkpoint_digest` content digest in a
``meta.json`` sidecar; `ServeEngine.restore(model, params, path)`
verifies the digest and resumes decoding **bitwise-identically** (the
pool is exact bytes; gated at (8,23) against the uninterrupted run).  A
snapshot taken mid-corruption restores the corrupt bytes AND the stale
digests, so the standard detect→repair path fires on the first
post-restore dispatch.

Fault injection rides the existing `resilience.FaultPlan` grammar:
``kv_flip@s:k`` flips one byte in slot ``k``'s first page, and the
serving-chaos kinds ``kv_storm@s:k`` (byte flips in up to ``k``
distinct live pages), ``slot_stall@s:k`` (slot ``k`` stops making
progress until the watchdog evicts it) and ``req_burst@s:k`` (a flash
crowd the load generator pops via `take_due_bursts`) exercise the
supervisor, the watchdog and the shed policy.  Injection, detection,
repair, shedding and completion are all deterministic: two runs of the
same (model, trace, plan) produce identical counters — the serve-smoke
gate.
"""

from __future__ import annotations

import dataclasses
import functools
import io
import json
import os
import shutil
from collections import OrderedDict, deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.timing import now
from ..obs.trace import NULL_SPAN
from . import kvcache
from .kvcache import KVCacheConfig, TRASH_PAGE
from .model import make_decode_step, make_prefill_step, spec_from_model
from .scheduler import DECODE, FREE, Request, SHED, Scheduler
from .supervisor import ServeSupervisor

__all__ = ["ServeEngine", "ResultStore"]

_COUNTERS = ("submitted", "admitted", "completed", "shed",
             "deadline_misses", "prompt_tokens", "tokens_generated",
             "decode_steps", "prefill_chunks", "repair_chunks", "scrubs",
             "kv_flips_injected", "kv_storms_injected", "kv_storm_pages",
             "slot_stalls_injected", "req_bursts_injected",
             "watchdog_evictions", "watchdog_chunks",
             "kv_inline_detects", "kv_pages_corrupt",
             "kv_corrupt_free_pages", "kv_repairs", "pages_reserved",
             "pages_freed", "results_evicted", "sup_hot_steps",
             "sup_degrades", "sup_probations", "kv_faults_unfired",
             # fleet hooks (ISSUE 13): live session migration +
             # content-addressed prefix-cache sharing
             "sessions_out", "sessions_in",
             "prefix_hits", "prefix_pages_shared",
             "prefix_tokens_skipped", "prefix_registered",
             "prefix_evictions", "prefix_invalidations")

_SNAP_STATE, _SNAP_META = "state.json", "meta.json"
_SNAP_POOL, _SNAP_DIGESTS = "pool.npy", "digests.npy"


def _json_default(o):
    """Snapshot-JSON coercion for numpy scalars (a trace built from a
    numpy RNG can legally carry np.int64 token ids)."""
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    raise TypeError(f"snapshot state is not JSON-serializable: "
                    f"{type(o).__name__}")


class ResultStore:
    """Bounded, drainable rid -> record mapping (ISSUE 10 satellite:
    the old ``Engine.finished`` dict grew forever under sustained
    traffic).  Past ``cap`` entries the OLDEST resolution is evicted
    (counted, never silent); `drain()` hands the current contents to
    the caller and clears — the pull API for long-running serving where
    nobody reads results out of the engine object."""

    def __init__(self, cap: int):
        if cap < 1:
            raise ValueError(f"cap must be >= 1, got {cap}")
        self.cap = int(cap)
        self.evicted = 0
        self._d: OrderedDict = OrderedDict()

    def put(self, rid: int, value) -> None:
        self._d[rid] = value
        while len(self._d) > self.cap:
            self._d.popitem(last=False)
            self.evicted += 1

    def drain(self) -> dict:
        """Return every held resolution and clear the store."""
        out = dict(self._d)
        self._d.clear()
        return out

    def get(self, rid, default=None):
        return self._d.get(rid, default)

    def __getitem__(self, rid):
        return self._d[rid]

    def __contains__(self, rid) -> bool:
        return rid in self._d

    def __len__(self) -> int:
        return len(self._d)

    def __iter__(self):
        return iter(self._d)

    def keys(self):
        return self._d.keys()

    def items(self):
        return self._d.items()

    def __eq__(self, other) -> bool:
        if isinstance(other, ResultStore):
            return dict(self._d) == dict(other._d)
        return dict(self._d) == other

    def __repr__(self) -> str:
        return (f"ResultStore(cap={self.cap}, len={len(self._d)}, "
                f"evicted={self.evicted})")

    def state_dict(self) -> dict:
        return {"cap": self.cap, "evicted": self.evicted,
                "items": [[rid, v] for rid, v in self._d.items()]}

    def load_state_dict(self, state: dict) -> "ResultStore":
        self.cap = int(state["cap"])
        self.evicted = int(state["evicted"])
        self._d = OrderedDict((int(r), v) for r, v in state["items"])
        return self


class ServeEngine:
    """SLA-guarded continuous-batching serving loop for one
    `TransformerLM` (module docstring).

    Parameters
    ----------
    model, params : the trained module (single-device config) + pytree.
    n_slots : fixed decode-batch width.
    max_seq : per-request capacity (prompt + max_new); rounded up to
        whole pages.  Requests exceeding it are rejected at `submit` —
        fail-fast, the serving twin of `generate(t_max=...)`.
    page_size : token positions per KV page.
    n_pages : total pool pages (default: full capacity for every slot
        plus the trash page — allocation can then never starve).
    kv_format : (exp_bits, man_bits) eXmY cache codec; (8, 23) is the
        lossless byte split, e5m2/e4m3 the 4x-compressed formats.
    raw_cache : fp32 pool, no codec — the bitwise oracle for (8, 23).
    kv_block_size : block-scale the pages (ISSUE 12): each K/V row is
        blocked-cast with one power-of-2 scale per this many elements
        and stored as codes + shift sidecar inside the page (so digests,
        scrubs, repair and snapshots cover the sidecar for free).
        Extends an e4m3/e5m2 page's dynamic range at ~3% extra bytes
        (`kv_page_bytes(block_size=...)` prices it); None = per-tensor
        pages (the PR 7 layout).  Needs a sub-fp32 kv_format.
    prefill_chunk : prompt tokens per prefill dispatch (a degradation
        rung may cap the VALID tokens per dispatch below this; the
        compiled chunk shape never changes).
    scrub_every : digest-scrub period in engine steps (0 = only explicit
        `scrub()` calls; a degradation rung may tighten it).
    fault_plan : `resilience.FaultPlan`; consumes the ``kv_flip`` and
        `SERVE_KINDS` specs (``kv_storm``/``slot_stall``/``req_burst``).
    supervisor : optional `ServeSupervisor` degradation ladder.
    max_queue : bounded-queue backpressure — submissions beyond this
        queue depth are SHED (None = unbounded, the pre-SLA behaviour).
    stall_patience : no-progress steps before the watchdog evicts and
        re-prefills a stuck decode slot.
    finished_cap : bound on each resolution store (finished/shed/missed).
    temperature / seed : 0 = greedy argmax; > 0 samples from
        softmax(logits / T) with a deterministic host RNG.
    tracer : optional `obs.Tracer` — per-request timeline events
        (submit→verdict→admit→first_chunk→first_token→complete, with
        deadline/shed/ladder annotations) plus per-phase spans on the
        step clock.  Pure observation: counters, sampled tokens and
        page bytes are bitwise identical with or without it (pinned in
        tests/test_obs.py).  Not part of the snapshot recipe — attach
        a fresh tracer after `restore`.
    flight : optional `obs.FlightRecorder` — one ring event per engine
        step; dumped automatically by `snapshot` (reason="snapshot").
    prefix_cache : optional content-addressed prefix cache
        (`cpd_tpu.fleet.prefix.PrefixCache`, ISSUE 13): full prompt-
        prefix pages are indexed by token digest and SHARED copy-on-
        write across requests — an admission whose prompt prefix is
        byte-confirmed in the cache adopts the cached pages (refcounted
        via the scheduler, `Scheduler.retain`/`release`) and skips
        those prefill chunks; sampled logits stay BITWISE identical to
        the cold path because quantize-on-append makes page bytes a
        pure function of the token prefix (gated in tests/test_fleet.py
        and the fleet-smoke).  A digest hit is only shared after a full
        byte comparison of the token prefixes — a Fletcher collision
        can never leak one tenant's KV bytes into another's attention
        window (docs/SERVING.md "Prefix cache").
    """

    def __init__(self, model, params, *, n_slots: int = 4,
                 max_seq: int = 128, page_size: int = 16,
                 n_pages: Optional[int] = None,
                 kv_format: tuple = (8, 23), raw_cache: bool = False,
                 kv_block_size: Optional[int] = None,
                 prefill_chunk: int = 16, scrub_every: int = 0,
                 fault_plan=None, supervisor: Optional[ServeSupervisor]
                 = None, max_queue: Optional[int] = None,
                 stall_patience: int = 4, finished_cap: int = 4096,
                 temperature: float = 0.0, seed: int = 0,
                 record_logits: bool = False, tracer=None, flight=None,
                 prefix_cache=None, tp: int = 1,
                 fused_attn: bool = False):
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if stall_patience < 1:
            raise ValueError(f"stall_patience must be >= 1, got "
                             f"{stall_patience}")
        spec = spec_from_model(model)
        max_pages = -(-max_seq // page_size)
        if n_pages is None:
            n_pages = 1 + n_slots * max_pages
        exp_bits, man_bits = kv_format
        # the restore() recipe: everything an identical engine needs
        # (fault_plan/supervisor ride the snapshot separately)
        self._init_kw = dict(
            n_slots=n_slots, max_seq=max_seq, page_size=page_size,
            n_pages=n_pages, kv_format=[int(exp_bits), int(man_bits)],
            raw_cache=bool(raw_cache),
            kv_block_size=(int(kv_block_size)
                           if kv_block_size is not None else None),
            prefill_chunk=prefill_chunk,
            scrub_every=scrub_every, max_queue=max_queue,
            stall_patience=stall_patience, finished_cap=finished_cap,
            temperature=float(temperature), seed=int(seed),
            record_logits=bool(record_logits), tp=int(tp),
            fused_attn=bool(fused_attn))
        self.cfg = KVCacheConfig(
            n_layers=spec.n_layers, n_kv_heads=spec.kv_heads,
            head_dim=spec.head_dim, page_size=page_size, n_pages=n_pages,
            exp_bits=exp_bits, man_bits=man_bits, raw=raw_cache,
            block_scale=kv_block_size is not None,
            block_size=(int(kv_block_size)
                        if kv_block_size is not None else 32),
            tp=int(tp))
        self.tp = int(tp)
        self.fused_attn = bool(fused_attn)
        self.spec = spec
        self.params = params
        self.sched = Scheduler(n_slots, n_pages, page_size, max_pages,
                               prefill_chunk=prefill_chunk,
                               max_queue=max_queue)
        self._prefill_chunk = prefill_chunk
        self._scrub_every = scrub_every
        self._stall_patience = stall_patience
        self.supervisor = supervisor
        self._temperature = float(temperature)
        self._rng = np.random.default_rng(seed)

        self._decode_fn = make_decode_step(spec, self.cfg,
                                           fused=self.fused_attn)
        self._prefill_fn = make_prefill_step(spec, self.cfg, prefill_chunk)
        self._scrub_fn = jax.jit(functools.partial(
            kvcache.all_digests, sharded=self.cfg.tp > 1))
        self._pool = kvcache.alloc_pool(self.cfg)
        # initial state: digest-of-zero-page everywhere, via the same
        # compiled scrub program every later pass reuses
        self._digests = self._scrub_fn(self._pool)

        serve = list(fault_plan.serve_faults()) if fault_plan else []
        self._kv_pending = list(fault_plan.kv_faults()) if fault_plan \
            else []
        self._storm_pending = [f for f in serve if f.kind == "kv_storm"]
        self._stall_pending = [f for f in serve if f.kind == "slot_stall"]
        self._burst_pending = [f for f in serve if f.kind == "req_burst"]
        self._stalled: set = set()    # slot indices not making progress
        self.counters = {k: 0 for k in _COUNTERS}
        # (kind, rid, step, wall-clock seconds); bounded like the
        # resolution stores (~6 events/request), oldest silently aged
        # out — latency metrics cover the retained window.
        # ``events_total`` counts every event EVER appended (monotone),
        # so incremental consumers — the streaming fleet load generator
        # (ISSUE 17) — can tail the bounded ring without re-reading or
        # double-counting: new events since a cursor are the last
        # ``events_total - cursor`` entries
        self.events: deque = deque(maxlen=8 * finished_cap)
        self.events_total = 0
        # bounded resolution stores: every submitted rid lands in
        # exactly one (the zero-silent-drops contract, `unresolved`)
        self.finished = ResultStore(finished_cap)   # rid -> token list
        self.shed = ResultStore(finished_cap)       # rid -> reason str
        self.missed = ResultStore(finished_cap)     # rid -> partial toks
        self._inflight: set = set()
        self.step_index = 0
        # effective (rung-capped) knobs, recomputed every step
        self._eff_chunk = prefill_chunk
        self._eff_scrub = scrub_every
        self._sig_prev = {"corrupt": 0, "misses": 0}
        # (rid, position, np logits row) per sampled token — the bitwise
        # oracle gate compares these across cache codecs (tests only;
        # unbounded, so keep it off in long-running serving)
        self.record_logits = record_logits
        self.logits_log: list = []
        # observability taps (ISSUE 11): host-side observation only —
        # neither may influence scheduling, sampling or page bytes
        self.tracer = tracer
        self.flight = flight
        # content-addressed prefix cache (ISSUE 13; class docstring) —
        # None leaves every path bit-identical to the cache-less engine
        self.prefix_cache = prefix_cache

    # -- public API -------------------------------------------------------

    def submit(self, req: Request) -> str:
        """Admission verdict (ACCEPT / QUEUE / SHED — scheduler.py).  A
        SHED request is resolved immediately (`shed` store + event);
        impossible requests still raise — BEFORE the submitted counter
        moves, so a validation error cannot leave a phantom submission
        that reads as a silent drop forever."""
        verdict = self.sched.submit(req, step=self.step_index)
        self.counters["submitted"] += 1
        if self.tracer is not None:
            # the timeline's opening record: verdict + the SLA terms
            # the later deadline/shed annotations are judged against
            self.tracer.request_event(
                req.rid, "submit", self.step_index, verdict=verdict,
                arrival=req.arrival, sla_class=req.sla_class,
                deadline_steps=req.deadline_steps,
                tpot_budget_steps=req.tpot_budget_steps,
                prompt_tokens=len(req.prompt),
                max_new_tokens=req.max_new_tokens)
        if verdict == SHED:
            self._resolve_shed(req.rid, "admission", self.step_index)
        else:
            self._inflight.add(req.rid)
        return verdict

    def drained(self) -> bool:
        return self.sched.drained()

    def unresolved(self) -> list:
        """Submitted rids not yet resolved to FINISHED/SHED/
        DEADLINE_MISS — empty on a drained engine (the zero-silent-drops
        acceptance check)."""
        return sorted(self._inflight)

    def run_until_drained(self, max_steps: int = 100000) -> None:
        while not self.drained():
            if self.step_index >= max_steps:
                raise RuntimeError(
                    f"serve loop not drained after {max_steps} steps "
                    f"({len(self.sched.queue)} queued, "
                    f"{sum(s.state != FREE for s in self.sched.slots)} "
                    "slots busy)")
            self.step()

    def has_pending_bursts(self) -> bool:
        """True while ``req_burst`` specs wait to fire — the load
        generator keeps the step clock running toward them even after
        the current work drains (a flash crowd scheduled for a quiet
        moment must still arrive)."""
        return bool(self._burst_pending)

    def take_due_bursts(self, step: Optional[int] = None) -> list:
        """Pop the ``req_burst`` specs due at ``step`` (default: the
        current step) — the load generator's hook
        (`loadgen.run_trace(burst_factory=...)`); each popped spec is
        counted fired.  Uncalled (no load generator driving the plan),
        the specs stay pending and surface through `report_unfired`."""
        s = self.step_index if step is None else step
        due = [f for f in self._burst_pending if f.step <= s]
        if due:
            self._burst_pending = [f for f in self._burst_pending
                                   if f.step > s]
            self.counters["req_bursts_injected"] += len(due)
        return due

    def report_unfired(self) -> list:
        """Fault specs that never found a live target (e.g. a kv_flip
        on a slot the trace never filled, or a req_burst no load
        generator consumed) — the serving twin of
        `resilience.report_unfired`; counted, never silent."""
        left = (list(self._kv_pending) + list(self._storm_pending)
                + list(self._stall_pending) + list(self._burst_pending))
        self.counters["kv_faults_unfired"] = len(left)
        return sorted(left)

    # -- the step ---------------------------------------------------------

    def step(self) -> None:
        s = self.step_index
        with self._span("serve_step", s):
            self._apply_rung(s)
            self._fire_kv_faults(s)
            if self._eff_scrub and s % self._eff_scrub == 0:
                with self._span("scrub", s):
                    self.scrub()
            self._expire_deadlines(s)
            self._watchdog(s)
            with self._span("admit", s):
                if self.prefix_cache is not None and self.sched.queue:
                    # cache-held pages are reclaimable capacity: make
                    # room for the queue head so the cache can never
                    # starve admission (head-of-line, FIFO preserved)
                    head = self.sched.queue[0]
                    if head.arrival <= s:
                        self._make_room(self.sched.pages_needed(head))
                for slot in self.sched.admit(s):
                    self.counters["admitted"] += 1
                    self.counters["pages_reserved"] += len(slot.pages)
                    self._adopt_prefix(slot, s)
                    self._event("admit", slot.req.rid, s,
                                pages=len(slot.pages))
            with self._span("prefill", s):
                self._prefill_phase(s)
            with self._span("decode", s):
                self._decode_phase(s)
            self._observe_supervisor(s)
        if self.flight is not None:
            self.flight.record(
                "serve_step", step=s, queued=len(self.sched.queue),
                busy=sum(sl.state != FREE for sl in self.sched.slots),
                inflight=len(self._inflight))
        self.step_index += 1

    # -- SLA guard rails --------------------------------------------------

    def _apply_rung(self, s: int) -> None:
        """Point the step's effective knobs at the supervisor's current
        rung (supervisor.py): prefill-chunk cap, admission cap, scrub
        cadence, and the shed class (applied to NEW submissions via the
        scheduler policy AND to already-queued low-class work)."""
        rung = self.supervisor.rung if self.supervisor is not None else None
        base = self._prefill_chunk
        self._eff_chunk = (base if rung is None
                           or rung.prefill_chunk_cap is None
                           else min(base, rung.prefill_chunk_cap))
        eff_scrub = self._scrub_every
        if rung is not None and rung.scrub_every_cap is not None:
            eff_scrub = (rung.scrub_every_cap if eff_scrub == 0
                         else min(eff_scrub, rung.scrub_every_cap))
        self._eff_scrub = eff_scrub
        self.sched.admission_cap = (rung.admission_cap
                                    if rung is not None else None)
        shed_above = rung.shed_class_above if rung is not None else None
        self.sched.shed_class_above = shed_above
        if shed_above is not None:
            for q in self.sched.shed_queued_class(shed_above):
                self._resolve_shed(q.rid, "rung-purge", s)

    def _expire_deadlines(self, s: int) -> None:
        """Cancel provably-late work: queued requests past their TTFT
        deadline, PREFILL slots that can no longer produce a first
        token in time, and DECODE slots past their per-token budget —
        pages released, partial output retained, DEADLINE_MISS
        resolved.  Strict ``>`` everywhere: a token produced AT the
        deadline step lands later in this same step, on time."""
        for q in self.sched.expire_queued(s):
            self._resolve_miss(q.rid, [], s)
        for slot in self.sched.slots:
            if slot.state == FREE:
                continue
            req = slot.req
            if slot.first_token_step < 0:
                late = (req.deadline_steps is not None
                        and s > req.arrival + req.deadline_steps)
            else:
                pending = len(slot.generated)   # index of the NEXT token
                late = (req.tpot_budget_steps is not None
                        and s > slot.first_token_step
                        + pending * req.tpot_budget_steps)
            if late:
                partial = list(slot.generated)
                self._stalled.discard(slot.index)
                self.counters["pages_freed"] += self.sched.evict(slot)
                self._resolve_miss(req.rid, partial, s)

    def _watchdog(self, s: int) -> None:
        """No-progress watchdog: a DECODE slot whose ``fed`` has not
        advanced for ``stall_patience`` steps (the ``slot_stall`` chaos
        kind, or any real wedged lane) is evicted — pages returned and
        fresh ones reserved — and its cache re-prefilled from the
        host-held token history; decode resumes from the same pending
        token.  The request is never dropped."""
        for slot in self.sched.decode_slots():
            if s - slot.last_progress < self._stall_patience:
                continue
            # a slot holding SHARED prefix pages returns fewer pages
            # than it re-reserves — the free list must cover the shared
            # count, so reclaim cache-held pages first; if room still
            # cannot be made (shared with another live slot on a
            # custom-small pool), LEAVE the stall for a later step
            # instead of crashing the serve loop mid-allocation
            shared = sum(1 for p in slot.pages
                         if self.sched.page_refs.get(p, 0) > 1)
            self._make_room(shared)
            if len(self.sched.free_pages) < shared:
                continue
            self.counters["watchdog_evictions"] += 1
            self._stalled.discard(slot.index)   # recovery clears a stall
            npages = len(slot.pages)
            # freed counts actual pool returns (a shared page survives
            # its release); the slot re-reserves its full width
            self.counters["pages_freed"] += self.sched.reassign_pages(
                slot)
            self.counters["pages_reserved"] += npages
            self._reprefill(slot, "watchdog_chunks")
            slot.last_progress = s
            self._event("watchdog_evict", slot.req.rid, s)

    def _observe_supervisor(self, s: int) -> None:
        if self.supervisor is None:
            return
        cur = {"corrupt": (self.counters["kv_inline_detects"]
                           + self.counters["kv_pages_corrupt"]),
               "misses": self.counters["deadline_misses"]}
        act = self.supervisor.on_step(
            s, page_util=self.sched.page_utilization(),
            corrupt=cur["corrupt"] - self._sig_prev["corrupt"],
            misses=cur["misses"] - self._sig_prev["misses"])
        self._sig_prev = cur
        if self.supervisor.last_hot:
            self.counters["sup_hot_steps"] += 1
        if act == "degrade":
            self.counters["sup_degrades"] += 1
            self._event("degrade", -1, s,
                        rung=self.supervisor.rung.name,
                        level=self.supervisor.level)
        elif act == "probate":
            self.counters["sup_probations"] += 1
            self._event("probate", -1, s,
                        rung=self.supervisor.rung.name,
                        level=self.supervisor.level)

    # -- prefix cache (ISSUE 13; fleet/prefix.py owns the index) ----------

    def _make_room(self, need: int) -> None:
        """Evict prefix-cache LRU entries until the free list holds
        ``need`` pages (or nothing reclaimable remains).  Cache-held
        pages are reclaimable capacity, never a reason to refuse
        admission — but ONLY entries whose page the cache alone
        references: evicting an entry a live slot still shares frees
        nothing, and would flush the cache for zero room (the
        `can_adopt` sole-reference rule, applied here too)."""
        if self.prefix_cache is None:
            return
        while len(self.sched.free_pages) < need:
            pid = self.prefix_cache.evict_where(
                lambda p: self.sched.page_refs.get(p, 0) == 1)
            if pid is None:
                return
            self.sched.release(pid)
            self.counters["prefix_evictions"] += 1

    def _adopt_prefix(self, slot, s: int) -> None:
        """Swap the freshly admitted slot's leading pages for cached
        ones when its prompt prefix is byte-confirmed in the cache:
        the shared pages are retained (refcount++), the displaced fresh
        pages return to the pool, and ``fed`` jumps past the shared
        positions — those prefill chunks never dispatch.  At least one
        prompt token is always left to feed (the final prompt position's
        dispatch produces the logits that sample token 0)."""
        if self.prefix_cache is None:
            return
        prompt = slot.req.prompt
        ps = self.sched.page_size
        max_share = (len(prompt) - 1) // ps
        if max_share < 1:
            return
        hit = self.prefix_cache.lookup(prompt, ps, max_pages=max_share)
        if not hit:
            return
        k = len(hit)
        for p in slot.pages[:k]:
            if self.sched.release(p):
                self.counters["pages_freed"] += 1
        for p in hit:
            self.sched.retain(p)
        slot.pages = tuple(hit) + slot.pages[k:]
        slot.fed = k * ps
        slot.prefix_registered = k     # adopted pages are already indexed
        self.counters["prefix_hits"] += 1
        self.counters["prefix_pages_shared"] += k
        self.counters["prefix_tokens_skipped"] += k * ps
        self._event("prefix_hit", slot.req.rid, s, pages=k,
                    tokens=k * ps)

    def _register_prefix_pages(self, slot) -> None:
        """Index every NEWLY completed prompt-prefix page of the slot
        (positions fully fed and all prompt tokens) under the token-
        prefix digest — `Slot.prefix_registered` is the watermark, so
        a chunked prefill registers each page exactly once.  The cache
        takes its own reference on a newly registered page, so the K/V
        bytes outlive the owning request; duplicates (re-registration
        after a snapshot restore) are dropped by the cache's
        byte-confirmed dedupe."""
        if self.prefix_cache is None:
            return
        prompt = slot.req.prompt
        ps = self.sched.page_size
        full = min(slot.fed, len(prompt)) // ps
        for j in range(slot.prefix_registered, full):
            pid = slot.pages[j]
            fresh, evicted = self.prefix_cache.register(
                prompt[:(j + 1) * ps], pid)
            if fresh:
                self.sched.retain(pid)
                self.counters["prefix_registered"] += 1
            for old in evicted:
                self.sched.release(old)
                self.counters["prefix_evictions"] += 1
        slot.prefix_registered = full

    # -- fleet hooks: live session migration (fleet/migrate.py) ----------

    def slot_of_rid(self, rid: int):
        """The live slot serving ``rid`` (PREFILL or DECODE), or None."""
        for sl in self.sched.slots:
            if sl.state != FREE and sl.req is not None \
                    and sl.req.rid == rid:
                return sl
        return None

    def withdraw(self, rid: int):
        """Remove a QUEUED request WITHOUT resolving it — the fleet
        drain re-places it on another engine, where it will resolve
        (zero-silent-drops accounting moves with it; the caller owns
        re-placement).  Returns the Request, or None if ``rid`` is not
        queued (live sessions move via `fleet.migrate.extract_capsule`,
        resolved ones are already final)."""
        for q in list(self.sched.queue):
            if q.rid == rid:
                self.sched.queue.remove(q)
                self._inflight.discard(rid)
                self.counters["sessions_out"] += 1
                self._event("withdraw", rid, self.step_index)
                return q
        return None

    # -- resolution bookkeeping -------------------------------------------

    def _resolve_shed(self, rid: int, reason: str, s: int) -> None:
        self.counters["shed"] += 1
        self.shed.put(rid, reason)
        self._inflight.discard(rid)
        self._event("shed", rid, s, reason=reason)
        self._refresh_evicted()

    def _resolve_miss(self, rid: int, partial: list, s: int) -> None:
        self.counters["deadline_misses"] += 1
        self.missed.put(rid, partial)
        self._inflight.discard(rid)
        self._event("deadline_miss", rid, s,
                    partial_tokens=len(partial))
        self._refresh_evicted()

    def _refresh_evicted(self) -> None:
        self.counters["results_evicted"] = (self.finished.evicted
                                            + self.shed.evicted
                                            + self.missed.evicted)

    # -- phases -----------------------------------------------------------

    def _checked(self, fn, *args):
        """Dispatch a jitted step; its pre-append integrity verdict
        (``bad`` > 0: a page this dispatch was about to append to — and
        whose digest the append would have re-blessed — holds corrupted
        bytes) DISCARDS the returned state, repairs through `scrub` on
        the intact pre-dispatch pool, and re-dispatches.  Two strikes on
        the same dispatch mean repair itself failed — loud, not silent."""
        for _ in range(2):
            pool, digests, out, bad = fn(self.params, self._pool,
                                         self._digests, *args)
            if int(bad) == 0:
                self._pool, self._digests = pool, digests
                return out
            self.counters["kv_inline_detects"] += 1
            self.scrub()
        raise RuntimeError(
            "KV page corruption persisted through scrub + repair "
            f"(counters: {self.counters})")

    def _prefill_phase(self, s: int) -> None:
        slot = self.sched.next_prefill_slot()
        if slot is None:
            return
        prompt = slot.req.prompt
        n = min(self._eff_chunk, len(prompt) - slot.fed)
        if slot.fed == 0 and self.tracer is not None:
            # tracer-only (the bounded host event log keeps its
            # pre-obs vocabulary): the timeline's prefill-start mark
            self.tracer.request_event(slot.req.rid, "first_chunk", s,
                                      chunk_tokens=n)
        buf = np.zeros((self._prefill_chunk,), np.int32)
        buf[:n] = prompt[slot.fed:slot.fed + n]
        last_logits = self._checked(
            self._prefill_fn, buf, np.int32(slot.fed), np.int32(n),
            self.sched.page_row(slot))
        slot.fed += n
        slot.last_progress = s
        self.counters["prefill_chunks"] += 1
        self.counters["prompt_tokens"] += n
        self._register_prefix_pages(slot)
        if slot.fed == len(prompt):
            row = np.asarray(last_logits)
            if self.record_logits:
                self.logits_log.append((slot.req.rid, slot.fed - 1, row))  # cpd: disable=host-unbounded -- tests-only oracle tap behind record_logits (default off); bounded by the test's own request count
            tok = self._sample(row)
            slot.generated.append(tok)
            slot.first_token_step = s
            self.counters["tokens_generated"] += 1
            self._event("first_token", slot.req.rid, s)
            if not self._maybe_complete(slot, tok, s):
                slot.state = DECODE
                slot.next_token = tok

    def _decode_phase(self, s: int) -> None:
        dec = [sl for sl in self.sched.decode_slots()
               if sl.index not in self._stalled]
        if not dec:
            return
        slots = self.sched.slots
        tokens = np.asarray([max(sl.next_token, 0) for sl in slots],
                            np.int32)
        positions = np.asarray([sl.fed for sl in slots], np.int32)
        active = np.asarray([sl.state == DECODE
                             and sl.index not in self._stalled
                             for sl in slots], bool)
        logits = np.asarray(self._checked(
            self._decode_fn, tokens, positions, self.sched.page_table(),
            active))
        self.counters["decode_steps"] += 1
        for sl in dec:
            sl.fed += 1
            sl.last_progress = s
            if self.record_logits:
                self.logits_log.append(
                    (sl.req.rid, sl.fed - 1, logits[sl.index]))
            tok = self._sample(logits[sl.index])
            sl.generated.append(tok)
            self.counters["tokens_generated"] += 1
            if not self._maybe_complete(sl, tok, s):
                sl.next_token = tok

    def _maybe_complete(self, slot, tok: int, s: int) -> bool:
        req = slot.req
        done = (len(slot.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id))
        if done:
            self.finished.put(req.rid, list(slot.generated))
            self._inflight.discard(req.rid)
            self._event("complete", req.rid, s,
                        n_generated=len(slot.generated))
            self.counters["completed"] += 1
            self.counters["pages_freed"] += self.sched.evict(slot)
            self._refresh_evicted()
        return done

    def _sample(self, logits_row: np.ndarray) -> int:
        if self._temperature == 0.0:
            return int(np.argmax(logits_row))
        z = logits_row.astype(np.float64) / self._temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(logits_row.shape[0], p=p))

    # -- integrity: scrub + repair ---------------------------------------

    def scrub(self) -> list:
        """Recompute every page digest, repair any live corruption.
        Returns the corrupt (layer, page) pairs found."""
        self.counters["scrubs"] += 1
        cur = np.asarray(self._scrub_fn(self._pool))
        stored = np.asarray(self._digests)
        # rows are (layer, page) at tp=1, (layer, page, shard) sharded —
        # index positionally, page is always column 1
        bad = np.argwhere(cur != stored)
        bad_pages = sorted({int(r[1]) for r in bad if r[1] != TRASH_PAGE})
        if not bad_pages:
            return []
        to_repair = []
        for p in bad_pages:
            self.counters["kv_pages_corrupt"] += 1
            owners = self.sched.owners_of_page(p)
            if not owners:
                # no live reader — but a cache-held page would be
                # SERVED to a future tenant after the digest re-sync
                # below re-blessed it, so the entry is invalidated and
                # the page released instead of absorbed
                if self.prefix_cache is not None \
                        and self.prefix_cache.invalidate_page(p):
                    self.sched.release(p)
                    self.counters["prefix_invalidations"] += 1
                else:
                    self.counters["kv_corrupt_free_pages"] += 1
                continue
            # a SHARED page has several owners; every one is recomputed
            # (identical prefixes write identical bytes, so the repairs
            # agree — the first rewrite already restores the page)
            for owner in owners:
                if owner not in to_repair:
                    to_repair.append(owner)
        for slot in to_repair:
            self.counters["kv_repairs"] += 1
            self._reprefill(slot, "repair_chunks")
        # repaired pages rewrote their digests; absorb the rest (free
        # pages and any corrupted-but-unwritten tail) by re-syncing the
        # stored digests to the pool's current bytes
        self._digests = self._scrub_fn(self._pool)
        return sorted({(int(r[0]), int(r[1])) for r in bad
                       if int(r[1]) != TRASH_PAGE})

    def _reprefill(self, slot, counter: str) -> None:
        """Rebuild a slot's cached K/V from its token history through the
        prefill program — the request is never dropped; decode resumes
        from the same pending token.  Shared by corruption repair and
        the watchdog eviction (``counter`` keeps their chunk accounting
        separate).  The pre-append verdict is ignored HERE (a nonzero
        count is exactly the corruption being repaired); the rewrite
        itself re-syncs the touched pages' digests."""
        feed = slot.history[:slot.fed]
        row = self.sched.page_row(slot)
        done = 0
        while done < len(feed):
            n = min(self._prefill_chunk, len(feed) - done)
            buf = np.zeros((self._prefill_chunk,), np.int32)
            buf[:n] = feed[done:done + n]
            self._pool, self._digests, _, _bad = self._prefill_fn(
                self.params, self._pool, self._digests, buf,
                np.int32(done), np.int32(n), row)
            done += n
            self.counters[counter] += 1

    # -- fault injection --------------------------------------------------

    def _fire_kv_faults(self, s: int) -> None:
        still = []
        for f in self._kv_pending:
            if f.step > s or not self._flip_slot_page(int(f.arg)):
                still.append(f)
        self._kv_pending = still
        still = []
        for f in self._storm_pending:
            if f.step > s or not self._fire_storm(f):
                still.append(f)
        self._storm_pending = still
        still = []
        for f in self._stall_pending:
            if f.step > s or not self._fire_stall(int(f.arg)):
                still.append(f)
        self._stall_pending = still

    def _flip_slot_page(self, slot_arg: int) -> bool:
        """``kv_flip``: flip one byte in the target slot's first page.
        Returns False when the slot holds no cached K/V yet — the spec
        stays pending until it can actually fire."""
        slot = self.sched.slots[max(slot_arg, 0) % self.sched.n_slots]
        if slot.state == FREE or slot.fed == 0 or not slot.pages:
            return False
        self._flip_page_byte(slot.pages[0])
        self.counters["kv_flips_injected"] += 1
        return True

    def _fire_storm(self, f) -> bool:
        """``kv_storm@s:k``: flip one byte in each of up to ``k``
        (default 3) DISTINCT live pages (`Scheduler.live_pages`,
        slot-index order) — wide enough corruption that the supervisor,
        not just the scrubber, reacts.  Held until at least one slot
        holds cached K/V."""
        targets = self.sched.live_pages()
        if not targets:
            return False
        k = int(f.arg) if f.arg > 0 else 3
        for pid in targets[:k]:
            self._flip_page_byte(pid)
            self.counters["kv_storm_pages"] += 1
        self.counters["kv_storms_injected"] += 1
        return True

    def _fire_stall(self, slot_arg: int) -> bool:
        """``slot_stall``: the target slot stops making token progress
        (masked out of the decode batch) until the no-progress watchdog
        evicts and re-prefills it.  Held until the slot is decoding."""
        idx = max(slot_arg, 0) % self.sched.n_slots
        if self.sched.slots[idx].state != DECODE:
            return False
        self._stalled.add(idx)
        self.counters["slot_stalls_injected"] += 1
        return True

    def _flip_page_byte(self, pid: int) -> None:
        """One REAL byte flip in page ``pid`` (layer 0, K plane,
        position 0; shard 0 on a tp-sharded pool — per-shard digests
        must catch a single shard's corruption).  On the raw fp32
        oracle pool this is a mantissa byte XOR (not an arithmetic
        perturbation: `old + 1.0` would round back to `old` for
        |old| >= 2^24 or non-finite values — a fault counted as fired
        that attacked nothing)."""
        shard = (0,) if self.cfg.tp > 1 else ()
        if self.cfg.raw:
            idx = (0, pid) + shard + (0, 0, 0, 0)
            old = np.float32(self._pool[idx])
            bits = old.view(np.uint32) ^ np.uint32(0xFF)
            self._pool = self._pool.at[idx].set(
                float(bits.view(np.float32)))
        elif self.cfg.block_scale:
            # blocked pool rows are flat byte vectors (codes + sidecar):
            # flip the row's first code byte
            idx = (0, pid) + shard + (0, 0, 0)
            old = self._pool[idx]
            self._pool = self._pool.at[idx].set(old ^ np.uint8(0xFF))
        else:
            idx = (0, pid) + shard + (0, 0, 0, 0, 0)
            old = self._pool[idx]
            self._pool = self._pool.at[idx].set(old ^ np.uint8(0xFF))

    # -- crash-recovery snapshots -----------------------------------------

    def snapshot(self, path: str) -> dict:
        """Serialize the FULL engine state into directory ``path``:
        the bit-packed u8 KV pool + per-page digests (exact bytes), the
        scheduler (slots / queue / page table / token histories), the
        resolution stores, supervisor, counters, RNG and pending fault
        specs — with a `train.checkpoint.checkpoint_digest` content
        digest in a ``meta.json`` sidecar so `restore` can refuse a
        truncated or bit-flipped snapshot.  Returns the digest record.

        Whole-directory atomicity (the orbax write-tmp-then-rename
        discipline, applied at directory granularity): the snapshot is
        built in ``path + ".tmp"`` and only swapped in once complete —
        a crash mid-save can never destroy the last good snapshot at
        ``path`` (the periodic snapshot-to-one-path loop's whole point
        is surviving exactly such a crash).  During the final swap the
        previous snapshot briefly lives at ``path + ".old"``; a crash
        in that window leaves it there, intact and restorable."""
        from ..train.checkpoint import checkpoint_digest

        tmp_dir = path.rstrip(os.sep) + ".tmp"
        if os.path.isdir(tmp_dir):
            shutil.rmtree(tmp_dir)
        os.makedirs(tmp_dir)
        blobs = self._snapshot_blobs()
        for name in (_SNAP_POOL, _SNAP_DIGESTS, _SNAP_STATE):
            with open(os.path.join(tmp_dir, name), "wb") as fh:
                fh.write(blobs[name])
        # the digest covers every data file; meta.json itself is
        # excluded (it cannot contain its own hash)
        record = checkpoint_digest(tmp_dir, exclude=(_SNAP_META,))
        with open(os.path.join(tmp_dir, _SNAP_META), "w") as fh:
            json.dump({"integrity": record}, fh)
        # the swap: retire the previous snapshot to .old, promote the
        # complete tmp dir, then drop .old — the only window without a
        # snapshot at `path` leaves the previous one intact at .old
        old_dir = path.rstrip(os.sep) + ".old"
        if os.path.isdir(path):
            shutil.rmtree(old_dir, ignore_errors=True)
            os.rename(path, old_dir)
        os.rename(tmp_dir, path)
        shutil.rmtree(old_dir, ignore_errors=True)
        if self.flight is not None:
            # the pre-crash flight ring rides NEXT TO the snapshot (its
            # own configured path — outside the digest-sealed dir, so
            # restore verification is unaffected)
            self.flight.dump("snapshot")
        return record

    def _snapshot_blobs(self) -> dict:
        """The ONE snapshot serialization body: the full engine state as
        three byte blobs (``pool.npy`` / ``digests.npy`` /
        ``state.json``), shared verbatim by the legacy directory
        `snapshot` and the durable-store `snapshot_store` — store-on
        and store-off snapshots are byte-identical by construction."""
        state = {
            "version": 1,
            "init": dict(self._init_kw),
            "step_index": self.step_index,
            "counters": dict(self.counters),
            "events": [[k, r, st, w] for k, r, st, w in self.events],
            "finished": self.finished.state_dict(),
            "shed": self.shed.state_dict(),
            "missed": self.missed.state_dict(),
            "inflight": sorted(self._inflight),
            "stalled": sorted(self._stalled),
            "sig_prev": dict(self._sig_prev),
            "rng": self._rng.bit_generator.state,
            "pending": {
                "kv": [dataclasses.asdict(f) for f in self._kv_pending],
                "storm": [dataclasses.asdict(f)
                          for f in self._storm_pending],
                "stall": [dataclasses.asdict(f)
                          for f in self._stall_pending],
                "burst": [dataclasses.asdict(f)
                          for f in self._burst_pending],
            },
            "supervisor": (self.supervisor.state_dict()
                           if self.supervisor is not None else None),
            "scheduler": self._sched_state(),
            "prefix_cache": (self.prefix_cache.state_dict()
                             if self.prefix_cache is not None else None),
        }
        json_blob = json.dumps(state, default=_json_default).encode()
        buf = io.BytesIO()
        np.save(buf, np.asarray(self._pool))
        pool_blob = buf.getvalue()
        buf = io.BytesIO()
        np.save(buf, np.asarray(self._digests))
        return {_SNAP_POOL: pool_blob, _SNAP_DIGESTS: buf.getvalue(),
                _SNAP_STATE: json_blob}

    def snapshot_store(self, store, *, writer=None):
        """Publish the snapshot as ONE sealed generation of a
        `cpd_tpu.store.DurableStore` (ISSUE 20): same three blobs as
        `snapshot`, but the atomicity story is the store's — fsynced
        artifacts, sealed manifest with per-artifact digests, atomic
        rename, writer fencing, quarantine on corruption — instead of
        the hand-rolled ``.tmp``/``.old`` dance.  Returns the published
        `GenerationInfo`."""
        info = store.publish(self._snapshot_blobs(),
                             step=int(self.step_index),
                             meta={"surface": "engine"}, writer=writer)
        if self.flight is not None:
            self.flight.dump("snapshot")
        return info

    @classmethod
    def restore_store(cls, model, params, store, prefix_cache=None,
                      token=None) -> "ServeEngine":
        """Rebuild an engine from the newest VALID generation of a
        durable store (or the exact ``token``).  Corrupt generations
        are quarantined by the store's scan and the next-newest valid
        one restores instead — the store-plane version of `restore`'s
        swap-window recovery, with the same bitwise (8,23) resume."""
        info = (store.newest_valid() if token is None
                else store.lookup(token))
        if info is None:
            raise FileNotFoundError(
                f"no valid engine snapshot generation in {store.root}")
        blobs = store.load(info)
        state = json.loads(blobs[_SNAP_STATE].decode())
        pool = np.load(io.BytesIO(blobs[_SNAP_POOL]))
        digests = np.load(io.BytesIO(blobs[_SNAP_DIGESTS]))
        return cls._rebuild(model, params, state, pool, digests,
                            prefix_cache)

    @classmethod
    def restore(cls, model, params, path: str,
                prefix_cache=None) -> "ServeEngine":
        """Rebuild an engine from a `snapshot` directory and resume
        decoding bitwise-identically (the pool is exact bytes — gated
        at (8,23) in tests/test_serve.py and the serve-smoke).  The
        content digest is verified FIRST; a tampered or truncated
        snapshot raises instead of restoring garbage.  A snapshot taken
        mid-corruption restores the corrupt page bytes AND the stale
        page digests, so the standard detect -> repair path fires on
        the first post-restore dispatch.

        Swap-window recovery: if ``path`` itself holds no complete
        snapshot (a crash landed between `snapshot`'s two directory
        renames), the COMPLETE sibling is used instead — ``path.tmp``
        first (the newer state, fully written before the swap begins),
        then ``path.old`` (the retired previous snapshot) — so the
        automated snapshot-to-one-path crash-recovery loop restores
        without operator surgery whatever instant the save died."""
        from ..train.checkpoint import checkpoint_digest

        base = path.rstrip(os.sep)
        candidates = [path, base + ".tmp", base + ".old"]
        complete = [p for p in candidates
                    if os.path.exists(os.path.join(p, _SNAP_META))]
        if not complete:
            raise FileNotFoundError(
                f"no complete snapshot at {path} (nor at the "
                f"swap-window siblings {base}.tmp / {base}.old)")
        path = complete[0]
        with open(os.path.join(path, _SNAP_META)) as fh:
            recorded = json.load(fh)["integrity"]
        actual = checkpoint_digest(path, exclude=(_SNAP_META,))
        if actual["digest"] != recorded["digest"]:
            raise ValueError(
                f"snapshot {path}: content digest mismatch "
                f"({actual['digest'][:12]}… != "
                f"{recorded['digest'][:12]}…) — refusing to restore a "
                "corrupted snapshot")
        with open(os.path.join(path, _SNAP_STATE)) as fh:
            state = json.load(fh)
        pool = np.load(os.path.join(path, _SNAP_POOL))
        digests = np.load(os.path.join(path, _SNAP_DIGESTS))
        return cls._rebuild(model, params, state, pool, digests,
                            prefix_cache)

    @classmethod
    def _rebuild(cls, model, params, state: dict, pool, digests,
                 prefix_cache) -> "ServeEngine":
        """The ONE snapshot-rebuild body (state dict + pool/digest
        arrays -> live engine), shared by the directory `restore` and
        the durable-store `restore_store`."""
        from ..resilience.inject import FaultSpec

        init = dict(state["init"])
        init["kv_format"] = tuple(init["kv_format"])
        eng = cls(model, params, **init)
        eng._pool = jnp.asarray(pool)
        eng._digests = jnp.asarray(digests)
        eng.step_index = int(state["step_index"])
        eng.counters = {k: int(v) for k, v in state["counters"].items()}
        eng.events = deque(((k, r, st, w) for k, r, st, w
                            in state["events"]), maxlen=eng.events.maxlen)
        # the monotone tail cursor restarts at the retained window's
        # length; consumers detect the restored object (new identity)
        # and re-anchor — their per-rid guards make re-reads idempotent
        eng.events_total = len(eng.events)
        eng.finished.load_state_dict(state["finished"])
        eng.shed.load_state_dict(state["shed"])
        eng.missed.load_state_dict(state["missed"])
        eng._inflight = set(state["inflight"])
        eng._stalled = set(state["stalled"])
        eng._sig_prev = {k: int(v) for k, v in state["sig_prev"].items()}
        eng._rng.bit_generator.state = state["rng"]
        pend = state["pending"]
        eng._kv_pending = [FaultSpec(**f) for f in pend["kv"]]
        eng._storm_pending = [FaultSpec(**f) for f in pend["storm"]]
        eng._stall_pending = [FaultSpec(**f) for f in pend["stall"]]
        eng._burst_pending = [FaultSpec(**f) for f in pend["burst"]]
        if state["supervisor"] is not None:
            eng.supervisor = ServeSupervisor.from_state_dict(
                state["supervisor"])
        eng._load_sched_state(state["scheduler"])
        blob = state.get("prefix_cache")
        if blob is not None and prefix_cache is not None:
            # exact resume: same index, same held pages, same LRU order
            prefix_cache.load_state_dict(blob)
            eng.prefix_cache = prefix_cache
        elif blob is not None:
            # cold-cache restore (no cache object supplied): drop the
            # cache's page references so its held pages return to the
            # pool instead of leaking — deterministic, documented in
            # docs/SERVING.md "Prefix cache"
            for ent in blob["entries"]:
                eng.sched.release(int(ent["page_id"]))
        elif prefix_cache is not None:
            eng.prefix_cache = prefix_cache
        return eng

    def _sched_state(self) -> dict:
        def req_dict(r):
            return None if r is None else dataclasses.asdict(r)

        return {
            "slots": [{
                "index": sl.index, "state": sl.state,
                "req": req_dict(sl.req), "pages": list(sl.pages),
                "fed": sl.fed, "next_token": sl.next_token,
                "generated": list(sl.generated), "seq": sl.seq,
                "first_token_step": sl.first_token_step,
                "last_progress": sl.last_progress,
                "prefix_registered": sl.prefix_registered,
            } for sl in self.sched.slots],
            "queue": [dataclasses.asdict(q) for q in self.sched.queue],
            "free_pages": list(self.sched.free_pages),
            "page_refs": {str(p): n
                          for p, n in sorted(self.sched.page_refs.items())},
            "admit_seq": self.sched._admit_seq,
        }

    def _load_sched_state(self, state: dict) -> None:
        def req_from(d):
            if d is None:
                return None
            d = dict(d)
            d["prompt"] = tuple(d["prompt"])
            return Request(**d)

        for sl, d in zip(self.sched.slots, state["slots"]):
            sl.state = d["state"]
            sl.req = req_from(d["req"])
            sl.pages = tuple(d["pages"])
            sl.fed = int(d["fed"])
            sl.next_token = int(d["next_token"])
            sl.generated = [int(t) for t in d["generated"]]
            sl.seq = int(d["seq"])
            sl.first_token_step = int(d["first_token_step"])
            sl.last_progress = int(d["last_progress"])
            sl.prefix_registered = int(d.get("prefix_registered", 0))
        self.sched.queue = deque(req_from(q) for q in state["queue"])
        self.sched.free_pages = deque(int(p)
                                      for p in state["free_pages"])
        if "page_refs" in state:
            self.sched.page_refs = {int(p): int(n)
                                    for p, n in state["page_refs"].items()}
        else:
            # pre-refcount snapshot: every live slot page held once
            self.sched.page_refs = {int(p): 1 for sl in self.sched.slots
                                    for p in sl.pages}
        self.sched._admit_seq = int(state["admit_seq"])

    # -- misc -------------------------------------------------------------

    def _span(self, name: str, step: int):
        """Phase span when tracing, THE shared no-op context otherwise
        (obs.trace.NULL_SPAN — zero allocation per step)."""
        if self.tracer is None:
            return NULL_SPAN
        return self.tracer.span(name, step=step, cat="serve")

    def _event(self, kind: str, rid: int, step: int, **ann) -> None:
        """One engine event: the bounded host log keeps its historical
        4-tuple shape (tests/snapshots parse it); the tracer — when
        attached — gets the SAME wall float plus the annotations, which
        is what makes `loadgen.timeline_metrics`'s reconstruction
        bit-exact against the published latency metrics."""
        w = now()
        self.events.append((kind, rid, step, w))
        self.events_total += 1
        if self.tracer is not None:
            self.tracer.request_event(rid, kind, step, wall=w, **ann)
