"""Serving forward pass for `TransformerLM` over the paged eXmY KV cache.

The flax decode path (`TransformerLM(decode=True)`) owns a dense
(B, T_max) cache collection with ONE scalar position shared by the whole
batch — exactly what continuous batching cannot use: slots in the same
decode batch sit at different positions, join and leave mid-flight, and
their K/V lives in pages, not a contiguous buffer.  So serving runs the
transformer math directly over the param pytree: same ops in the same
order as `models/transformer.py` (fast-variance LayerNorm, head-major
qkv split, RoPE, GQA grouped contraction, gelu MLP, tied embed head),
with attention reading K/V through `kvcache.gather_kv` and per-slot
positions instead of the module's cache variables.  Parity with
``model.apply`` is pinned to fp32 round-off by tests/test_serve.py.

Two jitted programs, both jit-stable in shape:

* ``decode_step`` — ONE token for every slot of the fixed-shape batch
  (S,), free slots masked to the trash page;
* ``prefill_step`` — one CHUNK of one slot's prompt (C tokens, tail
  padded + masked), so a long prompt never stalls the decode batch: the
  engine interleaves one chunk per engine step against ongoing decode.

Quantize-on-append ordering: each layer packs its K/V into the pages
FIRST and attends through the pool AFTER, so every K/V read — including
a token's own chunk — sees the dequantized page bytes.  That makes the
numerics independent of *when* a position was computed (prefill, decode,
or corruption-repair recompute), which is what makes repair-by-recompute
deterministic, and makes the (8,23) path bitwise equal to the fp32
oracle (the codec is lossless there; tests/test_serve.py gates it).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import kvcache
from .kvcache import KVCacheConfig
from ..compat import shard_map
from ..ops.serve_attn import fused_gather_attention
from ..parallel.mesh import AXIS_TENSOR, make_mesh
from ..parallel.ring import gather_transport_bytes
from ..quant.numerics import cast_body, pack_exmy, unpack_exmy
from ..utils.cache import LRUCache

__all__ = ["ModelSpec", "spec_from_model", "make_decode_step",
           "make_prefill_step"]

# jitted step programs keyed by their static configuration, shared across
# engines: a fresh ServeEngine for a warm (spec, cfg) re-uses the compile
# instead of re-tracing (the determinism smoke runs the same trace on two
# fresh engines).  Bounded, matching the make_sum_gradients_fn precedent.
_STEP_CACHE = LRUCache(maxsize=32)

# a Python float, not a jnp scalar: promotes to the same float32(-1e30)
# in `jnp.where`, and stays an inlined literal when `_paged_attention`
# traces INSIDE the fused Pallas kernel (a module-level device array
# would be a captured constant, which pallas_call rejects)
_NEG_INF = -1e30
_LN_EPS = 1e-6   # flax nn.LayerNorm default, matching transformer.py


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The static facts the serving forward needs about a TransformerLM."""
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: Optional[int]     # None = MHA (fused wqkv layout)
    d_ff: int

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads else self.n_heads


def spec_from_model(model) -> ModelSpec:
    """Extract a ModelSpec from a `TransformerLM` module, failing fast on
    configurations the serving forward does not mirror."""
    if getattr(model, "scan_layers", False):
        raise ValueError("serving needs the unrolled block{i} param "
                         "layout; scan_layers=True is not supported")
    if (model.ffn_exp, model.ffn_man) != (8, 23):
        raise ValueError(
            f"serving mirrors the plain Dense FFN only; quantized-"
            f"accumulator MLP (ffn e{model.ffn_exp}m{model.ffn_man}) is "
            "a training-path feature")
    if model.tp_axis or model.sp_axis:
        raise ValueError("serving is single-device (like decode=True); "
                         "unset tp_axis/sp_axis")
    return ModelSpec(vocab_size=model.vocab_size, d_model=model.d_model,
                     n_layers=model.n_layers, n_heads=model.n_heads,
                     n_kv_heads=model.n_kv_heads, d_ff=model.d_ff)


def _layernorm(x: jnp.ndarray, p: dict) -> jnp.ndarray:
    """flax nn.LayerNorm parity: fast variance (E[x²] − E[x]²), eps 1e-6,
    learned scale+bias."""
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    mean2 = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    var = jnp.maximum(0.0, mean2 - jnp.square(mean))
    y = (x - mean) * jax.lax.rsqrt(var + _LN_EPS)
    return y * p["scale"] + p["bias"]


def _rope(x: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
    """Rotary embedding on (B, T, H, D) with PER-SLOT (B, T) positions —
    the batched sibling of transformer._rope (whose positions are one
    (T,) vector shared by the batch; serving slots each sit elsewhere)."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32)
                    * (jnp.log(10000.0) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # (B, T, half)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


def _qkv(blk: dict, h: jnp.ndarray, spec: ModelSpec) -> tuple:
    """Mirror Block's head-major projection split: (q, k, v) with q
    (B, T, H, D) and k/v (B, T, H_kv, D) — GQA kv stays UNEXPANDED."""
    b, t, _ = h.shape
    hd = spec.head_dim
    if spec.n_kv_heads is None:
        qkv = h @ blk["wqkv"]["kernel"]
        qkv = qkv.reshape(b, t, spec.n_heads, 3, hd)
        return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    q = (h @ blk["wq"]["kernel"]).reshape(b, t, spec.n_heads, hd)
    kv = (h @ blk["wkv"]["kernel"]).reshape(b, t, spec.n_kv_heads, 2, hd)
    return q, kv[..., 0, :], kv[..., 1, :]


def _shard_qkv(blk: dict, h: jnp.ndarray, spec: ModelSpec,
               tp: int) -> tuple:
    """This shard's head group of `_qkv`, inside `shard_map`: params
    ride REPLICATED (one in_spec for the whole tree — robust to pytree
    container drift), and each shard slices its own contiguous kernel
    columns by ``axis_index``.  The projection layouts are head-major
    (transformer.py), so a contiguous column window IS a whole head
    group — shard s computes exactly heads [s·H/tp, (s+1)·H/tp), and
    the GQA q-group→kv-head mapping (j -> j // rep) stays shard-local
    because tp divides both H and H_kv."""
    b, t, _ = h.shape
    hd = spec.head_dim
    s = lax.axis_index(AXIS_TENSOR)
    if spec.n_kv_heads is None:
        h_loc = spec.n_heads // tp
        cols = h_loc * 3 * hd                 # 3·hd columns per head
        kern = lax.dynamic_slice_in_dim(blk["wqkv"]["kernel"], s * cols,
                                        cols, axis=1)
        qkv = (h @ kern).reshape(b, t, h_loc, 3, hd)
        return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    h_loc = spec.n_heads // tp
    kv_loc = spec.n_kv_heads // tp
    wq = lax.dynamic_slice_in_dim(blk["wq"]["kernel"], s * h_loc * hd,
                                  h_loc * hd, axis=1)
    wkv = lax.dynamic_slice_in_dim(blk["wkv"]["kernel"],
                                   s * kv_loc * 2 * hd, kv_loc * 2 * hd,
                                   axis=1)
    q = (h @ wq).reshape(b, t, h_loc, hd)
    kv = (h @ wkv).reshape(b, t, kv_loc, 2, hd)
    return q, kv[..., 0, :], kv[..., 1, :]


def _gather_heads(attn_local: jnp.ndarray, cfg: KVCacheConfig) -> jnp.ndarray:
    """all_gather the per-shard attention outputs over the QUANTIZED
    wire: pack to the cache's eXmY format, gather the code words, unpack
    — the EQuARX move applied to the tp gather.  At (8, 23) the cast is
    SKIPPED: `pack_exmy` there is a lossless byte split of ANY fp32
    (subnormals included), so the gathered heads are bit-identical to
    the tp=1 engine's — the sharded (8,23) bitwise contract rides on
    this.  Sub-fp32 formats quantize the attention output on the wire
    (the documented sharded error bound, docs/SERVING.md).  Shard-major
    concatenation == the original contiguous head order, so the merged
    (B, T, H, D) is layout-identical to `_qkv`'s."""
    if cfg.raw:
        full = lax.all_gather(attn_local, AXIS_TENSOR)
    else:
        x = attn_local
        if (cfg.exp_bits, cfg.man_bits) != (8, 23):
            x = cast_body(x, cfg.exp_bits, cfg.man_bits)
        wire = pack_exmy(x, cfg.exp_bits, cfg.man_bits)
        wire = lax.all_gather(wire, AXIS_TENSOR)
        full = unpack_exmy(wire, cfg.exp_bits, cfg.man_bits)
    full = jnp.moveaxis(full, 0, 2)           # (B, T, tp, h_loc, D)
    b, t = full.shape[:2]
    return full.reshape(b, t, -1, full.shape[-1])


def _paged_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     q_pos: jnp.ndarray,
                     last_pos: jnp.ndarray) -> jnp.ndarray:
    """GQA softmax attention against a gathered capacity window.

    q: (B, T, H, D); k/v: (B, T_cap, H_kv, D) — the slot's whole page
    window; q_pos: (B, T) int32 global query positions; last_pos: (B,)
    the newest LIVE position per slot.  The mask ``key_pos <=
    query_pos`` is both causality and the unwritten-tail guard, the
    same contract as Block._cached_attention.  fp32 softmax; grouped
    contraction, nothing rep-sized materialized."""
    b, t, h, d = q.shape
    hkv = k.shape[2]
    rep = h // hkv
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    ki = jnp.arange(k.shape[1], dtype=jnp.int32)
    # zero the window tail past each slot's newest LIVE position BEFORE
    # any contraction: a freshly reallocated page can still hold a
    # previous tenant's bytes — possibly corrupt ones decoding to NaN —
    # and while the logit mask below gives those positions zero
    # PROBABILITY, 0 * NaN in the value einsum would still poison the
    # output row.  Zeroed K/V make the dead tail inert in both
    # contractions.
    live = (ki[None, :] <= last_pos[:, None])[..., None, None]
    k = jnp.where(live, k, 0.0)                      # (B, T_cap, 1, 1)
    v = jnp.where(live, v, 0.0)
    qg = q.reshape(b, t, hkv, rep, d)
    logits = jnp.einsum("bqgrd,bkgd->bgrqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    mask = ki[None, None, :] <= q_pos[:, :, None]         # (B, T, T_cap)
    logits = jnp.where(mask[:, None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, t, h, d)


def _block(blk: dict, x: jnp.ndarray, positions: jnp.ndarray,
           last_pos: jnp.ndarray, pool: jnp.ndarray,
           digests: jnp.ndarray, layer: int,
           page_rows: jnp.ndarray, page_ids: jnp.ndarray,
           offsets: jnp.ndarray, spec: ModelSpec,
           cfg: KVCacheConfig, qkv_fn, merge_fn,
           fused: bool) -> tuple:
    """One decoder block over the paged cache: project, append-quantized,
    attend-through-pool, MLP.  page_ids/offsets: (N,) flattened targets
    of THIS call's (B·T) new positions (masked lanes -> trash page).

    ``cfg`` is the SHARD VIEW (== the engine config at tp=1): every
    kvcache call below is shard-oblivious.  ``qkv_fn``/``merge_fn`` are
    the tp hooks — identity projection/merge at tp=1, per-shard column
    slice + quantized-wire head gather under shard_map.  ``fused``
    routes the pool read through the one-pass Pallas kernel
    (ops/serve_attn.py) instead of gather_kv + attention, with the
    kernel's as-read page digests verified against the stored ones as a
    BONUS read-path check (the pre-append check stays: the kernel
    gathers post-refresh bytes, which are blessed by construction)."""
    h = _layernorm(x, blk["ln1"])
    q, k, v = qkv_fn(blk, h)
    q = _rope(q, positions)
    k = _rope(k, positions)
    # pre-append integrity check: the refresh below re-digests the page
    # from its POST-write bytes, which would re-bless corruption already
    # in it — so the stored digest is verified against the current bytes
    # first, and the step's verdict rides out to the engine (which
    # discards this dispatch's results and repairs on a nonzero count)
    bad = kvcache.check_digests(pool, digests, layer, page_ids)
    # quantize-on-append BEFORE attention (module docstring: every read
    # sees page bytes, so prefill/decode/repair agree on the value set)
    flat = (-1, cfg.n_kv_heads, cfg.head_dim)
    pool = kvcache.write_kv(pool, layer,
                            kvcache.pack_kv(k.reshape(flat), cfg),
                            kvcache.pack_kv(v.reshape(flat), cfg),
                            page_ids, offsets)
    digests = kvcache.refresh_digests(pool, digests, layer, page_ids)
    if fused:
        attn, read_dig = fused_gather_attention(
            pool[layer], q, page_rows, positions, last_pos,
            page_size=cfg.page_size,
            unpack_fn=lambda kv_pages: kvcache.unpack_kv(kv_pages, cfg),
            attend_fn=_paged_attention,
            interpret=jax.default_backend() != "tpu")
        bad = bad + jnp.sum(
            (read_dig != digests[layer][page_rows]).astype(jnp.int32))
    else:
        kc, vc = kvcache.gather_kv(pool, layer, page_rows, cfg)
        attn = _paged_attention(q, kc, vc, positions, last_pos)
    attn = merge_fn(attn)
    attn = attn.reshape(*attn.shape[:-2], spec.n_heads * spec.head_dim)
    x = x + attn @ blk["wo"]["kernel"]

    h = _layernorm(x, blk["ln2"])
    h = jax.nn.gelu(h @ blk["wi"]["kernel"])
    x = x + h @ blk["wo_mlp"]["kernel"]
    return x, pool, digests, bad


def _forward(params: dict, tokens: jnp.ndarray, positions: jnp.ndarray,
             last_pos: jnp.ndarray, pool: jnp.ndarray,
             digests: jnp.ndarray, page_rows: jnp.ndarray,
             page_ids: jnp.ndarray, offsets: jnp.ndarray,
             spec: ModelSpec, cfg: KVCacheConfig, qkv_fn=None,
             merge_fn=None, fused: bool = False) -> tuple:
    """Shared decode/prefill body: embed -> blocks -> ln_f -> tied head.
    tokens/positions: (B, T); last_pos: (B,) newest live position per
    slot; returns ((B, T, V) logits, pool, digests, bad) where ``bad``
    is the summed pre-append digest-mismatch count over all layers (the
    engine discards the dispatch and repairs when it is nonzero).
    ``cfg`` must be the shard view; ``qkv_fn``/``merge_fn``/``fused``
    as in `_block` (defaults are the tp=1 XLA path)."""
    if qkv_fn is None:
        qkv_fn = lambda blk, h: _qkv(blk, h, spec)  # noqa: E731
    if merge_fn is None:
        merge_fn = lambda attn: attn                # noqa: E731
    emb = params["embed"]["embedding"]
    x = emb[tokens].astype(jnp.float32)
    bad = jnp.zeros((), jnp.int32)
    for layer in range(spec.n_layers):
        x, pool, digests, layer_bad = _block(
            params[f"block{layer}"], x, positions, last_pos, pool,
            digests, layer, page_rows, page_ids, offsets, spec, cfg,
            qkv_fn, merge_fn, fused)
        bad = bad + layer_bad
    x = _layernorm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x, emb.astype(jnp.float32))
    return logits.astype(jnp.float32), pool, digests, bad


def _page_targets(positions: jnp.ndarray, page_rows: jnp.ndarray,
                  valid: jnp.ndarray, cfg: KVCacheConfig) -> tuple:
    """(page_ids, offsets) for new positions: look the position's page up
    in its slot's page-table row; invalid lanes -> the trash page.

    positions/valid: (B, T); page_rows: (B, max_pages).  Returns flat
    (B·T,) int32 pairs, matching _block's flattened K/V rows."""
    slot_page = jnp.clip(positions // cfg.page_size, 0,
                         page_rows.shape[1] - 1)
    pids = jnp.take_along_axis(page_rows, slot_page, axis=1)
    pids = jnp.where(valid, pids, kvcache.TRASH_PAGE)
    offs = jnp.where(valid, positions % cfg.page_size, 0)
    return pids.reshape(-1), offs.reshape(-1).astype(jnp.int32)


def _serve_mesh(tp: int):
    """The serving tp mesh: the first ``tp`` local devices on the one
    tensor axis.  Fails fast with the fix (the conftest/bench device-
    count forcing) when the platform is too small."""
    devices = jax.devices()
    if len(devices) < tp:
        raise RuntimeError(
            f"tp={tp} needs {tp} devices, have {len(devices)} — force "
            "more virtual CPU devices (XLA_FLAGS="
            "--xla_force_host_platform_device_count=N) before jax "
            "initializes, or lower tp")
    return make_mesh(tp=tp, devices=devices[:tp])


def _check_tp(spec: ModelSpec, cfg: KVCacheConfig) -> None:
    if spec.n_heads % cfg.tp != 0:
        raise ValueError(
            f"tp={cfg.tp} must divide n_heads={spec.n_heads}: decode "
            "shards by whole query-head groups")
    if spec.kv_heads % cfg.tp != 0:
        raise ValueError(
            f"tp={cfg.tp} must divide n_kv_heads={spec.kv_heads}")


def make_decode_step(spec: ModelSpec, cfg: KVCacheConfig,
                     fused: bool = False):
    """Jitted fixed-shape continuous-batching decode step.

    fn(params, pool, digests, tokens (S,), positions (S,), page_rows
    (S, max_pages), active (S,) bool) -> (pool, digests, logits (S, V),
    bad).  Each active slot feeds ONE token sitting at ``positions[s]``
    (appending its K/V there) and gets the next-token logits; inactive
    slots ride along masked to the trash page.

    ``cfg.tp > 1`` runs the step under `shard_map` on the serving tp
    mesh: params replicated, pool/digests sharded on their shard axis,
    per-shard projections + attention, and the head merge over the
    quantized all_gather wire (`_gather_heads` — bitwise == tp=1 at
    (8, 23)).  ``fused`` routes the pool read through the one-pass
    Pallas kernel; it is a retrace coordinate (`ladder_step_key`
    carries it) and composes with tp.  The fp32 oracle cache keeps the
    XLA read path — ``fused`` with ``raw=True`` is rejected."""
    if fused and cfg.raw:
        raise ValueError(
            "fused_attn with raw=True: the fp32 oracle cache is the "
            "reference the fused kernel is gated against — it keeps "
            "the XLA read path")
    _check_tp(spec, cfg)

    def build():
        if cfg.tp == 1:
            @jax.jit
            def step(params, pool, digests, tokens, positions, page_rows,
                     active):
                pos2 = positions[:, None]             # (S, 1)
                pids, offs = _page_targets(pos2, page_rows,
                                           active[:, None], cfg)
                logits, pool2, digests2, bad = _forward(
                    params, tokens[:, None], pos2, positions, pool,
                    digests, page_rows, pids, offs, spec, cfg,
                    fused=fused)
                return pool2, digests2, logits[:, 0], bad

            return step

        mesh = _serve_mesh(cfg.tp)
        sv = cfg.shard_view()
        qkv_fn = lambda blk, h: _shard_qkv(blk, h, spec, cfg.tp)  # noqa: E731
        merge_fn = lambda attn: _gather_heads(attn, cfg)          # noqa: E731

        def body(params, pool, digests, tokens, positions, page_rows,
                 active):
            # squeeze this shard's slice to the legacy tp=1 layout —
            # every kvcache call inside _forward is shard-oblivious
            pool = pool[:, :, 0]
            digests = digests[:, :, 0]
            pos2 = positions[:, None]
            pids, offs = _page_targets(pos2, page_rows, active[:, None],
                                       cfg)
            logits, pool, digests, bad = _forward(
                params, tokens[:, None], pos2, positions, pool, digests,
                page_rows, pids, offs, spec, sv, qkv_fn=qkv_fn,
                merge_fn=merge_fn, fused=fused)
            # one fleet-visible verdict: any shard's mismatch is the
            # engine's mismatch (psum is NOT a priced transport)
            bad = lax.psum(bad, AXIS_TENSOR)
            return (pool[:, :, None], digests[:, :, None],
                    logits[:, 0], bad)

        shard = P(None, None, AXIS_TENSOR)
        return jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(), shard, shard, P(), P(), P(), P()),
            out_specs=(shard, shard, P(), P()), check_vma=False))

    return _STEP_CACHE.get_or_create(("decode", spec, cfg, fused), build)


def make_prefill_step(spec: ModelSpec, cfg: KVCacheConfig, chunk: int):
    """Jitted chunked-prefill step for ONE slot.

    fn(params, pool, digests, tokens (C,), start, n_valid, page_row
    (max_pages,)) -> (pool, digests, last_logits (V,), bad): feeds
    prompt positions [start, start + n_valid) (the (C,) buffer's tail
    past n_valid is pad — masked to the trash page, its rows discarded)
    and returns the logits at the chunk's LAST VALID position —
    meaningful only for the prompt's final chunk, where it samples
    token 0.  ``cfg.tp > 1`` shards exactly like `make_decode_step`
    (prefill always keeps the XLA read path — the fused kernel is a
    decode-hot-path optimization)."""
    if chunk < 1:
        raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
    _check_tp(spec, cfg)

    def build():
        if cfg.tp == 1:
            @jax.jit
            def step(params, pool, digests, tokens, start, n_valid,
                     page_row):
                idx = jnp.arange(chunk, dtype=jnp.int32)
                positions = (start + idx)[None]        # (1, C)
                valid = (idx < n_valid)[None]
                pids, offs = _page_targets(positions, page_row[None],
                                           valid, cfg)
                # newest LIVE position: the last VALID chunk lane (pad
                # lanes have positions past it but write only to the
                # trash page)
                last_pos = (start + n_valid - 1)[None]
                logits, pool2, digests2, bad = _forward(
                    params, tokens[None], positions, last_pos, pool,
                    digests, page_row[None], pids, offs, spec, cfg)
                last = jnp.clip(n_valid - 1, 0, chunk - 1)
                return pool2, digests2, logits[0, last], bad

            return step

        mesh = _serve_mesh(cfg.tp)
        sv = cfg.shard_view()
        qkv_fn = lambda blk, h: _shard_qkv(blk, h, spec, cfg.tp)  # noqa: E731
        merge_fn = lambda attn: _gather_heads(attn, cfg)          # noqa: E731

        def body(params, pool, digests, tokens, start, n_valid,
                 page_row):
            pool = pool[:, :, 0]
            digests = digests[:, :, 0]
            idx = jnp.arange(chunk, dtype=jnp.int32)
            positions = (start + idx)[None]
            valid = (idx < n_valid)[None]
            pids, offs = _page_targets(positions, page_row[None], valid,
                                       cfg)
            last_pos = (start + n_valid - 1)[None]
            logits, pool, digests, bad = _forward(
                params, tokens[None], positions, last_pos, pool,
                digests, page_row[None], pids, offs, spec, sv,
                qkv_fn=qkv_fn, merge_fn=merge_fn)
            bad = lax.psum(bad, AXIS_TENSOR)
            last = jnp.clip(n_valid - 1, 0, chunk - 1)
            return (pool[:, :, None], digests[:, :, None],
                    logits[0, last], bad)

        shard = P(None, None, AXIS_TENSOR)
        return jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(), shard, shard, P(), P(), P(), P()),
            out_specs=(shard, shard, P(), P()), check_vma=False))

    return _STEP_CACHE.get_or_create(("prefill", spec, cfg, chunk), build)


def _ir_abstract_params(spec: ModelSpec):
    """ShapeDtypeStruct param pytree matching `_forward`'s layout (GQA
    form) — lets the IR analyzer trace the serving programs with no
    weights materialized."""
    d, ff, hd = spec.d_model, spec.d_ff, spec.head_dim

    def f32(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    ln = lambda: {"scale": f32(d), "bias": f32(d)}  # noqa: E731
    blk = {"ln1": ln(), "ln2": ln(),
           "wq": {"kernel": f32(d, spec.n_heads * hd)},
           "wkv": {"kernel": f32(d, spec.kv_heads * 2 * hd)},
           "wo": {"kernel": f32(d, d)},
           "wi": {"kernel": f32(d, ff)},
           "wo_mlp": {"kernel": f32(ff, d)}}
    params = {"embed": {"embedding": f32(spec.vocab_size, d)},
              "ln_f": ln()}
    for i in range(spec.n_layers):
        params[f"block{i}"] = blk
    return params


def ir_programs(reg):
    """Program-contract declarations (analysis/ir/registry.py): the
    serving decode/prefill programs are bitwise-gated — prefill writes
    pages one PROGRAM, decode and corruption-repair read them from
    OTHERS, and the (8,23) decode additionally claims bitwise parity
    with the fp32-cache oracle — exactly the cross-program contract an
    ulp-unstable transcendental (the PR 12 exp2 class) breaks."""
    S, MP, CHUNK = 4, 4, 4
    spec = ModelSpec(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                     n_kv_heads=2, d_ff=64)
    deps = ("cpd_tpu.serve.model", "cpd_tpu.serve.kvcache",
            "cpd_tpu.quant.numerics")

    def _cfg(block=None, fmt=(4, 3), tp=1):
        return KVCacheConfig(n_layers=spec.n_layers, n_pages=8,
                             page_size=4, n_kv_heads=spec.kv_heads,
                             head_dim=spec.head_dim, exp_bits=fmt[0],
                             man_bits=fmt[1],
                             block_scale=block is not None,
                             block_size=block if block is not None
                             else 32, tp=tp)

    def _decode(block=None, fmt=(4, 3), tp=1):
        def build():
            cfg = _cfg(block, fmt, tp)
            step = make_decode_step(spec, cfg)
            i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
            args = (_ir_abstract_params(spec),
                    jax.ShapeDtypeStruct(cfg.pool_shape, jnp.uint8),
                    jax.ShapeDtypeStruct(cfg.digests_shape,
                                         jnp.uint32),
                    i32(S), i32(S), i32(S, MP),
                    jax.ShapeDtypeStruct((S,), jnp.bool_))
            return step, args
        return build

    def _prefill(fmt=(4, 3), tp=1):
        def build():
            cfg = _cfg(fmt=fmt, tp=tp)
            step = make_prefill_step(spec, cfg, CHUNK)
            i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
            args = (_ir_abstract_params(spec),
                    jax.ShapeDtypeStruct(cfg.pool_shape, jnp.uint8),
                    jax.ShapeDtypeStruct(cfg.digests_shape,
                                         jnp.uint32),
                    i32(CHUNK), i32(), i32(), i32(MP))
            return step, args
        return build

    def _tp_wire(n_tokens, fmt):
        # analytic cross-shard bytes (per device): one quantized
        # all_gather of the per-shard attention outputs per layer —
        # `gather_transport_bytes` is the same price the training ring
        # quotes, so serving and training share one wire ledger.
        h_loc = spec.n_heads // 2
        n = n_tokens * h_loc * spec.head_dim
        return lambda: spec.n_layers * gather_transport_bytes(
            n, 2, fmt[0], fmt[1], compressed=True)

    reg.declare("serve.decode[e4m3]", _decode(), deps=deps,
                bitwise=True)
    reg.declare("serve.decode[blocked-e4m3,b32]", _decode(block=32),
                deps=deps, bitwise=True)
    reg.declare("serve.decode[e8m23]", _decode(fmt=(8, 23)),
                deps=deps, bitwise=True)
    reg.declare("serve.prefill[e4m3]", _prefill(), deps=deps,
                bitwise=True)
    # tp=2 sharded twins (ISSUE 18): same contracts lifted onto the
    # head-group mesh — the cross-shard attention gather is the ONLY
    # wire, priced analytically and bitwise-gated like the ring.
    reg.declare("serve.decode[tp2,e4m3]", _decode(tp=2), deps=deps,
                axis_sizes={"tp": 2}, wire=_tp_wire(S, (4, 3)),
                bitwise=True)
    reg.declare("serve.decode[tp2,blocked-e4m3,b32]",
                _decode(block=32, tp=2), deps=deps,
                axis_sizes={"tp": 2}, wire=_tp_wire(S, (4, 3)),
                bitwise=True)
    reg.declare("serve.decode[tp2,e8m23]", _decode(fmt=(8, 23), tp=2),
                deps=deps, axis_sizes={"tp": 2},
                wire=_tp_wire(S, (8, 23)), bitwise=True)
    reg.declare("serve.prefill[tp2,e4m3]", _prefill(tp=2), deps=deps,
                axis_sizes={"tp": 2}, wire=_tp_wire(CHUNK, (4, 3)),
                bitwise=True)
