"""Paged KV cache with bit-packed eXmY pages — the serving stack's memory.

The key insight of ROADMAP item 1: the `quant/numerics.pack_exmy` wire
codec (PR 3) **is** a KV-cache codec.  A K/V element that went through
``cast_to_format(·, e, m)`` carries only ``1+e+m`` bits of information,
so the cache stores the ``wire_bytes(e, m)``-byte code words instead of
fp32 — 4× less HBM at e5m2 — and `unpack_exmy` reconstructs the exact
fp32 bit pattern at attention time.  (8,23) bypasses quantization (the
code word IS the fp32 byte split), which is what makes the packed cache
**bitwise identical** to an fp32-cache oracle there — the gate
tests/test_serve.py pins.

Layout (one pool array, allocated ONCE at capacity — no allocation ever
happens on the serving hot path):

    pool:    (L, n_pages, 2, page_size, H_kv, D, WB)  uint8
    digests: (L, n_pages)                             uint32

Tensor-parallel engines (ISSUE 18) insert a shard axis at position 2 —
``(L, n_pages, tp, 2, page_size, H_kv/tp, D, WB)`` with digests
``(L, n_pages, tp)`` — so ``pool[:, :, s]`` is EXACTLY a tp=1 pool of
shard ``s``'s head group and every codec/digest function below runs
per shard under ``KVCacheConfig.shard_view()``, unchanged.

* ``L`` — decoder layers; axis FIRST so every per-layer read/write is a
  static slice (`pool[l]`) inside the jitted step.
* plane 2 — K then V.
* ``WB = wire_bytes(e, m)`` trailing code-word bytes (`pack_exmy`'s own
  trailing axis).
* page id 0 is the **trash page**: masked lanes (free slots in the
  fixed-shape decode batch, pad tokens in a prefill chunk) write there,
  so every scatter in the step has jit-stable shapes and no `cond`.
  The allocator never hands out page 0 and the scrubber skips it.
* ``digests[l, p]`` — `parallel/integrity.wire_digest` (Fletcher mod
  65521, position-weighted) over page p's bytes in layer l, updated in
  the same jitted program as every append.  The scrubber recomputes all
  of them and compares: any flipped byte in an allocated page surfaces
  as a (layer, page) mismatch the engine can map back to its owning
  request and repair by recomputation (docs/SERVING.md, repair ladder).

The page *table* lives in host/int32 land (scheduler.py): each request
slot owns an immutable tuple of page ids reserved at admission
(worst-case ``ceil((prompt + max_new) / page_size)`` — reservation is
what makes "zero dropped requests" a theorem instead of a hope), padded
with the trash page to the static ``max_pages`` row the jitted gather
uses.

A ``raw=True`` config skips the codec entirely (fp32 pool, no cast, no
pack): that IS the fp32-cache oracle the packed cache is gated against
— bitwise at (8,23), where packing is a lossless byte split and the
cast is the identity on every non-subnormal fp32; accuracy-bounded at
narrow formats (docs/SERVING.md documents the bound).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..parallel.integrity import wire_digest
from ..quant.numerics import (_validate_wire, cast_to_format,
                              kv_page_bytes, pack_exmy,
                              pack_exmy_blocked, sidecar_bytes,
                              unpack_exmy, unpack_exmy_blocked,
                              wire_bytes)

__all__ = ["KVCacheConfig", "alloc_pool", "pack_kv", "unpack_kv",
           "write_kv", "gather_kv", "refresh_digests", "check_digests",
           "all_digests", "TRASH_PAGE"]

TRASH_PAGE = 0   # reserved page id for masked writes; never allocated


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape/format description of one paged KV pool.

    ``block_scale`` (ISSUE 12 leg 2) switches each K/V row (one token
    position's ``n_kv_heads * head_dim`` elements) to the BLOCK-SCALED
    codec: the row is `cast_body_blocked` at append (one power-of-2
    scale per ``block_size`` consecutive elements of the flattened row,
    odd tail block included) and stored as `pack_exmy_blocked`'s flat
    wire — code bytes followed by the 1-byte-per-block shift sidecar —
    so an e4m3 page covers dynamic range a per-tensor e5m2 page cannot
    (the bench_reduce frontier trade applied to KV memory, the serving
    capacity ceiling).  The sidecar lives INSIDE the row, hence inside
    the page pool: every page digest, scrub, corruption check and
    snapshot covers it with zero extra machinery, and `kv_page_bytes`
    (block_size=...) prices it.  Requires a packable sub-fp32 format —
    at (8, 23) there is nothing to scale and the config is rejected."""
    n_layers: int
    n_kv_heads: int
    head_dim: int
    page_size: int
    n_pages: int          # INCLUDING the trash page
    exp_bits: int = 8
    man_bits: int = 23
    raw: bool = False     # fp32 pool, no codec — the oracle cache
    block_scale: bool = False
    block_size: int = 32
    tp: int = 1           # head-group shards (ISSUE 18): pool gains a
                          # shard axis at position 2, digests a trailing one

    def __post_init__(self):
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        if self.n_pages < 2:
            raise ValueError("n_pages must be >= 2 (page 0 is the trash "
                             f"page), got {self.n_pages}")
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.n_kv_heads % self.tp != 0:
            raise ValueError(
                f"tp={self.tp} must divide n_kv_heads={self.n_kv_heads}: "
                "the pool shards by whole KV head groups")
        if self.block_scale and self.raw:
            raise ValueError("block_scale=True with raw=True: the fp32 "
                             "oracle pool has no codec to scale")
        if self.raw:
            return
        # the ONE packed-wire validator (numerics._validate_wire — the
        # man>=2 special-code rule included), eagerly at config build
        # time rather than mid-trace; no copy of the rule to drift
        _validate_wire(self.exp_bits, self.man_bits)
        if self.block_scale:
            if (self.exp_bits, self.man_bits) == (8, 23):
                raise ValueError(
                    "block_scale=True at (8, 23): the lossless byte "
                    "split has nothing to scale — drop block_scale or "
                    "pick a sub-fp32 format")
            if self.block_size < 1:
                raise ValueError(f"block_size must be >= 1, got "
                                 f"{self.block_size}")

    @property
    def fmt(self) -> tuple:
        return (self.exp_bits, self.man_bits)

    def shard_view(self) -> "KVCacheConfig":
        """The ONE-shard view of a tp-sharded pool: same config with
        ``tp=1`` and ``n_kv_heads // tp`` heads.  Every existing kvcache
        function (pack/unpack/write/gather/check/refresh) operates on a
        single shard's legacy-shaped slice under this view — the sharded
        engine never needs shard-aware codec code, which is what keeps
        each shard's pages bitwise identical to a tp=1 pool holding the
        same head group."""
        if self.tp == 1:
            return self
        return dataclasses.replace(self, tp=1,
                                   n_kv_heads=self.n_kv_heads // self.tp)

    @property
    def word_bytes(self) -> int:
        return 4 if self.raw else wire_bytes(self.exp_bits, self.man_bits)

    @property
    def row_elems(self) -> int:
        """K or V elements of one token position (the blocked codec's
        row length)."""
        return self.n_kv_heads * self.head_dim

    @property
    def row_bytes(self) -> int:
        """Stored bytes of one token position's K (or V) row in the
        BLOCKED layout: code bytes + the shift sidecar."""
        return (self.row_elems * self.word_bytes
                + sidecar_bytes(self.row_elems, self.block_size))

    @property
    def page_bytes(self) -> int:
        """One layer's K+V bytes per page, summed over all ``tp`` shards
        — `quant.numerics.kv_page_bytes` is the single source of truth;
        the pool slice must agree.  (``shard_page_bytes`` is the
        per-shard slice.)"""
        if self.raw:
            return 2 * self.page_size * self.n_kv_heads * self.head_dim * 4
        return kv_page_bytes(self.exp_bits, self.man_bits, self.page_size,
                             self.n_kv_heads, self.head_dim,
                             block_size=(self.block_size if self.block_scale
                                         else None), tp=self.tp)

    @property
    def shard_page_bytes(self) -> int:
        """One SHARD's K+V bytes per layer-page (== ``page_bytes`` at
        tp=1).  Under the blocked codec this is NOT page_bytes // tp:
        scale blocks span the shard-local row, so each shard prices its
        own sidecar."""
        return self.shard_view().page_bytes

    @property
    def pool_shape(self) -> tuple:
        if self.tp > 1:
            # shard axis at position 2: page axis stays axis 1, so every
            # page-indexed host operation (snapshot, capsule extraction's
            # pool[:, idx]) works unchanged, and pool[:, :, s] is exactly
            # a tp=1 pool of the shard's head group
            sv = self.shard_view()
            return sv.pool_shape[:2] + (self.tp,) + sv.pool_shape[2:]
        if self.block_scale:
            # rows are flat blocked-wire byte vectors (codes + sidecar):
            # the per-element (H, D, WB) structure dissolves into the
            # codec's own layout, and the sidecar rides inside the page
            return (self.n_layers, self.n_pages, 2, self.page_size,
                    self.row_bytes)
        base = (self.n_layers, self.n_pages, 2, self.page_size,
                self.n_kv_heads, self.head_dim)
        return base if self.raw else base + (self.word_bytes,)

    @property
    def digests_shape(self) -> tuple:
        """(L, n_pages) at tp=1; (L, n_pages, tp) sharded — one Fletcher
        digest per shard-local page, so integrity stays per-shard-bitwise."""
        base = (self.n_layers, self.n_pages)
        return base if self.tp == 1 else base + (self.tp,)


def alloc_pool(cfg: KVCacheConfig) -> jnp.ndarray:
    """The once-at-capacity page pool (zeros — the defined empty state)."""
    return jnp.zeros(cfg.pool_shape,
                     jnp.float32 if cfg.raw else jnp.uint8)


def pack_kv(x: jnp.ndarray, cfg: KVCacheConfig) -> jnp.ndarray:
    """fp32 K or V block (..., H_kv, D) -> quantized packed code words
    (..., H_kv, D, WB), or the flat blocked row (..., row_bytes) when
    ``cfg.block_scale`` (raw oracle: the fp32 values unchanged).
    Quantize-on-append: the cast runs HERE, once per token, so attention
    reads the same value set no matter how often it re-reads a page."""
    x = jnp.asarray(x, jnp.float32)
    if cfg.raw:
        return x
    if cfg.block_scale:
        rows = x.reshape(x.shape[:-2] + (cfg.row_elems,))
        # pack_exmy_blocked IS the blocked cast + pack in one: the shift
        # derivation is a fixed point of the cast, so decode reproduces
        # cast_body_blocked(row) bit for bit (numerics block comment)
        return pack_exmy_blocked(rows, cfg.exp_bits, cfg.man_bits,
                                 cfg.block_size)
    q = cast_to_format(x, cfg.exp_bits, cfg.man_bits)
    return pack_exmy(q, cfg.exp_bits, cfg.man_bits)


def unpack_kv(packed: jnp.ndarray, cfg: KVCacheConfig) -> jnp.ndarray:
    """Inverse of `pack_kv`'s packing: (..., WB) uint8 (or the flat
    blocked (..., row_bytes) row) -> (..., H_kv, D) fp32 with the exact
    bit patterns the append-time cast produced."""
    if cfg.raw:
        return packed
    if cfg.block_scale:
        rows = unpack_exmy_blocked(packed, cfg.exp_bits, cfg.man_bits,
                                   cfg.row_elems, cfg.block_size)
        return rows.reshape(rows.shape[:-1] + (cfg.n_kv_heads,
                                               cfg.head_dim))
    return unpack_exmy(packed, cfg.exp_bits, cfg.man_bits)


def write_kv(pool: jnp.ndarray, layer: int, k: jnp.ndarray, v: jnp.ndarray,
             page_ids: jnp.ndarray, offsets: jnp.ndarray) -> jnp.ndarray:
    """Scatter already-packed K/V rows into layer ``layer``'s pages.

    k, v: (N, H_kv, D, WB) uint8 — or the flat blocked (N, row_bytes)
    rows when the config block-scales — one row per token position;
    page_ids, offsets: (N,) int32 — target page and in-page slot per row
    (masked rows point at TRASH_PAGE; duplicate trash targets are
    harmless, every lane writes garbage nobody reads)."""
    pool = pool.at[layer, page_ids, 0, offsets].set(k)
    return pool.at[layer, page_ids, 1, offsets].set(v)


def gather_kv(pool: jnp.ndarray, layer: int, page_rows: jnp.ndarray,
              cfg: KVCacheConfig) -> tuple:
    """Assemble per-slot contiguous K/V from the page table.

    page_rows: (S, max_pages) int32 — each slot's page ids, trash-padded.
    Returns fp32 ``(k, v)`` each (S, max_pages * page_size, H_kv, D):
    the slot's whole capacity window, unwritten tail included (callers
    mask by position, exactly like the dense cache path)."""
    s, max_pages = page_rows.shape
    kv = unpack_kv(pool[layer][page_rows], cfg)     # (S, P, 2, page, H, D)
    t_cap = max_pages * cfg.page_size
    k = kv[:, :, 0].reshape(s, t_cap, cfg.n_kv_heads, cfg.head_dim)
    v = kv[:, :, 1].reshape(s, t_cap, cfg.n_kv_heads, cfg.head_dim)
    return k, v


def check_digests(pool: jnp.ndarray, digests: jnp.ndarray, layer: int,
                  page_ids: jnp.ndarray) -> jnp.ndarray:
    """int32 count of pages among ``page_ids`` whose CURRENT bytes do not
    match their stored digest — the PRE-append integrity check.

    Appending to a page recomputes its digest from the post-write bytes
    (`refresh_digests`), which would silently re-bless any corruption
    already sitting in the page; checking right before the write closes
    that window: a corrupted page is either appended to (caught HERE,
    this step) or left alone (caught by the next periodic scrub).
    Duplicate ids re-count the same page — callers only branch on
    count > 0."""
    cur = jax.vmap(wire_digest)(pool[layer][page_ids])
    return jnp.sum((cur != digests[layer, page_ids]).astype(jnp.int32))


def refresh_digests(pool: jnp.ndarray, digests: jnp.ndarray, layer: int,
                    page_ids: jnp.ndarray) -> jnp.ndarray:
    """Recompute the integrity digest of layer ``layer``'s pages
    ``page_ids`` (N, duplicates fine — they all see the same post-write
    bytes) from the pool's CURRENT contents."""
    fresh = jax.vmap(wire_digest)(pool[layer][page_ids])
    return digests.at[layer, page_ids].set(fresh)


def all_digests(pool: jnp.ndarray, sharded: bool = False) -> jnp.ndarray:
    """(L, n_pages) uint32 digest of every page — the scrub pass (and the
    initial digest state: digest-of-zero-page for untouched pages).
    ``sharded=True`` digests a tp-sharded pool (shard axis at position 2)
    per shard-local page -> (L, n_pages, tp): each shard's digest is
    bitwise what a tp=1 pool of that head group would store."""
    if sharded:
        return jax.vmap(jax.vmap(jax.vmap(wire_digest)))(pool)
    return jax.vmap(jax.vmap(wire_digest))(pool)
