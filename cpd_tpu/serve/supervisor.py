"""Serving degradation supervision — the escalate/probation reflex for L5.

The training side grew this reflex twice: `TransportSupervisor` (PR 4)
degrades the reduce transport under wire corruption, and
`PrecisionSupervisor` (PR 5) escalates the eXmY format under saturation
pressure.  The serving engine had neither — under a flash crowd its
only behaviours were head-of-line blocking and the scrub loop.  This
module is the same state-machine shape pointed at serving overload
(ISSUE 10):

    normal ──(hot for `patience` steps)──> rung 1 ──(again)──> rung 2 …
      ^                                       |                   |
      └──── probation: N quiet steps ─────────┴──── N quiet ──────┘

* **sense** — three deterministic step-clock signals the engine feeds
  every step: page-pool pressure (reserved fraction of allocatable
  pages above ``pressure``), KV corruption (inline pre-append detects
  OR scrub-found corrupt pages this step), and deadline misses (a
  cancellation this step).  Any one makes the step *hot*.
* **degrade** — after ``patience`` consecutive hot steps, step one rung
  DOWN the configured ladder.  Each `Rung` names a restriction set the
  engine applies from the next step: cap the prefill chunk (smaller
  dispatches, finer interleave — the SAME compiled program, only
  ``n_valid`` shrinks, so no retrace), cap admissions per step, tighten
  the scrub cadence, and finally shed the lowest-SLA-class traffic at
  admission (including purging it from the queue).
* **probation** — after ``probation`` consecutive quiet steps at a
  degraded rung, move one rung back up; rung 0 (the configured
  behaviour) is home, never exceeded.

Pure host state — no RNG, no wall clock — so a run under a
deterministic `FaultPlan` (``kv_storm``/``req_burst``/``slot_stall``)
replays its exact transition sequence, and `state_dict()` is JSON-able
so crash-recovery snapshots (`ServeEngine.snapshot`) resume the ladder
mid-degradation exactly like the precision supervisor resumes
mid-escalation from checkpoint metadata.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

__all__ = ["Rung", "ServeSupervisor", "default_rungs"]


@dataclasses.dataclass(frozen=True)
class Rung:
    """One degradation rung: the restriction set the engine applies
    while the supervisor sits at this level.  ``None`` leaves the
    engine's configured behaviour untouched; rungs list their
    restrictions EXPLICITLY (no implicit inheritance from earlier
    rungs), so the active policy is always readable off one object."""
    name: str
    prefill_chunk_cap: Optional[int] = None  # max prompt tokens/dispatch
    admission_cap: Optional[int] = None      # max admissions per step
    scrub_every_cap: Optional[int] = None    # scrub at least this often
    shed_class_above: Optional[int] = None   # shed sla_class >= this

    def __post_init__(self):
        for field in ("prefill_chunk_cap", "admission_cap",
                      "scrub_every_cap", "shed_class_above"):
            v = getattr(self, field)
            if v is not None and v < 1:
                raise ValueError(f"rung {self.name!r}: {field} must be "
                                 f">= 1 (or None), got {v}")


def default_rungs(prefill_chunk: int) -> tuple:
    """The documented default ladder for an engine with the given base
    prefill chunk (docs/SERVING.md "Degradation ladder"): shrink the
    prefill chunk, then cap admissions, then tighten the scrub, then
    shed everything below the premium class."""
    half = max(1, prefill_chunk // 2)
    return (
        Rung("normal"),
        Rung("small-prefill", prefill_chunk_cap=half),
        Rung("cap-admissions", prefill_chunk_cap=half, admission_cap=1),
        Rung("tight-scrub", prefill_chunk_cap=half, admission_cap=1,
             scrub_every_cap=1),
        Rung("shed-low", prefill_chunk_cap=half, admission_cap=1,
             scrub_every_cap=1, shed_class_above=1),
    )


class ServeSupervisor:
    """The serving degradation ladder (module docstring).

    ``on_step(step, page_util=, corrupt=, misses=)`` -> None |
    "degrade" | "probate"; ``rung`` is the restriction set the engine
    should apply next step; ``transitions`` is the deterministic
    (step, from_name, to_name) log the chaos tests assert on, capped at
    the newest ``TRANSITION_CAP`` entries."""

    # plenty for any test/debug window; a process-lifetime supervisor
    # keeps the newest entries and drops the oldest past this
    TRANSITION_CAP = 4096

    def __init__(self, rungs: Optional[Sequence[Rung]] = None, *,
                 patience: int = 2, probation: int = 8,
                 pressure: float = 0.9, prefill_chunk: int = 16):
        self.rungs = tuple(rungs) if rungs is not None \
            else default_rungs(prefill_chunk)
        if len(self.rungs) < 2:
            raise ValueError(f"a degradation ladder needs >= 2 rungs "
                             f"(normal + at least one restriction), got "
                             f"{len(self.rungs)}")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        if probation < 1:
            raise ValueError(f"probation must be >= 1, got {probation}")
        if not 0.0 < pressure <= 1.0:
            raise ValueError(f"pressure is a fraction in (0, 1], got "
                             f"{pressure}")
        self.patience = int(patience)
        self.probation = int(probation)
        self.pressure = float(pressure)
        self._level = 0
        self.hot = 0              # consecutive hot steps
        self.quiet = 0            # consecutive quiet steps
        self.last_hot = False
        # (step, from_name, to_name); capped — a supervisor lives for
        # the whole serving process, and a flapping ladder would
        # otherwise grow this on the step clock forever (host-unbounded)
        self.transitions: list = []

    # -- introspection ----------------------------------------------------

    @property
    def rung(self) -> Rung:
        """The restriction set the engine should apply next step."""
        return self.rungs[self._level]

    @property
    def level(self) -> int:
        return self._level

    @property
    def degraded(self) -> bool:
        return self._level > 0

    # -- the state machine ------------------------------------------------

    def observe(self, *, page_util: float, corrupt: int,
                misses: int) -> bool:
        """The hot/quiet verdict for one engine step: page pressure at or
        above the threshold, any KV corruption seen this step (inline
        detects or scrub-found pages), or any deadline miss."""
        return (float(page_util) >= self.pressure or int(corrupt) > 0
                or int(misses) > 0)

    def on_step(self, step: int, *, page_util: float, corrupt: int = 0,
                misses: int = 0) -> Optional[str]:
        """Feed one engine step's signals; returns "degrade"/"probate"
        when the ladder moves, else None."""
        hot = self.observe(page_util=page_util, corrupt=corrupt,
                           misses=misses)
        self.last_hot = hot
        if hot:
            self.quiet = 0
            self.hot += 1
            if self.hot >= self.patience and \
                    self._level + 1 < len(self.rungs):
                old = self.rung.name
                self._level += 1
                self.hot = 0
                self._record(step, old)
                return "degrade"
            return None
        self.hot = 0
        self.quiet += 1
        if self._level > 0 and self.quiet >= self.probation:
            old = self.rung.name
            self._level -= 1
            self.quiet = 0
            self._record(step, old)
            return "probate"
        return None

    def _record(self, step: int, old: str) -> None:
        self.transitions.append((step, old, self.rung.name))
        if len(self.transitions) > self.TRANSITION_CAP:
            del self.transitions[0]

    # -- snapshot persistence ---------------------------------------------

    def state_dict(self) -> dict:
        """JSON-able snapshot (rung CONFIG included, so
        `ServeEngine.restore` rebuilds the identical ladder): a restored
        engine resumes mid-degradation instead of re-climbing from
        normal — the serving twin of the precision supervisor's
        checkpoint-metadata persistence."""
        return {
            "rungs": [dataclasses.asdict(r) for r in self.rungs],
            "patience": self.patience,
            "probation": self.probation,
            "pressure": self.pressure,
            "level": self._level,
            "hot": self.hot,
            "quiet": self.quiet,
            "transitions": [list(t) for t in self.transitions],
        }

    @classmethod
    def from_state_dict(cls, state: dict) -> "ServeSupervisor":
        """Rebuild a supervisor — config AND position — from a
        `state_dict` snapshot."""
        sup = cls(tuple(Rung(**r) for r in state["rungs"]),
                  patience=int(state["patience"]),
                  probation=int(state["probation"]),
                  pressure=float(state["pressure"]))
        sup.load_state_dict(state)
        return sup

    def load_state_dict(self, state: dict) -> "ServeSupervisor":
        """Restore ladder position onto a configured supervisor
        (returns self).  The saved rung list must match the configured
        one — resuming level 2 of a DIFFERENT ladder would silently
        apply an unintended restriction set."""
        saved = tuple(Rung(**r) for r in state["rungs"])
        if saved != self.rungs:
            raise ValueError(
                f"snapshotted serve ladder "
                f"{[r.name for r in saved]} does not match the "
                f"configured {[r.name for r in self.rungs]}; restore "
                f"with the same rung list")
        self._level = min(max(int(state["level"]), 0), len(self.rungs) - 1)
        self.hot = int(state.get("hot", 0))
        self.quiet = int(state.get("quiet", 0))
        self.transitions = [tuple(t) for t in state.get("transitions", [])]
        return self
