"""Loggers — the reference's three observability surfaces, unified.

Parity targets (SURVEY.md §5 "Metrics / logging"):
  * the stdout line protocol that doubles as the plotting data source —
    ``Iter: [i/N] ... Loss ... Prec@1 ...`` progress lines and the
    ``* All Loss {l} Prec@1 {p} ...`` validation summary lines that
    example/ResNet18/draw_curve.py:11-29 greps out of `tee`'d logs
    (printed at mix.py:326-335,422-425);
  * DavidNet's rank-gated column printer ``TableLogger`` (utils.py:44-56)
    and DAWNBench ``TSVLogger`` (dawn.py:37-47);
  * tensorboardX rank-0 scalars (mix.py:16,168-171,323-325,340-343) —
    re-imagined as a dependency-free JSONL scalar stream that tensorboard,
    pandas, or draw_curve can all ingest.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, IO, Optional

from ..obs.timing import epoch

__all__ = ["TableLogger", "TSVLogger", "ScalarWriter", "ProgressPrinter",
           "format_validation_line"]


class TableLogger:
    """Aligned-column stdout table (DavidNet utils.py:44-56 parity): prints
    the header once, then one row per call; only `rank` 0 prints."""

    def __init__(self, rank: int = 0):
        self.rank = rank
        self.keys: Optional[list] = None

    def append(self, output: Dict[str, Any]):
        if self.rank != 0:
            return
        if self.keys is None:
            self.keys = list(output)
            print(*(f"{k:>12s}" for k in self.keys))
        filtered = [output[k] for k in self.keys]
        print(*(f"{v:12.4f}" if isinstance(v, float) else f"{str(v):>12s}"
                for v in filtered), flush=True)


class TSVLogger:
    """DAWNBench submission format: ``epoch\\thours\\ttop1Accuracy``
    (dawn.py:37-47 parity, with the accuracy column actually populated —
    the reference hardcodes it to 0, dawn.py:42-43)."""

    def __init__(self):
        self.log = ["epoch\thours\ttop1Accuracy"]

    def append(self, output: Dict[str, Any]):
        epoch = output["epoch"]
        hours = output["total time"] / 3600
        acc = 100.0 * float(output.get("test acc", 0.0))
        self.log.append(f"{epoch}\t{hours:.8f}\t{acc:.2f}")  # cpd: disable=host-unbounded -- one line per epoch; the list IS the DAWNBench submission artifact __str__ serializes

    def __str__(self):
        return "\n".join(self.log)


class ScalarWriter:
    """Append-only JSONL scalar stream: one ``{"tag","step","value","ts"}``
    object per line.  Replaces the reference's tensorboardX SummaryWriter
    (mix.py:168-171) without the dependency; `rank`-gated like the
    reference's ``if rank == 0`` guards.

    ``tensorboard=True`` additionally mirrors every scalar into TensorBoard
    event files in the same directory (the reference's actual logging
    backend, mix.py:16,168-171), using ``torch.utils.tensorboard`` or
    ``tensorboardX`` — whichever imports.  If neither does, the writer
    degrades to JSONL-only with a one-line warning, mirroring the
    reference's graceful CPU-only contract (quant_function.py:18-19)."""

    def __init__(self, log_dir: str, rank: int = 0,
                 filename: str = "scalars.jsonl",
                 tensorboard: bool = False):
        self.rank = rank
        self._fh: Optional[IO] = None
        self._tb = None
        if rank == 0:
            os.makedirs(log_dir, exist_ok=True)
            self._fh = open(os.path.join(log_dir, filename), "a")
            if tensorboard:
                self._tb = self._open_tb(log_dir)

    @staticmethod
    def _open_tb(log_dir: str):
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError:
            try:
                from tensorboardX import SummaryWriter
            except ImportError:
                import sys

                print("ScalarWriter: tensorboard not importable; "
                      "JSONL-only", file=sys.stderr)
                return None
        return SummaryWriter(log_dir)

    def add_scalar(self, tag: str, value: float, step: int):
        if self._fh is None:
            return
        self._fh.write(json.dumps({"tag": tag, "step": int(step),
                                   "value": float(value),
                                   "ts": epoch()}) + "\n")
        self._fh.flush()
        if self._tb is not None:
            self._tb.add_scalar(tag, float(value), int(step))

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ProgressPrinter:
    """The per-iteration stdout protocol of mix.py:326-335: emitted every
    `print_freq` steps, rank-0 only."""

    def __init__(self, total_iters: int, print_freq: int = 50, rank: int = 0):
        self.total = total_iters
        self.freq = print_freq
        self.rank = rank

    def maybe_print(self, step: int, _suffix: str = "", **meters: float):
        """``_suffix``: pre-rendered tail (the resilience counters'
        ``ResilienceMeter.suffix()`` — integers, so they don't go
        through the float meter formatting); empty for healthy runs."""
        if self.rank != 0 or step % self.freq != 0:
            return
        body = "\t".join(f"{k} {v:.4f}" for k, v in meters.items())
        print(f"Iter: [{step}/{self.total}]\t{body}{_suffix}", flush=True)


def format_validation_line(loss: float, prec1: float, prec5: float) -> str:
    """The exact summary-line shape draw_curve greps for: token index -3
    must be Prec@1's value (draw_curve.py:16-18 splits on whitespace and
    takes ``split()[-3]``; mix.py:422-425 prints
    ``* All Loss {l} Prec@1 {p1} Prec@5 {p5}``)."""
    return f" * All Loss {loss:.4f} Prec@1 {prec1:.3f} Prec@5 {prec5:.3f}"
