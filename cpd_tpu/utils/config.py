"""YAML-into-argparse config merge (ResNet18 trainer parity).

The reference loads a YAML file and injects the ``common:`` block's keys
directly onto the argparse namespace (mix.py:69-72), so CLI flags and YAML
keys share one flat namespace.  Same contract here, plus explicit
precedence: a key given on the command line wins over the YAML value.
"""

from __future__ import annotations

import argparse
from typing import Any, Dict

import yaml

__all__ = ["load_yaml_config", "merge_config_into_args"]


def load_yaml_config(path: str, section: str = "common") -> Dict[str, Any]:
    """Read `path` and return its `section` mapping (mix.py:69-72 reads the
    ``common`` block of configs/res18_cifar.yaml)."""
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    cfg = doc.get(section, doc)
    if not isinstance(cfg, dict):
        raise ValueError(f"config section {section!r} in {path} is not a map")
    return cfg


def merge_config_into_args(args: argparse.Namespace, cfg: Dict[str, Any],
                           cli_overrides: Dict[str, Any] | None = None
                           ) -> argparse.Namespace:
    """Set each cfg key as an attribute on `args` unless the user passed it
    explicitly on the command line (keys in `cli_overrides`)."""
    explicit = cli_overrides or {}
    for key, value in cfg.items():
        if key not in explicit:
            setattr(args, key, value)
    return args
