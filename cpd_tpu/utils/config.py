"""YAML-into-argparse config merge (ResNet18 trainer parity) + the
shared resilience-flag surface.

The reference loads a YAML file and injects the ``common:`` block's keys
directly onto the argparse namespace (mix.py:69-72), so CLI flags and YAML
keys share one flat namespace.  Same contract here, plus explicit
precedence: a key given on the command line wins over the YAML value.

``add_resilience_flags`` / ``build_resilience`` give every trainer the
same ``--fault-plan`` / guard / watchdog / rollback vocabulary (the YAML
merge covers these keys too, since they are plain argparse dests).
Imports of the resilience package are lazy: a trainer that never passes
a resilience flag pays nothing.
"""

from __future__ import annotations

import argparse
from typing import Any, Dict

import yaml

__all__ = ["load_yaml_config", "merge_config_into_args",
           "add_resilience_flags", "add_transport_flags",
           "add_obs_flags", "build_obs", "finish_obs",
           "build_resilience", "overlap_key"]


def load_yaml_config(path: str, section: str = "common") -> Dict[str, Any]:
    """Read `path` and return its `section` mapping (mix.py:69-72 reads the
    ``common`` block of configs/res18_cifar.yaml)."""
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    cfg = doc.get(section, doc)
    if not isinstance(cfg, dict):
        raise ValueError(f"config section {section!r} in {path} is not a map")
    return cfg


def merge_config_into_args(args: argparse.Namespace, cfg: Dict[str, Any],
                           cli_overrides: Dict[str, Any] | None = None
                           ) -> argparse.Namespace:
    """Set each cfg key as an attribute on `args` unless the user passed it
    explicitly on the command line (keys in `cli_overrides`)."""
    explicit = cli_overrides or {}
    for key, value in cfg.items():
        if key not in explicit:
            setattr(args, key, value)
    return args


def add_transport_flags(parser: argparse.ArgumentParser) -> None:
    """The shared gradient-transport knobs (ISSUE 8: overlapped
    backward-reduce + bucket sizing), one surface for every trainer."""
    g = parser.add_argument_group(
        "transport", "gradient-reduce transport (parallel/overlap.py)")
    g.add_argument("--overlap-reduce", action="store_true",
                   help="bucketed, dependency-scheduled reduction: run "
                        "each gradient bucket's quantized all-reduce "
                        "INSIDE the backward pass (custom_vjp taps) the "
                        "moment the bucket's last gradient closes, so "
                        "XLA can overlap ring hops with backward "
                        "compute.  Bitwise identical to the "
                        "post-backward reduction.  Composes with "
                        "--emulate_node > 1 (unrolled micro chain "
                        "feeding the last micro-batch's taps) and with "
                        "--zero1/--zero2 (ZeRO-2 runs its per-bucket "
                        "all_to_all reduce-scatter inside the taps)")
    g.add_argument("--bucket-elems", default=None, type=int,
                   help="per-bucket element cap for the bucketed "
                        "faithful gather, the bucketed ring and the "
                        "overlapped schedule (default: parallel/dist."
                        "_BUCKET_ELEMS = 4M).  Smaller buckets close "
                        "earlier in the backward (more overlap) but "
                        "launch more collectives — sweep with "
                        "tools/bench_reduce.py --bucket-sweep")
    g.add_argument("--block-scale", action="store_true",
                   help="block-scaled ring wire (EQuARX-style, ISSUE 9): "
                        "every hop cast shares one power-of-2 scale per "
                        "--block-size consecutive elements; the 1-byte-"
                        "per-block shift sidecar rides the packed wire. "
                        "Recovers per-tensor-e5m7-class accuracy at e4m3 "
                        "wire bytes (tools/bench_reduce.py --block-sweep)."
                        "  Requires --mode ring and a packable gradient "
                        "format (man >= 2)")
    g.add_argument("--block-size", default=128, type=int,
                   help="elements per shared-scale block for "
                        "--block-scale (default 128; multiples of 128 "
                        "keep the fused Pallas wire kernel eligible — "
                        "other sizes fall back to the XLA hop bodies)")


def overlap_key(args: argparse.Namespace):
    """The `ladder_step_key(overlap=...)` coordinate for a parsed CLI:
    ``(overlap_reduce, bucket_elems)`` when the run touches the overlap
    surface, None otherwise (keeping the PR 4/5-compatible key shapes
    for runs that never saw the flags)."""
    ov = bool(getattr(args, "overlap_reduce", False))
    be = getattr(args, "bucket_elems", None)
    if not ov and be is None:
        return None
    return (ov, be)


def block_key(args: argparse.Namespace):
    """The `ladder_step_key(block=...)` coordinate for a parsed CLI:
    ``(block_scale, block_size)`` when the run turned block scaling on,
    None otherwise (keeping the PR 8-compatible key shapes for runs
    that never saw the flags).  Unlike `overlap_key`, a bare
    ``--block-size`` without ``--block-scale`` stays None — the size is
    inert until the sidecar wire exists."""
    if not bool(getattr(args, "block_scale", False)):
        return None
    return (True, int(getattr(args, "block_size", 128)))


def add_obs_flags(parser: argparse.ArgumentParser) -> None:
    """The shared observability surface (docs/OBSERVABILITY.md): every
    trainer/bench CLI speaks the same two flags."""
    g = parser.add_argument_group(
        "observability", "cpd_tpu.obs tracing / metrics / flight "
                         "recorder")
    g.add_argument("--obs-dir", default=None, metavar="DIR",
                   help="enable the obs spine: step/request tracing + "
                        "the metrics registry, exported into DIR on "
                        "exit as events.jsonl (deterministic event "
                        "stream), metrics.prom (Prometheus text) and "
                        "trace.json (Perfetto/Chrome-trace).  Unset = "
                        "zero instrumentation cost; either way step "
                        "outputs are bitwise unchanged (obs only "
                        "observes)")
    g.add_argument("--obs-flight", default=256, type=int,
                   metavar="N",
                   help="flight-recorder ring capacity (with "
                        "--obs-dir): the last N step events are "
                        "dumped to DIR/flight.jsonl on watchdog fire, "
                        "rollback, preemption or serve snapshot "
                        "(0 disables the recorder)")


def build_obs(args: argparse.Namespace, *, run: str,
              meta: Dict[str, Any] | None = None) -> Dict[str, Any]:
    """Materialize the obs stack from parsed flags: ``tracer`` /
    ``registry`` / ``flight`` (each None when --obs-dir is unset — the
    provably-free disabled path) plus ``finish(extra=...)``, which
    writes the artifact bundle and returns its paths+summary dict (or
    None when obs is off)."""
    import os

    obs_dir = getattr(args, "obs_dir", None)
    if not obs_dir:
        return {"tracer": None, "registry": None, "flight": None,
                "dir": None, "active": False,
                "finish": lambda **_kw: None}
    from cpd_tpu.obs import FlightRecorder, MetricsRegistry, Tracer
    cap = int(getattr(args, "obs_flight", 256) or 0)
    tracer = Tracer(run, meta=meta)
    registry = MetricsRegistry()
    flight = (FlightRecorder(os.path.join(obs_dir, "flight.jsonl"),
                             capacity=cap) if cap > 0 else None)

    def finish(**extra):
        from cpd_tpu.obs import write_all
        out = write_all(obs_dir, tracer, registry)
        if extra:
            out["summary"].update(extra)
        return out

    return {"tracer": tracer, "registry": registry, "flight": flight,
            "dir": obs_dir, "active": True, "finish": finish}


def finish_obs(obs: Dict[str, Any], *, meter=None, last=None,
               step_no=None, supervisor=None, precision=None,
               elastic=None, rank: int = 0, **extra):
    """The ONE trainer obs epilogue (shared by the lm and resnet18
    CLIs): absorb the run counters, the final step's telemetry
    families and the supervisors' ladder state into the registry, then
    write the artifact bundle.  Returns the bundle dict, or None when
    obs is off."""
    if not obs["active"]:
        return None
    reg = obs["registry"]
    if meter is not None:
        reg.absorb_resilience_meter(meter)
    if last:
        reg.absorb_step_metrics(last, step_no)
    if supervisor is not None:
        reg.absorb_supervisor("transport", {
            "mode": supervisor.mode, "home": supervisor.home,
            "degraded": supervisor.degraded,
            "transitions": supervisor.transitions})
    if precision is not None:
        reg.absorb_supervisor("precision", precision.state_dict())
    if elastic is not None:
        reg.absorb_elastic(elastic)
    out = obs["finish"](**extra)
    if rank == 0:
        import sys
        print(f"=> obs artifacts in {out['dir']}", file=sys.stderr)
    return out


def add_resilience_flags(parser: argparse.ArgumentParser) -> None:
    """The shared ``--fault-plan`` + defense knobs (docs/RESILIENCE.md)."""
    g = parser.add_argument_group(
        "resilience", "fault injection + guarded-loop defenses")
    g.add_argument("--fault-plan", default=None, metavar="SPEC|FILE",
                   help="inject faults: 'kind@step[:arg];...' (e.g. "
                        "'grad_nan@3;stall@5:1.5;ckpt_truncate@6'), a "
                        "JSON plan file, or 'random:<seed>' for a "
                        "seed-deterministic random plan over the run")
    g.add_argument("--guard-grads", action="store_true",
                   help="wrap the optimizer with resilience."
                        "with_grad_guard: skip non-finite / spiking / "
                        "replica-disagreeing gradient steps (implied by "
                        "--fault-plan with grad_* faults)")
    g.add_argument("--spike-factor", default=10.0, type=float,
                   help="guard: skip a finite step whose grad norm "
                        "exceeds this multiple of its running EMA")
    g.add_argument("--watchdog-timeout", default=0.0, type=float,
                   help="seconds a step may block before the watchdog "
                        "dumps diagnostics and forces a clean "
                        "checkpoint-and-exit (0 = off)")
    g.add_argument("--divergence-window", default=0, type=int,
                   help="divergence sentinel window of recent losses "
                        "(0 = off); trips when loss > factor x median")
    g.add_argument("--divergence-factor", default=10.0, type=float)
    g.add_argument("--divergence-mode", default="median",
                   choices=["median", "ema"],
                   help="sentinel detector: 'median' = factor x window-"
                        "median spike (default, PR-2 behavior); 'ema' = "
                        "dual-EMA relative drift — catches the SLOW "
                        "upward creep of quiet saturation that drags "
                        "the median up with it (use a smaller factor, "
                        "e.g. 2)")
    g.add_argument("--max-rollbacks", default=2, type=int,
                   help="bounded retries: rollbacks to the newest valid "
                        "checkpoint before declaring the run diverged")
    g.add_argument("--rollback-backoff", default=0.0, type=float,
                   help="seconds to sleep after rollback k (doubled "
                        "each retry)")
    g.add_argument("--no-ckpt-integrity", dest="ckpt_integrity",
                   action="store_false", default=True,
                   help="skip the per-save content digest (saves regain "
                        "their async overlap with compute, at the cost "
                        "of restore falling back only on restore "
                        "FAILURES, not on silent corruption)")
    g.add_argument("--verify-reduce", action="store_true",
                   help="self-verifying quantized reduction "
                        "(parallel/integrity.py): tagged checksums on "
                        "every ring hop + all-gather row, cross-replica "
                        "agreement digest, and the degraded-transport "
                        "ladder (ring -> faithful -> fp32) on failure")
    g.add_argument("--reduce-retries", default=1, type=int,
                   help="verified reduce: same-step retries before the "
                        "transport supervisor downgrades a level")
    g.add_argument("--transport-probation", default=8, type=int,
                   help="clean verified steps at a degraded transport "
                        "before probation moves one level back up")
    g.add_argument("--precision-ladder", default=None, metavar="F1,F2,..",
                   help="eXmY format-escalation ladder (resilience."
                        "precision): comma list of rungs, home first "
                        "and range-widening (e.g. 'e5m2,e5m7,e8m23'; "
                        "the home rung must equal --grad_exp/"
                        "--grad_man).  Turns on the reduce-wire "
                        "numeric-health telemetry and escalates the "
                        "gradient format when the agreed sat+NaN rate "
                        "stays hot; quiet steps probation back down, "
                        "never below home; ladder state persists in "
                        "checkpoints")
    g.add_argument("--sat-threshold", default=1e-3, type=float,
                   help="precision ladder: agreed (sat+NaN)/total rate "
                        "at the reduce wire above which a step is hot")
    g.add_argument("--sat-patience", default=2, type=int,
                   help="precision ladder: consecutive hot steps before "
                        "escalating one rung")
    g.add_argument("--precision-probation", default=16, type=int,
                   help="precision ladder: consecutive quiet steps at "
                        "an escalated rung before stepping one rung "
                        "back down")
    g.add_argument("--quant-telemetry", action="store_true",
                   help="reduce-wire numeric-health counters "
                        "(prec_wire_sat/underflow/nan + aps_bad "
                        "metrics) WITHOUT the ladder — observability "
                        "only (implied by --precision-ladder)")
    g.add_argument("--elastic", action="store_true",
                   help="elastic training (resilience.elastic): "
                        "heartbeat/straggler detection per host, "
                        "in-step link retries, deterministic mesh "
                        "shrink to the largest power-of-two world of "
                        "alive hosts through the digest-sealed "
                        "checkpoints, probationary regrow on rejoin "
                        "(arms host_kill/straggler/link_flaky plan "
                        "kinds)")
    g.add_argument("--heartbeat-patience", default=3, type=int,
                   help="elastic: consecutive slow heartbeats before a "
                        "host is hot and gets drained")
    g.add_argument("--straggler-factor", default=2.0, type=float,
                   help="elastic: a heartbeat slower than this multiple "
                        "of the host's own step-time EMA is slow")


def build_resilience(args: argparse.Namespace, *, n_steps: int,
                     rank: int = 0, world: int = 0) -> Dict[str, Any]:
    """Materialize the resilience stack from parsed flags.

    Returns a dict with ``injector`` / ``watchdog`` / ``sentinel`` /
    ``meter`` (each possibly None) and ``wrap_tx``, a callable that
    layers ``with_fault_injection`` (when the plan has gradient faults)
    and ``with_grad_guard`` (when requested or implied) around an
    optimizer — outermost-first, the order guard.py documents.

    ``world``: the data-parallel host count — needed only when
    ``--elastic`` is on (the ElasticSupervisor watches that many
    heartbeats); trainers that don't pass it get ``"elastic": None``
    and a warning if the flag was set.
    """
    from cpd_tpu.resilience import (DivergenceSentinel, FaultPlan,
                                    Injector, StepWatchdog,
                                    with_fault_injection, with_grad_guard)
    from cpd_tpu.train.metrics import ResilienceMeter

    plan = None
    spec = getattr(args, "fault_plan", None)
    if spec:
        if spec.startswith("random:"):
            plan = FaultPlan.random(int(spec.split(":", 1)[1]), n_steps)
        else:
            plan = FaultPlan.parse(spec)
    guard = bool(getattr(args, "guard_grads", False)
                 or (plan is not None and plan.grad_faults()))

    def wrap_tx(tx, axis_name=None):
        if guard:
            tx = with_grad_guard(tx, spike_factor=args.spike_factor,
                                 axis_name=axis_name)
        if plan is not None and plan.grad_faults():
            tx = with_fault_injection(tx, plan, n_steps,
                                      axis_name=axis_name)
        return tx

    timeout = float(getattr(args, "watchdog_timeout", 0.0) or 0.0)
    window = int(getattr(args, "divergence_window", 0) or 0)
    verify = bool(getattr(args, "verify_reduce", False))
    wire = plan.wire_faults() if plan is not None else ()
    if wire and not verify:
        # the attack without the defense silently corrupts sums — legal
        # (that IS the baseline the checksums are measured against) but
        # never what a CLI user means; make the footgun explicit
        import sys as _sys
        print("=> WARNING: fault plan schedules wire_* faults but "
              "--verify-reduce is off — the corrupted reduce will go "
              "UNDETECTED (pass --verify-reduce to arm the checksums)",
              file=_sys.stderr)
    supervisor = None
    if verify:
        from cpd_tpu.resilience.transport import TransportSupervisor
        start = getattr(args, "mode", "faithful")
        if start in TransportSupervisor.LEVELS:
            supervisor = TransportSupervisor(
                start=start, max_retries=int(args.reduce_retries),
                probation=int(args.transport_probation))
        # modes outside the ladder (e.g. fast) keep THEIR reduction and
        # verify by agreement digest only — detection without a ladder,
        # never a silent swap onto a transport the user didn't configure
    precision = None
    ladder_spec = getattr(args, "precision_ladder", None)
    if ladder_spec:
        from cpd_tpu.resilience.precision import (PrecisionSupervisor,
                                                  format_name)
        precision = PrecisionSupervisor(
            ladder_spec, threshold=float(args.sat_threshold),
            patience=int(args.sat_patience),
            probation=int(args.precision_probation))
        ge = getattr(args, "grad_exp", None)
        gm = getattr(args, "grad_man", None)
        if ge is not None and precision.home != (int(ge), int(gm)):
            # the ladder's rung 0 IS the run's gradient format; a
            # mismatch would silently train at a format the flags deny
            raise ValueError(
                f"--precision-ladder home rung "
                f"{format_name(precision.home)} must equal the "
                f"configured gradient format e{ge}m{gm} "
                f"(--grad_exp/--grad_man); put e{ge}m{gm} first")
        if getattr(args, "mode", None) == "ring":
            # fail at argument time, not hours in: the ring transport's
            # packed wire (quant.numerics.pack_exmy) needs man_bits >= 2
            # for its Inf/carry/NaN special codes, and the lazily
            # compiled escalated step would otherwise hit that
            # ValueError inside jit tracing at the exact moment the
            # ladder tries to save the run
            unpackable = [f for f in precision.ladder
                          if f[1] < 2 and f != (8, 23)]
            if unpackable:
                raise ValueError(
                    f"--precision-ladder rung(s) "
                    f"{[format_name(f) for f in unpackable]} cannot "
                    f"ride the ring transport's packed wire (pack_exmy "
                    f"needs man_bits >= 2 for the special codes); use "
                    f"man >= 2 rungs or --mode faithful")
    sat = plan.sat_faults() if plan is not None else ()
    quant_stats = bool(precision is not None
                       or getattr(args, "quant_telemetry", False))
    elastic = None
    wants_elastic = bool(getattr(args, "elastic", False))
    host_faults = plan.elastic_faults() if plan is not None else ()
    if host_faults and not wants_elastic:
        import sys as _sys
        print("=> WARNING: fault plan schedules host-level faults "
              "(host_kill/straggler/link_flaky) but --elastic is off — "
              "they will be flagged unfired, not survived (pass "
              "--elastic to arm the recovery ladder)", file=_sys.stderr)
    if wants_elastic:
        if world >= 1:
            from cpd_tpu.resilience.elastic import ElasticSupervisor
            elastic = ElasticSupervisor(
                world,
                patience=int(getattr(args, "heartbeat_patience", 3)),
                factor=float(getattr(args, "straggler_factor", 2.0)))
        elif rank == 0:
            import sys as _sys
            print("=> WARNING: --elastic needs the trainer to pass its "
                  "host world to build_resilience(world=...); elastic "
                  "supervision is OFF for this run", file=_sys.stderr)
    return {
        "plan": plan,
        "verify": verify,
        "wire_plan": (plan.wire_schedule(n_steps) if wire else None),
        "supervisor": supervisor,
        # precision-ladder surface (ISSUE 5): the supervisor (None when
        # --precision-ladder is off), whether step builders should
        # thread the prec_wire_* telemetry, and the baked 2^k
        # saturation-pressure table (None when the plan has no
        # sat_pressure specs)
        "precision": precision,
        "quant_stats": quant_stats,
        "sat_plan": (plan.sat_schedule(n_steps) if sat else None),
        # True only when wrap_tx is not the identity — what actually
        # composes (or not) with custom-update paths like ZeRO
        "wraps_optimizer": bool(guard
                                or (plan is not None and plan.grad_faults())),
        "injector": Injector(plan, rank=rank) if plan is not None else None,
        # hard_exit_after: a trip nobody acknowledges (step wedged in
        # native code, or the interrupt absorbed with no boundary in
        # sight) kills the process with diagnostics after one more
        # timeout, instead of hanging forever (watchdog.py docstring)
        "watchdog": (StepWatchdog(timeout, rank=rank,
                                  hard_exit_after=timeout)
                     if timeout > 0 else None),
        "sentinel": (DivergenceSentinel(window,
                                        factor=args.divergence_factor,
                                        mode=getattr(args,
                                                     "divergence_mode",
                                                     "median"))
                     if window > 0 else None),
        "meter": ResilienceMeter(),
        "wrap_tx": wrap_tx,
        # elastic-training surface (ISSUE 19): the ElasticSupervisor
        # (None unless --elastic AND the trainer passed world >= 1)
        "elastic": elastic,
        "active": bool(plan or guard or timeout > 0 or window > 0
                       or verify or quant_stats or elastic is not None),
    }
