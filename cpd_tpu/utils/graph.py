"""Dict-dataflow graph model definition — the reference's TorchGraph API.

The reference's DavidNet is *defined* as a nested-dict dataflow graph and
executed topologically by ``TorchGraph`` (reference:
example/DavidNet/utils.py:231-292 — ``union`` / ``path_iter`` /
``build_graph`` / ``TorchGraph``; example/DavidNet/davidnet.py:19-69 builds
the net that way).  This module provides the same model-definition surface
on the TPU stack:

* leaves of the nested dict are **Flax modules or plain callables**;
* :func:`build_graph` flattens paths with ``'_'`` and resolves default /
  relative / absolute input references exactly as the reference does
  (utils.py:251-257);
* :class:`GraphModule` executes the flattened graph inside one linen scope,
  so parameters and BatchNorm state are handled normally and XLA fuses
  across node boundaries — the graph is a *definition* convenience, not a
  runtime interpreter (everything still traces into a single jitted
  program, which is why this costs nothing on TPU).

Reference-semantics notes:
* a leaf is either ``node`` or ``(node, [input_refs])``; a node without
  explicit inputs consumes the previous node's output in flattened order,
  and the first node consumes ``'input'`` (utils.py:252).
* an input ref is a str (top-level name), a tuple path, or
  :func:`rel_path` parts resolved against the node's enclosing prefix
  (utils.py:255-256).
* execution returns the full activation cache — ``TorchGraph.forward``
  returns ``self.cache`` (utils.py:287-292) — so loss/metric nodes can
  live in the graph (davidnet.py:66-69).
* nodes whose call signature has a ``train`` parameter receive the
  executor's ``train`` flag (the linen analog of torch's module mode).
"""

from __future__ import annotations

import inspect
from collections.abc import Mapping
from typing import Any, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["SEP", "RelPath", "rel_path", "union", "path_iter",
           "build_graph", "GraphModule", "GraphClassifier", "Identity",
           "Mul", "Flatten", "Add", "Concat", "Correct",
           "CrossEntropySum"]

SEP = "_"


class RelPath(NamedTuple):
    """Input reference relative to the referencing node's dict prefix."""
    parts: tuple


def rel_path(*parts: str) -> RelPath:
    return RelPath(tuple(parts))


def union(*dicts: dict) -> dict:
    """Merge dicts left-to-right (utils.py:235)."""
    return {k: v for d in dicts for (k, v) in d.items()}


def path_iter(nested: Mapping, pfx: tuple = ()):
    """Yield ((path parts), leaf) for every non-mapping leaf, depth-first.

    Mapping, not dict: linen freezes dict fields into FrozenDict, and a
    net stored on a GraphModule must still flatten correctly.
    """
    for name, val in nested.items():
        if isinstance(val, Mapping):
            yield from path_iter(val, (*pfx, name))
        else:
            yield (*pfx, name), val


def _resolve(ref, pfx: tuple) -> str:
    if isinstance(ref, RelPath):
        return SEP.join((*pfx, *ref.parts))
    if isinstance(ref, str):
        return ref
    return SEP.join(ref)


def build_graph(net: dict) -> dict:
    """Flatten a nested net dict into ``{name: (node, [input names])}``.

    Default-input chaining and reference resolution follow
    utils.py:251-257: node *i* defaults to node *i-1*'s name ("input" for
    the first), explicit refs resolve via :func:`_resolve`.
    """
    graph = {}
    prev = "input"
    for path, leaf in path_iter(net):
        name, pfx = SEP.join(path), path[:-1]
        if isinstance(leaf, tuple):
            node, refs = leaf
            inputs = [_resolve(r, pfx) for r in refs]
        else:
            node, inputs = leaf, [prev]
        if name in graph:
            # '_'-flattening can alias distinct paths (e.g. {"a":{"b":...}}
            # vs {"a_b":...}); last-write-wins would silently train a
            # different architecture, so fail loudly instead.
            raise ValueError(f"duplicate flattened node name {name!r}")
        graph[name] = (node, inputs)
        prev = name
    return graph


def _accepts_train(node) -> bool:
    fn = node.__call__ if isinstance(node, nn.Module) else node
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return "train" in sig.parameters


# ---------------------------------------------------------------------------
# Stateless node helpers (utils.py:184-207 equivalents; plain callables, so
# the executor stores no parameters for them).
# ---------------------------------------------------------------------------

class Identity:
    def __call__(self, x):
        return x


class Mul:
    def __init__(self, weight: float):
        self.weight = weight

    def __call__(self, x):
        return x * self.weight


class Flatten:
    def __call__(self, x):
        return x.reshape(x.shape[0], -1)


class Add:
    def __call__(self, x, y):
        return x + y


class Concat:
    """Channel concat — NHWC axis -1 (the reference cats NCHW dim 1)."""

    def __call__(self, *xs):
        return jnp.concatenate(xs, axis=-1)


class Correct:
    def __call__(self, classifier, target):
        return jnp.argmax(classifier, axis=-1) == target


class CrossEntropySum:
    """CE summed over the batch — ``CrossEntropyLoss(size_average=False)``
    of the reference losses dict (davidnet.py:66-69)."""

    def __call__(self, logits, target):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        picked = jnp.take_along_axis(logp, target[:, None], axis=-1)
        return -jnp.sum(picked)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

class GraphModule(nn.Module):
    """Execute a dict-defined dataflow graph (TorchGraph parity).

    ``net`` is either the nested dict itself or a zero-arg builder
    returning it.  Prefer the builder form: module leaves are then
    constructed inside this module's ``setup`` and adopted exactly once,
    which keeps linen's submodule-ownership rules trivially satisfied and
    makes the instance reusable.

    ``__call__`` takes the input cache (``{"input": images, "target":
    labels, ...}`` or a bare array, which becomes ``"input"``) and returns
    the full cache of every node's output, keyed by flattened node name.
    """

    net: Any

    def setup(self):
        net = self.net if isinstance(self.net, Mapping) else self.net()
        graph = build_graph(net)
        # Assigning the dict registers each Module leaf as a named child
        # ("nodes_<flatname>"); plain-callable leaves are stored untouched.
        self.nodes = {name: node for name, (node, _) in graph.items()}
        self.wiring = tuple((name, tuple(ins), _accepts_train(node))
                            for name, (node, ins) in graph.items())

    def __call__(self, inputs, train: bool = True) -> dict:
        cache = dict(inputs) if isinstance(inputs, Mapping) else {
            "input": inputs}
        for name, input_names, wants_train in self.wiring:
            node = self.nodes[name]
            args = [cache[x] for x in input_names]
            if wants_train:
                cache[name] = node(*args, train=train)
            else:
                cache[name] = node(*args)
        return cache


class GraphClassifier(nn.Module):
    """Adapter: run a graph, return one node's output.

    Lets a graph-defined model plug into the standard train-step builders
    (``make_train_step`` expects ``model(x, train) -> logits``) — the graph
    definition style composes with the whole harness, the way the
    reference's TorchGraph feeds its generic train loop (utils.py:328-344).
    """

    net: Any
    output: str = "classifier_logits"

    def setup(self):
        self.graph = GraphModule(self.net)

    def __call__(self, x, train: bool = True):
        return self.graph({"input": x}, train=train)[self.output]
