"""Shared utilities: config loading, loggers, profiling."""

from .config import load_yaml_config, merge_config_into_args
from .logging import (ProgressPrinter, ScalarWriter, TableLogger, TSVLogger,
                      format_validation_line)

__all__ = ["load_yaml_config", "merge_config_into_args", "TableLogger",
           "TSVLogger", "ScalarWriter", "ProgressPrinter",
           "format_validation_line"]
