"""Shared utilities: config loading, loggers, profiling."""

from .cache import clear_cache, default_cache_dir, enable_compile_cache
from .config import load_yaml_config, merge_config_into_args
from .logging import (ProgressPrinter, ScalarWriter, TableLogger, TSVLogger,
                      format_validation_line)
from .profiling import StepProfiler

__all__ = ["load_yaml_config", "merge_config_into_args", "TableLogger",
           "TSVLogger", "ScalarWriter", "ProgressPrinter",
           "format_validation_line", "enable_compile_cache",
           "default_cache_dir", "clear_cache", "StepProfiler"]
