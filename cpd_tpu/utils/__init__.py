"""Shared utilities: config loading, loggers, profiling."""

from .cache import (LRUCache, clear_cache, default_cache_dir,
                    enable_compile_cache)
from .config import load_yaml_config, merge_config_into_args
from .logging import (ProgressPrinter, ScalarWriter, TableLogger, TSVLogger,
                      format_validation_line)
from .profiling import StepProfiler

# graph re-exports are lazy (PEP 562): utils.graph imports flax+jax, and
# `import cpd_tpu.utils` must stay stdlib-cheap so CLIs can parse config
# and set JAX env vars before jax ever loads (see cpd_tpu/__init__.py).
_GRAPH_NAMES = ("GraphModule", "GraphClassifier", "build_graph", "rel_path",
                "union", "path_iter")

__all__ = ["load_yaml_config", "merge_config_into_args", "TableLogger",
           "TSVLogger", "ScalarWriter", "ProgressPrinter",
           "format_validation_line", "enable_compile_cache",
           "default_cache_dir", "clear_cache", "LRUCache", "StepProfiler",
           *_GRAPH_NAMES]


def __getattr__(name):
    if name in _GRAPH_NAMES:
        from . import graph

        return getattr(graph, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
