"""Shared utilities (config loading, logging) — populated as they land."""
