"""Persistent XLA compilation cache — one shared switch.

Full-model train steps cost tens of seconds of XLA compile; caching them
makes driver re-runs of the bench / dryrun / test suite near-free.  Used by
bench.py, __graft_entry__.py and tests/conftest.py so the cache-dir logic
lives in exactly one place.
"""

from __future__ import annotations

import os

__all__ = ["enable_compile_cache", "default_cache_dir"]


def _machine_tag() -> str:
    """Short hash of the host CPU's feature flags.

    XLA:CPU AOT cache entries bake in the compile machine's features;
    loading them on a different host warns 'could lead to SIGILL'
    (observed when this repo's cache dir was shared across machines).
    Keying the cache dir by machine identity makes that impossible."""
    import hashlib
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha1(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform
    return hashlib.sha1(platform.processor().encode()).hexdigest()[:10]


def default_cache_dir() -> str:
    """<repo root>/.jax_cache/<machine tag> (repo root = parent of the
    cpd_tpu package)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), ".jax_cache", _machine_tag())


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax's persistent compilation cache at `cache_dir` (default:
    repo-root .jax_cache).  Best-effort: a jax without these flags just
    skips the optimization."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          cache_dir or default_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
