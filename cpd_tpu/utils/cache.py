"""Caching utilities: the persistent XLA compilation cache switch, and a
small bounded LRU mapping for host-side jit-callable caches.

Full-model train steps cost tens of seconds of XLA compile; caching them
makes driver re-runs of the bench / dryrun / test suite near-free.  Used by
bench.py, __graft_entry__.py and tests/conftest.py so the cache-dir logic
lives in exactly one place.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Callable, Hashable

__all__ = ["enable_compile_cache", "default_cache_dir", "clear_cache",
           "LRUCache"]


class LRUCache:
    """Bounded insertion/recency-ordered mapping for host-side caches of
    jitted callables (e.g. parallel/dist.py `make_sum_gradients_fn`, keyed
    by treedef).  A plain dict there grows without bound when callers keep
    presenting new pytree structures; evicting the least-recently-used
    entry just drops a compiled callable — the next call with that
    structure re-traces, which is a cost, never an error."""

    def __init__(self, maxsize: int):
        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self.maxsize = maxsize
        self._d: OrderedDict = OrderedDict()

    def get_or_create(self, key: Hashable, create: Callable[[], Any]) -> Any:
        """Return the cached value for `key`, creating (and inserting) it
        via `create()` on a miss; either way `key` becomes most-recent."""
        if key in self._d:
            self._d.move_to_end(key)
            return self._d[key]
        value = create()
        self._d[key] = value
        while len(self._d) > self.maxsize:
            self._d.popitem(last=False)
        return value

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        return key in self._d


def _cpuid(leaf: int, subleaf: int = 0) -> tuple[int, int, int, int]:
    """Raw x86 CPUID from userspace (eax, ebx, ecx, edx).

    Why not /proc/cpuinfo: this sandbox is a VM that can be snapshot-
    restored onto a different physical host mid-lifetime.  The kernel
    caches CPU capabilities at boot, so /proc/cpuinfo keeps showing the
    *boot* host's (virtualized, generic) identity — while the CPUID
    instruction reports the *current* host, which is exactly what LLVM's
    getHostCPUFeatures bakes into XLA:CPU AOT cache entries.  Observed
    failure: entries compiled with +amx/+prefer-no-scatter on host A
    crashed AllReduceThunk after a migration to host B, with identical
    /proc/cpuinfo on both."""
    import ctypes
    import mmap

    code = bytes([
        0x53,                          # push rbx (callee-saved, cpuid clobbers)
        0x89, 0xF8,                    # mov eax, edi   (leaf)
        0x89, 0xF1,                    # mov ecx, esi   (subleaf)
        0x49, 0x89, 0xD1,              # mov r9, rdx    (out ptr)
        0x0F, 0xA2,                    # cpuid
        0x41, 0x89, 0x01,              # mov [r9],    eax
        0x41, 0x89, 0x59, 0x04,        # mov [r9+4],  ebx
        0x41, 0x89, 0x49, 0x08,        # mov [r9+8],  ecx
        0x41, 0x89, 0x51, 0x0C,        # mov [r9+12], edx
        0x5B,                          # pop rbx
        0xC3,                          # ret
    ])
    buf = mmap.mmap(-1, mmap.PAGESIZE,
                    prot=mmap.PROT_READ | mmap.PROT_WRITE | mmap.PROT_EXEC)
    try:
        buf.write(code)
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        fn = ctypes.CFUNCTYPE(None, ctypes.c_uint32, ctypes.c_uint32,
                              ctypes.c_void_p)(addr)
        out = (ctypes.c_uint32 * 4)()
        fn(leaf, subleaf, ctypes.addressof(out))
        del fn
        return tuple(out)
    finally:
        buf.close()


def _machine_tag() -> str:
    """Short hash of the *current* host CPU's identity and feature leaves.

    XLA:CPU AOT cache entries bake in the compile machine's features;
    loading them on a different host crashes or SIGILLs.  Keying the
    cache dir by what CPUID reports right now (vendor, family/model/
    stepping, feature leaves 1 / 7.0 / 7.1 / 0xD.1 / ext 0x80000001 —
    the ones LLVM reads) makes a cross-host hit impossible."""
    import hashlib
    import platform

    if platform.machine() == "x86_64":
        try:
            words = []
            for leaf, sub in ((0, 0), (1, 0), (7, 0), (7, 1), (0xD, 1),
                              (0x80000001, 0)):
                a, b, c, d = _cpuid(leaf, sub)
                if leaf == 1:
                    # EBX[31:24] is the *executing core's* initial APIC ID —
                    # scheduler-dependent, so it must not enter the hash.
                    b &= 0x00FFFFFF
                words.extend((a, b, c, d))
            blob = ",".join(f"{w:08x}" for w in words)
            return hashlib.sha1(blob.encode()).hexdigest()[:10]
        except Exception:  # cpd: disable=swallow — fallback IS the handling
            pass  # W^X kernels etc. — fall through to cpuinfo
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha1(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    return hashlib.sha1(platform.processor().encode()).hexdigest()[:10]


def _cache_root() -> str:
    """<repo root>/.jax_cache (repo root = parent of the cpd_tpu package)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), ".jax_cache")


def default_cache_dir() -> str:
    return os.path.join(_cache_root(), _machine_tag())


def clear_cache() -> None:
    """Delete the current machine's compile-cache dir.

    Last-resort recovery for a supervisor whose child crashed in native
    code: if a cache entry somehow went bad, the retry recompiles clean.
    Only the *current* machine's tag dir is wiped — other tags' entries
    can never have been read by the crashed child, and keeping them
    bounds the collateral cost.  Lives here so the drivers (bench.py)
    stay in sync with the cache layout."""
    import shutil

    shutil.rmtree(default_cache_dir(), ignore_errors=True)


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax's persistent compilation cache at `cache_dir` (default:
    repo-root .jax_cache).  Best-effort: a jax without these flags just
    skips the optimization.

    TPU-only: on the CPU backend this is a no-op, because reloading
    XLA:CPU AOT executables that contain collectives is broken in this
    jaxlib — deserialized modules get conflicting rendezvous op_ids
    (observed: a collective-permute and an all-reduce joining the same
    RendezvousKey op_id=1), the rendezvous times out, and the runtime
    F-aborts the process (rc=-6).  Reproduced deterministically on warm
    re-runs of the 8-virtual-device dryrun.  A cold compile of the tiny
    dryrun models is ~70s, so CPU runs simply recompile.

    When the platform is explicitly configured as cpu (tests, dryrun) the
    check is free — no backend init.  Otherwise the *resolved* backend is
    consulted: a platform list like "axon,cpu" falls back to CPU when the
    TPU plugin fails init, and enabling the cache on that silent-fallback
    path is exactly the crash above (callers configure their platform
    before calling this, so initializing the backend here is safe)."""
    import jax

    try:
        configured = (jax.config.jax_platforms or
                      os.environ.get("JAX_PLATFORMS") or "")
        if configured.split(",")[0] == "cpu":
            return
        if jax.default_backend() == "cpu":
            return
        jax.config.update("jax_compilation_cache_dir",
                          cache_dir or default_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # cpd: disable=swallow — cache is best-effort opt-in
        pass
