"""Persistent XLA compilation cache — one shared switch.

Full-model train steps cost tens of seconds of XLA compile; caching them
makes driver re-runs of the bench / dryrun / test suite near-free.  Used by
bench.py, __graft_entry__.py and tests/conftest.py so the cache-dir logic
lives in exactly one place.
"""

from __future__ import annotations

import os

__all__ = ["enable_compile_cache", "default_cache_dir"]


def default_cache_dir() -> str:
    """<repo root>/.jax_cache (repo root = parent of the cpd_tpu package)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), ".jax_cache")


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax's persistent compilation cache at `cache_dir` (default:
    repo-root .jax_cache).  Best-effort: a jax without these flags just
    skips the optimization."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          cache_dir or default_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
