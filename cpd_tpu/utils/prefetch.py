"""Background-thread batch prefetcher — overlap input prep with device steps.

The reference leans on torch DataLoader worker processes (main.py:111-120);
the analog here is a small bounded-queue thread that runs the numpy side
(augmentation, host_batch_to_global) while the device executes the previous
step.  One thread suffices: the heavy per-pixel work is already native
(cpd_tpu/native/augment_native.cpp releases the GIL in C++), so the Python
thread mostly coordinates.

    for x, y in Prefetcher(pipe.epoch(indices, seed), depth=2):
        state, m = step(state, x, y)

Exceptions from the producer are re-raised at the consuming site; the
thread is a daemon and also shuts down cleanly on `close()` / GC / break.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator

__all__ = ["Prefetcher"]

_SENTINEL = object()


class Prefetcher:
    """Iterate `source` on a background thread, `depth` items ahead."""

    def __init__(self, source: Iterable, depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, args=(source,),
                                        daemon=True)
        self._thread.start()

    def _run(self, source):
        try:
            for item in source:
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
            self._q.put(_SENTINEL)
        except BaseException as e:  # noqa: BLE001 — deliver to consumer
            self._q.put(e)

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self):
        # Never block indefinitely: after close() the queue may stay empty
        # forever (the drain discards even the end-of-stream sentinel), so
        # a bare get() would hang the consumer.  A closed prefetcher is
        # exhausted — close() already discards in-flight items — and an
        # open one polls with a short timeout so a concurrent close()
        # still unblocks it.
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._q.get(timeout=0.05)
                break
            except queue.Empty:
                continue
        if item is _SENTINEL:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self):
        self._stop.set()
        # drain so the producer's blocked put() can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __del__(self):
        self.close()
