"""jax.profiler trace hooks — SURVEY.md §5's TPU-native equivalent of the
reference's wall-clock meters (AverageMeter windows, train_util.py:21-48;
DavidNet Timer, utils.py:28-38).

The reference only ever *times* steps; on TPU the profiler trace is
strictly more informative (per-op HLO timeline, HBM traffic, ICI
collectives) and costs nothing when off.  Every trainer exposes it as
`--profile-dir DIR`: steps [start, start+num) are wrapped in a trace whose
artifacts land under DIR (viewable in TensorBoard / Perfetto).
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["StepProfiler"]


class StepProfiler:
    """Trace a window of training steps.

    Call `step(it)` at the top of every iteration (1-based or 0-based —
    only equality with the configured window matters) and `close()` after
    the loop.  With `trace_dir=None` every call is a no-op.
    """

    def __init__(self, trace_dir: Optional[str], start: int = 2,
                 num_steps: int = 3):
        self.trace_dir = trace_dir
        self.start = start
        self.num_steps = num_steps
        self._running = False
        self._started = False

    def step(self, it: int) -> None:
        if not self.trace_dir:
            return
        import jax

        if it == self.start and not self._started:
            # `not self._started` guards the rollback replay: a loop
            # that rewinds past the window start and marches through it
            # again must not call start_trace on an already-running (or
            # already-completed) trace — jax.profiler raises on the
            # double start, killing the run the profiler was observing
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._running = True
            self._started = True
        elif self._running and it >= self.start + self.num_steps:
            jax.profiler.stop_trace()
            self._running = False

    def close(self) -> None:
        """Stop a still-open trace (loop ended inside the window); warn if
        the window never opened (run shorter than `start` steps)."""
        if self._running:
            import jax

            jax.profiler.stop_trace()
            self._running = False
        elif self.trace_dir and not self._started:
            import sys

            print(f"# profile window never opened: run ended before step "
                  f"{self.start}; no trace written to {self.trace_dir}",
                  file=sys.stderr)
