"""cpd_tpu.analysis — JAX/precision-aware static lint for this repo.

A stdlib-only (``ast``, no jax import) lint pass encoding the invariants
the Python type system cannot see but CPD's bit-faithful emulation
depends on: eXmY format bounds, collective axis-name bindings, jit and
Pallas purity/tiling rules, ordered-reduction semantics over quantized
values, and buffer-donation aliasing.  See docs/ANALYSIS.md for the rule
catalog and rationale.

Usage:

    python -m cpd_tpu.analysis cpd_tpu tests tools examples
    python -m cpd_tpu.analysis --format=json --select=format-bounds src/

Exit-code contract (stable for tooling): 0 = clean, 1 = findings,
2 = internal error (bad arguments, unreadable input, rule crash).

Suppression: append ``# cpd: disable=<rule>[,<rule>...]`` to the flagged
line (with a justification), or ``# cpd: disable-file=<rule>`` anywhere
in the file for a file-wide waiver.  ``# cpd: skip-file`` excludes a
file entirely (reserved for generated code).

The module deliberately avoids importing jax/flax/numpy so the lint
gate costs milliseconds and runs anywhere — including the minimal CI
image before heavyweight deps install.
"""

from .core import (Finding, Rule, all_rules, host_rules, lint_file,
                   lint_source, lint_tree, module_rules, program_rules,
                   project_rules, register, render_json, render_text)
from .config import Config, load_config
from .engine import AnalysisResult, run_analysis
from .project import ProjectGraph, ProjectRule
from .sarif import render_sarif

__all__ = ["Finding", "Rule", "all_rules", "module_rules",
           "project_rules", "program_rules", "host_rules", "lint_file",
           "lint_source", "lint_tree", "register", "render_json",
           "render_text", "render_sarif", "Config", "load_config",
           "AnalysisResult", "run_analysis", "ProjectGraph",
           "ProjectRule"]

# importing the rules packages registers every built-in rule; the
# program-scope (ir) and host-scope rule classes are stdlib-only too —
# jax is touched only when the --ir pass actually traces
from . import rules as _rules  # noqa: E402,F401  (registration side effect)
from .ir import rules as _ir_rules  # noqa: E402,F401  (same)
from .host import rules as _host_rules  # noqa: E402,F401  (same)
