"""SARIF 2.1.0 rendering (``--format=sarif``) so CI can annotate PRs.

One run, one tool (``cpd-lint``), one result per finding.  The shape is
the minimal valid static-analysis SARIF — ``version``, ``$schema``,
``runs[].tool.driver`` with the rule catalog, ``runs[].results[]`` with
physical locations — pinned by tests/test_analysis.py so downstream
uploaders (GitHub code-scanning, reviewdog) keep parsing it.  Paths are
emitted repo-relative (forward slashes) when a base is given, because
SARIF consumers resolve ``artifactLocation.uri`` against the checkout
root, not the runner's CWD.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

from .core import Finding, all_rules

__all__ = ["render_sarif"]

_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
           "master/Schemata/sarif-schema-2.1.0.json")


def _uri(path: str, base: Optional[str]) -> str:
    if base:
        try:
            rel = os.path.relpath(os.path.abspath(path),
                                  os.path.abspath(base))
            if not rel.startswith(".."):
                path = rel
        except ValueError:
            pass
    return path.replace(os.sep, "/")


def render_sarif(findings: Iterable[Finding],
                 base_dir: Optional[str] = None) -> str:
    rules_meta = [
        {"id": rid,
         "shortDescription": {"text": rule.summary},
         "helpUri": "docs/ANALYSIS.md"}
        for rid, rule in sorted(all_rules().items())
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(f.path, base_dir)},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                }
            }],
        }
        for f in findings
    ]
    doc = {
        "version": "2.1.0",
        "$schema": _SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": "cpd-lint",
                "informationUri":
                    "https://github.com/cpd-tpu/cpd-tpu",
                "rules": rules_meta,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True)
