"""pallas-hygiene: TPU kernel bodies and BlockSpecs, checked statically.

Three checks, all derived from /opt/skills-style Pallas TPU guidance and
the conventions ops/quantize.py + ops/qgemm.py establish:

1. **No fresh allocations in kernels.**  ``jnp.zeros((1024, 1024))``
   inside a kernel body materializes outside the BlockSpec-managed VMEM
   tiles; persistent accumulators belong in ``scratch_shapes`` and
   initialization should go through the refs (``jnp.zeros_like(ref)``
   and ``ref[...] =`` are fine and excluded).
2. **Tile-aligned block shapes.**  BlockSpec block-shape literals whose
   last dimension is not a multiple of 128 (lanes) or whose
   second-to-last is not a multiple of 8 (fp32 sublanes) force Mosaic to
   pad every block — legal but silently wasteful; leading dims of 1 are
   the standard grid-mapped form and allowed.  Module-level integer
   constants (``_LANES = 128``) are resolved before judging.
3. **Explicit memory spaces.**  A BlockSpec that declares a block shape
   but no ``memory_space`` leaves placement to defaults; this repo pins
   every spec (``pltpu.VMEM`` et al.) so kernels read as their VMEM
   budget (ops/quantize.py's 256 KiB note).

Kernel bodies are found two ways: functions passed (possibly through
``functools.partial``) as the first argument of a ``pallas_call`` in the
same module, plus the ``*_kernel`` naming convention.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import (Finding, ModuleContext, Rule, base_name, call_arg,
                    int_tuple_literal, iter_functions, register,
                    unwrap_partial)

_ALLOC_FNS = {"zeros", "ones", "full", "empty", "eye", "identity"}
_LANES = 128
_SUBLANES = 8


def _kernel_names(ctx: ModuleContext) -> set[str]:
    names: set[str] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and base_name(node.func) == "pallas_call" and node.args):
            first = node.args[0]
            part = unwrap_partial(first)
            if part is not None and part.args:
                first = part.args[0]
            if isinstance(first, ast.Name):
                names.add(first.id)
    for fn in iter_functions(ctx.tree):
        if fn.name.endswith("_kernel"):
            names.add(fn.name)
    return names


@register
class PallasHygiene(Rule):
    id = "pallas-hygiene"
    summary = ("kernels must not allocate fresh arrays; BlockSpec shapes "
               "should be (8,128)-tile aligned with explicit memory_space")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        kernels = _kernel_names(ctx)

        # (1) allocations inside kernel bodies
        for fn in iter_functions(ctx.tree):
            if fn.name not in kernels:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = base_name(node.func)
                if name in _ALLOC_FNS and node.args:
                    # zeros_like(ref) etc. have their own names and are
                    # excluded by construction; zeros(()) scalars are fine
                    shape = int_tuple_literal(node.args[0],
                                              ctx.int_constants)
                    if shape is not None and len(shape) == 0:
                        continue
                    yield ctx.finding(
                        self.id, node,
                        f"jnp.{name}(...) inside kernel {fn.name!r} "
                        f"allocates outside the BlockSpec tiles — use "
                        f"scratch_shapes (pltpu.VMEM) and initialize "
                        f"through the ref")

        # (2)+(3) BlockSpec shape/memory-space checks, module-wide
        for node in ast.walk(ctx.tree):
            if (not isinstance(node, ast.Call)
                    or base_name(node.func) != "BlockSpec"):
                continue
            shape_arg = call_arg(node, 0, "block_shape")
            if shape_arg is None:
                continue  # full-array spec: nothing to judge
            dims = int_tuple_literal(shape_arg, ctx.int_constants)
            if dims:
                last = dims[-1]
                if last is not None and last != 1 and last % _LANES:
                    yield ctx.finding(
                        self.id, shape_arg,
                        f"BlockSpec last dim {last} is not a multiple of "
                        f"{_LANES} (TPU lane count) — Mosaic pads every "
                        f"block; pick a {_LANES}-multiple")
                if len(dims) >= 2:
                    sub = dims[-2]
                    if sub is not None and sub != 1 and sub % _SUBLANES:
                        yield ctx.finding(
                            self.id, shape_arg,
                            f"BlockSpec second-to-last dim {sub} is not "
                            f"a multiple of {_SUBLANES} (fp32 sublanes) "
                            f"— pick an {_SUBLANES}-multiple")
            if call_arg(node, None, "memory_space") is None:
                yield ctx.finding(
                    self.id, node,
                    "BlockSpec declares a block shape but no "
                    "memory_space — pin it (pltpu.VMEM/SMEM/ANY) so the "
                    "kernel's VMEM budget is explicit")
