"""jit-hazards: tracing-unsafe Python inside ``@jax.jit`` bodies.

Three classes of bug that crash (or silently retrace) only when the
function is first traced:

1. Python ``if``/``while`` on a traced argument — raises
   TracerBoolConversionError at trace time; the fix is ``lax.cond`` /
   ``jnp.where`` or marking the argument static.  Shape/dtype
   inspection (``x.ndim``, ``x.shape[0]``, ``x.size``…) is static and
   allowed, including through simple local aliases (``n = x.size``).
2. Host escapes on traced values: ``np.*`` calls taking a traced arg,
   ``.item()`` / ``.tolist()``, and ``float()/int()/bool()`` coercions —
   ConcretizationTypeError at trace time.
3. ``static_argnums`` pointing at a parameter whose default is an
   unhashable literal (list/dict/set) — TypeError at the first cache
   lookup, i.e. the first CALL, possibly much later than import.

The rule analyzes functions decorated ``@jax.jit`` / ``@jit`` /
``@functools.partial(jax.jit, ...)`` — the only forms whose static
arguments are statically knowable.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import (Finding, ModuleContext, Rule, base_name, dotted_name,
                    iter_functions, jit_decoration, literal_int, register)

# attributes of a traced array that are static metadata, safe in
# Python control flow
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "sharding",
                 "weak_type", "itemsize"}

# builtins whose truthiness/branching over a traced value is fine
_SAFE_CALLS = {"isinstance", "len", "callable", "hasattr", "getattr",
               "type", "jnp.shape", "jnp.ndim", "jnp.size",
               "jnp.result_type"}

_HOST_COERCIONS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist", "numpy"}
_NUMPY_PREFIXES = ("np.", "numpy.")


def _param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


def _static_params(fn: ast.FunctionDef, jit_call: ast.Call) -> set[str]:
    """Parameter names pinned static by static_argnums/static_argnames."""
    positional = [p.arg for p in fn.args.posonlyargs] + \
                 [p.arg for p in fn.args.args]
    static: set[str] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            nums = ([literal_int(kw.value)]
                    if literal_int(kw.value) is not None else
                    [literal_int(el) for el in kw.value.elts]
                    if isinstance(kw.value, (ast.Tuple, ast.List)) else [])
            for n in nums:
                if n is not None and 0 <= n < len(positional):
                    static.add(positional[n])
        elif kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    static.add(c.value)
    return static


def _static_derived(fn: ast.FunctionDef) -> set[str]:
    """Local names assigned from static metadata of traced values:
    ``n = x.size``, ``m, k = a.shape``, ``d = x.shape[1]``,
    ``r = len(x)``."""

    def is_static_expr(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute):
            return node.attr in _STATIC_ATTRS
        if isinstance(node, ast.Subscript):
            return is_static_expr(node.value)
        if (isinstance(node, ast.Call) and base_name(node.func) == "len"):
            return True
        return False

    derived: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or not is_static_expr(node.value):
            continue
        for tgt in node.targets:
            names = (tgt.elts if isinstance(tgt, ast.Tuple) else [tgt])
            for n in names:
                if isinstance(n, ast.Name):
                    derived.add(n.id)
    return derived


def _traced_names_in_test(node: ast.AST, traced: set[str]) -> list[ast.Name]:
    """Occurrences of traced params in a branch test, skipping static
    attribute accesses and shape-inspection calls."""
    hits: list[ast.Name] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return  # x.ndim etc: static, don't descend into x
        if isinstance(n, ast.Call):
            cal = dotted_name(n.func)
            if cal in _SAFE_CALLS or base_name(n.func) in _SAFE_CALLS:
                return
        if isinstance(n, ast.Name) and n.id in traced:
            hits.append(n)
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return hits


@register
class JitHazards(Rule):
    id = "jit-hazards"
    summary = ("no Python branching, numpy/host calls, or unhashable "
               "static defaults on traced values inside @jax.jit bodies")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in iter_functions(ctx.tree):
            jit_call = jit_decoration(fn)
            if jit_call is None:
                continue
            static = _static_params(fn, jit_call)
            traced = {p for p in _param_names(fn) if p not in static}
            traced -= {"self", "cls"}
            traced -= _static_derived(fn)

            # (3) unhashable static defaults
            positional = [p.arg for p in fn.args.posonlyargs] + \
                         [p.arg for p in fn.args.args]
            defaults = fn.args.defaults
            defaulted = positional[len(positional) - len(defaults):]
            for pname, dflt in zip(defaulted, defaults):
                if pname in static and isinstance(
                        dflt, (ast.List, ast.Dict, ast.Set)):
                    yield ctx.finding(
                        self.id, dflt,
                        f"static argument {pname!r} has an unhashable "
                        f"{type(dflt).__name__.lower()} default — jit's "
                        f"cache lookup raises TypeError at first call")

            for node in ast.walk(fn):
                # (1) control flow on traced values
                if isinstance(node, (ast.If, ast.While)):
                    for hit in _traced_names_in_test(node.test, traced):
                        kind = ("while" if isinstance(node, ast.While)
                                else "if")
                        yield ctx.finding(
                            self.id, hit,
                            f"Python `{kind}` on traced argument "
                            f"{hit.id!r} raises at trace time — use "
                            f"lax.cond/jnp.where, or mark it static")
                # (2) host escapes
                elif isinstance(node, ast.Call):
                    cal = dotted_name(node.func)
                    if cal.startswith(_NUMPY_PREFIXES):
                        if any(isinstance(sub, ast.Name) and sub.id in traced
                               for arg in node.args
                               for sub in ast.walk(arg)):
                            yield ctx.finding(
                                self.id, node,
                                f"host numpy call `{cal}` consumes a "
                                f"traced value inside jit — use jnp")
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _HOST_METHODS
                          and isinstance(node.func.value, ast.Name)
                          and node.func.value.id in traced):
                        yield ctx.finding(
                            self.id, node,
                            f".{node.func.attr}() on traced argument "
                            f"{node.func.value.id!r} forces a host "
                            f"transfer — ConcretizationTypeError under "
                            f"jit")
                    elif (isinstance(node.func, ast.Name)
                          and node.func.id in _HOST_COERCIONS
                          and len(node.args) == 1
                          and isinstance(node.args[0], ast.Name)
                          and node.args[0].id in traced):
                        yield ctx.finding(
                            self.id, node,
                            f"{node.func.id}() coercion of traced "
                            f"argument {node.args[0].id!r} — "
                            f"ConcretizationTypeError under jit")
