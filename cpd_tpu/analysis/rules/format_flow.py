"""format-flow: whole-program eXmY format consistency.

`format-bounds` (PR 1) checks each call site against the legal eXmY
ranges — per file.  Every real incident since crossed a file boundary:
a ladder string built in a trainer CLI dies three calls later inside
``pack_exmy`` (the man<2 rung PR 5 review caught at argument time), a
helper forwards ``(exp, man)`` swapped into a ``(man, exp)`` API, a
packer and its unpacker drift to different declared widths.  This rule
runs those checks over the project graph (analysis/project.py):

1. **ladder → ring**: a ladder rung list (a literal ``"e5m2,e4m1"``
   string or tuple of ``(exp, man)`` pairs) that flows into a function
   from which a ring sink is reachable through the call graph — a call
   with ``mode="ring"``, a ``ring_quantized_sum`` call, or a
   ``pack_exmy`` call — must have ``man >= 2`` on every rung: the wire
   codec rejects man<2 formats, so the first escalation onto that rung
   dies mid-jit, hours in.  Calls inside ``pytest.raises`` blocks are
   skipped (tests that PROVE the rejection are not bugs).
2. **component swap**: at any known format API, passing a man-named
   variable into the exp slot (or vice versa) across a call boundary —
   both-in-range swaps that format-bounds cannot see.
3. **pack/unpack width drift**: an ``unpack_exmy`` /
   ``unpack_exmy_blocked`` whose payload traces (locally or through a
   returning callee) to a packer with a DIFFERENT resolved
   ``(exp, man)`` — the decoded words are garbage, bitwise-silently.
   Block-scaled payloads carry a third lattice coordinate
   (``("packed", fmt, block)``, analysis/project.py): a blocked wire
   into the per-tensor unpacker (or vice versa), and a matched-format
   pack/unpack pair whose BLOCK sizes differ, are findings too — the
   sidecar scale lane re-slices at wrong boundaries and every element
   unscales by a wrong 2^k.
"""

from __future__ import annotations

import re
from typing import Iterator, Optional

from ..core import Finding, Rule, register
from ..project import ProjectGraph, ProjectRule, TOP

_FMT_TOKEN = re.compile(r"^e(\d+)m(\d+)$")

# slots (positional index, keyword) per API with (exp, man) semantics —
# mirrors format_bounds._APIS positions for the swap check
_SWAP_APIS = {
    "cast_to_format": ((1, "exp_bits"), (2, "man_bits")),
    "cast_to_format_sr": ((1, "exp_bits"), (2, "man_bits")),
    "cast_to_format_sr_at": ((1, "exp_bits"), (2, "man_bits")),
    "cast_body": ((1, "exp_bits"), (2, "man_bits")),
    "cast_oracle": ((1, "exp_bits"), (2, "man_bits")),
    "quantize_pallas": ((1, "exp_bits"), (2, "man_bits")),
    "float_quantize": ((1, "exp"), (2, "man")),
    "ordered_quantized_sum": ((1, "exp"), (2, "man")),
    "kahan_quantized_sum": ((1, "exp"), (2, "man")),
    "quantized_sum": ((1, "exp"), (2, "man")),
    "ring_quantized_sum": ((2, "exp"), (3, "man")),
    "pack_exmy": ((1, "exp_bits"), (2, "man_bits")),
    "unpack_exmy": ((1, "exp_bits"), (2, "man_bits")),
    "pack_exmy_blocked": ((1, "exp_bits"), (2, "man_bits")),
    "unpack_exmy_blocked": ((1, "exp_bits"), (2, "man_bits")),
    "cast_to_format_blocked": ((1, "exp_bits"), (2, "man_bits")),
    "cast_body_blocked": ((1, "exp_bits"), (2, "man_bits")),
    # NOTE quant_gemm's real signature is (x, w, man, exp) — the swap
    # check must use ITS order, not assume (exp, man).  The entry stays
    # only for the back-compat shim; `qgemm` (ISSUE 15) is the
    # (exp, man)-consistent spelling in-repo call sites migrated to.
    "quant_gemm": ((3, "exp"), (2, "man")),
    "qgemm": ((2, "exp"), (3, "man")),
    "qgemm_stats": ((2, "exp"), (3, "man")),
}

_EXP_NAMES = re.compile(r"(^|_)exp(_bits)?$")
_MAN_NAMES = re.compile(r"(^|_)man(_bits)?$")


def _looks_exp(name: str) -> bool:
    return bool(_EXP_NAMES.search(name))


def _looks_man(name: str) -> bool:
    return bool(_MAN_NAMES.search(name))


def parse_ladder_value(value) -> Optional[list]:
    """Rungs [(exp, man), ...] from a concrete lattice value: an eXmY
    spec string ("e5m2,e4m1") or a tuple of 2-int tuples; None when the
    value is not ladder-shaped."""
    if isinstance(value, str):
        rungs = []
        for part in value.split(","):
            m = _FMT_TOKEN.match(part.strip().lower())
            if not m:
                return None
            rungs.append((int(m.group(1)), int(m.group(2))))
        return rungs if rungs else None
    if isinstance(value, tuple) and value and all(
            isinstance(r, tuple) and len(r) == 2
            and all(isinstance(c, int) for c in r) for r in value):
        return list(value)
    return None


def _aval_name(av: dict) -> str:
    """Variable name behind a param/name aval ('' otherwise)."""
    if av.get("k") in ("param", "name"):
        return av["v"]
    if av.get("k") == "attr" and av["v"]:
        return av["v"][-1]
    return ""


@register
class FormatFlow(ProjectRule):
    id = "format-flow"
    summary = ("whole-program eXmY flow: man<2 ladder rungs reaching the "
               "ring wire, (exp, man) swaps across calls, pack/unpack "
               "width drift")

    def check(self, project: ProjectGraph) -> Iterator[Finding]:
        yield from self._ladders(project)
        yield from self._swaps(project)
        yield from self._pack_drift(project)

    # -- 1. ladder rungs reaching a ring sink -----------------------------

    def _ladders(self, project: ProjectGraph) -> Iterator[Finding]:
        for fkey, f, mod in project.iter_functions():
            for call in f["calls"]:
                if call["raises_ctx"]:
                    continue   # asserting the rejection, not hitting it
                ladder_av = call["kw"].get("ladder")
                base = call["callee"].rsplit(".", 1)[-1]
                if ladder_av is None and base in ("parse_ladder",
                                                  "PrecisionSupervisor"):
                    if call["args"]:
                        ladder_av = call["args"][0]
                if ladder_av is None:
                    continue
                values = project.eval_in(fkey, ladder_av)
                if values is TOP:
                    continue
                target = project.resolve(fkey[0], call["callee"])
                ring_line = None
                # the ladder's consumer (or, for unresolvable callees,
                # this function itself) must reach a ring sink; THIS
                # call's own argument bindings override the consumer's
                # joined parameter env (one level of context
                # sensitivity — see ring_reaching)
                if target is not None:
                    bindings = {}
                    tf = project.funcs[target]
                    if not call["star"]:
                        for pname, pav in zip(tf["params"], call["args"]):
                            vs = project.eval_in(fkey, pav)
                            if vs is not TOP:
                                bindings[pname] = vs
                        for kname, kav in call["kw"].items():
                            if kname in tf["params"] \
                                    or kname in tf["kwonly"]:
                                vs = project.eval_in(fkey, kav)
                                if vs is not TOP:
                                    bindings[kname] = vs
                    ring_line = project.ring_reaching(
                        target, root_bindings=bindings or None)
                else:
                    # unresolvable consumer (e.g. PrecisionSupervisor
                    # from outside the analyzed set): a ring-mode kwarg
                    # on the SAME call, or a ring sink reachable from
                    # the constructing function, condemns the ladder
                    mode = call["kw"].get("mode")
                    if mode is not None:
                        mv = project.eval_in(fkey, mode)
                        if mv is not TOP and "ring" in mv:
                            ring_line = call["line"]
                    if ring_line is None:
                        ring_line = project.ring_reaching(fkey)
                if ring_line is None:
                    continue
                for value in values:
                    rungs = parse_ladder_value(value)
                    if not rungs:
                        continue
                    bad = [r for r in rungs if r[1] < 2]
                    for exp, man in bad:
                        yield Finding(
                            path=mod["path"], line=call["line"],
                            col=call["col"], rule=self.id,
                            message=(
                                f"ladder rung e{exp}m{man} (man < 2) can "
                                f"reach the ring transport through this "
                                f"call — pack_exmy rejects man<2 formats, "
                                f"so the first escalation onto that rung "
                                f"dies mid-jit (ring sink reachable via "
                                f"the call graph)"))

    # -- 2. (exp, man) component swaps ------------------------------------

    def _swaps(self, project: ProjectGraph) -> Iterator[Finding]:
        for fkey, f, mod in project.iter_functions():
            for call in f["calls"]:
                base = call["callee"].rsplit(".", 1)[-1]
                spec = _SWAP_APIS.get(base)
                if spec is None or call["star"]:
                    continue
                (epos, ekw), (mpos, mkw) = spec

                def slot(pos, kw):
                    if kw in call["kw"]:
                        return call["kw"][kw]
                    if pos is not None and pos < len(call["args"]):
                        return call["args"][pos]
                    return None

                e_name = _aval_name(slot(epos, ekw) or {})
                m_name = _aval_name(slot(mpos, mkw) or {})
                e_crossed = bool(e_name) and _looks_man(e_name) \
                    and not _looks_exp(e_name)
                m_crossed = bool(m_name) and _looks_exp(m_name) \
                    and not _looks_man(m_name)
                if e_crossed or m_crossed:
                    got = []
                    if e_crossed:
                        got.append(f"exp slot receives {e_name!r}")
                    if m_crossed:
                        got.append(f"man slot receives {m_name!r}")
                    yield Finding(
                        path=mod["path"], line=call["line"],
                        col=call["col"], rule=self.id,
                        message=(
                            f"{base}: (exp, man) components look swapped "
                            f"across the call boundary — {'; '.join(got)} "
                            f"(both values can be in-range, so "
                            f"format-bounds cannot catch this; the cast "
                            f"silently runs at the wrong format)"))

    # -- 3. pack/unpack width drift ---------------------------------------

    def _fmt_of_call(self, project, fkey, av) -> Optional[tuple]:
        """(exp, man) of a pack/unpack-style call aval when concrete."""
        if av.get("k") != "call" or len(av.get("args", [])) < 3:
            return None
        e = project.eval_in(fkey, av["args"][1])
        m = project.eval_in(fkey, av["args"][2])
        if e is TOP or m is TOP or len(e) != 1 or len(m) != 1:
            return None
        ev, mv = next(iter(e)), next(iter(m))
        if isinstance(ev, int) and isinstance(mv, int):
            return (ev, mv)
        return None

    def _block_of_call(self, project, fkey, call) -> Optional[int]:
        """Concrete block_size of an unpack_exmy_blocked call site
        (positional slot 4, after (packed, exp, man, n))."""
        av = (call["args"][4] if len(call["args"]) >= 5
              else call["kw"].get("block_size"))
        if av is None:
            return None
        b = project.eval_in(fkey, av)
        if b is TOP or len(b) != 1:
            return None
        bv = next(iter(b))
        return bv if isinstance(bv, int) else None

    def _pack_drift(self, project: ProjectGraph) -> Iterator[Finding]:
        for fkey, f, mod in project.iter_functions():
            for call in f["calls"]:
                base = call["callee"].rsplit(".", 1)[-1]
                if base not in ("unpack_exmy", "unpack_exmy_blocked") \
                        or call["star"]:
                    continue
                fake = {"k": "call", "f": call["callee"],
                        "args": call["args"], "kw": call["kw"]}
                unpack_fmt = self._fmt_of_call(project, fkey, fake)
                if unpack_fmt is None or not call["args"]:
                    continue
                blocked_call = base == "unpack_exmy_blocked"
                unpack_blk = (self._block_of_call(project, fkey, call)
                              if blocked_call else None)
                payload = call["args"][0]
                sources = project.eval_in(fkey, payload)
                if sources is TOP:
                    continue
                for src in sources:
                    if not (isinstance(src, tuple) and len(src) >= 2
                            and src[0] == "packed"):
                        continue
                    src_blk = src[2] if len(src) == 3 else None
                    ue, um = unpack_fmt
                    if src[1] != unpack_fmt:
                        pe, pm = src[1]
                        yield Finding(
                            path=mod["path"], line=call["line"],
                            col=call["col"], rule=self.id,
                            message=(
                                f"{base} declares e{ue}m{um} but the "
                                f"payload was packed as e{pe}m{pm} — the "
                                f"decoded values are silently garbage "
                                f"(wire words re-sliced at the wrong "
                                f"width)"))
                    elif blocked_call and src_blk is None:
                        yield Finding(
                            path=mod["path"], line=call["line"],
                            col=call["col"], rule=self.id,
                            message=(
                                f"unpack_exmy_blocked on a PER-TENSOR "
                                f"pack_exmy payload — the wire has no "
                                f"sidecar lane, so the unpacker reads "
                                f"the last code bytes as scale shifts "
                                f"(use pack_exmy_blocked, or "
                                f"unpack_exmy)"))
                    elif not blocked_call and src_blk is not None:
                        yield Finding(
                            path=mod["path"], line=call["line"],
                            col=call["col"], rule=self.id,
                            message=(
                                f"unpack_exmy on a BLOCK-SCALED "
                                f"pack_exmy_blocked payload (block "
                                f"{src_blk}) — the sidecar scale lane "
                                f"is decoded as code words and every "
                                f"block's 2^k scale is dropped (use "
                                f"unpack_exmy_blocked)"))
                    elif (blocked_call and unpack_blk is not None
                          and src_blk != unpack_blk):
                        yield Finding(
                            path=mod["path"], line=call["line"],
                            col=call["col"], rule=self.id,
                            message=(
                                f"unpack_exmy_blocked declares block "
                                f"size {unpack_blk} but the payload was "
                                f"packed with block {src_blk} — the "
                                f"sidecar lane re-slices at the wrong "
                                f"block boundaries and every element "
                                f"unscales by a wrong 2^k, bitwise-"
                                f"silently"))
