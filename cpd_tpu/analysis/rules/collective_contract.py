"""collective-contract: ppermute permutations must be bijections; Kahan
compensation must ride every wire the partial sum rides.

Two contracts XLA never checks and trace time cannot:

* **bijection** — ``lax.ppermute`` takes ``(source, dest)`` pairs.  A
  repeated source silently DROPS one payload; a repeated destination
  makes the received value backend-order dependent; a stride that
  shares a factor with the axis size collides ranks for even worlds.
  The ring transport's entire correctness story (parallel/ring.py's
  documented per-chunk rotation) assumes the hop permutation is exactly
  the cyclic bijection.  Literal perm lists and
  ``[(f(i), g(i)) for i in range(w)]`` comprehensions are classified at
  extraction (analysis/project.py `_perm_violation`); anything
  unresolvable stays silent.

* **Kahan-on-the-wire** — a Kahan-compensated partial is a PAIR
  ``(res, comp)``: the next hop's casts need the compensation term, or
  the scheme silently degrades to plain quantized accumulation (the
  error the +2x wire cost exists to remove — ring.py ships both values
  in the reduce-scatter phase for exactly this reason).  In any scope
  that unpacks ``res, comp = <kahan-producing call>`` (callee named
  *kahan*, or transitively calling one — resolved through the project
  graph), a ``ppermute``/``all_gather`` payload whose name closure
  (traced through local assignments) contains ``res`` but NOT ``comp``
  is a finding.
"""

from __future__ import annotations

from typing import Iterator

from ..core import Finding, register
from ..project import ProjectGraph, ProjectRule


@register
class CollectiveContract(ProjectRule):
    id = "collective-contract"
    summary = ("ppermute permutations must be bijections; Kahan "
               "compensation must ride every wire the partial rides")

    def check(self, project: ProjectGraph) -> Iterator[Finding]:
        for fkey, f, mod in project.iter_functions():
            for pf in f["perm_findings"]:
                yield Finding(
                    path=mod["path"], line=pf["line"], col=pf["col"],
                    rule=self.id, message="ppermute: " + pf["msg"])
            yield from self._kahan_wire(project, fkey, f, mod)

    def _kahan_wire(self, project, fkey, f, mod) -> Iterator[Finding]:
        if not f["kahan_unpacks"] or not f["wire_payloads"]:
            return
        pairs = [(u["res"], u["comp"]) for u in f["kahan_unpacks"]
                 if project.kahan_producing(fkey[0], u["callee"])]
        if not pairs:
            return
        for wp in f["wire_payloads"]:
            names = set(wp["names"])
            for res, comp in pairs:
                if res in names and comp not in names:
                    yield Finding(
                        path=mod["path"], line=wp["line"], col=wp["col"],
                        rule=self.id,
                        message=(
                            f"{wp['collective']}: payload carries the "
                            f"Kahan partial {res!r} but not its "
                            f"compensation {comp!r} — the next hop's "
                            f"casts lose the compensated bits and the "
                            f"scheme silently degrades to plain "
                            f"quantized accumulation (ring.py ships "
                            f"both: `jnp.stack([res, comp])`)"))
                    break
