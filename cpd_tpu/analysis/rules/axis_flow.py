"""axis-flow: collective axis literals must be reachable from a mesh
that binds them — whole-program.

The module-local `axis-name` rule exempts every module that declares no
mesh ("library code takes axis_name as a parameter") — a blanket hole:
a library function that HARDCODES an axis string is exactly the case
that rule exists for, and it hides in the exemption.  This rule kills
the hole: for each collective call with a literal axis in a
**no-mesh module**, the literal must be bound by at least one mesh
constructor in SOME module that reaches this function through the call
graph (transitive callers; bare-name references like
``shard_map(step, ...)`` count as edges).  A literal no mesh anywhere
can justify is a finding — it can only ever trace against somebody
else's axis names.

Modules that DO declare axes stay `axis-name`'s territory (module-local
check, no double report).  Test modules additionally inherit the axes of
any ``conftest.py`` above them — pytest wires those fixtures in without
a visible call edge.
"""

from __future__ import annotations

import os
from typing import Iterator

from ..core import Finding, register
from ..project import ProjectGraph, ProjectRule


def _conftest_axes(project: ProjectGraph, path: str) -> set:
    """Axes declared by conftest.py files in ancestor directories of
    `path` (pytest's implicit reach)."""
    axes: set = set()
    d = os.path.dirname(os.path.abspath(path))
    for s in project.modules.values():
        if os.path.basename(s["path"]) == "conftest.py":
            cdir = os.path.dirname(os.path.abspath(s["path"]))
            if d == cdir or d.startswith(cdir + os.sep):
                axes.update(s["declared_axes"])
    return axes


@register
class AxisFlow(ProjectRule):
    id = "axis-flow"
    summary = ("collective axis literals in library (no-mesh) modules "
               "must be bound by a mesh that reaches them through the "
               "call graph")

    def check(self, project: ProjectGraph) -> Iterator[Finding]:
        for fkey, f, mod in project.iter_functions():
            if mod["declared_axes"]:
                continue          # axis-name's (module-local) territory
            if not f["axis_literals"]:
                continue
            callers = len(project.callers(fkey))
            if not callers:
                # no caller in the ANALYZED SET: the binding driver may
                # simply be outside it (--changed-only lints one file at
                # a time) — degrade to silence, never to guesses.  The
                # full-tree gate, where every live function has test/CLI
                # callers, is where absence of a mesh becomes a verdict.
                continue
            reachable = project.reachable_axes(fkey)
            reachable |= _conftest_axes(project, mod["path"])
            for lit in f["axis_literals"]:
                if lit["axis"] in reachable:
                    continue
                via = f"{callers} transitive caller(s) checked"
                yield Finding(
                    path=mod["path"], line=lit["line"], col=lit["col"],
                    rule=self.id,
                    message=(
                        f"{lit['collective']}: axis {lit['axis']!r} is "
                        f"not bound by any mesh constructor that reaches "
                        f"this function through the call graph ({via}"
                        f"{'; reachable axes: ' + str(sorted(reachable)) if reachable else ''}) "
                        f"— the literal can only trace against someone "
                        f"else's axis names"))
