"""swallow: silently-swallowed exceptions outside the resilience package.

A resilient system's retries are EXPLICIT — counted, logged, bounded
(cpd_tpu/resilience/loop.py).  A ``bare except`` or an
``except Exception: pass`` is the opposite: it converts every failure,
including the injected ones the chaos tests rely on, into silence.  The
classic incident shape: a swallowed checkpoint-write error turns a
recoverable preemption into a run that resumes from a stale step.

Flagged shapes:

    try: ...
    except: ...                      # bare: catches SystemExit too

    except Exception: pass           # (or BaseException, or a tuple
    except Exception: ...            #  containing either) with a body
                                     #  that only passes/continues

A broad handler whose body DOES something (logs, re-raises, returns a
fallback, counts the failure) is fine — breadth is sometimes right at
top-level entry points; silence never is.  Files under ``resilience/``
are exempt — that package is the sanctioned home of failure handling,
and its handlers are themselves exercised by fault injection — but the
carve-out lives in CONFIG (the ``[tool.cpd-lint] exempt`` table /
analysis/config.py defaults), not in this rule: path policy is the
project's to own, review and override.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, ModuleContext, Rule, dotted_name, register

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node: ast.AST) -> bool:
    """True for Exception/BaseException, bare or inside a tuple."""
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(el) for el in type_node.elts)
    name = dotted_name(type_node)
    return name.rsplit(".", 1)[-1] in _BROAD


def _swallows(handler: ast.ExceptHandler) -> bool:
    """Body does nothing with the failure: only pass/.../continue."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Continue):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return True


@register
class Swallow(Rule):
    id = "swallow"
    summary = ("bare except / silently-passed broad except — failure "
               "handling must be explicit (resilience/ carve-out lives "
               "in [tool.cpd-lint] config)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield ctx.finding(
                    self.id, node,
                    "bare `except:` catches everything (SystemExit, "
                    "KeyboardInterrupt, injected preemptions) — name "
                    "the exception, or route recovery through "
                    "cpd_tpu.resilience")
            elif _is_broad(node.type) and _swallows(node):
                yield ctx.finding(
                    self.id, node,
                    "broad except with a pass-only body swallows the "
                    "failure — count it, log it, or re-raise (retries "
                    "must be explicit; see resilience/loop.py)")
