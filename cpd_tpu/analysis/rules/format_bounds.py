"""format-bounds: eXmY format literals must be representable.

The whole stack funnels through ``quant/numerics.py:_validate`` —
``exp_bits in [1, 8]``, ``man_bits in [0, 23]`` — but that check fires at
TRACE time, which for a 90-epoch run config can be hours into a job (or
never, when the bad call sits on a rarely-taken branch).  This rule moves
the check to lint time for every call site that passes literal ints.

Second check: a numeric constant passed as the DATA argument of a cast
whose literal format cannot represent it (|x| > max finite) silently
saturates to ±Inf under the reference semantics (pre-rounding exponent
overflow, numerics.py docstring) — almost always a wrong-format bug, not
an intended Inf.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import (Finding, ModuleContext, Rule, base_name, call_arg,
                    literal_float, literal_int, register)

# API name -> ((exp pos, exp kw), (man pos, man kw), data positions)
# Positions mirror the real signatures; note quant_gemm's (man, exp)
# order and quantizer's two format pairs.
_APIS: dict[str, list[tuple[tuple[Optional[int], Optional[str]],
                            tuple[Optional[int], Optional[str]],
                            tuple[int, ...]]]] = {
    "cast_to_format":      [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "cast_to_format_sr":   [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "cast_to_format_sr_at": [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "cast_body":           [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "cast_body_sr":        [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "cast_oracle":         [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "cast_oracle_sr":      [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "quantize_pallas":     [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "quantize_pallas_sr":  [((1, "exp_bits"), (2, "man_bits"), (0,))],
    "qgemm_pallas":        [((2, "exp_bits"), (3, "man_bits"), (0, 1))],
    "max_finite":          [((0, "exp_bits"), (1, "man_bits"), ())],
    "float_quantize":      [((1, "exp"), (2, "man"), (0,))],
    "quant_gemm":          [((3, "exp"), (2, "man"), (0, 1))],
    "qgemm":               [((2, "exp"), (3, "man"), (0, 1))],
    "qgemm_stats":         [((2, "exp"), (3, "man"), (0, 1))],
    "ordered_quantized_sum": [((1, "exp"), (2, "man"), (0,))],
    "kahan_quantized_sum": [((1, "exp"), (2, "man"), (0,))],
    "quantized_sum":       [((1, "exp"), (2, "man"), (0,))],
    "sum_gradients":       [((3, "grad_exp"), (4, "grad_man"), ())],
    "emulate_node_reduce": [((3, "grad_exp"), (4, "grad_man"), ())],
    "make_sum_gradients_fn": [((None, "grad_exp"), (None, "grad_man"), ())],
    "quantizer":           [((0, "forward_exp"), (1, "forward_man"), ()),
                            ((2, "backward_exp"), (3, "backward_man"), ())],
    "quantizer_sr":        [((0, "forward_exp"), (1, "forward_man"), ()),
                            ((2, "backward_exp"), (3, "backward_man"), ())],
}

# Keyword names that carry an eXmY component on ANY call (quant modules,
# train-step builders, configs all reuse this vocabulary).
_GENERIC_KW = {
    "exp_bits": "exp", "grad_exp": "exp", "forward_exp": "exp",
    "backward_exp": "exp", "act_exp": "exp", "weight_exp": "exp",
    "man_bits": "man", "grad_man": "man", "forward_man": "man",
    "backward_man": "man", "act_man": "man", "weight_man": "man",
}

_EXP_RANGE = (1, 8)
_MAN_RANGE = (0, 23)


def _max_finite(exp_bits: int, man_bits: int) -> float:
    """Largest normal value of the format (same formula as
    quant/numerics.py max_finite, restated here so the linter never
    imports jax)."""
    bias = (1 << (exp_bits - 1)) - 1
    e_max = ((1 << exp_bits) - 2) - bias
    return (2.0 - 2.0 ** (-man_bits)) * (2.0 ** e_max)


def _check_component(value: Optional[int], kind: str):
    """Return an error string for an out-of-range literal, else None."""
    if value is None:
        return None
    lo, hi = _EXP_RANGE if kind == "exp" else _MAN_RANGE
    if not (lo <= value <= hi):
        what = "exp_bits" if kind == "exp" else "man_bits"
        return (f"{what}={value} outside the legal eXmY range "
                f"[{lo}, {hi}] (quant/numerics.py _validate would raise "
                f"at trace time)")
    return None


@register
class FormatBounds(Rule):
    id = "format-bounds"
    summary = ("literal eXmY components must satisfy exp in [1,8] / man "
               "in [0,23]; literal operands must fit the declared format")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = base_name(node.func)
            specs = _APIS.get(name)
            if specs is not None:
                for (epos, ekw), (mpos, mkw), data_pos in specs:
                    e_arg = call_arg(node, epos, ekw)
                    m_arg = call_arg(node, mpos, mkw)
                    exp = literal_int(e_arg) if e_arg is not None else None
                    man = literal_int(m_arg) if m_arg is not None else None
                    for val, kind, anchor in ((exp, "exp", e_arg),
                                              (man, "man", m_arg)):
                        msg = _check_component(val, kind)
                        if msg:
                            yield ctx.finding(self.id, anchor or node,
                                              f"{name}: {msg}")
                    # representability of literal data in a fully-literal,
                    # in-range format
                    if (exp is not None and man is not None
                            and _check_component(exp, "exp") is None
                            and _check_component(man, "man") is None):
                        limit = _max_finite(exp, man)
                        for dp in data_pos:
                            d_arg = call_arg(node, dp, None)
                            if d_arg is None:
                                continue
                            v = literal_float(d_arg)
                            if v is not None and abs(v) > limit:
                                yield ctx.finding(
                                    self.id, d_arg,
                                    f"{name}: constant {v!r} exceeds "
                                    f"e{exp}m{man}'s max finite value "
                                    f"{limit!r} — the cast saturates to "
                                    f"±Inf (pre-rounding overflow, "
                                    f"quant/numerics.py)")
            else:
                # unknown callee: still police the shared kwarg vocabulary
                for kw in node.keywords:
                    kind = _GENERIC_KW.get(kw.arg or "")
                    if kind is None:
                        continue
                    msg = _check_component(literal_int(kw.value), kind)
                    if msg:
                        yield ctx.finding(self.id, kw.value,
                                          f"{name or 'call'}: {msg}")
