"""donation: a donated buffer must not be read after the donating call.

``jax.jit(..., donate_argnums=...)`` hands the argument's buffer to XLA
for reuse; touching the Python handle afterwards raises (on strict
backends) or silently reads garbage (on others — the worse outcome).
The one legitimate idiom is rebinding the result over the donated name
(``state = step(state, batch)``), which this rule recognizes and allows
— including tuple unpacking (``state, metrics = step(state, ...)``) and
the same pattern inside loops.

Tracked donors (module-local, literal donate_argnums only):

* ``@functools.partial(jax.jit, donate_argnums=(0,))`` decorated defs;
* names bound to ``jax.jit(fn, donate_argnums=...)`` assignments.

A use is flagged when the donated argument is a plain name read later in
the same scope with no intervening rebind.  Ordering is by line number —
an approximation of control flow that is cheap, predictable, and right
for the straight-line train-loop code this repo writes.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import (Finding, ModuleContext, Rule, dotted_name,
                    iter_functions, jit_decoration, literal_int, register,
                    walk_scope)


def _donated_indices(jit_call: ast.Call) -> set[int]:
    out: set[int] = set()
    for kw in jit_call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = literal_int(kw.value)
        if v is not None:
            out.add(v)
        elif isinstance(kw.value, (ast.Tuple, ast.List)):
            for el in kw.value.elts:
                v = literal_int(el)
                if v is not None:
                    out.add(v)
    return out


def _donors(ctx: ModuleContext) -> dict[str, set[int]]:
    """callable name -> donated positional indices."""
    donors: dict[str, set[int]] = {}
    for fn in iter_functions(ctx.tree):
        jit_call = jit_decoration(fn)
        if jit_call is not None:
            idx = _donated_indices(jit_call)
            if idx:
                donors[fn.name] = idx
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and dotted_name(node.value.func) in ("jax.jit", "jit")):
            idx = _donated_indices(node.value)
            if idx:
                donors[node.targets[0].id] = idx
    return donors


def _rebinds_same_name(parents: dict, call: ast.Call, name: str) -> bool:
    """True when the donating call's own assignment rebinds ``name``
    (the ``state = step(state, ...)`` idiom, tuple targets included)."""
    node = call
    while node in parents:
        node = parents[node]
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                els = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                if any(isinstance(e, ast.Name) and e.id == name
                       for e in els):
                    return True
            return False
        if isinstance(node, (ast.stmt,)):
            return False
    return False


@register
class Donation(Rule):
    id = "donation"
    summary = ("a buffer passed at a donate_argnums position is dead "
               "after the call unless rebound from its result")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        donors = _donors(ctx)
        if not donors:
            return
        for scope in [ctx.tree, *iter_functions(ctx.tree)]:
            nodes = list(walk_scope(scope))
            parents: dict = {}
            for n in nodes:
                for child in ast.iter_child_nodes(n):
                    parents.setdefault(child, n)
            stores = [(n.lineno, n.id) for n in nodes
                      if isinstance(n, ast.Name)
                      and isinstance(n.ctx, (ast.Store, ast.Del))]
            loads = [n for n in nodes
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Load)]

            events: list[tuple[str, int]] = []
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                fname = (node.func.id
                         if isinstance(node.func, ast.Name) else None)
                idxs = donors.get(fname or "")
                if not idxs:
                    continue
                for i in sorted(idxs):
                    if i < len(node.args) and isinstance(
                            node.args[i], ast.Name):
                        name = node.args[i].id
                        if not _rebinds_same_name(parents, node, name):
                            events.append((name, node.lineno))

            for name, line in events:
                for load in sorted(loads, key=lambda n: n.lineno):
                    if load.lineno <= line or load.id != name:
                        continue
                    if any(s_name == name and line < s_line <= load.lineno
                           for s_line, s_name in stores):
                        break  # rebound first: later reads are fine
                    yield ctx.finding(
                        self.id, load,
                        f"{name!r} was donated at line {line} "
                        f"(donate_argnums) — its buffer may already be "
                        f"reused; read the call's result instead, or "
                        f"drop donation")
                    break
