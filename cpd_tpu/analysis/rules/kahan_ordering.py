"""kahan-ordering: unordered reductions over quantized values.

The reduction order of ``jnp.sum`` / ``lax.psum`` is XLA's to choose —
the exact property the faithful pipeline exists to remove
(parallel/reduction.py's docstring: order *is* the semantics being
emulated; qgemm.py: a property psum cannot give).  Summing values that
just went through an eXmY cast with an unordered reduction therefore
silently reintroduces tree-order nondeterminism: results change across
backends, topologies, and XLA versions, which is an accuracy bug in an
emulator whose claim is bit-faithfulness.

Detected shapes (function-scope dataflow, one level deep):

    q = cast_to_format(x, 5, 2);  jnp.sum(q)          # direct
    jnp.sum(float_quantize(x, 5, 2))                   # nested
    g = quantize_tree_sr(g, e, m, k)
    jax.tree.map(lambda v: lax.psum(v, ax), g)         # tree.map'd

Fix: route through ``parallel.reduction.quantized_sum`` (ordered scan,
optionally Kahan) or ``ops.qgemm_pallas`` for dots — or suppress with a
justification where XLA-order reduction is the documented intent (the
``mode="fast"`` deployment path).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import (Finding, ModuleContext, Rule, base_name, dotted_name,
                    iter_functions, register, walk_scope)

_PRODUCERS = {"cast_to_format", "cast_to_format_sr", "cast_to_format_sr_at",
              "cast_body", "cast_body_sr", "float_quantize",
              "quantize_pallas", "quantize_pallas_sr", "quantize_tree_sr"}

_UNORDERED = {"jnp.sum", "jnp.mean", "jnp.nansum", "np.sum",
              "jax.numpy.sum", "jax.numpy.mean",
              "lax.psum", "lax.pmean", "jax.lax.psum", "jax.lax.pmean",
              "psum", "pmean"}

_TREE_MAPS = {"jax.tree.map", "jax.tree_util.tree_map", "tree_map",
              "jax.tree_map"}


def _is_producer_call(node: ast.AST, local_producers: set[str]) -> bool:
    return (isinstance(node, ast.Call)
            and (base_name(node.func) in _PRODUCERS
                 or base_name(node.func) in local_producers))


def _local_producer_names(scope: ast.AST) -> set[str]:
    """Functions/lambdas defined in this scope whose body calls a quant
    producer — one level of wrapper, enough for the `q = partial(cast…)`
    / `def q_tree(...)` idioms."""
    out: set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and base_name(sub.func) in _PRODUCERS):
                    out.add(node.name)
                    break
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
              and isinstance(node.targets[0], ast.Name)):
            val = node.value
            if isinstance(val, ast.Lambda):
                for sub in ast.walk(val):
                    if (isinstance(sub, ast.Call)
                            and base_name(sub.func) in _PRODUCERS):
                        out.add(node.targets[0].id)
                        break
    return out


def _unordered_name(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    if name in _UNORDERED or base_name(call.func) in ("psum", "pmean"):
        return name or base_name(call.func)
    return None


def _contains_unordered(node: ast.AST) -> Optional[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            n = _unordered_name(sub)
            if n:
                return n
    return None


@register
class KahanOrdering(Rule):
    id = "kahan-ordering"
    summary = ("quantized values must be reduced with the ordered "
               "primitives (parallel.reduction), not jnp.sum/lax.psum")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        scopes: list[ast.AST] = [ctx.tree, *iter_functions(ctx.tree)]
        scopes += [n for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.Lambda)]
        for scope in scopes:
            local_prod = _local_producer_names(scope)
            quant_names: set[str] = set()
            body = getattr(scope, "body", [])
            if isinstance(scope, ast.Lambda):
                body = [scope.body]  # single expression scope
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # separate scope, analyzed on its own
                # track assignments binding quantized values
                for node in walk_scope(stmt):
                    if not isinstance(node, ast.Assign):
                        continue
                    is_q = _is_producer_call(node.value, local_prod)
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            if is_q:
                                quant_names.add(tgt.id)
                            else:
                                quant_names.discard(tgt.id)
                # flag unordered reductions of quantized operands
                for node in walk_scope(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    red = _unordered_name(node)
                    if red is not None and node.args:
                        arg = node.args[0]
                        quant = (_is_producer_call(arg, local_prod)
                                 or (isinstance(arg, ast.Name)
                                     and arg.id in quant_names))
                        if quant:
                            yield ctx.finding(
                                self.id, node,
                                f"{red} over a quantized value: XLA's "
                                f"reduction order is opaque, so this "
                                f"drops the ordered-accumulation "
                                f"semantics — use parallel.reduction."
                                f"quantized_sum (or suppress if the "
                                f"fast/deployment path is intended)")
                        continue
                    # jax.tree.map(f_with_psum, quantized_tree)
                    if (dotted_name(node.func) in _TREE_MAPS
                            and len(node.args) >= 2):
                        tree_arg = node.args[1]
                        if (isinstance(tree_arg, ast.Name)
                                and tree_arg.id in quant_names):
                            red = _contains_unordered(node.args[0])
                            if red:
                                yield ctx.finding(
                                    self.id, node,
                                    f"tree.map applies {red} over the "
                                    f"quantized tree "
                                    f"{tree_arg.id!r} — unordered "
                                    f"reduction of quantized values "
                                    f"(see parallel.reduction)")
