"""obs-print: ad-hoc stdout telemetry bypassing the obs registry.

The obs subsystem (ISSUE 11, cpd_tpu/obs/) gives every number one home
— `MetricsRegistry` for counters/gauges, the tracer's event stream for
occurrences, `ScalarWriter` for training curves.  A bare ``print(...)``
in library code is the regression vector: an un-named, un-labelled,
un-exported number on stdout that no dashboard, determinism gate or
flight dump will ever see again.

Flagged shape — a ``print`` call **without a ``file=`` keyword** in a
module that is **not a script** (no top-level ``if __name__ ==
"__main__"`` guard):

    def scrub(self):
        print(f"corrupt pages: {n}")        # <- ad-hoc counter

Deliberately NOT flagged:

* ``print(..., file=sys.stderr)`` — rank-gated operator diagnostics
  (the ``=> ...`` protocol every defense uses) are stderr's job;
* any print in a module with a ``__main__`` guard — a CLI/tool's
  stdout IS its product (bench JSON lines, the linter's own output);
* the legacy reference-parity loggers (``utils/logging.py``'s
  TableLogger/ProgressPrinter stdout line protocol, which
  draw_curve.py greps) — that carve-out lives in config
  (``[tool.cpd-lint] exempt``), not here: path policy is the
  project's to own.

New counters should be `MetricsRegistry` series; new one-off prints
that really are operator diagnostics should say so by writing to
stderr.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, ModuleContext, Rule, register

__all__ = ["ObsPrint"]


def _has_main_guard(tree: ast.Module) -> bool:
    """Top-level ``if __name__ == "__main__"`` (either comparison
    order) — the marker that this module's stdout is its product."""
    for node in tree.body:
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if not (isinstance(test, ast.Compare)
                and len(test.comparators) == 1):
            continue
        sides = (test.left, test.comparators[0])
        names = [s.id for s in sides if isinstance(s, ast.Name)]
        consts = [s.value for s in sides
                  if isinstance(s, ast.Constant)]
        if "__name__" in names and "__main__" in consts:
            return True
    return False


@register
class ObsPrint(Rule):
    id = "obs-print"
    summary = ("bare print() in library code bypasses the obs "
               "MetricsRegistry/event stream — use stderr for operator "
               "diagnostics or a registry metric for numbers "
               "(script modules with a __main__ guard are exempt; the "
               "utils/logging.py reference-parity loggers' carve-out "
               "lives in [tool.cpd-lint] config)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if _has_main_guard(ctx.tree):
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                continue
            if any(kw.arg == "file" for kw in node.keywords):
                continue   # routed diagnostics (stderr/stream) are fine
            yield ctx.finding(
                self.id, node,
                "bare print() in library code — telemetry belongs in "
                "the obs MetricsRegistry (a number), the tracer event "
                "stream (an occurrence), or stderr via file=sys.stderr "
                "(an operator diagnostic); stdout is reserved for "
                "script products (docs/OBSERVABILITY.md)")
