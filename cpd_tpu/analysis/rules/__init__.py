"""Built-in rules.  Importing this package registers every rule with the
core registry (each module applies ``@core.register`` at import time).

Rule IDs (stable — they are the suppression-comment vocabulary):

  format-bounds    eXmY literals outside exp[1,8]/man[0,23]; constants
                   that overflow a literal-declared format
  axis-name        collective axis names with no mesh binding in module
  jit-hazards      traced-value control flow / host calls / unhashable
                   static defaults inside @jax.jit bodies
  pallas-hygiene   fresh allocations in kernels; off-tile BlockSpec
                   shapes; BlockSpecs without a memory space
  kahan-ordering   unordered jnp.sum/lax.psum over quantized values
                   where the ordered primitives exist
  donation         reuse of a buffer after donating it to a jitted call
  swallow          bare except / pass-only broad except outside
                   resilience/ (failure handling must be explicit)
"""

from . import (axis_name, donation, format_bounds, jit_hazards,  # noqa: F401
               kahan_ordering, pallas_hygiene, swallow)

__all__ = ["format_bounds", "axis_name", "jit_hazards", "pallas_hygiene",
           "kahan_ordering", "donation", "swallow"]
