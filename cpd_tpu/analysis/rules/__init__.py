"""Built-in rules.  Importing this package registers every rule with the
core registry (each module applies ``@core.register`` at import time).

Rule IDs (stable — they are the suppression-comment vocabulary).
Module-scoped (one file at a time):

  format-bounds    eXmY literals outside exp[1,8]/man[0,23]; constants
                   that overflow a literal-declared format
  axis-name        collective axis names with no mesh binding in module
  jit-hazards      traced-value control flow / host calls / unhashable
                   static defaults inside @jax.jit bodies
  pallas-hygiene   fresh allocations in kernels; off-tile BlockSpec
                   shapes; BlockSpecs without a memory space
  kahan-ordering   unordered jnp.sum/lax.psum over quantized values
                   where the ordered primitives exist
  donation         reuse of a buffer after donating it to a jitted call
  swallow          bare except / pass-only broad except (failure
                   handling must be explicit; the resilience/ carve-out
                   lives in [tool.cpd-lint] config, not here)
  compat-drift     jax.experimental.* / removed-API use outside
                   compat.py (ROADMAP item 5 precondition)
  obs-print        bare print() in non-script library code — ad-hoc
                   telemetry bypassing the obs MetricsRegistry/event
                   stream (utils/logging.py's reference-parity loggers
                   carved out in [tool.cpd-lint] config)

Project-scoped (whole-program, over analysis/project.py's graph):

  format-flow      man<2 ladder rungs reaching the ring wire; (exp,man)
                   swaps across call boundaries; pack/unpack width drift
  axis-flow        axis literals in no-mesh library modules unreachable
                   from any mesh constructor through the call graph
  collective-contract  non-bijective ppermute permutations; Kahan
                   compensation missing from a wire the partial rides
  retrace          jit built per-iteration; step tables keyed outside
                   ladder_step_key/StepTable (the PR 5 stale-step bug)
"""

from . import (axis_flow, axis_name, collective_contract,  # noqa: F401
               compat_drift, donation, format_bounds, format_flow,
               jit_hazards, kahan_ordering, obs_print, pallas_hygiene,
               retrace, swallow)

__all__ = ["format_bounds", "axis_name", "jit_hazards", "pallas_hygiene",
           "kahan_ordering", "donation", "swallow", "compat_drift",
           "format_flow", "axis_flow", "collective_contract", "retrace",
           "obs_print"]
