"""compat-drift: version-sensitive JAX API use outside compat.py.

ROADMAP item 5 (un-pin from jax 0.4.x, kill the shim) needs a
machine-checked inventory of every version-gated API use before the
migration can start — and needs the inventory to STAY empty afterwards.
This rule is that inventory: any ``jax.experimental.*`` import or
attribute chain, and any known-removed/renamed jax API, is a finding
unless it sits in ``cpd_tpu/compat.py`` (the one sanctioned shim site,
carved out via the [tool.cpd-lint] exempt table — config, not rule
code).

``jax.experimental`` is exactly the surface jax upstream renames,
promotes and deletes between minor releases (`shard_map` →
``jax.shard_map``, ``maps``/``pjit`` internals gone, Pallas still
migrating).  Routing every such use through compat.py means an upstream
rename costs ONE file, and the dual-pin CI of ROADMAP item 5 has a
single choke point to verify.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, ModuleContext, Rule, dotted_name, register

# APIs removed or renamed across the 0.4.x -> current window: using one
# is drift even outside jax.experimental
_REMOVED = {
    "jax.tree_multimap": "removed in jax 0.4 — use jax.tree.map",
    "jax.tree_map": "deprecated alias — use jax.tree.map",
    "jax.abstract_arrays": "module removed — use jax.core aval types",
    "jax.linear_util": "moved to jax.extend.linear_util",
    "jax.xla_computation": "removed — use jax.jit(f).lower(...)",
    "jax.core.NamedShape": "removed in jax 0.5",
}

_MSG = ("version-gated API ({name}) outside compat.py — route it "
        "through cpd_tpu/compat.py so the jax un-pin (ROADMAP item 5) "
        "has one choke point; see docs/ANALYSIS.md")


@register
class CompatDrift(Rule):
    id = "compat-drift"
    summary = ("jax.experimental.* / removed-API use outside compat.py "
               "— the machine-checked precondition for the jax un-pin")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        inner: set = set()   # ids of Attribute nodes inside a reported chain

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("jax.experimental"):
                        yield ctx.finding(self.id, node,
                                          _MSG.format(name=alias.name))
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level == 0 and mod.startswith("jax.experimental"):
                    yield ctx.finding(self.id, node, _MSG.format(name=mod))
                elif node.level == 0 and mod == "jax":
                    # `from jax import experimental [as e]` is the same
                    # surface through a side door
                    for alias in node.names:
                        if alias.name == "experimental":
                            yield ctx.finding(
                                self.id, node,
                                _MSG.format(name="jax.experimental"))
            elif isinstance(node, ast.Attribute) and id(node) not in inner:
                chain = dotted_name(node)
                if not chain:
                    continue
                hit = None
                if chain.startswith("jax.experimental"):
                    hit = _MSG.format(name=chain)
                elif chain in _REMOVED:
                    hit = (_MSG.format(name=chain)
                           + f" ({_REMOVED[chain]})")
                if hit:
                    # report the OUTERMOST chain once, not every nested
                    # Attribute node it contains (they share positions)
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Attribute) and sub is not node:
                            inner.add(id(sub))
                    yield ctx.finding(self.id, node, hit)
